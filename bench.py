"""Benchmark harness.

Measures the polishing hot loop (per-window POA consensus — the cudapoa
role, BASELINE.md north star "windows/sec/chip") on the reference's own
sample data (lambda phage, ~48.5 kb, 181 overlaps, PAF + FASTQ path), then
prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "windows/sec", "vs_baseline": N}

Device handling: the TPU path (batched layer prealignment,
ops/poa_device.py) is used when an accelerator is reachable — probed in a
subprocess with a hard timeout because the axon tunnel blocks forever when
it is down — and warmed up (one untimed polish) so the reported number is
steady-state throughput, not XLA compile time. With no reachable device
the host engine is measured (RACON_TPU_POA_BATCHES=0/1 forces either).

vs_baseline compares against the reference CPU implementation's
throughput on the same data: racon 1.4.x with 4 threads polishes this
sample's ~100 windows in about 2 s of consensus time on a modern x86 core
(the reference's CI runs all ten sample fixtures in well under a minute),
i.e. ~50 windows/sec. The reference publishes no official throughput
numbers (BASELINE.md), so this locally-grounded estimate is the
comparison point until a like-for-like A100 cudapoa run is available.

Side metrics (consensus identity vs the curated reference assembly, phase
wall-clocks) go to stderr so the one-line stdout contract stays intact.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REFERENCE_CPU_WINDOWS_PER_SEC = 50.0

DATA = "/root/reference/test/data/"


def probe_device(timeout: float = 90.0) -> bool:
    """True when jax can reach an accelerator (TPU) without hanging."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; ds = jax.devices(); "
             "print('OK' if ds and ds[0].platform != 'cpu' else 'CPU')"],
            capture_output=True, text=True, timeout=timeout)
        return proc.returncode == 0 and "OK" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def build_polisher(device_batches: int):
    from racon_tpu.core.polisher import create_polisher, PolisherType

    polisher = create_polisher(
        DATA + "sample_reads.fastq.gz", DATA + "sample_overlaps.paf.gz",
        DATA + "sample_layout.fasta.gz", PolisherType.kC, 500, 10.0, 0.3,
        True, 5, -4, -8, num_threads=os.cpu_count() or 1,
        tpu_poa_batches=device_batches)
    polisher.initialize()
    return polisher


def main() -> int:
    from racon_tpu.io.parsers import create_sequence_parser
    from racon_tpu.native import edit_distance

    forced = os.environ.get("RACON_TPU_POA_BATCHES")
    if forced is not None:
        device_batches = int(forced)
    else:
        device_batches = 1 if probe_device() else 0
    mode = "device" if device_batches else "host"
    print(f"[bench] consensus engine: {mode}", file=sys.stderr)

    t0 = time.perf_counter()
    polisher = build_polisher(device_batches)
    init_time = time.perf_counter() - t0

    if device_batches:
        # warm-up run so XLA compiles don't count against throughput
        build_polisher(device_batches).polish()

    n_windows = len(polisher.windows)
    t1 = time.perf_counter()
    polished = polisher.polish()
    t2 = time.perf_counter()

    ref: list = []
    create_sequence_parser(DATA + "sample_reference.fasta.gz",
                           "bench").parse(ref, -1)
    dist = edit_distance(polished[0].reverse_complement, ref[0].data)
    identity = 1.0 - dist / len(ref[0].data)

    polish_time = t2 - t1
    wps = n_windows / polish_time if polish_time > 0 else 0.0

    print(f"[bench] initialize: {init_time:.2f}s  polish: {polish_time:.2f}s "
          f"({n_windows} windows, {mode} engine)", file=sys.stderr)
    print(f"[bench] edit distance vs reference assembly: {dist} "
          f"(identity {identity * 100:.2f}%; reference CPU fixture: 1312)",
          file=sys.stderr)

    print(json.dumps({
        "metric": f"sample_polish_consensus_throughput_{mode}",
        "value": round(wps, 2),
        "unit": "windows/sec",
        "vs_baseline": round(wps / REFERENCE_CPU_WINDOWS_PER_SEC, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
