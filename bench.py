"""Benchmark harness.

Measures the polishing hot loop (per-window POA consensus — the cudapoa
role, BASELINE.md north star "windows/sec/chip") on the reference's own
sample data (lambda phage, ~48.5 kb, 181 overlaps, PAF + FASTQ path), then
prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "windows/sec", "vs_baseline": N}

Failure discipline (round-3 lesson: a pathological device path must not
eat the whole budget and lose the host number too): every measurement runs
in a SUBPROCESS with a hard wall-clock cap. The device phase (evolving-
graph engine, ops/poa_graph.py, RACON_TPU_STRICT so a device failure
raises instead of silently reporting the host fallback as "device") gets
_DEVICE_CAP seconds including its kernel precompile; the host phase gets
_HOST_CAP. The final JSON line is the device number when that phase
succeeded, else the host number, else an explicit zero — the line is
emitted under every failure mode.

Device warm-up is `DeviceGraphPOA.precompile()` — all four pinned
(bucket, batch) programs compiled before the timed loop — instead of a
second full pipeline run.

An optional device-aligner smoke (the cudaaligner role, ops/align.py;
enabled with the device phase) reports wall time and skipped-pair counts
on stderr, mirroring the reference's "[CUDAPolisher] Aligned overlaps ...
on GPU" accounting (cudapolisher.cpp:204-206). It never affects the JSON.

vs_baseline compares against the reference CPU implementation's
throughput on the same data: racon 1.4.x with 4 threads polishes this
sample's ~100 windows in about 2 s of consensus time on a modern x86 core
(the reference's CI runs all ten sample fixtures in well under a minute),
i.e. ~50 windows/sec. The reference publishes no official throughput
numbers (BASELINE.md), so this locally-grounded estimate is the
comparison point until a like-for-like A100 cudapoa run is available.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REFERENCE_CPU_WINDOWS_PER_SEC = 50.0

DATA = "/root/reference/test/data/"

_DEVICE_CAP = 780.0   # seconds, includes XLA precompile of 4 programs
_FUSED_CAP = 600.0    # fused engine phase (precompile of 4 depth buckets)
_HOST_CAP = 300.0     # host run is ~20 s; generous margin
_ALIGNER_CAP = 300.0


def probe_device(timeout: float | None = None, retries: int = 1) -> bool:
    """True when jax can reach an accelerator (TPU) without hanging.

    The axon tunnel's first device claim can take minutes; the default
    timeout matches tools/tpu_smoke.py's probe (420 s) and one retry is
    attempted, because a probe that gives up early silently downgrades
    the whole bench to host-only (round-4 failure mode). Env-tunable via
    RACON_TPU_PROBE_TIMEOUT."""
    if timeout is None:
        timeout = float(os.environ.get("RACON_TPU_PROBE_TIMEOUT", "420"))
    for attempt in range(1 + max(0, retries)):
        # retry gets a shorter slice: its job is catching a tunnel that
        # came up between attempts, not doubling the dead-tunnel cost
        t = timeout if attempt == 0 else min(timeout, 240.0)
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; ds = jax.devices(); "
                 "print('OK' if ds and ds[0].platform != 'cpu' else 'CPU')"],
                capture_output=True, text=True, timeout=t)
            if proc.returncode == 0 and "OK" in proc.stdout:
                return True
            if proc.returncode == 0 and "CPU" in proc.stdout:
                return False  # backend answered: no accelerator — final
            why = (f"rc={proc.returncode}, stderr tail: "
                   f"{proc.stderr[-300:]!r}")
        except subprocess.TimeoutExpired:
            why = f"timeout after {t:.0f}s"
        print(f"[bench] device probe attempt {attempt + 1} failed ({why})",
              file=sys.stderr)
    return False


def build_polisher(device_batches: int, aligner_batches: int = 0):
    from racon_tpu.core.polisher import create_polisher, PolisherType

    polisher = create_polisher(
        DATA + "sample_reads.fastq.gz", DATA + "sample_overlaps.paf.gz",
        DATA + "sample_layout.fasta.gz", PolisherType.kC, 500, 10.0, 0.3,
        True, 5, -4, -8, num_threads=os.cpu_count() or 1,
        tpu_poa_batches=device_batches,
        tpu_aligner_batches=aligner_batches,
        # the async dispatch pipeline depth (0 = synchronous, for A/B
        # bisection of the overlap win on the same data)
        tpu_pipeline_depth=int(
            os.environ.get("RACON_TPU_PIPELINE_DEPTH", "2")))
    return polisher


def _stage_fields(polisher) -> dict:
    """The polisher's per-stage pipeline counters, rounded for the JSON
    artifact. Overlap evidence: pack+device+unpack stage seconds exceeding
    the phase wall time means the stages really ran concurrently; device
    seconds ~ 0 means the pipeline is silently dead.

    The snapshot also carries the resilience degradation report (faults /
    retries / timeouts / backoff_s / breaker_trips / quarantined /
    cancelled — racon_tpu/resilience/): all zero on a clean run, and a
    nonzero `quarantined` or `breaker_trips` on a STRICT-less phase means
    the throughput number was earned on a degraded path — CI should read
    these next to the stage counters before trusting a comparison."""
    return {k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in polisher.stage_stats.items()}


def _identity(polished) -> tuple[int, float]:
    from racon_tpu.io.parsers import create_sequence_parser
    from racon_tpu.native import edit_distance

    ref: list = []
    create_sequence_parser(DATA + "sample_reference.fasta.gz",
                           "bench").parse(ref, -1)
    dist = edit_distance(polished[0].reverse_complement, ref[0].data)
    return dist, 1.0 - dist / len(ref[0].data)


def phase_consensus(mode: str) -> int:
    """Child process: measure one engine end-to-end; last stdout line is
    the phase's JSON result. Modes: "host" (C++ engine), "device" (the
    per-layer session engine), "fused" (the single-launch whole-window
    engine, failed/ineligible windows host-polished — the reference's own
    per-window GPU->CPU fallback discipline, cudapolisher.cpp:354-383)."""
    device = 0 if mode == "host" else 1
    if device and _cpu_backend_refused():
        return 3
    if mode == "fused":
        os.environ["RACON_TPU_ENGINE"] = "fused"
        os.environ.setdefault("RACON_TPU_FUSED_FALLBACK", "host")
    else:
        # pin: an inherited RACON_TPU_ENGINE=fused must not make the
        # session-engine phase silently measure the fused engine
        os.environ["RACON_TPU_ENGINE"] = "session"
    # warm-vs-cold compile-cache evidence: a non-empty persistent cache
    # at phase start means this phase's XLA compiles (inside initialize
    # for the aligner, inside precompile for the consensus engines)
    # should mostly be disk hits — the JSON records which run this was
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR", "")
    cache_warm = bool(cache_dir) and bool(
        os.path.isdir(cache_dir) and os.listdir(cache_dir))
    polisher = build_polisher(device)
    t0 = time.perf_counter()
    polisher.initialize()
    init_time = time.perf_counter() - t0

    precompile_time = 0.0
    if device:
        t = time.perf_counter()
        from racon_tpu.ops.poa import _pack

        # with adaptive buckets armed, precompile the DERIVED shapes —
        # each engine's ladder is a pure (idempotent) function of the
        # window set, so the polish run's own engine instance re-derives
        # the same shapes and hits these programs in the jit cache
        wins = ([_pack(w) for w in polisher.windows]
                if polisher.scheduler.adaptive else None)
        if mode == "fused":
            from racon_tpu.ops.poa_fused import FusedPOA

            depth = max((len(w.sequences) - 1 for w in polisher.windows),
                        default=0)
            # banded_only must match what the timed polish constructs
            # (create_polisher's tpu_banded_alignment flows into
            # FusedPOA(banded_only=...) and keys its compiled programs);
            # a mismatch would recompile every depth bucket inside the
            # timed loop and waste the precompile entirely
            FusedPOA(5, -4, -8,
                     banded_only=polisher.tpu_banded_alignment,
                     scheduler=polisher.scheduler).precompile(
                max_depth=depth, windows=wins)
        else:
            from racon_tpu.ops.poa_graph import DeviceGraphPOA

            DeviceGraphPOA(5, -4, -8,
                           scheduler=polisher.scheduler).precompile(
                windows=wins)
        precompile_time = time.perf_counter() - t
        print(f"[bench] device precompile: {precompile_time:.2f}s "
              f"(compile cache {'warm' if cache_warm else 'cold'})",
              file=sys.stderr)

    n_windows = len(polisher.windows)
    t1 = time.perf_counter()
    polished = polisher.polish()
    t2 = time.perf_counter()

    dist, identity = _identity(polished)
    polish_time = t2 - t1
    wps = n_windows / polish_time if polish_time > 0 else 0.0
    print(f"[bench] initialize: {init_time:.2f}s  polish: {polish_time:.2f}s "
          f"({n_windows} windows, {mode} engine)", file=sys.stderr)
    print(f"[bench] edit distance vs reference assembly: {dist} "
          f"(identity {identity * 100:.2f}%; reference CPU fixture: 1312)",
          file=sys.stderr)
    rec = {"mode": mode, "wps": wps, "windows": n_windows, "dist": dist,
           "init_s": round(init_time, 2),
           "precompile_s": round(precompile_time, 2),
           "cache_warm": cache_warm,
           "adaptive_buckets": polisher.scheduler.adaptive,
           "stages": _stage_fields(polisher),
           "occupancy": polisher.occupancy_stats,
           "mesh": _mesh_info(),
           # the unified observability snapshot (racon_tpu/obs): the
           # stage/occupancy fields above, re-published under one
           # namespaced schema (pipeline.* / sched.* / resilience.*)
           "metrics": polisher.metrics.snapshot()}
    if device:
        rec["platform"] = _jax_platform()
    print(json.dumps(rec))
    return 0


def _jax_platform() -> str:
    import jax

    return jax.devices()[0].platform


def _mesh_info() -> dict:
    """The shared mesh-block schema (parallel/mesh.py). Worker lanes
    are a serve-only concept — one-shot bench phases always run 1."""
    from racon_tpu.parallel.mesh import mesh_info

    return mesh_info()


def _cpu_backend_refused() -> bool:
    """Blind attempt (probe failed): a jax that silently fell back to the
    CPU backend must not mislabel a CPU number as a device number."""
    if not os.environ.get("RACON_TPU_REQUIRE_ACCELERATOR"):
        return False
    if _jax_platform() == "cpu":
        print("[bench] blind device phase: backend is CPU — refusing to "
              "report it as a device number", file=sys.stderr)
        return True
    return False


def phase_aligner() -> int:
    """Child process: device-aligner smoke — overlap alignment phase only
    (initialize), device kernel mandatory (STRICT). Long overlaps host-
    align (counted as device skips, the cudaaligner exceeded_max_length
    discipline) so the smoke stays inside its wall cap."""
    if _cpu_backend_refused():
        return 3
    os.environ.setdefault("RACON_TPU_ALIGNER_MAXLEN", "16384")
    polisher = build_polisher(0, aligner_batches=1)
    t0 = time.perf_counter()
    polisher.initialize()
    t1 = time.perf_counter()
    print(f"[bench] device aligner initialize: {t1 - t0:.2f}s "
          f"({polisher.n_aligner_device}/{polisher.n_aligner_pairs} pairs "
          f"on device, {polisher.n_aligner_host_fallback} host fallbacks)",
          file=sys.stderr)
    # initialize-only flow: polish() never runs, so emit any armed
    # trace/metrics artifacts explicitly
    polisher.emit_observability()
    print(json.dumps({"mode": "aligner", "seconds": round(t1 - t0, 2),
                      "platform": _jax_platform(),
                      "pairs": polisher.n_aligner_pairs,
                      "device_pairs": polisher.n_aligner_device,
                      "host_fallbacks": polisher.n_aligner_host_fallback,
                      "adaptive_buckets": polisher.scheduler.adaptive,
                      "stages": _stage_fields(polisher),
                      "occupancy": polisher.occupancy_stats,
                      "mesh": _mesh_info(),
                      "metrics": polisher.metrics.snapshot()}))
    return 0


def _run_phase(phase: str, cap: float, strict: bool, argv=None,
               env_extra=None, expect_json: bool = True):
    """Run one phase in a subprocess under a wall-clock cap. Returns the
    parsed JSON result dict (or {"rc": 0} when expect_json=False), or
    None on timeout/failure."""
    env = dict(os.environ, **(env_extra or {}))
    # a None value removes the variable (e.g. PYTHONPATH, where the axon
    # shim lives — dropping it keeps a CPU-pinned child from hanging on a
    # dead tunnel)
    env = {k: v for k, v in env.items() if v is not None}
    if strict:
        env["RACON_TPU_STRICT"] = "1"
    # phases are separate processes; a persistent compilation cache lets
    # later phases (and warm re-runs) reuse earlier phases' XLA compiles.
    # RACON_TPU_COMPILE_CACHE (the --tpu-compile-cache knob's env twin)
    # redirects it; a second bench run against the same directory shows
    # the warm-run initialize/precompile reduction in the phase JSON
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.environ.get("RACON_TPU_COMPILE_CACHE")
                   or "/tmp/racon_tpu_jax_cache")
    cmd = argv or [sys.executable, os.path.abspath(__file__),
                   "--phase", phase]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=cap, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired as e:
        if e.stderr:
            text = (e.stderr.decode(errors="replace")
                    if isinstance(e.stderr, bytes) else e.stderr)
            sys.stderr.write(text[-2000:])
        print(f"[bench] phase {phase}: TIMEOUT after {cap:.0f}s",
              file=sys.stderr)
        return None
    sys.stderr.write(proc.stderr[-4000:])
    if proc.returncode != 0:
        print(f"[bench] phase {phase}: rc={proc.returncode}; stdout tail: "
              f"{proc.stdout[-500:]!r}", file=sys.stderr)
        return None
    if not expect_json:
        return {"rc": 0}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print(f"[bench] phase {phase}: unparseable stdout "
              f"{proc.stdout[-500:]!r}", file=sys.stderr)
        return None


def _run_scale(cap: float) -> None:
    """Synthetic 250 kb / 20x polish on the fused device engine
    (tools/synthbench.py) — a scale data point toward BASELINE.md's
    E.-coli north star, reported on stderr only. STRICT so a device
    failure cannot masquerade as a device scale number."""
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "synthbench.py")
    _run_phase("scale", cap, strict=True,
               argv=[sys.executable, tool, "--genome-kb", "250",
                     "--coverage", "20", "-c", "1", "--fast-sim"],
               env_extra={"RACON_TPU_ENGINE": "fused",
                          "RACON_TPU_FUSED_FALLBACK": "host"},
               expect_json=False)


def main() -> int:
    if len(sys.argv) >= 3 and sys.argv[1] == "--phase":
        if sys.argv[2] == "aligner":
            return phase_aligner()
        return phase_consensus(sys.argv[2])

    t_start = time.monotonic()
    budget = float(os.environ.get("RACON_TPU_BENCH_BUDGET", "1500"))

    def room(reserve: float) -> float:
        """Wall-clock left inside the overall budget after `reserve`."""
        return budget - (time.monotonic() - t_start) - reserve

    forced = os.environ.get("RACON_TPU_POA_BATCHES")
    try_blind = False
    if forced is not None:
        want_device = int(forced) > 0
    else:
        want_device = probe_device()
        if not want_device:
            # A failed probe must not silently downgrade the round to
            # host-only (round-4 failure mode): attempt ONE capped STRICT
            # fused phase anyway. On a dead tunnel this costs exactly one
            # subprocess cap; on a slow-but-alive tunnel it saves the
            # round's device number.
            try_blind = True
    print(f"[bench] device reachable: {want_device}"
          + (" (probe failed; will attempt fused phase blind)"
             if try_blind else ""), file=sys.stderr)

    # Two device engines, both measured when the chip is up: the fused
    # single-launch engine first (the cudapoa-shaped flagship; leftover
    # windows host-polished), then the per-layer session engine (device
    # consensus byte-identical to host). The headline metric is the
    # faster one; every phase runs under both its own cap and the global
    # budget (the host phase's slice is always reserved).
    fused_res = None
    device_res = None
    fused_attempted = False
    if want_device or try_blind:
        cap = min(_FUSED_CAP, room(_HOST_CAP + 60))
        if cap > 120:
            fused_attempted = True
            extra = ({"RACON_TPU_REQUIRE_ACCELERATOR": "1"}
                     if try_blind else None)
            fused_res = _run_phase("fused", cap, strict=True,
                                   env_extra=extra)
        if try_blind and fused_res is not None:
            # the blind attempt reached the chip after all — the tunnel
            # was slow, not dead; run the remaining device phases too
            want_device = True
    if want_device:
        cap = min(_DEVICE_CAP, room(_HOST_CAP + 60))
        if cap > 120:
            device_res = _run_phase("device", cap, strict=True)
    # aligner phase: attempted whenever a device is KNOWN to exist (probe
    # success, forced, or the blind fused phase reached the chip — which
    # sets want_device), NOT gated on a consensus phase succeeding
    # (round-4 verdict: the gate meant this kernel never produced a
    # recorded number). A blind fused phase that RAN and failed means the
    # tunnel is dead: skip the blind aligner attempt too, so a dead
    # tunnel costs exactly one subprocess cap and the CPU-pinned fallback
    # below runs immediately (ADVICE round-5). A blind fused phase that
    # never ran (budget too tight) proves nothing, so the blind aligner
    # attempt is still made then.
    aligner_res = None
    aligner_backend = "device"
    if want_device or (try_blind and not fused_attempted):
        cap = min(_ALIGNER_CAP, room(_HOST_CAP + 60 + 180))
        if cap > 60:
            extra = ({"RACON_TPU_REQUIRE_ACCELERATOR": "1"}
                     if not want_device else None)
            aligner_res = _run_phase("aligner", cap, strict=True,
                                     env_extra=extra)
    if aligner_res is None and (forced is None or int(forced) > 0):
        # no device-aligner number — record a CPU-backend one instead so
        # the artifact always carries cudaaligner-role evidence (pinned to
        # the CPU backend and labeled as such; PYTHONPATH dropped so a
        # dead axon tunnel cannot hang the child). Skipped only when the
        # operator explicitly forced the device off (tests do this).
        aligner_backend = "cpu"
        cap = min(240.0, room(_HOST_CAP + 60))
        if cap > 60:
            aligner_res = _run_phase(
                "aligner", cap, strict=True,
                env_extra={"JAX_PLATFORMS": "cpu", "PYTHONPATH": None,
                           "RACON_TPU_REQUIRE_ACCELERATOR": None})
    if want_device:
        # scale phase (stderr only, never the JSON artifact): the
        # north-star workload shape at ~5x the sample's window count,
        # on the fused device engine — run only when THAT engine just
        # proved itself and the budget has room
        cap = min(480.0, room(_HOST_CAP + 60))
        if fused_res is not None and cap > 240:
            _run_scale(cap)

    # host engine measured in every run: the comparison point for the
    # device number (stderr only when a device phase succeeded); its cap
    # honors the global budget too, but never drops below the floor it
    # needs to emit a number
    host_res = _run_phase("host", min(_HOST_CAP, max(120.0, room(0.0))),
                          strict=False)
    if host_res is not None:
        print(f"[bench] host engine: {host_res['wps']:.2f} windows/sec",
              file=sys.stderr)
    for r in (fused_res, device_res):
        if r is not None:
            print(f"[bench] {r['mode']} engine: {r['wps']:.2f} windows/sec",
                  file=sys.stderr)

    # aligner evidence rides the artifact line as extra fields (round-4
    # verdict #6: the cudaaligner-role kernel must produce a recorded
    # number regardless of the consensus phases' outcome)
    aligner_fields = {}
    if aligner_res is not None:
        aligner_fields = {
            # the phase reports the platform jax actually ran on — a
            # forced run on a silently-CPU jax is labeled cpu, not device
            "aligner_backend": aligner_res.get("platform",
                                               aligner_backend),
            "aligner_seconds": aligner_res.get("seconds"),
            "aligner_pairs": aligner_res.get("pairs"),
            "aligner_device_pairs": aligner_res.get("device_pairs"),
            "aligner_host_fallbacks": aligner_res.get("host_fallbacks"),
        }

    on_device = [r for r in (fused_res, device_res) if r is not None]
    res = max(on_device, key=lambda r: r["wps"]) if on_device else host_res
    if res is None:
        print(json.dumps({
            "metric": "sample_polish_consensus_throughput_failed",
            "value": 0.0, "unit": "windows/sec", "vs_baseline": 0.0,
            **aligner_fields}))
        return 1
    wps = float(res["wps"])
    # per-stage pipeline counters of the headline phase: the overlap win
    # is measurable (pack+device+unpack > phase wall) and a silently-dead
    # pipeline is visible (device seconds ~ 0)
    stage_fields = ({"stages": res["stages"]} if "stages" in res else {})
    # per-bucket occupancy of the headline phase (sched/ telemetry): how
    # much of each dispatched device shape was real work, plus warm-vs-
    # cold compile-cache evidence for the initialize-time comparison
    for key in ("occupancy", "init_s", "precompile_s", "cache_warm",
                "adaptive_buckets", "metrics", "mesh"):
        if key in res:
            stage_fields[key] = res[key]
    label = {"fused": "device_fused", "device": "device",
             "host": "host"}[res["mode"]]
    # honesty clause: a device-engine phase that actually ran on the CPU
    # backend (forced rehearsal, or jax silently falling back) must not
    # be labeled as a device number
    if res["mode"] != "host" and res.get("platform") == "cpu":
        label += "_cpubackend"
    print(json.dumps({
        "metric": f"sample_polish_consensus_throughput_{label}",
        "value": round(wps, 2),
        "unit": "windows/sec",
        "vs_baseline": round(wps / REFERENCE_CPU_WINDOWS_PER_SEC, 3),
        **stage_fields,
        **aligner_fields,
    }))
    # optional perf regression gate (tools/perfgate.py): stderr verdict
    # only — the JSON-line contract above is the artifact either way,
    # and a gate bug must never cost the round its number
    if os.environ.get("RACON_TPU_PERFGATE"):
        try:
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools"))
            import perfgate

            ok, delta = perfgate.gate(
                wps, REFERENCE_CPU_WINDOWS_PER_SEC,
                float(os.environ.get("RACON_TPU_PERFGATE_TOL", "10")),
                higher_better=True)
            print(f"[bench] perfgate {'PASS' if ok else 'FAIL'}: "
                  f"{wps:.2f} windows/sec vs reference-CPU baseline "
                  f"{REFERENCE_CPU_WINDOWS_PER_SEC:g} ({delta:+.1f}%)",
                  file=sys.stderr)
        except Exception as exc:
            print(f"[bench] perfgate unavailable ({exc})",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
