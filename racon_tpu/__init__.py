"""racon_tpu — TPU-native consensus / polishing framework.

A ground-up re-design of the capabilities of NVIDIA-Genomics-Research/racon-gpu
for TPU hardware: the two compute hot spots (pairwise read<->contig alignment and
per-window partial-order-alignment consensus) run as batched, fixed-shape JAX/XLA
programs sharded over a TPU mesh; the host pipeline (parsing, overlap filtering,
windowing, stitching) mirrors the reference's semantics
(reference: src/polisher.cpp, src/overlap.cpp, src/window.cpp).

Public API (mirrors reference src/polisher.hpp:42-57):
    create_polisher(...) -> Polisher
    Polisher.initialize()
    Polisher.polish(drop_unpolished_sequences) -> list[Sequence]
"""

from .errors import RaconError
from .core.sequence import Sequence, create_sequence
from .core.overlap import Overlap
from .core.window import Window, WindowType, create_window
from .core.polisher import Polisher, PolisherType, create_polisher

__version__ = "0.1.0"

__all__ = [
    "RaconError",
    "Sequence",
    "create_sequence",
    "Overlap",
    "Window",
    "WindowType",
    "create_window",
    "Polisher",
    "PolisherType",
    "create_polisher",
    "__version__",
]
