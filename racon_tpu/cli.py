"""Command-line interface.

TPU-native equivalent of the reference CLI (src/main.cpp:47-169): same 12
common options plus TPU device knobs paralleling the reference's CUDA flags
(src/main.cpp:36-41, --cudapoa-batches/--cuda-banded-alignment/
--cudaaligner-batches/--cudaaligner-band-width), polished FASTA on stdout,
errors as `[racon_tpu::...] error: ...` on stderr with exit status 1.
"""

from __future__ import annotations

import sys

from . import __version__
from .errors import RaconError

HELP = """\
usage: racon_tpu [options ...] <sequences> <overlaps> <target sequences>
       racon_tpu serve [serve options ...]
       racon_tpu submit [submit options ...] <sequences> <overlaps> <target>
       racon_tpu cancel --socket SOCK (--job-id ID | --trace-id ID)
       racon_tpu router [router options ...]
       racon_tpu fleet [fleet options ...]

    subcommands (see `racon_tpu serve --help` / `racon_tpu submit --help`
    and the README "Serving" section):
        serve   run the warm polishing job server (one process keeps the
                engines compiled; jobs from many clients share device
                batches; live Prometheus metrics via the `scrape` RPC
                or `--metrics-port`, post-mortems via the always-on
                flight recorder and the `debug` RPC, an auditable
                lifecycle journal via `--journal`)
        submit  send one polishing job to a running server; polished
                FASTA on stdout, byte-identical to the one-shot run;
                `--progress` streams live phase/window progress (incl.
                queue position), `--stream` writes each polished
                contig the moment it finishes on the server,
                `--tenant` names the fair-scheduling bucket, and
                `--trace-out t.json` writes one merged Chrome trace of
                the request — through the router, a DISTRIBUTED trace:
                client, router and every participating replica as
                clock-synced process tracks in one artifact
                (`tools/tracereport.py` prints its critical path and
                per-stage cost attribution)
        cancel  cancel a queued or running job by --job-id or
                --trace-id (name jobs via `submit --trace-id`): queued
                jobs dequeue with a typed `cancelled` error to their
                submitter, running jobs withdraw at the next
                iteration/round boundary; through the router the
                cancel fans out to the job's shards
        router  shard-aware front-end over N warm serve replicas: one
                submit is split by contig across routable replicas
                (wrapper partition math, output byte-identical to a
                solo server), merged back in contig order; a durable
                journal ledger requeues a dead replica's shards onto
                healthy ones with streamed contigs deduped (each
                contig exactly once), and rolling restarts — drain,
                restart, rejoin on clean healthz — lose no jobs; when
                replicas outnumber contigs, contigs split further by
                window-range so a one-contig job scales past a single
                replica, and --autoscale arms the elastic-fleet loop
                that spawns/drains replicas with backlog pressure
                (README "Serving"; RACON_TPU_ROUTER_* env knobs,
                RACON_TPU_ROUTER_AUTOSCALE_* for the loop); the router
                keeps its own flight ring of plan/dispatch/merge spans
                — `--trace` (or RACON_TPU_ROUTER_TRACE) dumps it at
                drain, and a traced submit pulls every replica's spans
                into ONE merged trace (README "Distributed tracing &
                cost accounting")
        fleet   federate N replicas' metrics and health into one view:
                polls every endpoint in --endpoints /
                RACON_TPU_FLEET_ENDPOINTS, merges counters and latency
                histograms (exact bucket pooling, exemplars preserved),
                and serves the merged /metrics + /healthz on --port —
                healthy only while EVERY replica is reachable and not
                draining; `--json` prints one machine-readable fleet
                snapshot instead (README "Fleet view"; the live
                console is tools/servetop.py)

    #default output is stdout
    <sequences>
        input file in FASTA/FASTQ format (can be compressed with gzip)
        containing sequences used for correction
    <overlaps>
        input file in MHAP/PAF/SAM format (can be compressed with gzip)
        containing overlaps between sequences and target sequences
    <target sequences>
        input file in FASTA/FASTQ format (can be compressed with gzip)
        containing sequences which will be corrected

    options:
        -u, --include-unpolished
            output unpolished target sequences
        -f, --fragment-correction
            perform fragment correction instead of contig polishing
            (overlaps file should contain dual/self overlaps!)
        -w, --window-length <int>
            default: 500
            size of window on which POA is performed
        -q, --quality-threshold <float>
            default: 10.0
            threshold for average base quality of windows used in POA
        -e, --error-threshold <float>
            default: 0.3
            maximum allowed error rate used for filtering overlaps
        --no-trimming
            disables consensus trimming at window ends
        -m, --match <int>
            default: 3
            score for matching bases
        -x, --mismatch <int>
            default: -5
            score for mismatching bases
        -g, --gap <int>
            default: -4
            gap penalty (must be negative)
        -t, --threads <int>
            default: 1
            number of threads
        --version
            prints the version number
        -h, --help
            prints the usage
        -c, --tpupoa-batches <int>
            default: 0
            number of device batches for TPU accelerated polishing
        -b, --tpu-banded-alignment
            use banding approximation for alignment on TPU: banded POA
            results are trusted as-is (the clipped-result full-DP retry is
            skipped), trading exact host-engine parity for speed
        --tpu-engine <session|fused>
            default: session
            device consensus engine: per-layer evolving-graph session
            (byte-identical to the host engine) or single-launch
            whole-window fused (equal aggregate quality; rare tie-order
            divergence possible on deep windows)
        --tpu-pipeline-depth <int>
            default: 2
            async dispatch pipeline depth: chunks packed/in flight ahead
            of the one being unpacked (host pack, device compute, host
            unpack and host-fallback work all overlap); 0 disables the
            overlap entirely (synchronous path, for bisection)
        --tpu-device-timeout <float>
            default: 0 (off)
            watchdog deadline in seconds for each device-stage call; a
            call past the deadline raises a timeout and the chunk is
            retried with exponential backoff (RACON_TPU_DEVICE_RETRIES,
            default 1) before routing to the host fallback
        --tpu-adaptive-buckets
            derive each device engine's shape ladder from the run's own
            job-shape histogram (occupancy-aware batch scheduler) and
            pack shape-sorted batches, instead of the static worst-case
            ladders; output is byte-identical either way (mirrors
            RACON_TPU_ADAPTIVE_BUCKETS)
        --tpu-compile-cache <dir>
            default: none
            persistent XLA compilation cache directory: repeated runs
            skip recompiles, including adaptive-bucket runs whose shapes
            are data-derived (mirrors RACON_TPU_COMPILE_CACHE /
            JAX_COMPILATION_CACHE_DIR)
        --tpu-pallas <0|1|auto>
            default: 0
            hand-tiled Pallas device kernels for the banded aligner and
            the session POA sweep: 1 = whenever the VMEM envelope fits,
            auto = per-bucket from the persisted autotuner winner table
            (profile with tools/tpu_smoke.py; buckets without an entry
            dispatch XLA), 0 = XLA programs only. Output is
            byte-identical in every mode (mirrors RACON_TPU_PALLAS)
        --tpu-dtype <auto|int32|int16>
            default: auto
            DP score dtype policy: auto shrinks each bucket to int16
            when its overflow envelope proof holds (half the DP bytes,
            bit-identical results), int32 forces the wide oracle
            everywhere (mirrors RACON_TPU_DTYPE)
        --tpu-fused <auto|0|1>
            default: auto
            fused-engine chunk dispatch: 1 = the single-launch fused
            align->window-slice->POA program (device-side slicing, one
            launch + one fetch per chunk), 0 = the split chained path,
            auto = per depth bucket from the persisted autotuner winner
            table. Output is byte-identical in every mode; a faulted
            fused chunk falls back to the split path (mirrors
            RACON_TPU_FUSED)
        --tpu-strict
            re-raise device failures instead of degrading to the host
            fallback / per-window quarantine (mirrors RACON_TPU_STRICT;
            the bench/CI discipline)
        --tpu-fault-plan <spec>
            default: none
            deterministic fault injection for resilience testing
            (mirrors RACON_TPU_FAULT_PLAN): comma-separated
            <stage>:chunk=<N>:<action> entries with stage one of
            pack|device|unpack|fallback and action raise | corrupt |
            hang=<seconds>, e.g. 'device:chunk=3:raise,unpack:chunk=2:corrupt'
        --tpu-trace <file>
            default: none
            record a span trace of the run (pipeline stages per chunk,
            engine dispatch loops, XLA compiles, fault/quarantine
            events) as Chrome trace-event JSON loadable in Perfetto /
            chrome://tracing (mirrors RACON_TPU_TRACE)
        --tpu-metrics <file>
            default: none
            dump the end-of-run metrics snapshot (pipeline.* / sched.* /
            resilience.* namespaces) as JSON, and render it as a stderr
            summary table (mirrors RACON_TPU_METRICS)
        --tpu-log-level <quiet|info|debug>
            default: info
            stderr verbosity: quiet silences progress/timing lines, info
            is the classic output, debug additionally shows every
            deduplicated per-chunk warning (mirrors RACON_TPU_LOG_LEVEL)
        --tpu-jax-profile <dir>
            default: none
            bracket the device phases with a jax.profiler capture into
            <dir> (deep-dive XLA/TPU view; no-op when the backend cannot
            profile; mirrors RACON_TPU_PROFILE)
        --tpualigner-batches <int>
            default: 0
            number of device batches for TPU accelerated alignment
        --tpualigner-band-width <int>
            default: 0
            Band width for TPU alignment. Must be >= 0. Non-zero allows user
            defined band width, whereas 0 implies auto band width
            determination.
"""


def parse_args(argv: list[str]) -> dict | None:
    """getopt-style parser mirroring src/main.cpp:75-155.

    Returns the option dict, or None when --help/--version already handled.
    Mimics getopt_long behaviors the reference relies on: intermixed options
    and positionals, `-c` with an optional argument (src/main.cpp:113-125).
    """
    opts = {
        "window_length": 500,
        "quality_threshold": 10.0,
        "error_threshold": 0.3,
        "trim": True,
        "match": 3,
        "mismatch": -5,
        "gap": -4,
        "fragment_correction": False,
        "drop_unpolished_sequences": True,
        "num_threads": 1,
        "tpu_poa_batches": 0,
        "tpu_aligner_batches": 0,
        "tpu_aligner_band_width": 0,
        "tpu_banded_alignment": False,
        "tpu_engine": None,
        "tpu_pipeline_depth": 2,
        "tpu_device_timeout": 0.0,
        "tpu_strict": False,
        "tpu_fault_plan": None,
        "tpu_adaptive_buckets": None,
        "tpu_compile_cache": None,
        "tpu_pallas": None,
        "tpu_dtype": None,
        "tpu_fused": None,
        "tpu_trace": None,
        "tpu_metrics": None,
        "tpu_log_level": None,
        "tpu_jax_profile": None,
        "paths": [],
    }

    def _engine_choice(v: str) -> str:
        if v not in ("session", "fused"):
            print("racon_tpu: --tpu-engine must be 'session' or 'fused'",
                  file=sys.stderr)
            sys.exit(1)
        return v

    def _pallas_choice(v: str) -> str:
        if v not in ("0", "1", "auto"):
            print("racon_tpu: --tpu-pallas must be '0', '1' or 'auto'",
                  file=sys.stderr)
            sys.exit(1)
        return v

    def _dtype_choice(v: str) -> str:
        if v not in ("auto", "int32", "int16"):
            print("racon_tpu: --tpu-dtype must be 'auto', 'int32' or "
                  "'int16'", file=sys.stderr)
            sys.exit(1)
        return v

    def _fused_choice(v: str) -> str:
        if v not in ("0", "1", "auto"):
            print("racon_tpu: --tpu-fused must be '0', '1' or 'auto'",
                  file=sys.stderr)
            sys.exit(1)
        return v

    def _level_choice(v: str) -> str:
        from .utils.logger import LEVEL_NAMES

        if v not in LEVEL_NAMES:
            names = ", ".join(f"'{n}'" for n in LEVEL_NAMES)
            print(f"racon_tpu: --tpu-log-level must be one of {names}",
                  file=sys.stderr)
            sys.exit(1)
        return v

    value_short = {"w": ("window_length", int),
                   "q": ("quality_threshold", float),
                   "e": ("error_threshold", float),
                   "m": ("match", int),
                   "x": ("mismatch", int),
                   "g": ("gap", int),
                   "t": ("num_threads", int)}
    value_long = {"window-length": ("window_length", int),
                  "quality-threshold": ("quality_threshold", float),
                  "error-threshold": ("error_threshold", float),
                  "match": ("match", int),
                  "mismatch": ("mismatch", int),
                  "gap": ("gap", int),
                  "threads": ("num_threads", int),
                  "tpualigner-batches": ("tpu_aligner_batches", int),
                  "tpualigner-band-width": ("tpu_aligner_band_width", int),
                  "tpu-engine": ("tpu_engine", _engine_choice),
                  "tpu-pipeline-depth": ("tpu_pipeline_depth", int),
                  "tpu-device-timeout": ("tpu_device_timeout", float),
                  "tpu-fault-plan": ("tpu_fault_plan", str),
                  "tpu-compile-cache": ("tpu_compile_cache", str),
                  "tpu-pallas": ("tpu_pallas", _pallas_choice),
                  "tpu-dtype": ("tpu_dtype", _dtype_choice),
                  "tpu-fused": ("tpu_fused", _fused_choice),
                  "tpu-trace": ("tpu_trace", str),
                  "tpu-metrics": ("tpu_metrics", str),
                  "tpu-log-level": ("tpu_log_level", _level_choice),
                  "tpu-jax-profile": ("tpu_jax_profile", str)}

    def flag(name: str) -> bool:
        if name in ("u", "include-unpolished"):
            opts["drop_unpolished_sequences"] = False
        elif name in ("f", "fragment-correction"):
            opts["fragment_correction"] = True
        elif name in ("T", "no-trimming"):
            opts["trim"] = False
        elif name in ("b", "tpu-banded-alignment"):
            opts["tpu_banded_alignment"] = True
        elif name == "tpu-strict":
            opts["tpu_strict"] = True
        elif name == "tpu-adaptive-buckets":
            opts["tpu_adaptive_buckets"] = True
        else:
            return False
        return True

    i = 0
    n = len(argv)

    def take_value(display: str) -> str:
        nonlocal i
        i += 1
        if i >= n:
            print(f"racon_tpu: option '{display}' requires an argument",
                  file=sys.stderr)
            sys.exit(1)
        return argv[i]

    while i < n:
        arg = argv[i]
        if arg == "--":
            opts["paths"].extend(argv[i + 1:])
            break
        if arg.startswith("--"):
            name, eq, inline = arg[2:].partition("=")
            if name in ("help",):
                print(HELP, end="")
                return None
            if name == "version":
                print(f"v{__version__}")
                return None
            if flag(name):
                pass
            elif name in value_long:
                key, conv = value_long[name]
                opts[key] = conv(inline if eq else take_value(arg))
            elif name == "tpupoa-batches":
                if eq:
                    opts["tpu_poa_batches"] = int(inline)
                elif i + 1 < n and argv[i + 1].isdigit():
                    i += 1
                    opts["tpu_poa_batches"] = int(argv[i])
                else:
                    opts["tpu_poa_batches"] = 1
            else:
                print(f"racon_tpu: unrecognized option '{arg}'",
                      file=sys.stderr)
                sys.exit(1)
        elif arg.startswith("-") and arg != "-":
            # short option cluster, getopt-style
            j = 1
            while j < len(arg):
                c = arg[j]
                if c == "h":
                    print(HELP, end="")
                    return None
                if c == "v":
                    print(f"v{__version__}")
                    return None
                if flag(c) and c != "b":
                    j += 1
                    continue
                if c == "b":
                    j += 1
                    continue
                if c in value_short:
                    key, conv = value_short[c]
                    rest = arg[j + 1:]
                    opts[key] = conv(rest) if rest else conv(take_value("-" + c))
                    break
                if c == "c":
                    # optional argument: attached, or next non-option argv
                    # (reference src/main.cpp:113-125)
                    rest = arg[j + 1:]
                    if rest:
                        opts["tpu_poa_batches"] = int(rest)
                    elif i + 1 < n and argv[i + 1].isdigit():
                        i += 1
                        opts["tpu_poa_batches"] = int(argv[i])
                    else:
                        opts["tpu_poa_batches"] = 1
                    break
                print(f"racon_tpu: invalid option -- '{c}'", file=sys.stderr)
                sys.exit(1)
        else:
            opts["paths"].append(arg)
        i += 1

    return opts


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    # serve-mode subcommands (README "Serving"): `serve` runs the warm
    # polishing job server, `submit` sends one job to it. Everything
    # else is the classic one-shot surface below, untouched.
    if argv and argv[0] == "serve":
        from .serve.server import serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "submit":
        from .serve.client import submit_main

        return submit_main(argv[1:])
    if argv and argv[0] == "cancel":
        from .serve.client import cancel_main

        return cancel_main(argv[1:])
    if argv and argv[0] == "router":
        from .serve.router import router_main

        return router_main(argv[1:])
    if argv and argv[0] == "fleet":
        from .obs.fleet import fleet_main

        return fleet_main(argv[1:])
    opts = parse_args(argv)
    if opts is None:
        return 0

    if len(opts["paths"]) < 3:
        print("[racon_tpu::] error: missing input file(s)!", file=sys.stderr)
        print(HELP, end="")
        return 1

    from .core.polisher import create_polisher, PolisherType
    from .obs import trace
    from .utils.logger import set_log_level

    import os

    saved_env: dict[str, str | None] = {}
    try:
        # posture flags mirror their env knobs (env-only knobs are
        # invisible in --help): set the env so every layer — pipelines
        # constructed anywhere, strict checks in the ops — sees them
        if opts["tpu_strict"]:
            os.environ["RACON_TPU_STRICT"] = "1"
        # kernel-plane posture: the engines resolve these env knobs at
        # construction, so setting them here threads the CLI choice
        # through every dispatcher (aligner, session, fused)
        if opts["tpu_pallas"] is not None:
            os.environ["RACON_TPU_PALLAS"] = opts["tpu_pallas"]
        if opts["tpu_dtype"] is not None:
            os.environ["RACON_TPU_DTYPE"] = opts["tpu_dtype"]
        if opts["tpu_fused"] is not None:
            os.environ["RACON_TPU_FUSED"] = opts["tpu_fused"]
        if opts["tpu_fault_plan"]:
            from .resilience import FaultPlan

            FaultPlan.parse(opts["tpu_fault_plan"])  # fail fast on typos
            os.environ["RACON_TPU_FAULT_PLAN"] = opts["tpu_fault_plan"]
        # observability knobs follow the same pattern, but restore on
        # exit (saved_env) — unlike the posture flags, a stale armed
        # tracer would make a later flagless in-process main() call
        # record (and overwrite) the earlier run's trace
        for key, env in (("tpu_trace", "RACON_TPU_TRACE"),
                         ("tpu_metrics", "RACON_TPU_METRICS"),
                         ("tpu_log_level", "RACON_TPU_LOG_LEVEL"),
                         ("tpu_jax_profile", "RACON_TPU_PROFILE")):
            if opts[key]:
                saved_env[env] = os.environ.get(env)
                os.environ[env] = opts[key]
        # the level and tracer resolve once per process: force a fresh
        # resolution from the environment just set, so this invocation's
        # flags win over any earlier resolution and every main() call
        # records into its own recorder
        set_log_level(opts["tpu_log_level"] or None)
        trace.reset()
        polisher = create_polisher(
            opts["paths"][0], opts["paths"][1], opts["paths"][2],
            PolisherType.kF if opts["fragment_correction"]
            else PolisherType.kC,
            opts["window_length"], opts["quality_threshold"],
            opts["error_threshold"], opts["trim"], opts["match"],
            opts["mismatch"], opts["gap"], opts["num_threads"],
            opts["tpu_poa_batches"], opts["tpu_banded_alignment"],
            opts["tpu_aligner_batches"], opts["tpu_aligner_band_width"],
            opts["tpu_engine"], opts["tpu_pipeline_depth"],
            opts["tpu_device_timeout"], opts["tpu_adaptive_buckets"],
            opts["tpu_compile_cache"])
        polisher.initialize()
        polished = polisher.polish(opts["drop_unpolished_sequences"])

        out = sys.stdout.buffer
        for seq in polished:
            out.write(b">" + seq.name.encode() + b"\n" + seq.data + b"\n")
        out.flush()
        return 0
    except RaconError as exc:
        print(str(exc), file=sys.stderr)
        return 1
    finally:
        if saved_env:
            for env, old in saved_env.items():
                if old is None:
                    os.environ.pop(env, None)
                else:
                    os.environ[env] = old
            set_log_level(None)
            trace.reset()


if __name__ == "__main__":
    sys.exit(main())
