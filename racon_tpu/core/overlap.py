"""Overlap record between a query (read) and a target (contig).

Behavioral contract (reference src/overlap.cpp):
  - MHAP constructor: 1-based ids -> 0-based (overlap.cpp:15-27); strand is
    a_rc XOR b_rc; file's own error estimate is IGNORED and recomputed;
  - PAF constructor: names kept, strand from '-' orientation (overlap.cpp:29-42);
  - SAM constructor: full CIGAR walk deriving q_begin/q_end/q_length and
    t_end; strand flips query coordinates into the reverse-complement frame
    (overlap.cpp:44-108); 0x4 flag -> invalid record;
  - error() = 1 - min(q_span, t_span) / max(q_span, t_span)  (overlap.cpp:24-26);
  - transmute() maps names / file-local ids to global sequence indices and
    validates lengths against the loaded sequences (overlap.cpp:129-177);
  - find_breaking_points() walks the CIGAR over a `window_length` grid on
    target coordinates, recording per-window (t, q) of the first match and
    one-past the last match (overlap.cpp:226-292). Here the walk is
    vectorized over match segments (no per-base loop).

Overlaps that arrive without a CIGAR (MHAP/PAF) are aligned in batches on
the device by the polisher (ops/align.py) — the TPU-native replacement for
both edlib (CPU) and GenomeWorks cudaaligner (GPU) in the reference.
"""

from __future__ import annotations

import numpy as np

from ..errors import RaconError
from ..utils.cigar import parse_cigar, match_segments


class Overlap:
    __slots__ = (
        "q_name", "q_id", "q_begin", "q_end", "q_length",
        "t_name", "t_id", "t_begin", "t_end", "t_length",
        "strand", "length", "error", "cigar",
        "is_valid", "is_transmuted", "breaking_points",
    )

    def __init__(self):
        self.q_name = ""
        self.q_id = -1
        self.q_begin = 0
        self.q_end = 0
        self.q_length = 0
        self.t_name = ""
        self.t_id = -1
        self.t_begin = 0
        self.t_end = 0
        self.t_length = 0
        self.strand = False
        self.length = 0
        self.error = 0.0
        self.cigar = b""
        self.is_valid = True
        self.is_transmuted = False
        # ndarray [k, 4]: (t_first, q_first, t_last+1, q_last+1) per window hit
        self.breaking_points: np.ndarray | None = None

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_mhap(cls, a_id: int, b_id: int, _error: float, _minmers: int,
                  a_rc: int, a_begin: int, a_end: int, a_length: int,
                  b_rc: int, b_begin: int, b_end: int, b_length: int) -> "Overlap":
        o = cls()
        o.q_id = a_id - 1
        o.q_begin, o.q_end, o.q_length = a_begin, a_end, a_length
        o.t_id = b_id - 1
        o.t_begin, o.t_end, o.t_length = b_begin, b_end, b_length
        o.strand = bool(a_rc ^ b_rc)
        o._compute_error(o.q_end - o.q_begin, o.t_end - o.t_begin)
        return o

    @classmethod
    def from_paf(cls, q_name: str, q_length: int, q_begin: int, q_end: int,
                 orientation: str, t_name: str, t_length: int, t_begin: int,
                 t_end: int, _matches: int, _aln_length: int, _mapq: int) -> "Overlap":
        o = cls()
        o.q_name = q_name
        o.q_begin, o.q_end, o.q_length = q_begin, q_end, q_length
        o.t_name = t_name
        o.t_begin, o.t_end, o.t_length = t_begin, t_end, t_length
        o.strand = orientation == "-"
        o._compute_error(o.q_end - o.q_begin, o.t_end - o.t_begin)
        return o

    @classmethod
    def from_sam(cls, q_name: str, flag: int, t_name: str, pos: int,
                 _mapq: int, cigar: bytes) -> "Overlap":
        o = cls()
        o.q_name = q_name
        o.t_name = t_name
        o.t_begin = pos - 1
        o.strand = bool(flag & 0x10)
        o.is_valid = not (flag & 0x4)
        o.cigar = cigar

        if len(cigar) < 2:
            if o.is_valid:
                raise RaconError("Overlap.from_sam", "missing alignment from SAM object!")
            return o

        ops, lens = parse_cigar(cigar)
        is_m = (ops == ord("M")) | (ops == ord("=")) | (ops == ord("X"))
        is_i = ops == ord("I")
        is_d = (ops == ord("D")) | (ops == ord("N"))
        is_clip = (ops == ord("S")) | (ops == ord("H"))

        q_aln = int(lens[is_m | is_i].sum())
        t_aln = int(lens[is_m | is_d].sum())
        q_clip = int(lens[is_clip].sum())

        # leading clip -> q_begin (reference only honors a clip that is the
        # FIRST op, overlap.cpp:60-69)
        q_begin = int(lens[0]) if len(ops) and is_clip[0] else 0

        o.q_begin = q_begin
        o.q_end = q_begin + q_aln
        o.q_length = q_clip + q_aln
        if o.strand:
            o.q_begin, o.q_end = o.q_length - o.q_end, o.q_length - o.q_begin
        o.t_end = o.t_begin + t_aln
        o.t_length = 0  # filled by transmute from the target sequence
        o._compute_error(q_aln, t_aln)
        return o

    def _compute_error(self, q_span: int, t_span: int) -> None:
        self.length = max(q_span, t_span)
        self.error = 1 - min(q_span, t_span) / float(self.length) if self.length else 0.0

    # -- id resolution ------------------------------------------------------
    def transmute(self, sequences: list, name_to_id: dict, id_to_id: dict) -> None:
        """Resolve q/t to global sequence indices (reference overlap.cpp:129-177).

        Reads are keyed `name + "q"` / `file_index << 1 | 0`; targets
        `name + "t"` / `file_index << 1 | 1`. Unknown names/ids mark the
        overlap invalid; length mismatches are fatal.
        """
        if not self.is_valid or self.is_transmuted:
            return

        if self.q_name:
            qid = name_to_id.get(self.q_name + "q")
            if qid is None:
                self.is_valid = False
                return
            self.q_id = qid
            self.q_name = ""
        else:
            qid = id_to_id.get(self.q_id << 1 | 0)
            if qid is None:
                self.is_valid = False
                return
            self.q_id = qid

        if self.q_length != len(sequences[self.q_id].data):
            raise RaconError(
                "Overlap.transmute",
                "unequal lengths in sequence and overlap file for sequence "
                f"{sequences[self.q_id].name}!",
            )

        if self.t_name:
            tid = name_to_id.get(self.t_name + "t")
            if tid is None:
                self.is_valid = False
                return
            self.t_id = tid
            self.t_name = ""
        else:
            tid = id_to_id.get(self.t_id << 1 | 1)
            if tid is None:
                self.is_valid = False
                return
            self.t_id = tid

        if self.t_length != 0 and self.t_length != len(sequences[self.t_id].data):
            raise RaconError(
                "Overlap.transmute",
                "unequal lengths in target and overlap file for target "
                f"{sequences[self.t_id].name}!",
            )
        # for SAM input the target length comes from the loaded sequence
        self.t_length = len(sequences[self.t_id].data)
        self.is_transmuted = True

    # -- alignment / windows ------------------------------------------------
    def aligned_query_span(self, sequences: list) -> bytes:
        """The query slice that aligns against target[t_begin:t_end] —
        forward or reverse-complement frame depending on strand
        (reference overlap.cpp:192-195)."""
        seq = sequences[self.q_id]
        if self.strand:
            return seq.reverse_complement[self.q_length - self.q_end:
                                          self.q_length - self.q_begin]
        return seq.data[self.q_begin:self.q_end]

    def find_breaking_points(self, sequences: list, window_length: int) -> None:
        """Compute per-window breaking points; requires a CIGAR (either from
        SAM input or set by the batched device aligner)."""
        if not self.is_transmuted:
            raise RaconError("Overlap.find_breaking_points", "overlap is not transmuted!")
        if self.breaking_points is not None:
            return
        if not self.cigar:
            raise RaconError(
                "Overlap.find_breaking_points",
                "no CIGAR available — overlap must be aligned first!",
            )
        self.breaking_points = self._breaking_points_from_cigar(window_length)
        self.cigar = b""

    def _breaking_points_from_cigar(self, window_length: int) -> np.ndarray:
        """Vectorized equivalent of the per-base CIGAR walk of reference
        overlap.cpp:226-292.

        Window w covers target positions (ends[w-1], ends[w]] where ends are
        `k*window_length - 1` grid points inside (t_begin, t_end) plus
        t_end - 1. For every window containing at least one match column the
        reference records (first_match_t, first_match_q) and
        (last_match_t + 1, last_match_q + 1).
        """
        ops, lens = parse_cigar(self.cigar)
        q_start = (self.q_length - self.q_end) if self.strand else self.q_begin
        t0, q0, seg_len, _t_end, _q_end = match_segments(ops, lens, self.t_begin, q_start)

        if len(t0) == 0:
            return np.empty((0, 4), dtype=np.int64)

        # window end grid (reference overlap.cpp:229-235)
        first_grid = (self.t_begin // window_length + 1) * window_length
        grid = np.arange(first_grid, self.t_end, window_length, dtype=np.int64)
        ends = np.concatenate([grid - 1, [self.t_end - 1]])

        lo = np.concatenate([[np.iinfo(np.int64).min + 1], ends[:-1] + 1])  # window start
        hi = ends                                                            # window end

        seg_last = t0 + seg_len - 1
        # first segment whose last match >= window start
        i = np.searchsorted(seg_last, lo, side="left")
        # last segment whose first match <= window end
        j = np.searchsorted(t0, hi, side="right") - 1

        valid = (i < len(t0)) & (j >= 0) & (i <= j)
        i = np.clip(i, 0, len(t0) - 1)
        j = np.clip(j, 0, len(t0) - 1)

        first_t = np.maximum(t0[i], lo)
        last_t = np.minimum(seg_last[j], hi)
        valid &= (first_t <= hi) & (last_t >= lo)

        first_q = q0[i] + (first_t - t0[i])
        last_q = q0[j] + (last_t - t0[j])

        out = np.stack([first_t, first_q, last_t + 1, last_q + 1], axis=1)
        return out[valid]

    def __repr__(self) -> str:  # pragma: no cover
        return (f"Overlap(q={self.q_name or self.q_id}:{self.q_begin}-{self.q_end}, "
                f"t={self.t_name or self.t_id}:{self.t_begin}-{self.t_end}, "
                f"strand={'-' if self.strand else '+'}, err={self.error:.3f})")
