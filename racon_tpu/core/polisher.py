"""Polisher: whole-pipeline orchestration.

parse -> filter -> align (device) -> window -> POA consensus (device) -> stitch.

Mirrors the reference pipeline semantics (src/polisher.cpp:192-548) while
replacing both compute hot spots with batched XLA programs:

  - overlap CIGARs: ops/align.BatchAligner  (vs edlib / cudaaligner)
  - window consensus: ops/poa.BatchPOA      (vs spoa / cudapoa)

The reference's CPU/GPU split (Polisher vs CUDAPolisher,
src/cuda/cudapolisher.cpp) becomes a single Polisher whose device batches run
wherever JAX is pointed (TPU chip(s) or CPU), optionally sharded over a mesh
(parallel/mesh.py) — the TPU-native equivalent of its multi-GPU batch loop.
"""

from __future__ import annotations

import enum
import os
import time

import numpy as np

from ..errors import DeviceError, RaconError, as_device_error
from ..obs import jax_profile, trace
from ..obs.metrics import MetricsRegistry
from ..resilience import REPORT_KEYS, degradation_summary, strict_mode
from ..io.parsers import create_sequence_parser, create_overlap_parser
from ..utils.logger import (Logger, flush_dedup, log_info, log_level,
                            reset_dedup, DEBUG)
from ..utils.cigar import cigar_from_ops
from .sequence import Sequence, create_sequence
from .window import Window, WindowType, create_window

KCHUNK_SIZE = 1024 * 1024 * 1024  # reference polisher.cpp:26


class PolisherType(enum.Enum):
    kC = 0  # contig polishing
    kF = 1  # fragment (read) error correction


def create_polisher(sequences_path: str, overlaps_path: str, target_path: str,
                    type_: PolisherType, window_length: int,
                    quality_threshold: float, error_threshold: float,
                    trim: bool = True, match: int = 3, mismatch: int = -5,
                    gap: int = -4, num_threads: int = 1,
                    tpu_poa_batches: int = 0, tpu_banded_alignment: bool = True,
                    tpu_aligner_batches: int = 0,
                    tpu_aligner_band_width: int = 0,
                    tpu_engine: str | None = None,
                    tpu_pipeline_depth: int = 2,
                    tpu_device_timeout: float = 0.0,
                    tpu_adaptive_buckets: bool | None = None,
                    tpu_compile_cache: str | None = None,
                    tpu_fault_plan: str | None = None) -> "Polisher":
    """Factory mirroring reference createPolisher (polisher.cpp:55-160).

    The tpu_* knobs parallel the reference's CUDA flags (main.cpp:36-41); the
    device path is always available, so they tune batching rather than select
    a different subclass. `tpu_pipeline_depth` sizes the async dispatch
    pipeline (pipeline.DispatchPipeline) both hot phases run through;
    0 disables the overlap entirely (the synchronous path, for bisection).
    `tpu_device_timeout` (seconds, 0 = off) arms the resilience watchdog:
    device-stage calls run under that deadline with bounded retry +
    backoff before a chunk routes to host fallback.
    `tpu_adaptive_buckets` arms the occupancy-aware batch scheduler
    (racon_tpu/sched/): every device engine derives its shape ladder from
    the run's job-shape histogram and packs shape-sorted chunks (output
    stays byte-identical; None defers to RACON_TPU_ADAPTIVE_BUCKETS).
    `tpu_compile_cache` points jax's persistent compilation cache at a
    directory so repeated runs — including adaptive ones with
    data-derived shapes — skip recompiles (None defers to
    RACON_TPU_COMPILE_CACHE).
    `tpu_fault_plan` arms a fault-injection plan for THIS polisher only
    (the serve layer's per-job isolation; None defers to the process-wide
    RACON_TPU_FAULT_PLAN posture).
    """
    if not isinstance(type_, PolisherType):
        raise RaconError("createPolisher", "invalid polisher type!")
    if window_length == 0:
        raise RaconError("createPolisher", "invalid window length!")

    sparser = create_sequence_parser(sequences_path, "createPolisher")
    oparser = create_overlap_parser(overlaps_path, "createPolisher")
    tparser = create_sequence_parser(target_path, "createPolisher")

    return Polisher(sparser, oparser, tparser, type_, window_length,
                    quality_threshold, error_threshold, trim, match, mismatch,
                    gap, num_threads, tpu_poa_batches, tpu_banded_alignment,
                    tpu_aligner_batches, tpu_aligner_band_width, tpu_engine,
                    tpu_pipeline_depth, tpu_device_timeout,
                    tpu_adaptive_buckets, tpu_compile_cache, tpu_fault_plan)


class Polisher:
    def __init__(self, sparser, oparser, tparser, type_: PolisherType,
                 window_length: int, quality_threshold: float,
                 error_threshold: float, trim: bool, match: int, mismatch: int,
                 gap: int, num_threads: int = 1, tpu_poa_batches: int = 0,
                 tpu_banded_alignment: bool = True, tpu_aligner_batches: int = 0,
                 tpu_aligner_band_width: int = 0,
                 tpu_engine: str | None = None,
                 tpu_pipeline_depth: int = 2,
                 tpu_device_timeout: float = 0.0,
                 tpu_adaptive_buckets: bool | None = None,
                 tpu_compile_cache: str | None = None,
                 tpu_fault_plan: str | None = None):
        self.sparser = sparser
        self.oparser = oparser
        self.tparser = tparser
        self.type = type_
        self.window_length = window_length
        self.quality_threshold = quality_threshold
        self.error_threshold = error_threshold
        self.trim = trim
        self.match = match
        self.mismatch = mismatch
        self.gap = gap
        self.num_threads = num_threads
        self.tpu_poa_batches = tpu_poa_batches
        self.tpu_banded_alignment = tpu_banded_alignment
        self.tpu_aligner_batches = tpu_aligner_batches
        self.tpu_aligner_band_width = tpu_aligner_band_width
        self.tpu_engine = tpu_engine
        self.tpu_pipeline_depth = max(0, tpu_pipeline_depth)
        self.tpu_device_timeout = max(0.0, tpu_device_timeout)
        # per-polisher fault plan (serve mode: each job's injected faults
        # stay its own); None defers every pipeline to the process-wide
        # RACON_TPU_FAULT_PLAN posture, the one-shot CLI behavior
        from ..resilience import FaultPlan

        self.faults = (FaultPlan.parse(tpu_fault_plan)
                       if tpu_fault_plan else None)
        # per-stage wall-clock counters shared by both hot phases' dispatch
        # pipelines (pack / device / unpack / fallback seconds, launch and
        # chunk counts) — the observability half of the overlap design;
        # bench.py emits the snapshot in its JSON artifact
        from ..obs.hist import HistogramSet
        from ..pipeline import PipelineStats

        # per-run latency histograms (obs/hist.py): per-chunk pipeline
        # stage durations, per-engine compile stalls and the polisher
        # phase durations, snapshotted as the metrics registry's
        # `latency` namespace — the serve layer folds each job's set
        # into its lifetime scrape view
        self.hists = HistogramSet()
        self.pipeline_stats = PipelineStats(hists=self.hists)
        # the occupancy-aware batch scheduler (racon_tpu/sched/), shared
        # by the aligner and whichever consensus engine runs: adaptive
        # ladders + sorted packing when armed (CLI flag winning over
        # RACON_TPU_ADAPTIVE_BUCKETS), per-bucket occupancy telemetry
        # always; the compile-cache knob composes so adaptive shapes
        # survive process restarts
        from ..sched import BatchScheduler

        self.scheduler = BatchScheduler.from_env(
            adaptive=tpu_adaptive_buckets,
            compile_cache=tpu_compile_cache)
        self.scheduler.stats.hists = self.hists

        self.sequences: list[Sequence] = []
        self.windows: list[Window] = []
        self.targets_coverages: list[int] = []
        # window-range shard slice (serve/router.py sub-contig sharding):
        # (lo, hi) target coordinates — initialize() keeps only windows
        # whose grid start j satisfies lo <= j < hi (boundary windows
        # owned by exactly one shard since starts are exact), and
        # _stitch_contig emits bare-named SEGMENTS with their stitch
        # accounting in `segment_meta` instead of tagged contigs. None
        # (the default) is the classic whole-target run, byte-identical
        # to the pre-range code path.
        self.window_range: tuple[int, int] | None = None
        # fragment read-range shard slice (serve/router.py fragment
        # fan-out): (lo, hi) TARGET-INDEX bounds — initialize() keeps
        # only targets whose index in the target file falls in [lo, hi).
        # Overlaps onto dropped targets resolve to no target and are
        # skipped (Overlap.transmute marks them invalid), so a shard
        # corrects exactly its read slice. None (the default) is the
        # classic whole-set run. Orthogonal to window_range, which
        # slices one target's COORDINATE axis.
        self.target_range: tuple[int, int] | None = None
        #: per-contig segment accounting for range-shard runs —
        #: {name: {polished, windows, total_windows, coverage, lo, hi}};
        #: the router re-derives the solo LN/RC/XC tags from these when
        #: it stitches sibling segments back together
        self.segment_meta: dict[str, dict] = {}
        #: per-target rank of the first KEPT window (all zeros outside
        #: range mode) — the layer loop's window-id remap offset
        self._range_first_rank: list[int] = []
        self.dummy_quality = b"!" * window_length
        self.logger = Logger()
        # live progress hook (serve mode: the server forwards these as
        # interleaved progress frames; see README "End-to-end tracing &
        # progress"): callable(event_dict) or None — the zero-overhead
        # default. Events carry phase / done / total; emission is
        # best-effort and monotonic per phase (emit_progress).
        self.progress_hook = None
        # device-mesh pin (serve worker lanes): a parallel.mesh
        # BatchRunner the consensus engines dispatch through instead of
        # the full auto-discovered mesh. The serve batcher sets it so an
        # ISOLATION job (own fault plan / strict) runs solo on ONE
        # lane's sub-mesh while the other lanes keep serving; None (the
        # one-shot default) lets every engine build its own full-mesh
        # runner.
        self.device_runner = None
        self._progress_phase: str | None = None
        self._progress_hwm: tuple[str, int, int] = ("", 0, 0)
        import threading as _threading

        # built eagerly: a lazy check-then-set would race the first two
        # concurrent bar ticks (pipeline unpack worker vs fallback
        # pool) into two different locks, defeating the monotone HWM
        self._progress_lock = _threading.Lock()
        self._num_targets = 0
        #: completed initialize()+polish() cycles — a reused (warm)
        #: polisher resets its per-run counters at the next initialize()
        #: so every run's stats describe that run alone
        self._runs_completed = 0
        # alignment-phase accounting (reference cudapolisher.cpp:204-206)
        self.n_aligner_pairs = 0
        self.n_aligner_device = 0
        self.n_aligner_host_fallback = 0
        # the unified metrics registry (obs/metrics.py): the pipeline
        # stage counters, the resilience degradation counters, the
        # scheduler's occupancy telemetry and the aligner accounting, one
        # namespaced snapshot — bench JSON "metrics" field, the
        # --tpu-metrics dump, and the end-of-run stderr table
        # resolve the env-armed tracer NOW so its time base predates
        # every phase span (a lazy first-hook resolution mid-initialize
        # would start the clock after t_init and clamp the ts to 0)
        trace.get_tracer()
        self.metrics = MetricsRegistry()
        self.metrics.register(
            "pipeline", lambda: {k: v
                                 for k, v in self.stage_stats.items()
                                 if k not in REPORT_KEYS})
        self.metrics.register(
            "resilience", lambda: {k: self.stage_stats.get(k, 0)
                                   for k in REPORT_KEYS})
        # late-bound lambda, not the bound method: a warm-reused polisher
        # swaps in a fresh OccupancyStats per run and the registry must
        # follow it
        self.metrics.register("sched",
                              lambda: self.scheduler.stats.snapshot())
        self.metrics.register("latency", lambda: self.hists.snapshot())
        self.metrics.register(
            "aligner", lambda: {
                "pairs": self.n_aligner_pairs,
                "device_pairs": self.n_aligner_device,
                "host_fallbacks": self.n_aligner_host_fallback,
                "band_width": self.tpu_aligner_band_width})

    def _make_pipeline(self):
        """One DispatchPipeline per hot phase, all feeding the shared
        stage counters. depth 0 = the synchronous path (bisection).
        The resilience posture rides along: the device watchdog (CLI
        --tpu-device-timeout winning over the env knobs) and the armed
        fault plan, both usually None — the zero-overhead clean path."""
        from ..pipeline import DispatchPipeline
        from ..resilience import Watchdog, get_fault_plan

        return DispatchPipeline(depth=self.tpu_pipeline_depth,
                                stats=self.pipeline_stats,
                                fallback_workers=max(
                                    1, min(4, self.num_threads)),
                                watchdog=Watchdog.from_env(
                                    timeout=self.tpu_device_timeout
                                    or None),
                                faults=(self.faults
                                        if self.faults is not None
                                        else get_fault_plan()))

    @property
    def stage_stats(self) -> dict:
        """Snapshot of the per-stage pipeline counters (both phases)."""
        return self.pipeline_stats.snapshot()

    @property
    def occupancy_stats(self) -> dict:
        """Snapshot of the scheduler's per-bucket occupancy counters
        (jobs / batches / lanes / useful vs padded cells / occupancy %
        per engine, plus compile count and seconds) — bench.py publishes
        this next to `stages` in its JSON artifact."""
        return self.scheduler.stats.snapshot()

    # ------------------------------------------------------- progress
    def emit_progress(self, done, total, phase: str | None = None,
                      **extra) -> None:
        """Push one live-progress event at the armed hook. Contract the
        serve layer's progress frames inherit: per phase, `done` and
        `total` are monotonically non-decreasing (a fallback engine
        re-arming a smaller bar inside the same phase cannot make the
        client's bar run backwards), and emission NEVER raises — live
        progress is decoration on a run, not a dependency of it."""
        hook = self.progress_hook
        if hook is None:
            return
        ph = phase or self._progress_phase or "run"
        # the hook is invoked INSIDE the lock: two concurrent bar ticks
        # that computed done=5 and done=6 under the lock could
        # otherwise deliver 6 then 5 and run the client's bar
        # backwards; hooks only enqueue (Job.notify_progress pushes onto
        # its DeliveryQueue), so holding the lock across them is safe and cheap
        with self._progress_lock:
            hwm_phase, hwm_done, hwm_total = self._progress_hwm
            if ph != hwm_phase:
                hwm_done = hwm_total = 0
            d = max(int(done), hwm_done)
            t = max(int(total), hwm_total)
            self._progress_hwm = (ph, d, t)
            ev = {"phase": ph, "done": min(d, t), "total": t}
            ev.update(extra)
            try:
                hook(ev)
            except Exception:  # noqa: BLE001 — see docstring
                pass

    def _progress_tick(self, count: int, total: int) -> None:
        """Logger.on_bar adapter: bar bin transitions become progress
        events attributed to the phase currently running."""
        self.emit_progress(min(count, total), total)

    def _arm_progress(self) -> None:
        """Wire the (per-run) logger's bar ticks into the progress hook;
        called at phase starts because _reset_run_state swaps loggers."""
        if self.progress_hook is not None:
            self.logger.on_bar = self._progress_tick

    # ------------------------------------------------------- warm reuse
    def _reset_run_state(self) -> None:
        """Fresh per-run counters for a warm-reused polisher: a second
        initialize()+polish() cycle must report ITS OWN stage seconds,
        occupancy, degradation and aligner counts — not a running total
        across jobs — and its FASTA must be byte-identical to a fresh-
        process run (tests/test_serve.py pins both). Engines, jit caches
        and the compile-cache posture are process-level and deliberately
        stay warm."""
        from ..obs.hist import HistogramSet
        from ..pipeline import PipelineStats
        from ..sched import OccupancyStats

        self.hists = HistogramSet()
        self.pipeline_stats = PipelineStats(hists=self.hists)
        self.scheduler.stats = OccupancyStats()
        self.scheduler.stats.hists = self.hists
        self.n_aligner_pairs = 0
        self.n_aligner_device = 0
        self.n_aligner_host_fallback = 0
        self.logger = Logger()
        self.targets_coverages = []
        self.segment_meta = {}
        self._range_first_rank = []
        self._num_targets = 0
        self._progress_phase = None
        self._progress_hwm = ("", 0, 0)

    def rebind(self, sequences_path: str, overlaps_path: str,
               target_path: str) -> "Polisher":
        """Warm-reuse entry point: point this polisher at a new input
        triple (parsers rebuilt, per-run state reset) while keeping the
        warm process-level state — jit caches, adaptive posture, compile
        cache, metrics registry. The serve layer uses this shape of
        reuse; the next initialize() parses the new inputs."""
        if self.windows:
            raise RaconError("Polisher.rebind",
                             "cannot rebind mid-run (windows pending)!")
        self.sparser = create_sequence_parser(sequences_path,
                                              "Polisher.rebind")
        self.oparser = create_overlap_parser(overlaps_path,
                                             "Polisher.rebind")
        self.tparser = create_sequence_parser(target_path,
                                              "Polisher.rebind")
        self._reset_run_state()
        return self

    def redraft(self, polished, workdir: str,
                tag: str = "round") -> tuple[str, str]:
        """Warm re-draft for serve-native polishing rounds: take round
        k's stitched contigs, write them as round k+1's draft, re-map
        the ORIGINAL reads against them in-process (core/remap.py — no
        external mapper, no process exit), and rebind this polisher to
        the new triple. The next initialize()+polish() cycle IS round
        k+1, on the same warm engines/jit caches/autotune posture.

        Both the serve rounds loop and the chained-solo test path call
        this one entry, so `rounds=N` output is byte-identical to N
        chained runs by construction (tests/test_rounds.py pins it).
        Returns the (draft_fasta, overlaps_paf) paths written under
        `workdir`. The reads are re-parsed from the ORIGINAL reads path
        (the polisher streams reads and never holds them whole — one
        extra parse per round is the cost of the bounded-memory
        contract)."""
        import os as _os

        from .remap import remap_overlaps, write_fasta, write_paf

        if not polished:
            raise RaconError("Polisher.redraft",
                             "no polished sequences to re-draft from!")
        reads_path = self.sparser.path
        fasta_path = write_fasta(
            polished, _os.path.join(workdir, f"{tag}_draft.fasta"))
        reads: list[Sequence] = []
        rparser = create_sequence_parser(reads_path, "Polisher.redraft")
        rparser.reset()
        rparser.parse(reads, -1)
        rows = remap_overlaps(reads, polished)
        if not rows:
            raise RaconError("Polisher.redraft",
                             "no reads re-mapped onto the new draft!")
        paf_path = write_paf(
            rows, _os.path.join(workdir, f"{tag}_ovl.paf"))
        self.rebind(reads_path, paf_path, fasta_path)
        return fasta_path, paf_path

    # ------------------------------------------------------------------ init
    def initialize(self) -> None:
        if self.windows:
            log_info("[racon_tpu::Polisher.initialize] warning: "
                     "object already initialized!")
            return
        if self._runs_completed:
            # warm reuse: this is run N+1 in the same process — counters
            # describe one run each (see _reset_run_state)
            self._reset_run_state()

        # a new run starts with clean dedup state: a previous in-process
        # run that crashed before its flush must not leave keys behind
        # that would silently swallow this run's first warnings
        reset_dedup()
        self._arm_progress()
        t_init = time.perf_counter()
        log = self.logger
        log.log()

        # -- targets (loaded whole; reference polisher.cpp:202-217)
        self.tparser.reset()
        self.tparser.parse(self.sequences, -1)
        target_base = 0
        if self.target_range is not None:
            # fragment read-range shard: keep only the targets whose
            # FILE index falls in [lo, hi). The id_to_id keys below use
            # the original file index, so id-keyed overlap formats
            # (MHAP) resolve identically to name-keyed ones; overlaps
            # onto dropped targets simply fail to resolve and are
            # skipped as invalid.
            lo, hi = self.target_range
            total = len(self.sequences)
            lo, hi = max(0, int(lo)), min(int(hi), total)
            if hi <= lo:
                raise RaconError(
                    "Polisher.initialize",
                    f"target_range [{self.target_range[0]}, "
                    f"{self.target_range[1]}) selects no targets out of "
                    f"{total}!")
            del self.sequences[hi:]
            del self.sequences[:lo]
            target_base = lo
        targets_size = len(self.sequences)
        self._num_targets = targets_size
        if targets_size == 0:
            raise RaconError("Polisher.initialize", "empty target sequences set!")

        name_to_id: dict[str, int] = {}
        id_to_id: dict[int, int] = {}
        for i in range(targets_size):
            name_to_id[self.sequences[i].name + "t"] = i
            id_to_id[(target_base + i) << 1 | 1] = i

        has_name = [True] * targets_size
        has_data = [True] * targets_size
        has_reverse_data = [False] * targets_size

        log.log("[racon_tpu::Polisher.initialize] loaded target sequences")
        log.log()

        # -- reads streamed in chunks; duplicates of targets share storage
        #    (reference polisher.cpp:228-264)
        sequences_size = 0
        total_sequences_length = 0
        self.sparser.reset()
        more = True
        while more:
            start = len(self.sequences)
            more = self.sparser.parse(self.sequences, KCHUNK_SIZE)
            kept: list[Sequence] = []
            for seq in self.sequences[start:]:
                total_sequences_length += len(seq.data)
                tgt = name_to_id.get(seq.name + "t")
                if tgt is not None:
                    dup = self.sequences[tgt]
                    if len(seq.data) != len(dup.data) or \
                       len(seq.quality) != len(dup.quality):
                        raise RaconError(
                            "Polisher.initialize",
                            f"duplicate sequence {seq.name} with unequal data")
                    name_to_id[seq.name + "q"] = tgt
                    id_to_id[sequences_size << 1 | 0] = tgt
                else:
                    gid = start + len(kept)
                    name_to_id[seq.name + "q"] = gid
                    id_to_id[sequences_size << 1 | 0] = gid
                    kept.append(seq)
                sequences_size += 1
            del self.sequences[start:]
            self.sequences.extend(kept)

        if sequences_size == 0:
            raise RaconError("Polisher.initialize", "empty sequences set!")

        n_seqs = len(self.sequences)
        has_name += [False] * (n_seqs - targets_size)
        has_data += [False] * (n_seqs - targets_size)
        has_reverse_data += [False] * (n_seqs - targets_size)

        window_type = (WindowType.kNGS
                       if total_sequences_length / sequences_size <= 1000
                       else WindowType.kTGS)

        log.log("[racon_tpu::Polisher.initialize] loaded sequences")
        log.log()

        # -- overlaps streamed; per-query filtering (polisher.cpp:284-355)
        overlaps = self._load_overlaps(name_to_id, id_to_id,
                                       has_data, has_reverse_data)
        if not overlaps and self.target_range is None:
            # a fragment read-range shard may legitimately hold only
            # targets without overlaps (they come back unpolished, and
            # drop the same way a solo run drops them) — the whole-set
            # run keeps the reference's hard error
            raise RaconError("Polisher.initialize", "empty overlap set!")

        log.log("[racon_tpu::Polisher.initialize] loaded overlaps")
        log.log()

        # -- free unneeded storage; build revcomps where needed
        for i, seq in enumerate(self.sequences):
            seq.transmute(has_name[i], has_data[i], has_reverse_data[i])

        self._progress_phase = "align"
        with trace.span("polisher.align_overlaps"):
            self.find_overlap_breaking_points(overlaps)

        log.log()

        # -- windows (polisher.cpp:384-399); in range mode only the grid
        #    positions with lo <= start < hi materialize, but `rank`
        #    stays the GLOBAL grid rank so per-window identity (and
        #    output) is independent of which slice holds the window
        rng = self.window_range
        id_to_first_window_id = [0] * (targets_size + 1)
        self._range_first_rank = [0] * targets_size
        for i in range(targets_size):
            data = self.sequences[i].data
            quality = self.sequences[i].quality
            k = 0
            kept = 0
            for j in range(0, len(data), self.window_length):
                if rng is None or rng[0] <= j < rng[1]:
                    length = min(j + self.window_length, len(data)) - j
                    q = quality[j:j + length] if quality \
                        else self.dummy_quality[:length]
                    self.windows.append(create_window(
                        i, k, window_type, data[j:j + length], q))
                    if kept == 0:
                        self._range_first_rank[i] = k
                    kept += 1
                k += 1
            id_to_first_window_id[i + 1] = id_to_first_window_id[i] + kept

        self.targets_coverages = [0] * targets_size

        # -- layer assignment (polisher.cpp:403-457)
        wl = self.window_length
        for o in overlaps:
            self.targets_coverages[o.t_id] += 1
            seq = self.sequences[o.q_id]
            bps = o.breaking_points
            if bps is None:
                continue
            qual_fwd = seq.quality
            has_qual = bool(qual_fwd) or bool(seq._reverse_quality)
            if o.strand:
                data_src = seq.reverse_complement
                qual_src = seq.reverse_quality if has_qual else None
            else:
                data_src = seq.data
                qual_src = qual_fwd if has_qual else None
            qual_arr = (np.frombuffer(qual_src, dtype=np.uint8)
                        if qual_src else None)

            for t_first, q_first, t_last1, q_last1 in bps:
                if q_last1 - q_first < 0.02 * wl:
                    continue
                if qual_arr is not None:
                    avg = float(qual_arr[q_first:q_last1].mean()) - 33.0
                    if avg < self.quality_threshold:
                        continue
                window_start = (t_first // wl) * wl
                if rng is not None and \
                        not rng[0] <= window_start < rng[1]:
                    continue
                window_id = (id_to_first_window_id[o.t_id]
                             + t_first // wl
                             - self._range_first_rank[o.t_id])
                data = data_src[q_first:q_last1]
                qual = (qual_src[q_first:q_last1] if qual_src else None)
                self.windows[window_id].add_layer(
                    data, qual, int(t_first - window_start),
                    int(t_last1 - window_start - 1))
            o.breaking_points = None

        log.log("[racon_tpu::Polisher.initialize] transformed data into windows")
        # announce the window total as consensus progress zero: the
        # client's bar knows its denominator before the first round
        self.emit_progress(0, len(self.windows), phase="consensus")
        self.hists.observe("phase.initialize",
                           time.perf_counter() - t_init)
        tr = trace.get_tracer()
        if tr is not None:
            tr.complete("polisher.initialize", t_init, time.perf_counter(),
                        {"windows": len(self.windows),
                         "targets": self._num_targets})
        # per-phase flush: initialize-only flows (bench's aligner phase)
        # must still report suppressed duplicate-warning counts; a repeat
        # spanning both phases then reports once per phase
        flush_dedup()

    def _load_overlaps(self, name_to_id, id_to_id, has_data, has_reverse_data):
        overlaps: list = []
        error_threshold = self.error_threshold
        is_kc = self.type == PolisherType.kC

        def filter_group(group: list) -> list:
            """Drop high-error/self overlaps; for contig polishing keep only
            the longest overlap per query. Replicates the reference's exact
            pass structure (polisher.cpp:284-308): the error check runs when
            the outer scan reaches an overlap, so a high-error overlap can
            still knock out a longer-or-equal earlier one before being
            removed itself, and length ties keep the LATER overlap."""
            arr: list = list(group)
            for i in range(len(arr)):
                o = arr[i]
                if o is None:
                    continue
                if o.error > error_threshold or o.q_id == o.t_id:
                    arr[i] = None
                    continue
                if is_kc:
                    for j in range(i + 1, len(arr)):
                        if arr[j] is None:
                            continue
                        if o.length > arr[j].length:
                            arr[j] = None
                        else:
                            arr[i] = None
                            break
            return [o for o in arr if o is not None]

        self.oparser.reset()
        pending: list = []   # current same-q_id run
        more = True
        while more:
            chunk: list = []
            more = self.oparser.parse(chunk, KCHUNK_SIZE)
            for o in chunk:
                o.transmute(self.sequences, name_to_id, id_to_id)
                if not o.is_valid:
                    continue
                if pending and pending[0].q_id != o.q_id:
                    for f in filter_group(pending):
                        overlaps.append(f)
                        if f.strand:
                            has_reverse_data[f.q_id] = True
                        else:
                            has_data[f.q_id] = True
                    pending = []
                pending.append(o)
        for f in filter_group(pending):
            overlaps.append(f)
            if f.strand:
                has_reverse_data[f.q_id] = True
            else:
                has_data[f.q_id] = True
        return overlaps

    # ------------------------------------------------------- alignment phase
    def find_overlap_breaking_points(self, overlaps: list) -> None:
        """Align CIGAR-less overlaps, then walk all CIGARs into per-window
        breaking points (reference polisher.cpp:462-484 /
        cudapolisher.cpp:74-214).

        Default path is the host exact aligner (the edlib role). With
        tpu_aligner_batches > 0 the batched device kernel handles everything
        it can and the host aligns the rejects — the reference's GPU->CPU
        fallback (cudapolisher.cpp:203-213): no overlap is ever dropped.
        """
        from ..native import nw_cigar_batch

        need = [o for o in overlaps
                if not o.cigar and o.is_valid and self._range_keeps(o)]
        if need:
            pairs = []
            for o in need:
                q_span = o.aligned_query_span(self.sequences)
                t_span = self.sequences[o.t_id].data[o.t_begin:o.t_end]
                pairs.append((q_span, t_span))

            self.logger.bar_total(len(pairs))
            bar_msg = "[racon_tpu::Polisher.initialize] aligning overlaps"

            def bar_n(n):
                for _ in range(n):
                    self.logger.bar(bar_msg)

            runs = [None] * len(pairs)
            self.n_aligner_pairs = len(pairs)
            handled: set[int] = set()
            if self.tpu_aligner_batches > 0:
                from ..ops.align import BatchAligner
                aligner = BatchAligner(band_width=self.tpu_aligner_band_width,
                                       scheduler=self.scheduler,
                                       runner=self.device_runner)
                pipeline = self._make_pipeline()
                fb: list[tuple[list[int], object]] = []
                # concurrent fallback jobs split the thread budget so the
                # pool never oversubscribes the host beyond num_threads;
                # at depth 0 jobs run inline (serial) and keep the full
                # budget — the synchronous bisection path must not be
                # slower than the pre-pipeline code
                fb_threads = (self.num_threads if pipeline.depth == 0
                              else max(1, self.num_threads
                                       // pipeline.fallback_workers))

                def on_reject(idxs):
                    # rejected pairs (too long for any bucket, or band-
                    # clipped) start host-aligning the moment they are
                    # known — the reference's GPU->CPU fallback
                    # (cudapolisher.cpp:203-213), overlapped with the
                    # device pass instead of serialized after it
                    fb.extend(pipeline.map_fallback(
                        idxs,
                        lambda sub: nw_cigar_batch(
                            [pairs[i] for i in sub], n_threads=fb_threads,
                            progress=bar_n),
                        chunk=512))

                def degrade(exc: DeviceError):
                    # the cudautils-style device error check with graceful
                    # degradation instead of exit (cudautils.hpp:10-18).
                    # Before the host re-align pass restarts, the fallback
                    # pool must be emptied — cancel the queued jobs and
                    # drain the running ones — or orphaned fallback
                    # threads would keep aligning (and bumping the
                    # just-restarted progress bar) underneath it
                    cancelled, drained = pipeline.cancel_fallback()
                    log_info("[racon_tpu::Polisher.initialize] warning: "
                             f"device alignment failed ({exc}); falling "
                             f"back to host aligner ({cancelled} fallback "
                             f"jobs cancelled, {drained} drained)")
                    self.logger.bar_total(len(pairs))  # restart progress
                    return [None] * len(pairs), set()

                try:
                    # optional deep-dive: --tpu-jax-profile brackets the
                    # device alignment pass with a jax.profiler capture
                    with jax_profile("align"):
                        runs = aligner.align(pairs, progress=bar_n,
                                             pipeline=pipeline,
                                             on_reject=on_reject)
                        pipeline.drain_fallback()
                    for sub, fut in fb:
                        for i, c in zip(sub, fut.result()):
                            need[i].cigar = c
                        handled.update(sub)
                except DeviceError as exc:
                    if strict_mode():
                        raise
                    runs, handled = degrade(exc)
                except RaconError:
                    raise  # user-facing input error: never degraded away
                except Exception as exc:  # device init/OOM: host completes
                    if strict_mode():
                        raise
                    runs, handled = degrade(as_device_error(
                        exc, "Polisher.initialize"))
                finally:
                    pipeline.close()

            # host exact aligner for everything the device didn't take and
            # the fallback pool didn't already finish
            rest = [i for i, r in enumerate(runs)
                    if r is None and i not in handled]
            if rest:
                cigars = nw_cigar_batch([pairs[i] for i in rest],
                                        n_threads=self.num_threads,
                                        progress=bar_n)
                for i, c in zip(rest, cigars):
                    need[i].cigar = c
            for o, r in zip(need, runs):
                if r is not None:
                    o.cigar = cigar_from_ops(r).encode()
            # skip accounting mirrors the reference's "Aligned overlaps ...
            # on GPU" line (cudapolisher.cpp:204-206); exposed as counters
            # so the bench can put them in its JSON artifact
            self.n_aligner_host_fallback = len(rest) + len(handled)
            self.n_aligner_device = len(pairs) - self.n_aligner_host_fallback
            if self.tpu_aligner_batches > 0 and self.n_aligner_host_fallback:
                log_info(f"[racon_tpu::Polisher.initialize] "
                         f"{self.n_aligner_host_fallback} overlaps "
                         "aligned on host (device capacity fallback)")

        for o in overlaps:
            if o.is_valid and o.cigar and self._range_keeps(o):
                o.find_breaking_points(self.sequences, self.window_length)

        self.logger.log("[racon_tpu::Polisher.initialize] aligned overlaps")

    def _range_keeps(self, o) -> bool:
        """Whether an overlap can contribute layers to this run's kept
        window slice (always True outside range mode — the classic path
        pays one attribute check). Coverage (RC) is counted for EVERY
        overlap regardless: the layer loop increments it before
        consulting breaking points, so skipping the aligner and the
        breaking-point walk here is pure saved work, never a semantic
        change — this is where range sharding's per-shard speedup
        comes from."""
        rng = self.window_range
        if rng is None:
            return True
        wl = self.window_length
        length = len(self.sequences[o.t_id].data)
        lo, hi = rng
        # the kept windows' covered coordinate region: window starts are
        # exact multiples of wl, so membership never depends on the
        # split points being wl-aligned
        first_start = -(-max(lo, 0) // wl) * wl
        cap = min(hi, length)
        if first_start >= cap:
            return False
        last_start = ((cap - 1) // wl) * wl
        region_hi = min(length, last_start + wl)
        return o.t_begin < region_hi and o.t_end > first_start

    # ---------------------------------------------------------------- polish
    def polish(self, drop_unpolished_sequences: bool = True,
               batcher=None, on_part=None, on_group=None,
               group_size: int = 64) -> list[Sequence]:
        """Per-window consensus + stitch (reference polisher.cpp:486-548).

        Set RACON_TPU_PROFILE=<dir> (CLI: --tpu-jax-profile) to capture a
        jax.profiler trace of the device phases (the TPU analogue of the
        reference's nvprof `-lineinfo` support, CMakeLists.txt:26) — a
        no-op when the backend cannot profile; per-phase windows/sec is
        reported on stderr either way.

        `batcher` (serve mode) replaces the in-process consensus pass:
        this job's windows join the shared continuous window batcher
        (serve/batcher.py), which merges them into bounded device
        iterations alongside concurrent jobs' windows and delivers them
        back incrementally as each iteration lands. Contigs whose
        windows are all complete are stitched IMMEDIATELY (in contig
        order) — `on_part` (callable(Sequence)) receives each finished
        contig before the job as a whole completes, which is what the
        server streams to clients as `result_part` frames. Per-window
        results are independent of batch composition, so both the
        streamed parts and the final list stay byte-identical to a solo
        run (test-pinned).

        `on_group` (fragment serve jobs, mutually exclusive with
        `on_part`) swaps the streamer for the read-order
        FragmentStreamer: callable(list[Sequence], lo, hi) receives
        corrected reads in bounded groups of `group_size` instead of
        one callback per read — see FragmentStreamer.
        """
        import time as _time

        if batcher is not None:
            if on_group is not None:
                streamer = FragmentStreamer(self,
                                            drop_unpolished_sequences,
                                            on_group, group_size)
            else:
                streamer = ContigStreamer(self,
                                          drop_unpolished_sequences,
                                          on_part)
            batcher.consensus(self, on_windows=streamer.on_windows)
            dst = streamer.finish()
            stitch_s = streamer.stitch_s
            t_stitch = _time.perf_counter() - stitch_s
        else:
            self._consensus_pass()
            t_stitch = _time.perf_counter()
            dst = self._stitch(drop_unpolished_sequences)
            stitch_s = _time.perf_counter() - t_stitch
        self.emit_progress(len(self.windows), len(self.windows),
                           phase="stitch", sequences=len(dst))
        self.hists.observe("phase.stitch", stitch_s)
        tr = trace.get_tracer()
        if tr is not None:
            tr.complete("polisher.stitch", t_stitch,
                        t_stitch + stitch_s, {"sequences": len(dst)})
        self.logger.log("[racon_tpu::Polisher.polish] generated consensus")
        # cumulative wall-clock, mirroring ~Polisher (polisher.cpp:189)
        self.logger.total("[racon_tpu::Polisher.] total =")
        self.windows = []
        self.sequences = []
        self._runs_completed += 1
        self.emit_observability()
        return dst

    def _consensus_pass(self) -> None:
        """Run the consensus engine over this run's windows (every
        window ends up carrying `consensus`/`polished`) and emit the
        per-phase reports. polish() calls this for the one-shot path;
        serve mode substitutes the cross-job batcher."""
        import contextlib
        import time as _time

        from ..ops.poa import BatchPOA

        self.logger.log()
        self._progress_phase = "consensus"
        self._arm_progress()
        self.emit_progress(0, len(self.windows))

        profile_ctx = (jax_profile("consensus") if self.tpu_poa_batches > 0
                       else contextlib.nullcontext())

        pipeline = self._make_pipeline()
        # stage counters accumulate across phases (bench artifact wants
        # the run total); the diagnostic line below must describe THIS
        # phase only, so delta against the pre-phase snapshot
        stats_base = self.pipeline_stats.snapshot()
        engine = BatchPOA(self.match, self.mismatch, self.gap,
                          self.window_length, num_threads=self.num_threads,
                          device_batches=self.tpu_poa_batches,
                          banded=self.tpu_banded_alignment,
                          band_width=self.tpu_aligner_band_width,
                          logger=self.logger, engine=self.tpu_engine,
                          pipeline=pipeline, scheduler=self.scheduler,
                          runner=self.device_runner)
        t_consensus = _time.perf_counter()
        with profile_ctx, pipeline:
            engine.generate_consensus(self.windows, self.trim)
        dt = _time.perf_counter() - t_consensus
        snap_occ = self.scheduler.stats.snapshot()
        self.emit_progress(
            len(self.windows), len(self.windows),
            occupancy={e: round(v["occupancy_pct"], 1)
                       for e, v in snap_occ.items()
                       if "occupancy_pct" in v} or None)
        self.hists.observe("phase.consensus", dt)
        tr = trace.get_tracer()
        if tr is not None:
            tr.complete("polisher.consensus", t_consensus,
                        _time.perf_counter(),
                        {"windows": len(self.windows),
                         "engine": engine.engine
                         if self.tpu_poa_batches > 0 else "host"})
        if dt > 0 and self.windows:
            log_info(f"[racon_tpu::Polisher.polish] consensus throughput: "
                     f"{len(self.windows) / dt:.1f} windows/s")
        ss = {k: v - stats_base[k] for k, v in self.stage_stats.items()}
        # overlap evidence: with the pipeline live, pack+device+unpack
        # stage seconds exceed the phase wall time; additive means dead
        log_info(f"[racon_tpu::Polisher.polish] pipeline stages (depth "
                 f"{self.tpu_pipeline_depth}): pack {ss['pack_s']:.2f}s "
                 f"device {ss['device_s']:.2f}s unpack {ss['unpack_s']:.2f}s "
                 f"fallback {ss['fallback_s']:.2f}s, {ss['chunks']} chunks / "
                 f"{ss['launches']} launches")
        # degradation report: what the resilience layer absorbed across
        # the whole run (silent on a clean run); the same counters ride
        # stage_stats into bench.py's JSON artifact
        degraded = degradation_summary(self.stage_stats)
        if degraded:
            log_info(f"[racon_tpu::Polisher.polish] degradation report: "
                     f"{degraded}")
        # occupancy report: how much of the dispatched device shapes was
        # real work (silent on host-only runs); adaptive ladders move
        # this number, the bench JSON records it per bucket
        occ = self.scheduler.stats.summary()
        if occ:
            log_info(f"[racon_tpu::Polisher.polish] batch occupancy "
                     f"(adaptive={'on' if self.scheduler.adaptive else 'off'})"
                     f": {occ}")

    def _contig_slices(self) -> list[tuple[int, int]]:
        """[start, end) window-index ranges, one per target contig, in
        target order — a contig boundary is the next window belonging
        to a different target id (equivalent to the historical rank-0
        test on whole-target runs; range-shard slices start at a
        nonzero rank, where only the id transition is right). The unit
        the incremental stitcher completes on."""
        slices: list[tuple[int, int]] = []
        start = 0
        for i in range(len(self.windows)):
            if (i == len(self.windows) - 1
                    or self.windows[i + 1].id != self.windows[i].id):
                slices.append((start, i + 1))
                start = i + 1
        return slices

    def _stitch_contig(self, windows: list[Window],
                       drop_unpolished_sequences: bool) -> Sequence | None:
        """Stitch ONE contig's windows (rank-ascending) into a polished
        sequence with the reference's LN/RC/XC tagging
        (polisher.cpp:506-545); None when the contig is dropped as
        fully unpolished."""
        polished_data = bytearray()
        num_polished_windows = 0
        for window in windows:
            num_polished_windows += 1 if window.polished else 0
            polished_data += window.consensus
        last = windows[-1]
        if self.window_range is not None:
            # range-shard segment: bare name, never dropped — the
            # router stitches sibling segments back together and
            # re-derives the solo LN/RC/XC tags (and the drop rule)
            # from the accounting recorded here
            name = self.sequences[last.id].name
            data_len = len(self.sequences[last.id].data)
            wl = self.window_length
            self.segment_meta[name] = {
                "polished": num_polished_windows,
                "windows": len(windows),
                "total_windows": (data_len + wl - 1) // wl,
                "coverage": self.targets_coverages[last.id],
                "lo": self.window_range[0],
                "hi": self.window_range[1],
            }
            return create_sequence(name, bytes(polished_data))
        ratio = num_polished_windows / float(last.rank + 1)
        if drop_unpolished_sequences and ratio <= 0:
            return None
        tags = "r" if self.type == PolisherType.kF else ""
        tags += f" LN:i:{len(polished_data)}"
        tags += f" RC:i:{self.targets_coverages[last.id]}"
        tags += f" XC:f:{ratio:.6f}"
        return create_sequence(self.sequences[last.id].name + tags,
                               bytes(polished_data))

    def _stitch(self, drop_unpolished_sequences: bool) -> list[Sequence]:
        """Stitch per-window consensus back into whole sequences, one
        contig at a time."""
        dst: list[Sequence] = []
        for start, end in self._contig_slices():
            seq = self._stitch_contig(self.windows[start:end],
                                      drop_unpolished_sequences)
            if seq is not None:
                dst.append(seq)
        return dst

    def emit_observability(self) -> None:
        """End-of-run observability emission — every part a no-op when
        its knob is off, so the default run's stderr stays byte-identical:
        report suppressed duplicate warnings, dump the metrics snapshot
        (RACON_TPU_METRICS / --tpu-metrics), render the stderr metrics
        table (when metrics are dumped or at debug level), and write the
        Chrome trace (RACON_TPU_TRACE / --tpu-trace). polish() calls
        this; initialize-only flows (bench's aligner phase) call it
        themselves so an armed trace/metrics artifact is never silently
        dropped."""
        flush_dedup()
        metrics_path = os.environ.get("RACON_TPU_METRICS")
        if metrics_path:
            # observability must never take a finished run down: an
            # unwritable path loses the artifact, not the polished FASTA
            try:
                self.metrics.dump(metrics_path)
                log_info(f"[racon_tpu::obs] metrics written to "
                         f"{metrics_path}")
            except OSError as exc:
                log_info(f"[racon_tpu::obs] warning: could not write "
                         f"metrics to {metrics_path} ({exc})")
        if metrics_path or log_level() >= DEBUG:
            log_info("[racon_tpu::obs] end-of-run metrics:\n"
                     + self.metrics.table())
        try:
            saved = trace.save()
        except OSError as exc:
            saved = None
            log_info(f"[racon_tpu::obs] warning: could not write trace "
                     f"({exc})")
        if saved:
            log_info(f"[racon_tpu::obs] trace written to {saved} "
                     "(open in https://ui.perfetto.dev)")


class ContigStreamer:
    """Incremental stitcher over the continuous batcher's iteration
    stream: feed completed windows in ANY order (`on_windows` is the
    batcher's per-iteration delivery hook), receive finished contigs in
    CONTIG order — a contig ships the moment its last window lands AND
    every earlier contig has shipped, so the concatenation of emitted
    parts is byte-identical to `Polisher._stitch`'s one-shot output
    (test-pinned, including with quarantined windows in the mix).

    `on_part` (callable(Sequence) or None) sees each stitched contig as
    it completes — the serve layer forwards these as `result_part`
    frames; exceptions from it are swallowed (streaming is decoration
    on the polish, never a dependency of it)."""

    def __init__(self, polisher: "Polisher", drop_unpolished: bool,
                 on_part=None):
        self._polisher = polisher
        self._drop = drop_unpolished
        self._on_part = on_part
        self._slices = polisher._contig_slices()
        self._remaining = [end - start for start, end in self._slices]
        self._contig_of: dict[int, int] = {}
        for ci, (start, end) in enumerate(self._slices):
            for w in polisher.windows[start:end]:
                self._contig_of[id(w)] = ci
        self._next = 0
        self._out: list[Sequence] = []
        #: cumulative stitch seconds, scattered across deliveries —
        #: polish() observes it as the phase.stitch latency
        self.stitch_s = 0.0

    def on_windows(self, windows: list[Window]) -> None:
        for w in windows:
            self._remaining[self._contig_of[id(w)]] -= 1
        while (self._next < len(self._slices)
               and self._remaining[self._next] == 0):
            start, end = self._slices[self._next]
            t0 = time.perf_counter()
            seq = self._polisher._stitch_contig(
                self._polisher.windows[start:end], self._drop)
            self.stitch_s += time.perf_counter() - t0
            self._next += 1
            if seq is None:
                continue
            self._out.append(seq)
            if self._on_part is not None:
                try:
                    self._on_part(seq)
                except Exception:  # noqa: BLE001 — see docstring
                    pass

    def finish(self) -> list[Sequence]:
        """The full stitched output, identical to `_stitch`'s list.
        Valid once the batcher's consensus() returned (every window
        delivered)."""
        return self._out


class FragmentStreamer(ContigStreamer):
    """Read-order analogue of ContigStreamer for fragment correction
    (PolisherType.kF): every target is a READ, so the per-contig
    delivery contract would mean one `result_part` frame per read —
    millions of tiny frames on a real read set. Corrected reads instead
    ship in bounded GROUPS: `on_group(seqs, lo, hi)` fires once per
    completed group of `group_size` consecutive targets, where
    [lo, hi) is the contiguous local target-INDEX range the group
    covers. Reads dropped as unpolished still advance the range (a
    group may even be empty), so sibling shards' group receipts tile
    the read axis exactly — the dedupe/requeue ledger and obsreport's
    receipt checks lean on that.

    finish() flushes the final partial group; the returned list is the
    authoritative output, byte-identical to `Polisher._stitch`'s
    one-shot result exactly like the contig streamer's. `on_group`
    exceptions are swallowed (streaming is decoration)."""

    def __init__(self, polisher: "Polisher", drop_unpolished: bool,
                 on_group=None, group_size: int = 64):
        super().__init__(polisher, drop_unpolished, on_part=None)
        self._on_group = on_group
        self._group_size = max(1, int(group_size))
        self._pend: list[Sequence] = []
        self._group_lo = 0

    def on_windows(self, windows: list[Window]) -> None:
        for w in windows:
            self._remaining[self._contig_of[id(w)]] -= 1
        while (self._next < len(self._slices)
               and self._remaining[self._next] == 0):
            start, end = self._slices[self._next]
            t0 = time.perf_counter()
            seq = self._polisher._stitch_contig(
                self._polisher.windows[start:end], self._drop)
            self.stitch_s += time.perf_counter() - t0
            self._next += 1
            if seq is not None:
                self._out.append(seq)
                self._pend.append(seq)
            if self._next - self._group_lo >= self._group_size:
                self._flush_group()

    def _flush_group(self) -> None:
        if self._next == self._group_lo:
            return
        group, lo, hi = self._pend, self._group_lo, self._next
        self._pend = []
        self._group_lo = self._next
        if self._on_group is not None:
            try:
                self._on_group(group, lo, hi)
            except Exception:  # noqa: BLE001 — see docstring
                pass

    def finish(self) -> list[Sequence]:
        self._flush_group()
        return self._out
