"""In-process re-overlap for serve-native polishing rounds.

Standard racon practice is 2-4 polishing rounds; the serve layer's
`rounds=N` submit field keeps every round inside the warm process
(core/polisher.py `Polisher.redraft`). Round k+1 needs read-to-draft
overlaps against round k's freshly stitched contigs — the external
workflow would shell out to minimap2 here, which the serve process
cannot (and must not) do. This module is the in-process replacement: a
deterministic k-mer anchor mapper that emits PAF rows compatible with
`io/parsers.PafParser` + `core/overlap.Overlap.from_paf`.

It is NOT a general-purpose aligner. It exploits exactly the structure
a polishing round has: round k's contigs are a lightly edited copy of
the draft the reads already mapped to, so every read still anchors on
a single diagonal with abundant exact k-mers (at 5% divergence the
expected 15-mer survival rate is ~0.46 — hundreds of anchors per
read). The algorithm:

  1. index every target k-mer position (repetitive k-mers above
     `max_occ` occurrences are dropped, the standard repeat filter);
  2. per read and strand, collect (target, diagonal, qpos, tpos)
     anchors and vote them into `band`-wide diagonal buckets;
  3. the winning bucket pair (bucket + right neighbor, so a band
     boundary cannot split a chain) defines the overlap: begins/ends
     are the anchor extremes, which guarantees every coordinate lies
     inside the respective sequence and that `q_length`/`t_length`
     are exact — the two invariants `Overlap.transmute` hard-fails on.

Determinism: no RNG, no hashing with per-process seeds, explicit
tie-breaks (score desc, then target order, then diagonal) — the same
inputs always produce the same PAF bytes, which is what lets the
rounds byte-identity pins in tests/test_rounds.py hold across the
serve path and the chained solo path (both call this mapper through
`Polisher.redraft`)."""

from __future__ import annotations

_COMP = bytes.maketrans(b"ACGTUacgtuNnKkMmRrYySsWwBbVvHhDd",
                        b"TGCAAtgcaaNnMmKkYyRrSsWwVvBbDdHh")

#: defaults sized for the read-vs-polished-draft regime (see module
#: docstring); k=15 matches the minimap2 map-ont preset's seed length
DEFAULT_K = 15
DEFAULT_BAND = 64
DEFAULT_MIN_ANCHORS = 2
DEFAULT_MAX_OCC = 64


def revcomp(data: bytes) -> bytes:
    return data.translate(_COMP)[::-1]


def build_index(targets, k: int = DEFAULT_K,
                max_occ: int = DEFAULT_MAX_OCC) -> dict:
    """k-mer -> [(target_index, position)] over every target, minus
    k-mers occurring more than `max_occ` times (repeats would vote for
    every copy of themselves and drown the true diagonal)."""
    index: dict[bytes, list] = {}
    for tid, t in enumerate(targets):
        data = t.data
        for pos in range(len(data) - k + 1):
            index.setdefault(data[pos:pos + k], []).append((tid, pos))
    if max_occ > 0:
        for km in [km for km, v in index.items() if len(v) > max_occ]:
            del index[km]
    return index


def _best_group(data: bytes, index: dict, k: int, band: int):
    """The densest (target, diagonal-bucket) anchor group for one
    oriented read: (score, tid, bucket, anchors) or None. Score counts
    anchors in the bucket plus its right neighbor, so a chain that
    straddles a bucket boundary still wins whole."""
    groups: dict[tuple, list] = {}
    for qpos in range(len(data) - k + 1):
        for tid, tpos in index.get(data[qpos:qpos + k], ()):
            groups.setdefault((tid, (tpos - qpos) // band),
                              []).append((qpos, tpos))
    best = None
    for (tid, b), hits in sorted(groups.items()):
        merged = hits + groups.get((tid, b + 1), [])
        score = len(merged)
        # strict > : ties resolve to the sorted-first (tid, bucket)
        if best is None or score > best[0]:
            best = (score, tid, b, merged)
    return best


def remap_read(read, targets, index: dict, k: int = DEFAULT_K,
               band: int = DEFAULT_BAND,
               min_anchors: int = DEFAULT_MIN_ANCHORS) -> str | None:
    """One read's best overlap as a PAF row (or None when the read no
    longer anchors anywhere — it simply stops contributing layers,
    matching how an external mapper would drop it)."""
    fwd = _best_group(read.data, index, k, band)
    rev = _best_group(revcomp(read.data), index, k, band)
    strand, best = "+", fwd
    if rev is not None and (best is None or rev[0] > best[0]):
        strand, best = "-", rev
    if best is None or best[0] < min_anchors:
        return None
    score, tid, _b, anchors = best
    q0 = min(a[0] for a in anchors)
    q1 = max(a[0] for a in anchors) + k
    t0 = min(a[1] for a in anchors)
    t1 = max(a[1] for a in anchors) + k
    q_len = len(read.data)
    if strand == "-":
        # anchors live in the reverse-complement frame; PAF '-' rows
        # carry query coordinates in the FORWARD read frame
        q0, q1 = q_len - q1, q_len - q0
    matches = min(score * k, q1 - q0, t1 - t0)
    aln_len = max(q1 - q0, t1 - t0)
    # stitched contigs carry " LN:i:.. RC:i:.. XC:f:.." name tags, but
    # a FASTA re-parse keeps only the first token — the PAF target name
    # must match THAT or Overlap.transmute drops every row
    t_name = targets[tid].name.split(None, 1)[0]
    return "\t".join(map(str, (
        read.name, q_len, q0, q1, strand,
        t_name, len(targets[tid].data), t0, t1,
        matches, aln_len, 60)))


def remap_overlaps(reads, targets, k: int = DEFAULT_K,
                   band: int = DEFAULT_BAND,
                   min_anchors: int = DEFAULT_MIN_ANCHORS,
                   max_occ: int = DEFAULT_MAX_OCC) -> list[str]:
    """PAF rows for every read that anchors on some target (one best
    hit per read — the kC overlap filter keeps the longest per query
    anyway). Deterministic: same inputs, same rows, same order."""
    index = build_index(targets, k, max_occ)
    rows: list[str] = []
    for read in reads:
        row = remap_read(read, targets, index, k, band, min_anchors)
        if row is not None:
            rows.append(row)
    return rows


def write_paf(rows: list[str], path: str) -> str:
    """Write PAF rows (the extension must be `.paf` — that is what
    routes `create_overlap_parser` to the PAF reader)."""
    with open(path, "w") as fh:
        for row in rows:
            fh.write(row + "\n")
    return path


def write_fasta(sequences, path: str) -> str:
    """Write sequences as plain FASTA, the exact byte shape the serve
    layer streams (`>` + full tagged name + newline + data + newline)."""
    with open(path, "wb") as fh:
        for s in sequences:
            fh.write(b">" + s.name.encode() + b"\n" + s.data + b"\n")
    return path
