"""Sequence record: read or target contig.

Behavioral contract (reference src/sequence.cpp):
  - bases are uppercased on ingest (sequence.cpp:24-27);
  - an all-'!' (all-zero Phred) quality string is dropped entirely
    (sequence.cpp:34-41) so downstream treats the record as quality-less;
  - reverse complement and reversed quality are built lazily on demand
    (sequence.cpp:49-84); non-ACGT bases are left unchanged by complementing;
  - `transmute` releases name/data/quality that later stages will not need
    (sequence.cpp:86-100).

Data and quality are stored as `bytes` (ASCII) — cheap slicing, zero-copy
views into them via memoryview where needed, and direct conversion to numpy
for device encoding.
"""

from __future__ import annotations

# A<->T, C<->G; everything else (N, IUPAC codes) maps to itself
# (reference sequence.cpp:58-75 leaves non-ACGT bases unchanged).
_COMPLEMENT = bytes(
    {ord("A"): ord("T"), ord("T"): ord("A"), ord("C"): ord("G"), ord("G"): ord("C")}.get(i, i)
    for i in range(256)
)


class Sequence:
    """A named nucleotide sequence with optional Phred+33 quality."""

    __slots__ = (
        "name",
        "data",
        "quality",
        "_reverse_complement",
        "_reverse_quality",
    )

    def __init__(self, name: str, data: bytes, quality: bytes = b""):
        self.name = name
        self.data = data.upper()
        # Drop qualities that are all-zero Phred (all '!'), reference
        # sequence.cpp:34-41: they carry no information.
        if quality and any(q != 0x21 for q in quality):
            self.quality = quality
        else:
            self.quality = b""
        self._reverse_complement: bytes | None = None
        self._reverse_quality: bytes | None = None

    # -- lazy reverse complement -------------------------------------------
    @property
    def reverse_complement(self) -> bytes:
        if self._reverse_complement is None:
            self.create_reverse_complement()
        return self._reverse_complement

    @property
    def reverse_quality(self) -> bytes:
        if self._reverse_quality is None:
            self.create_reverse_complement()
        return self._reverse_quality

    def create_reverse_complement(self) -> None:
        """Build (once) the reverse complement and reversed quality."""
        if self._reverse_complement is not None:
            return
        self._reverse_complement = self.data.translate(_COMPLEMENT)[::-1]
        self._reverse_quality = self.quality[::-1]

    def transmute(self, has_name: bool, has_data: bool, has_reverse_data: bool) -> None:
        """Free unneeded fields; precompute revcomp where overlaps need it
        (reference sequence.cpp:86-100)."""
        if not has_name:
            self.name = ""
        if has_reverse_data:
            self.create_reverse_complement()
        if not has_data:
            self.data = b""
            self.quality = b""

    def __len__(self) -> int:
        return len(self.data)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Sequence(name={self.name!r}, len={len(self.data)}, qual={bool(self.quality)})"


def create_sequence(name: str, data: bytes | str) -> Sequence:
    """Factory mirroring reference createSequence (sequence.cpp:13-17).

    Unlike the parser path, this does NOT uppercase or drop quality — it is
    used for already-polished output records (reference uses the 2-arg ctor
    at sequence.cpp:44-47 which stores data verbatim).
    """
    if isinstance(data, str):
        data = data.encode()
    seq = Sequence.__new__(Sequence)
    seq.name = name
    seq.data = data
    seq.quality = b""
    seq._reverse_complement = None
    seq._reverse_quality = None
    return seq
