"""Window: the unit of POA consensus.

A window is a `window_length` slice of a target contig (the backbone) plus the
read slices (layers) that overlap it. Behavioral contract (reference
src/window.cpp):
  - backbone is sequence 0 with position (0, 0) (window.cpp:29-37);
  - empty layers or begin == end layers are ignored (window.cpp:45-47);
  - invalid layer positions are fatal (window.cpp:54-58);
  - fewer than 3 total sequences -> consensus = backbone, "not polished"
    (window.cpp:68-71);
  - layers are processed sorted by begin position (window.cpp:84-85);
  - TGS windows trim consensus ends where coverage < (n_seqs - 1) / 2 and
    warn about chimerism when nothing survives (window.cpp:118-139).

Unlike the reference (whose Window owns spoa calls), consensus generation
here is batched: the polisher packs many windows into fixed-shape tensors
and runs the POA engine (ops/poa.py) over all of them at once — the
TPU-native analogue of GenomeWorks cudapoa batches (src/cuda/cudabatch.cpp).
"""

from __future__ import annotations

import enum

from ..errors import RaconError
from ..utils.logger import warn_dedup


class WindowType(enum.Enum):
    kNGS = 0   # short reads (mean length <= 1000)
    kTGS = 1   # long reads


class Window:
    __slots__ = ("id", "rank", "type", "consensus", "sequences", "qualities",
                 "positions", "polished")

    def __init__(self, id_: int, rank: int, type_: WindowType,
                 backbone: bytes, quality: bytes):
        self.id = id_            # target sequence index
        self.rank = rank         # window index within the target
        self.type = type_
        self.consensus = b""
        self.polished = False
        # layer 0 is the backbone
        self.sequences: list[bytes] = [backbone]
        self.qualities: list[bytes | None] = [quality]
        self.positions: list[tuple[int, int]] = [(0, 0)]

    def add_layer(self, sequence: bytes, quality: bytes | None,
                  begin: int, end: int) -> None:
        if len(sequence) == 0 or begin == end:
            return
        if quality is not None and len(sequence) != len(quality):
            raise RaconError("Window.add_layer", "unequal quality size!")
        backbone_len = len(self.sequences[0])
        if begin >= end or begin > backbone_len or end > backbone_len:
            raise RaconError("Window.add_layer",
                             "layer begin and end positions are invalid!")
        self.sequences.append(sequence)
        self.qualities.append(quality)
        self.positions.append((begin, end))

    @property
    def num_layers(self) -> int:
        return len(self.sequences) - 1

    def backbone_fallback(self) -> None:
        """Use the unpolished backbone as consensus (reference window.cpp:68-71)."""
        self.consensus = self.sequences[0]
        self.polished = False

    def sorted_layer_order(self) -> list[int]:
        """Layer indices (1-based into sequences) sorted by begin position,
        stable — reference window.cpp:78-85."""
        return sorted(range(1, len(self.sequences)),
                      key=lambda i: self.positions[i][0])

    def apply_trim(self, consensus: bytes, coverages, trim: bool = True) -> None:
        """Post-consensus coverage trim for TGS windows (window.cpp:118-139)."""
        self.consensus = consensus
        self.polished = True
        if self.type != WindowType.kTGS or not trim:
            return
        average_coverage = (len(self.sequences) - 1) // 2
        begin, end = 0, len(consensus) - 1
        while begin < len(consensus) and coverages[begin] < average_coverage:
            begin += 1
        while end >= 0 and coverages[end] < average_coverage:
            end -= 1
        if begin >= end:
            # one line per run, not one per suspect window (a noisy draft
            # can trip this on hundreds of windows); debug shows each
            warn_dedup(
                "Window.chimeric",
                f"[racon_tpu::Window.generate_consensus] warning: "
                f"contig {self.id} might be chimeric in window "
                f"{self.rank}!")
        else:
            self.consensus = consensus[begin:end + 1]


def create_window(id_: int, rank: int, type_: WindowType, backbone: bytes,
                  quality: bytes) -> Window:
    """Factory mirroring reference createWindow (window.cpp:15-27)."""
    if len(backbone) == 0 or len(backbone) != len(quality):
        raise RaconError("create_window",
                         "empty backbone sequence/unequal quality length!")
    return Window(id_, rank, type_, backbone, quality)
