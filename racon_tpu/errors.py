"""Error type for user-facing failures.

The reference hard-exits with a diagnostic prefix `[racon::Class::method] error: ...`
(e.g. src/polisher.cpp:206-209, src/overlap.cpp:148-153, src/window.cpp:19-23).
We raise RaconError with the same message shape; the CLI converts it to
stderr + exit(1) so the observable behavior matches.
"""


class RaconError(RuntimeError):
    """User-facing error carrying a `[racon_tpu::Scope] error: ...` message."""

    def __init__(self, scope: str, message: str):
        self.scope = scope
        super().__init__(f"[racon_tpu::{scope}] error: {message}")
