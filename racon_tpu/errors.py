"""Error types: user-facing failures and the device-failure taxonomy.

The reference hard-exits with a diagnostic prefix `[racon::Class::method] error: ...`
(e.g. src/polisher.cpp:206-209, src/overlap.cpp:148-153, src/window.cpp:19-23).
We raise RaconError with the same message shape; the CLI converts it to
stderr + exit(1) so the observable behavior matches.

The reference's only *device* failure posture is a hard exit via
`CU_CHECK_ERR` (cudautils.hpp:10-18). Here device-side failures get their
own taxonomy under `DeviceError` so degradation decisions (retry, host
fallback, per-window quarantine — racon_tpu/resilience/) and the strict
mode key on error CLASS, not string matching:

  - DeviceError:   a device launch/compute/fetch failed (the CU_CHECK_ERR
    role; also the class injected faults raise);
  - DeviceTimeout: a device-stage call exceeded the watchdog deadline
    (resilience.Watchdog) — the "stuck launch" failure mode CUDA surfaces
    as a hung stream;
  - ChunkCorrupt:  fetched results failed validation / could not be
    unpacked (detected-corruption model: bad data raises rather than
    flowing downstream).

All three are RaconErrors, so an un-degraded escape still exits the CLI
with the reference's diagnostic shape instead of a traceback.
"""

from __future__ import annotations


class RaconError(RuntimeError):
    """User-facing error carrying a `[racon_tpu::Scope] error: ...` message."""

    def __init__(self, scope: str, message: str):
        self.scope = scope
        super().__init__(f"[racon_tpu::{scope}] error: {message}")


class DeviceError(RaconError):
    """A device launch, compute or result fetch failed (CU_CHECK_ERR role)."""


class DeviceTimeout(DeviceError):
    """A device-stage call exceeded the watchdog deadline (stuck launch)."""


class ChunkCorrupt(DeviceError):
    """Fetched chunk results failed validation or could not be unpacked."""


def as_device_error(exc: BaseException, scope: str) -> DeviceError:
    """Classify an arbitrary device-path exception: DeviceErrors pass
    through unchanged (their class carries the failure mode), anything
    else — a raw XLA/jax/runtime error — is wrapped so callers can key
    degradation on `except DeviceError` instead of a bare `except
    Exception`."""
    if isinstance(exc, DeviceError):
        return exc
    wrapped = DeviceError(scope, f"device failure "
                                 f"({type(exc).__name__}: {exc})")
    wrapped.__cause__ = exc
    return wrapped
