from .parsers import (
    FastaParser,
    FastqParser,
    MhapParser,
    PafParser,
    SamParser,
    create_sequence_parser,
    create_overlap_parser,
)

__all__ = [
    "FastaParser",
    "FastqParser",
    "MhapParser",
    "PafParser",
    "SamParser",
    "create_sequence_parser",
    "create_overlap_parser",
]
