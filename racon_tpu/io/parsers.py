"""Streaming parsers for FASTA/FASTQ (sequences) and MHAP/PAF/SAM (overlaps),
transparently gzipped.

API mirrors the reference's bioparser contract (vendor, used at
src/polisher.cpp:86-125, 202-203, 229-231, 313):

    parser = FastaParser(path)
    more = parser.parse(dst, max_bytes)   # append records; False at EOF
    parser.reset()

`max_bytes` bounds the approximate in-memory size of the records appended per
call (-1 = everything), so multi-GiB read sets stream in reference-sized
chunks (kChunkSize, polisher.cpp:26). Gzip is sniffed from the magic bytes,
not the extension — extensions are validated separately by the polisher
factory exactly like the reference (polisher.cpp:83-133).
"""

from __future__ import annotations

import gzip
import io
import zlib

from ..errors import RaconError
from ..core.sequence import Sequence
from ..core.overlap import Overlap

#: what a truncated or corrupt gzip member raises mid-stream; mapped to a
#: RaconError naming the offending file so the CLI reports it cleanly
#: instead of leaking a raw traceback
_GZIP_ERRORS = (EOFError, zlib.error, gzip.BadGzipFile)


def _open(path: str):
    f = open(path, "rb")
    magic = f.read(2)
    f.seek(0)
    if magic == b"\x1f\x8b":
        # decompress stream; buffer for fast line iteration
        return io.BufferedReader(gzip.GzipFile(fileobj=f), buffer_size=1 << 20)
    return io.BufferedReader(f, buffer_size=1 << 20)


def _first_token(line: bytes) -> str:
    return line.split(None, 1)[0].decode()


class _StreamingParser:
    """Base: lazily yields records; parse() drains up to a byte budget."""

    def __init__(self, path: str):
        self.path = path
        self._file = None
        self._gen = None

    def reset(self) -> None:
        if self._file is not None:
            self._file.close()
        self._file = _open(self.path)
        self._gen = self._records(self._file)

    def parse(self, dst: list, max_bytes: int = -1) -> bool:
        """Append records to dst until ~max_bytes of payload is consumed.
        Returns True if the file may have more records, False at EOF."""
        if self._gen is None:
            self.reset()
        total = 0
        try:
            for record, nbytes in self._gen:
                dst.append(record)
                total += nbytes
                if max_bytes != -1 and total >= max_bytes:
                    return True
        except _GZIP_ERRORS as exc:
            raise RaconError(
                type(self).__name__,
                f"truncated or corrupt gzip input {self.path}! "
                f"({type(exc).__name__}: {exc})") from None
        return False

    def _records(self, f):  # pragma: no cover - abstract
        raise NotImplementedError


class FastaParser(_StreamingParser):
    def _records(self, f):
        name = None
        chunks: list[bytes] = []
        for raw in f:
            line = raw.rstrip()
            if not line:
                continue
            if line.startswith(b">"):
                if name is not None:
                    data = b"".join(chunks)
                    yield Sequence(name, data), len(name) + len(data)
                name = _first_token(line[1:])
                chunks = []
            else:
                if name is None:
                    raise RaconError("FastaParser", f"malformed FASTA file {self.path}!")
                chunks.append(line)
        if name is not None:
            data = b"".join(chunks)
            yield Sequence(name, data), len(name) + len(data)


class FastqParser(_StreamingParser):
    def _records(self, f):
        """Multi-line (wrapped) FASTQ: sequence lines accumulate until the
        '+' separator, quality lines until their length reaches the sequence
        length — the reference's bioparser contract (its own
        test/data/sample_reads.fastq.gz is line-wrapped)."""
        while True:
            header = f.readline()
            if not header:
                return
            header = header.rstrip()
            if not header:
                continue
            if not header.startswith(b"@"):
                raise RaconError("FastqParser", f"malformed FASTQ file {self.path}!")
            chunks: list[bytes] = []
            while True:
                line = f.readline()
                if not line:
                    raise RaconError("FastqParser",
                                     f"malformed FASTQ file {self.path}!")
                line = line.rstrip()
                if line.startswith(b"+"):
                    break
                chunks.append(line)
            data = b"".join(chunks)
            qchunks: list[bytes] = []
            qlen = 0
            while qlen < len(data):
                line = f.readline()
                if not line:
                    raise RaconError("FastqParser",
                                     f"malformed FASTQ file {self.path}!")
                line = line.rstrip()  # Phred+33 bytes are never whitespace
                qchunks.append(line)
                qlen += len(line)
            quality = b"".join(qchunks)
            if len(quality) != len(data):
                raise RaconError("FastqParser", f"malformed FASTQ file {self.path}!")
            name = _first_token(header[1:])
            yield Sequence(name, data, quality), len(name) + len(data) + len(quality)


class MhapParser(_StreamingParser):
    """MHAP: a_id b_id error shared_minmers a_rc a_begin a_end a_length
    b_rc b_begin b_end b_length (space separated)."""

    def _records(self, f):
        for raw in f:
            line = raw.strip()
            if not line:
                continue
            t = line.split()
            if len(t) < 12:
                raise RaconError("MhapParser", f"malformed MHAP file {self.path}!")
            o = Overlap.from_mhap(
                int(t[0]), int(t[1]), float(t[2]), int(t[3]),
                int(t[4]), int(t[5]), int(t[6]), int(t[7]),
                int(t[8]), int(t[9]), int(t[10]), int(t[11]))
            yield o, len(line)


class PafParser(_StreamingParser):
    """PAF: q_name q_len q_begin q_end strand t_name t_len t_begin t_end
    matches aln_len mapq [tags...] (tab separated; tags ignored, matching
    the reference's bioparser)."""

    def _records(self, f):
        for raw in f:
            line = raw.rstrip()
            if not line:
                continue
            t = line.split(b"\t")
            if len(t) < 12:
                raise RaconError("PafParser", f"malformed PAF file {self.path}!")
            o = Overlap.from_paf(
                t[0].decode(), int(t[1]), int(t[2]), int(t[3]),
                t[4].decode(), t[5].decode(), int(t[6]), int(t[7]),
                int(t[8]), int(t[9]), int(t[10]), int(t[11]))
            yield o, len(line)


class SamParser(_StreamingParser):
    """SAM alignments: @-header lines skipped; fields qname flag rname pos
    mapq cigar ... (tab separated)."""

    def _records(self, f):
        for raw in f:
            if raw.startswith(b"@"):
                continue
            line = raw.rstrip()
            if not line:
                continue
            t = line.split(b"\t")
            if len(t) < 11:
                raise RaconError("SamParser", f"malformed SAM file {self.path}!")
            o = Overlap.from_sam(
                t[0].decode(), int(t[1]), t[2].decode(), int(t[3]),
                int(t[4]), t[5])
            yield o, len(line)


class _NativeSequenceParser(_StreamingParser):
    """FASTA/FASTQ via the native zlib loader (native/src/parse.cpp) —
    tokenization and IO in C++, Python only wraps the record slices. Same
    streaming contract as the pure-Python parsers above."""

    def __init__(self, path: str, fastq: bool):
        super().__init__(path)
        self._fastq = fastq
        self._sf = None

    def reset(self) -> None:
        from ..native import SequenceFile

        if self._sf is not None:
            self._sf.close()
        self._sf = SequenceFile(self.path, self._fastq)

    def parse(self, dst: list, max_bytes: int = -1) -> bool:
        if self._sf is None:
            self.reset()
        try:
            records, more = self._sf.chunk(max_bytes)
        except ValueError:
            if self._fastq:
                raise RaconError("FastqParser",
                                 f"malformed FASTQ file {self.path}!") from None
            raise RaconError("FastaParser",
                             f"malformed FASTA file {self.path}!") from None
        for name, seq, qual in records:
            dst.append(Sequence(name.decode(), seq, qual or b""))
        return more


_SEQUENCE_EXTENSIONS_FASTA = (".fasta", ".fasta.gz", ".fna", ".fna.gz", ".fa", ".fa.gz")
_SEQUENCE_EXTENSIONS_FASTQ = (".fastq", ".fastq.gz", ".fq", ".fq.gz")


def create_sequence_parser(path: str, scope: str) -> _StreamingParser:
    """Extension-sniffed sequence parser (reference polisher.cpp:83-99,117-133).

    Prefers the native loader; falls back to the pure-Python parsers when
    the native library is unavailable (e.g. no compiler)."""
    if path.endswith(_SEQUENCE_EXTENSIONS_FASTA):
        fastq = False
    elif path.endswith(_SEQUENCE_EXTENSIONS_FASTQ):
        fastq = True
    else:
        raise RaconError(scope,
            f"file {path} has unsupported format extension (valid extensions: "
            ".fasta, .fasta.gz, .fna, .fna.gz, .fa, .fa.gz, .fastq, .fastq.gz, "
            ".fq, .fq.gz)!")
    try:
        from ..native import get_lib

        get_lib()
        return _NativeSequenceParser(path, fastq)
    except Exception:  # pragma: no cover - no toolchain
        return FastqParser(path) if fastq else FastaParser(path)


def create_overlap_parser(path: str, scope: str) -> _StreamingParser:
    """Extension-sniffed overlap parser (reference polisher.cpp:101-115)."""
    if path.endswith((".mhap", ".mhap.gz")):
        return MhapParser(path)
    if path.endswith((".paf", ".paf.gz")):
        return PafParser(path)
    if path.endswith((".sam", ".sam.gz")):
        return SamParser(path)
    raise RaconError(scope,
        f"file {path} has unsupported format extension (valid extensions: "
        ".mhap, .mhap.gz, .paf, .paf.gz, .sam, .sam.gz)!")
