"""Native host library: POA engine, exact aligner, threaded batch API.

C++ equivalents of the reference's vendored native dependencies (SURVEY.md
§2b): spoa (POA graph + consensus), edlib (exact NW + CIGAR), thread_pool
(worker pool inside the batch entry point). Loaded through ctypes — no
pybind11; the shared object is built on demand with g++ and cached next to
the sources (rebuilt when any source is newer).
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
import threading

import numpy as np

_DIR = pathlib.Path(__file__).resolve().parent
_SRC = _DIR / "src"
_LIB = _DIR / "libracon_host.so"
_SOURCES = ("poa.cpp", "myers.cpp", "parse.cpp", "api.cpp", "session.cpp")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def _host_fingerprint() -> str:
    """CPU identity for the -march=native build: a binary built on another
    machine must be rebuilt here, not SIGILL at the first AVX instruction."""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith(("flags", "Features")):
                    import hashlib

                    return hashlib.sha256(line.encode()).hexdigest()[:16]
    except OSError:
        pass
    import platform

    return platform.machine()


def _needs_build(lib: pathlib.Path) -> bool:
    if not lib.exists():
        return True
    tag = lib.with_suffix(".so.host")
    try:
        if tag.read_text() != _host_fingerprint():
            return True
    except OSError:
        return True
    lib_mtime = lib.stat().st_mtime
    return any((_SRC / s).stat().st_mtime > lib_mtime for s in _SOURCES)


def build(force: bool = False, debug: bool = False) -> pathlib.Path:
    """Compile the shared library if missing or stale.

    debug=True (or RACON_TPU_NATIVE_DEBUG=1 at import) is the analogue of
    the reference's sanitizer build (`Makefile:23-25`,
    `-Db_sanitize=address`): -O1 -g with ASan+UBSan, built to a separate
    libracon_host_debug.so. ctypes-loading an ASan library requires the
    runtime to be preloaded, e.g.:
        LD_PRELOAD=$(g++ -print-file-name=libasan.so) \
        RACON_TPU_NATIVE_DEBUG=1 python -m pytest tests/test_native.py
    """
    lib = _LIB.with_name("libracon_host_debug.so") if debug else _LIB
    with _lock:
        if force or _needs_build(lib):
            if debug:
                variants = [["-O1", "-g", "-fsanitize=address,undefined",
                             "-fno-omit-frame-pointer"]]
            else:
                # native codegen is ~20% faster on the POA DP loops; fall
                # back for toolchains without the flag
                variants = [["-O3", "-march=native", "-funroll-loops"],
                            ["-O3"]]
            proc = None
            for flags in variants:
                cmd = [
                    os.environ.get("CXX", "g++"),
                    *flags, "-std=c++17", "-fPIC", "-shared", "-pthread",
                    "-o", str(lib),
                ] + [str(_SRC / s) for s in _SOURCES] + ["-lz"]
                proc = subprocess.run(cmd, capture_output=True, text=True)
                if proc.returncode == 0:
                    break
            if proc is None or proc.returncode != 0:
                raise RuntimeError(
                    f"native build failed ({' '.join(cmd)}):\n{proc.stderr}")
            lib.with_suffix(".so.host").write_text(_host_fingerprint())
    return lib


def get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        debug = bool(os.environ.get("RACON_TPU_NATIVE_DEBUG"))
        path = build(debug=debug)
        try:
            lib = ctypes.CDLL(str(path))
        except OSError:
            # stale/foreign binary (e.g. copied between machines) — rebuild
            path = build(force=True, debug=debug)
            lib = ctypes.CDLL(str(path))
        i64, i32, u8p = ctypes.c_int64, ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8)
        i64p, i32p = ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32)
        u32p = ctypes.POINTER(ctypes.c_uint32)

        lib.rh_edit_distance.restype = i64
        lib.rh_edit_distance.argtypes = [u8p, i64, u8p, i64]
        lib.rh_nw_cigar.restype = i64
        lib.rh_nw_cigar.argtypes = [u8p, i64, u8p, i64, ctypes.c_char_p, i64]
        lib.rh_nw_cigar_batch.restype = None
        lib.rh_nw_cigar_batch.argtypes = [
            u8p, i64p, u8p, i64p, i64, i32, ctypes.c_char_p, i64, i64p,
        ]
        lib.rh_poa_batch.restype = i64
        lib.rh_poa_batch.argtypes = [
            u8p, i64p, u8p, i64p, i32p, i32p, i64p, i64,
            i32p, i32p, i64p,
            i32, i32, i32, i32,
            u8p, u32p, i64, i64p,
        ]
        vp = ctypes.c_void_p
        u8pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
        i64pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))
        lib.rh_sf_open.restype = vp
        lib.rh_sf_open.argtypes = [ctypes.c_char_p, i32]
        lib.rh_sf_chunk.restype = i64
        lib.rh_sf_chunk.argtypes = [vp, i64, ctypes.POINTER(i32),
                                    u8pp, i64pp, u8pp, i64pp, u8pp, i64pp]
        lib.rh_sf_close.restype = None
        lib.rh_sf_close.argtypes = [vp]
        i8p = ctypes.POINTER(ctypes.c_int8)
        i16p = ctypes.POINTER(ctypes.c_int16)
        lib.rh_poa_session_new.restype = i64
        lib.rh_poa_session_new.argtypes = [
            u8p, i64p, u8p, i64p, i32p, i32p, i64p, i64,
            i32, i32, i32, i32, i32, i32, i32,
        ]
        lib.rh_poa_session_prepare.restype = i32
        lib.rh_poa_session_prepare.argtypes = [
            i64, i32, i32, i32p, i32p, i32p, i32p, i32p, i32p, i32p,
            i8p, i16p, i16p, u8p, i8p,
        ]
        lib.rh_poa_session_commit.restype = None
        lib.rh_poa_session_commit.argtypes = [i64, i32, i32, i32p, i32p,
                                              i32p, i32p]
        lib.rh_poa_session_stats.restype = None
        lib.rh_poa_session_stats.argtypes = [i64, i64p]
        lib.rh_poa_session_finish.restype = i64
        lib.rh_poa_session_finish.argtypes = [i64, i32, u8p, u32p, i64,
                                              i64p, i32p]
        lib.rh_poa_session_free.restype = None
        lib.rh_poa_session_free.argtypes = [i64]
        lib.rh_poa_finish_arrays.restype = i64
        lib.rh_poa_finish_arrays.argtypes = [
            i8p, i16p, i32p, i32p, i16p, i32p, i64,
            i32, i32, i32, u8p, u32p, i64, i64p,
        ]
        _lib = lib
    return _lib


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def _pack_windows(windows):
    """Flatten the poa_batch window layout into the native call arrays."""
    seq_parts, qual_parts = [], []
    seq_off = [0]
    qual_off = [0]
    begins, ends = [], []
    win_off = [0]
    for win in windows:
        for seq, qual, b, e in win:
            seq_parts.append(seq)
            seq_off.append(seq_off[-1] + len(seq))
            if qual is not None:
                qual_parts.append(qual)
                qual_off.append(qual_off[-1] + len(qual))
            else:
                qual_off.append(qual_off[-1])
            begins.append(b)
            ends.append(e)
        win_off.append(win_off[-1] + len(win))
    return (
        np.frombuffer(b"".join(seq_parts) or b"\x00", dtype=np.uint8),
        np.asarray(seq_off, dtype=np.int64),
        np.frombuffer(b"".join(qual_parts) or b"\x00", dtype=np.uint8),
        np.asarray(qual_off, dtype=np.int64),
        np.asarray(begins, dtype=np.int32),
        np.asarray(ends, dtype=np.int32),
        np.asarray(win_off, dtype=np.int64),
    )


class PoaSession:
    """Round-based evolving-graph POA session (the host half of the device
    consensus engine — see native/src/session.cpp and ops/poa_graph.py).

    Lifecycle: construct with the full window batch, then loop
    `prepare()` -> run the returned jobs on device -> `commit()` until
    prepare returns None, then `finish()`.
    """

    def __init__(self, windows, match: int, mismatch: int, gap: int,
                 max_nodes: int, max_pred: int, max_len: int,
                 max_jobs: int = 256, banded_only: bool = False,
                 n_threads: int = 1):
        self._lib = get_lib()
        self.n_windows = len(windows)
        self.max_nodes = max_nodes
        self.max_pred = max_pred
        self.max_len = max_len
        self.max_jobs = max_jobs
        self.n_threads = n_threads
        packed = _pack_windows(windows)
        self._total_seq_bytes = int(packed[1][-1])
        i32, u8 = ctypes.c_int32, ctypes.c_uint8
        self._handle = int(self._lib.rh_poa_session_new(
            _ptr(packed[0], u8), _ptr(packed[1], ctypes.c_int64),
            _ptr(packed[2], u8), _ptr(packed[3], ctypes.c_int64),
            _ptr(packed[4], i32), _ptr(packed[5], i32),
            _ptr(packed[6], ctypes.c_int64), self.n_windows,
            match, mismatch, gap, max_nodes, max_pred, max_len,
            1 if banded_only else 0))
        J, N, P, L = max_jobs, max_nodes, max_pred, max_len
        self._buf = {
            "win": np.empty(J, dtype=np.int32),
            "layer": np.empty(J, dtype=np.int32),
            "band": np.empty(J, dtype=np.int32),
            "nnodes": np.empty(J, dtype=np.int32),
            "len": np.empty(J, dtype=np.int32),
            "origin": np.empty(J, dtype=np.int32),
            "maxpred": np.empty(J, dtype=np.int32),
            "codes": np.empty((J, N), dtype=np.int8),
            "preds": np.empty((J, N, P), dtype=np.int16),
            "centers": np.empty((J, N), dtype=np.int16),
            "sinks": np.empty((J, N), dtype=np.uint8),
            "seqs": np.empty((J, L), dtype=np.int8),
        }

    def prepare(self, max_jobs: int | None = None):
        """Returns a dict of job arrays (buffers reused across calls — the
        caller must consume/copy before the next prepare) with key "n" =
        job count, or None when no window is ready. `max_jobs` limits this
        call (defaults to the buffer capacity) — the scheduler uses it to
        split windows into interleaved half-batches for pipelining."""
        b = self._buf
        i32, i8, u8 = ctypes.c_int32, ctypes.c_int8, ctypes.c_uint8
        i16 = ctypes.c_int16
        want = self.max_jobs if max_jobs is None else min(max_jobs,
                                                          self.max_jobs)
        n = int(self._lib.rh_poa_session_prepare(
            self._handle, want, self.n_threads,
            _ptr(b["win"], i32), _ptr(b["layer"], i32), _ptr(b["band"], i32),
            _ptr(b["nnodes"], i32), _ptr(b["len"], i32),
            _ptr(b["origin"], i32), _ptr(b["maxpred"], i32),
            _ptr(b["codes"], i8), _ptr(b["preds"], i16),
            _ptr(b["centers"], i16), _ptr(b["sinks"], u8),
            _ptr(b["seqs"], i8)))
        if n <= 0:
            return None
        return dict(b, n=n)

    def commit(self, win, layer, band, ranks):
        """Commit device results for one dispatched batch. win/layer/band:
        int32 arrays snapshotted at dispatch; ranks: [n, lb] int32 node
        ranks (-1 insertion)."""
        n = len(win)
        win = np.ascontiguousarray(win, dtype=np.int32)
        layer = np.ascontiguousarray(layer, dtype=np.int32)
        band = np.ascontiguousarray(band, dtype=np.int32)
        full = np.full((n, self.max_len), -2, dtype=np.int32)
        full[:, :ranks.shape[1]] = ranks[:n]
        i32 = ctypes.c_int32
        self._lib.rh_poa_session_commit(
            self._handle, n, self.n_threads, _ptr(win, i32),
            _ptr(layer, i32), _ptr(band, i32), _ptr(full, i32))

    def stats(self) -> dict:
        """Session counters: jobs prepared, layers committed, banded
        clipped->full-DP redos, unfit (host-fallback) windows."""
        out = np.zeros(4, dtype=np.int64)
        self._lib.rh_poa_session_stats(self._handle,
                                       _ptr(out, ctypes.c_int64))
        return {"prepared": int(out[0]), "committed": int(out[1]),
                "redos": int(out[2]), "unfit": int(out[3])}

    def finish(self, n_threads: int = 1):
        """Generate consensus for every window. Returns (results, statuses):
        results like poa_batch's [(consensus bytes, coverages array)];
        statuses[w] = 0 device-built, 1 host fallback, 2 backbone-only."""
        cons_cap = 2 * self._total_seq_bytes + 64 * self.n_windows
        cons_off = np.empty(self.n_windows + 1, dtype=np.int64)
        statuses = np.empty(self.n_windows, dtype=np.int32)
        u8, u32 = ctypes.c_uint8, ctypes.c_uint32
        while True:
            cons_data = np.empty(cons_cap, dtype=np.uint8)
            cov_data = np.empty(cons_cap, dtype=np.uint32)
            total = int(self._lib.rh_poa_session_finish(
                self._handle, n_threads, _ptr(cons_data, u8),
                _ptr(cov_data, u32), cons_cap,
                _ptr(cons_off, ctypes.c_int64),
                _ptr(statuses, ctypes.c_int32)))
            if total >= 0:
                break
            cons_cap = -total
        out = []
        for w in range(self.n_windows):
            a, b = int(cons_off[w]), int(cons_off[w + 1])
            out.append((cons_data[a:b].tobytes(), cov_data[a:b].copy()))
        return out, statuses

    def close(self):
        if self._handle:
            self._lib.rh_poa_session_free(self._handle)
            self._handle = 0

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


def poa_finish_arrays(codes, preds, predw, nseq, col_of, colkey, n_nodes,
                      n_threads: int = 1):
    """Consensus + coverages from the fused device engine's graph arrays
    (ops/poa_fused.py) via the exact host heaviest-bundle
    (rh_poa_finish_arrays). Returns [(consensus bytes, coverages)] per
    window. `colkey` is accepted for interface symmetry (column grouping
    needs only col_of)."""
    lib = get_lib()
    B, N = codes.shape
    P = preds.shape[2]
    codes = np.ascontiguousarray(codes, dtype=np.int8)
    preds = np.ascontiguousarray(preds, dtype=np.int16)
    predw = np.ascontiguousarray(predw, dtype=np.int32)
    nseq = np.ascontiguousarray(nseq, dtype=np.int32)
    col_of = np.ascontiguousarray(col_of, dtype=np.int16)
    n_nodes = np.ascontiguousarray(n_nodes, dtype=np.int32)
    cons_cap = int(n_nodes.sum()) + 64 * B + 64
    cons_off = np.empty(B + 1, dtype=np.int64)
    i8, i16, i32 = ctypes.c_int8, ctypes.c_int16, ctypes.c_int32
    u8, u32 = ctypes.c_uint8, ctypes.c_uint32
    while True:
        cons_data = np.empty(cons_cap, dtype=np.uint8)
        cov_data = np.empty(cons_cap, dtype=np.uint32)
        total = int(lib.rh_poa_finish_arrays(
            _ptr(codes, i8), _ptr(preds, i16), _ptr(predw, i32),
            _ptr(nseq, i32), _ptr(col_of, i16), _ptr(n_nodes, i32),
            B, N, P, n_threads,
            _ptr(cons_data, u8), _ptr(cov_data, u32), cons_cap,
            _ptr(cons_off, ctypes.c_int64)))
        if total >= 0:
            break
        cons_cap = -total
    out = []
    for w in range(B):
        a, b = int(cons_off[w]), int(cons_off[w + 1])
        out.append((cons_data[a:b].tobytes(), cov_data[a:b].copy()))
    return out


class SequenceFile:
    """Streaming native FASTA/FASTQ reader (the bioparser role). Yields
    per-chunk flat buffers; see io/parsers.py for the record wrapper."""

    def __init__(self, path: str, fastq: bool):
        self._lib = get_lib()
        self._path = path
        self._fastq = fastq
        self._handle = self._lib.rh_sf_open(path.encode(), 1 if fastq else 0)
        if not self._handle:
            raise OSError(f"cannot open {path}")

    def chunk(self, max_bytes: int = -1):
        """Returns (records, more) where records is a list of
        (name_bytes, seq_bytes, qual_bytes|None). Raises ValueError on
        malformed input."""
        i32 = ctypes.c_int32
        more = i32(0)
        names = ctypes.POINTER(ctypes.c_uint8)()
        seqs = ctypes.POINTER(ctypes.c_uint8)()
        quals = ctypes.POINTER(ctypes.c_uint8)()
        name_offs = ctypes.POINTER(ctypes.c_int64)()
        seq_offs = ctypes.POINTER(ctypes.c_int64)()
        qual_offs = ctypes.POINTER(ctypes.c_int64)()
        n = self._lib.rh_sf_chunk(
            self._handle, max_bytes, ctypes.byref(more),
            ctypes.byref(names), ctypes.byref(name_offs),
            ctypes.byref(seqs), ctypes.byref(seq_offs),
            ctypes.byref(quals), ctypes.byref(qual_offs))
        if n < 0:
            raise ValueError(f"malformed input {self._path}")
        records = []
        for i in range(n):
            name = ctypes.string_at(
                ctypes.addressof(names.contents) + name_offs[i],
                name_offs[i + 1] - name_offs[i])
            seq = ctypes.string_at(
                ctypes.addressof(seqs.contents) + seq_offs[i],
                seq_offs[i + 1] - seq_offs[i])
            qlen = qual_offs[i + 1] - qual_offs[i]
            qual = (ctypes.string_at(
                ctypes.addressof(quals.contents) + qual_offs[i], qlen)
                if qlen else None)
            records.append((name, seq, qual))
        return records, bool(more.value)

    def close(self):
        if self._handle:
            self._lib.rh_sf_close(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


def _u8(data: bytes | np.ndarray):
    arr = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else data
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), arr


def edit_distance(a: bytes, b: bytes) -> int:
    """Exact edit distance (Myers bit-parallel NW) — the metric role edlib
    plays in reference test/racon_test.cpp:16-25."""
    lib = get_lib()
    pa, ka = _u8(a)
    pb, kb = _u8(b)
    return int(lib.rh_edit_distance(pa, len(a), pb, len(b)))


def nw_cigar(query: bytes, target: bytes) -> bytes:
    """Global alignment CIGAR of query vs target, unit costs — the edlib NW
    path role (reference src/overlap.cpp:205-224)."""
    lib = get_lib()
    pq, kq = _u8(query)
    pt, kt = _u8(target)
    cap = 4 * (len(query) + len(target)) + 64
    buf = ctypes.create_string_buffer(cap)
    n = int(lib.rh_nw_cigar(pq, len(query), pt, len(target), buf, cap))
    if n < 0:
        raise RuntimeError("rh_nw_cigar failed")
    return buf.raw[:n]


def nw_cigar_batch(pairs, n_threads: int = 1, progress=None,
                   chunk: int = 256):
    """Globally align many (query, target) pairs on the host thread pool.

    Returns a list of CIGAR bytes (parallel to `pairs`). `progress(n)` is
    called after each internal chunk completes.
    """
    lib = get_lib()
    out: list[bytes | None] = [None] * len(pairs)
    for s in range(0, len(pairs), chunk):
        part = pairs[s:s + chunk]
        q_off = np.zeros(len(part) + 1, dtype=np.int64)
        t_off = np.zeros(len(part) + 1, dtype=np.int64)
        for i, (q, t) in enumerate(part):
            q_off[i + 1] = q_off[i] + len(q)
            t_off[i + 1] = t_off[i] + len(t)
        q_data = np.frombuffer(b"".join(q for q, _ in part) or b"\x00",
                               dtype=np.uint8)
        t_data = np.frombuffer(b"".join(t for _, t in part) or b"\x00",
                               dtype=np.uint8)
        slot = 4 * int(max(q_off[-1] // max(len(part), 1),
                           t_off[-1] // max(len(part), 1)) + 1) + 64
        lens = np.empty(len(part), dtype=np.int64)
        buf = ctypes.create_string_buffer(slot * len(part))
        lib.rh_nw_cigar_batch(
            q_data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            q_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            t_data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            t_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(part), n_threads, buf, slot,
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        raw = buf.raw
        for i in range(len(part)):
            if lens[i] >= 0:
                out[s + i] = raw[i * slot:i * slot + int(lens[i])]
            else:
                # slot overflow for this pair only: re-align it singly
                out[s + i] = nw_cigar(*part[i])
        if progress is not None:
            progress(len(part))
    return out


def poa_batch(windows, match: int, mismatch: int, gap: int,
              n_threads: int = 1, prealigned=None):
    """Batched per-window POA consensus.

    Args:
      windows: list of windows; each is a list of (seq_bytes, qual_bytes|None,
        begin, end) with element 0 the backbone.
      prealigned: optional list (parallel to windows) of per-layer alignments;
        each window entry is a list (parallel to its sequences, [0] ignored)
        of (nodes int32 array, poss int32 array) or None for "engine-align".
        All-or-nothing per call: either every layer of every window has a
        path, or pass None.

    Returns:
      list of (consensus bytes, coverages uint32 array) per window.
    """
    lib = get_lib()
    n_windows = len(windows)
    if n_windows == 0:
        return []

    (seq_data, seq_off_a, qual_data, qual_off_a, begins_a, ends_a,
     win_off_a) = _pack_windows(windows)

    if prealigned is not None:
        nodes_parts, pos_parts = [], []
        aln_off = [0]
        for w, win in enumerate(windows):
            for i in range(len(win)):
                entry = prealigned[w][i] if i > 0 else None
                if entry is None:
                    aln_off.append(aln_off[-1])
                else:
                    nodes, poss = entry
                    nodes_parts.append(np.asarray(nodes, dtype=np.int32))
                    pos_parts.append(np.asarray(poss, dtype=np.int32))
                    aln_off.append(aln_off[-1] + len(nodes_parts[-1]))
        aln_nodes = (np.concatenate(nodes_parts) if nodes_parts
                     else np.empty(0, dtype=np.int32))
        aln_pos = (np.concatenate(pos_parts) if pos_parts
                   else np.empty(0, dtype=np.int32))
        aln_off_a = np.asarray(aln_off, dtype=np.int64)
        aln_args = (
            aln_nodes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            aln_pos.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            aln_off_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        keep = (aln_nodes, aln_pos, aln_off_a)
    else:
        aln_args = (None, None, None)
        keep = ()

    cons_cap = 2 * int(seq_off_a[-1]) + 64 * n_windows
    cons_off = np.empty(n_windows + 1, dtype=np.int64)
    while True:
        cons_data = np.empty(cons_cap, dtype=np.uint8)
        cov_data = np.empty(cons_cap, dtype=np.uint32)
        total = int(lib.rh_poa_batch(
            seq_data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            seq_off_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            qual_data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            qual_off_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            begins_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ends_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            win_off_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n_windows,
            *aln_args,
            match, mismatch, gap, n_threads,
            cons_data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            cov_data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            cons_cap,
            cons_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ))
        if total >= 0:
            break
        cons_cap = -total
    del keep

    out = []
    for w in range(n_windows):
        a, b = int(cons_off[w]), int(cons_off[w + 1])
        out.append((cons_data[a:b].tobytes(), cov_data[a:b].copy()))
    return out
