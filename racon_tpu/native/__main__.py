"""`python -m racon_tpu.native` — build the native host library ahead of
time (it otherwise builds on first import). `--debug` builds the
ASan+UBSan variant (the reference's sanitizer target, Makefile:23-25);
`--force` rebuilds even when fresh."""

import argparse
import sys

from . import build


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m racon_tpu.native")
    ap.add_argument("--debug", action="store_true",
                    help="ASan+UBSan debug build (libracon_host_debug.so)")
    ap.add_argument("--force", action="store_true",
                    help="rebuild even if up to date")
    args = ap.parse_args(argv)
    path = build(force=args.force, debug=args.debug)
    print(path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
