// C API for the host library (loaded from Python via ctypes —
// no pybind11 dependency, plain C symbols only).
//
// Provides the native-equivalents the reference gets from vendored C++
// libraries (SURVEY.md §2b): spoa -> rh_poa_batch (threaded batched POA),
// edlib -> rh_nw_cigar / rh_edit_distance, thread_pool -> the worker pool
// inside rh_poa_batch.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include "poa.hpp"

namespace racon_host {
int64_t myers_nw(const uint8_t* q, int64_t m, const uint8_t* t, int64_t n,
                 std::vector<char>* cigar);
}  // namespace racon_host

using racon_host::Alignment;
using racon_host::AlnPair;

extern "C" {

int64_t rh_edit_distance(const uint8_t* a, int64_t m, const uint8_t* b,
                         int64_t n) {
    return racon_host::myers_nw(a, m, b, n, nullptr);
}

// Globally align query q against target t (unit costs). Writes the CIGAR
// into `out` (capacity `cap`); returns the CIGAR length, or -needed when the
// buffer is too small, or -1 on failure.
int64_t rh_nw_cigar(const uint8_t* q, int64_t m, const uint8_t* t, int64_t n,
                    char* out, int64_t cap) {
    std::vector<char> cigar;
    const int64_t d = racon_host::myers_nw(q, m, t, n, &cigar);
    if (d < 0) {
        return -1;
    }
    const int64_t len = static_cast<int64_t>(cigar.size());
    if (len > cap) {
        return -len;
    }
    std::memcpy(out, cigar.data(), len);
    return len;
}

// Batched per-window POA consensus (the spoa role in reference
// src/polisher.cpp:491-504, batched like the GPU path cudapolisher.cpp:228-345).
//
// Layout: all sequences of all windows are concatenated; `seq_off` has
// total_seqs + 1 entries; window w owns sequences [win_off[w], win_off[w+1]),
// the first being the backbone. `qual_off[i] == qual_off[i+1]` means "no
// quality" for sequence i. Optional prealigned paths (device alignment
// results) come as flat (node, pos) pair arrays with per-sequence `aln_off`;
// pass aln_off == nullptr to let the host engine align layers itself.
//
// Outputs: consensus bytes concatenated into cons_data with per-window
// cons_off (n_windows + 1), per-base column coverages into cov_data
// (same offsets). Returns total consensus bytes, or -needed when cons_cap
// is too small.
int64_t rh_poa_batch(
    const uint8_t* seq_data, const int64_t* seq_off,
    const uint8_t* qual_data, const int64_t* qual_off,
    const int32_t* begins, const int32_t* ends,
    const int64_t* win_off, int64_t n_windows,
    const int32_t* aln_nodes, const int32_t* aln_pos, const int64_t* aln_off,
    int32_t match, int32_t mismatch, int32_t gap, int32_t n_threads,
    uint8_t* cons_data, uint32_t* cov_data, int64_t cons_cap,
    int64_t* cons_off) {
    std::vector<std::vector<uint8_t>> results(n_windows);
    std::vector<std::vector<uint32_t>> coverages(n_windows);

    std::atomic<int64_t> next(0);
    auto worker = [&]() {
        std::vector<const uint8_t*> seqs, quals;
        std::vector<int32_t> lens;
        std::vector<Alignment> prealigned;
        while (true) {
            const int64_t w = next.fetch_add(1);
            if (w >= n_windows) {
                return;
            }
            const int64_t s0 = win_off[w], s1 = win_off[w + 1];
            const int64_t count = s1 - s0;
            seqs.clear();
            quals.clear();
            lens.clear();
            for (int64_t s = s0; s < s1; ++s) {
                seqs.push_back(seq_data + seq_off[s]);
                lens.push_back(static_cast<int32_t>(seq_off[s + 1] - seq_off[s]));
                quals.push_back(qual_off[s + 1] > qual_off[s]
                                    ? qual_data + qual_off[s]
                                    : nullptr);
            }
            if (count < 3) {
                // backbone fallback (reference window.cpp:68-71); caller
                // normally filters these out
                results[w].assign(seqs[0], seqs[0] + lens[0]);
                coverages[w].assign(lens[0], 0);
                continue;
            }
            const Alignment* pre = nullptr;
            if (aln_off != nullptr) {
                prealigned.assign(count, Alignment());
                for (int64_t s = s0 + 1; s < s1; ++s) {
                    Alignment& a = prealigned[s - s0];
                    for (int64_t k = aln_off[s]; k < aln_off[s + 1]; ++k) {
                        a.push_back(AlnPair{aln_nodes[k], aln_pos[k]});
                    }
                }
                pre = prealigned.data();
            }
            results[w] = racon_host::window_consensus(
                seqs.data(), lens.data(), quals.data(), begins + s0,
                ends + s0, static_cast<int32_t>(count), match, mismatch, gap,
                coverages[w], pre);
        }
    };

    int32_t nt = n_threads > 0 ? n_threads : 1;
    if (nt > n_windows) {
        nt = static_cast<int32_t>(n_windows > 0 ? n_windows : 1);
    }
    if (nt == 1) {
        worker();
    } else {
        std::vector<std::thread> pool;
        pool.reserve(nt);
        for (int32_t i = 0; i < nt; ++i) {
            pool.emplace_back(worker);
        }
        for (auto& th : pool) {
            th.join();
        }
    }

    int64_t total = 0;
    for (int64_t w = 0; w < n_windows; ++w) {
        total += static_cast<int64_t>(results[w].size());
    }
    if (total > cons_cap) {
        return -total;
    }
    int64_t at = 0;
    for (int64_t w = 0; w < n_windows; ++w) {
        cons_off[w] = at;
        std::memcpy(cons_data + at, results[w].data(), results[w].size());
        std::memcpy(cov_data + at, coverages[w].data(),
                    coverages[w].size() * sizeof(uint32_t));
        at += static_cast<int64_t>(results[w].size());
    }
    cons_off[n_windows] = at;
    return total;
}

// Threaded batch variant of rh_nw_cigar: aligns pairs[i] = (q, t) given by
// flat data + offsets, writing CIGARs into per-pair slots of `out`
// (stride `slot`). out_lens[i] receives the CIGAR length, or -needed when
// the slot is too small (caller retries that pair with a bigger buffer).
// The host-parallel analogue of the reference's pooled edlib fan-out
// (src/polisher.cpp:462-470).
void rh_nw_cigar_batch(const uint8_t* q_data, const int64_t* q_off,
                       const uint8_t* t_data, const int64_t* t_off,
                       int64_t n_pairs, int32_t n_threads, char* out,
                       int64_t slot, int64_t* out_lens) {
    std::atomic<int64_t> next(0);
    auto worker = [&]() {
        std::vector<char> cigar;
        while (true) {
            const int64_t i = next.fetch_add(1);
            if (i >= n_pairs) {
                return;
            }
            const int64_t m = q_off[i + 1] - q_off[i];
            const int64_t n = t_off[i + 1] - t_off[i];
            const int64_t d = racon_host::myers_nw(
                q_data + q_off[i], m, t_data + t_off[i], n, &cigar);
            if (d < 0) {
                out_lens[i] = -1;
                continue;
            }
            const int64_t len = static_cast<int64_t>(cigar.size());
            if (len > slot) {
                out_lens[i] = -len;
                continue;
            }
            std::memcpy(out + i * slot, cigar.data(), len);
            out_lens[i] = len;
        }
    };
    int32_t nt = n_threads > 0 ? n_threads : 1;
    if (nt > n_pairs) {
        nt = static_cast<int32_t>(n_pairs > 0 ? n_pairs : 1);
    }
    if (nt == 1) {
        worker();
    } else {
        std::vector<std::thread> pool;
        for (int32_t i = 0; i < nt; ++i) {
            pool.emplace_back(worker);
        }
        for (auto& th : pool) {
            th.join();
        }
    }
}

int32_t rh_version() { return 2; }

}  // extern "C"
