// Myers bit-parallel global (NW) alignment: exact edit distance + CIGAR.
//
// The edlib role in the reference (src/overlap.cpp:205-224 uses edlib's
// banded Myers NW with CIGAR path; test/racon_test.cpp:16-25 uses it for
// edit-distance scoring). This is a from-scratch implementation of the
// Myers/Hyyrö block algorithm: the DP column is packed into 64-bit
// delta vectors (Pv/Mv), one column update costs ceil(m/64) word ops, and
// the traceback replays checkpointed columns so memory stays
// O(m/64 * (n/K + K)) instead of O(m*n).
//
// Deterministic tie order during traceback: diagonal, then up (I, consumes
// query), then left (D, consumes target).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace racon_host {

// Append "<len><op>" to dst.
void emit_cigar_run(std::vector<char>& dst, int64_t len, char op) {
    if (len <= 0) return;
    char buf[24];
    int k = 0;
    while (len > 0) {
        buf[k++] = static_cast<char>('0' + len % 10);
        len /= 10;
    }
    while (k > 0) dst.push_back(buf[--k]);
    dst.push_back(op);
}

namespace {

constexpr uint64_t kHigh = 1ull << 63;

struct BlockState {
    uint64_t Pv;     // bit r: D[r][j] - D[r-1][j] == +1
    uint64_t Mv;     // bit r: D[r][j] - D[r-1][j] == -1
    int32_t score;   // D at the block's bottom row
};

// One Hyyrö block update. hin is the horizontal delta entering the block's
// top row (-1/0/+1); returns the delta leaving the bottom row.
inline int block_step(uint64_t Eq, int hin, uint64_t& Pv, uint64_t& Mv) {
    const uint64_t Xv = Eq | Mv;
    if (hin < 0) {
        Eq |= 1ull;
    }
    const uint64_t Xh = (((Eq & Pv) + Pv) ^ Pv) | Eq;
    uint64_t Ph = Mv | ~(Xh | Pv);
    uint64_t Mh = Pv & Xh;
    int hout = 0;
    if (Ph & kHigh) {
        hout = 1;
    } else if (Mh & kHigh) {
        hout = -1;
    }
    Ph <<= 1;
    Mh <<= 1;
    if (hin < 0) {
        Mh |= 1ull;
    } else if (hin > 0) {
        Ph |= 1ull;
    }
    Pv = Mh | ~(Xv | Ph);
    Mv = Ph & Xv;
    return hout;
}

// Score at pattern row `row` (1-based, <= 64*nb) given a column's blocks.
inline int32_t score_at_row(const BlockState* col, int64_t row, int64_t nb) {
    const int64_t b = (row - 1) / 64;
    int32_t s = col[b].score;
    // walk up from the block's bottom row to `row`
    for (int64_t r = 64 * (b + 1); r > row; --r) {
        const uint64_t bit = 1ull << ((r - 1) & 63);
        if (col[b].Pv & bit) {
            s -= 1;
        } else if (col[b].Mv & bit) {
            s += 1;
        }
    }
    return s;
}

}  // namespace

// Exact NW edit distance of q (length m) vs t (length n); when `cigar` is
// non-null the CIGAR path is appended (I consumes query, D consumes target).
int64_t myers_nw(const uint8_t* q, int64_t m, const uint8_t* t, int64_t n,
                 std::vector<char>* cigar) {
    if (cigar != nullptr) {
        cigar->clear();
    }
    if (m == 0 || n == 0) {
        if (cigar != nullptr) {
            if (m > 0) emit_cigar_run(*cigar, m, 'I');
            if (n > 0) emit_cigar_run(*cigar, n, 'D');
        }
        return m + n;
    }

    const int64_t nb = (m + 63) / 64;  // blocks per column

    // Exact-byte alphabet: each distinct byte of q gets a class; target
    // bytes absent from q match nothing (Eq = 0, class = n_classes slot of
    // zeros). Matches the scalar DP / edlib semantics of raw byte equality.
    int cls_of[256];
    std::fill(cls_of, cls_of + 256, -1);
    int n_classes = 0;
    for (int64_t i = 0; i < m; ++i) {
        if (cls_of[q[i]] < 0) {
            cls_of[q[i]] = n_classes++;
        }
    }
    std::vector<uint64_t> peq(static_cast<size_t>(n_classes + 1) * nb, 0);
    for (int64_t i = 0; i < m; ++i) {
        peq[static_cast<size_t>(cls_of[q[i]]) * nb + (i >> 6)] |=
            1ull << (i & 63);
    }
    auto code_of = [&](uint8_t c) -> int {
        const int k = cls_of[c];
        return k < 0 ? n_classes : k;  // n_classes row is all zeros
    };

    std::vector<BlockState> cur(nb);
    for (int64_t b = 0; b < nb; ++b) {
        cur[b].Pv = ~0ull;
        cur[b].Mv = 0;
        cur[b].score = static_cast<int32_t>(64 * (b + 1));
    }

    const int64_t kCheckpoint = 128;  // columns between stored snapshots
    std::vector<BlockState> snaps;    // column 0, K, 2K, ... (col 0 included)
    const bool want_path = cigar != nullptr;
    if (want_path) {
        snaps.reserve(static_cast<size_t>((n / kCheckpoint + 2) * nb));
        snaps.insert(snaps.end(), cur.begin(), cur.end());
    }

    for (int64_t j = 1; j <= n; ++j) {
        const int c = code_of(t[j - 1]);
        int hin = 1;  // D[0][j] - D[0][j-1] = +1
        for (int64_t b = 0; b < nb; ++b) {
            const uint64_t Eq = peq[static_cast<size_t>(c) * nb + b];
            const int hout = block_step(Eq, hin, cur[b].Pv, cur[b].Mv);
            cur[b].score += hout;
            hin = hout;
        }
        if (want_path && j % kCheckpoint == 0) {
            snaps.insert(snaps.end(), cur.begin(), cur.end());
        }
    }

    const int64_t dist = score_at_row(cur.data(), m, nb);
    if (!want_path) {
        return dist;
    }

    // -- traceback over replayed segments ---------------------------------
    // A segment holds kCheckpoint + 1 consecutive columns [seg_lo,
    // seg_lo + kCheckpoint] so that any (j-1, j) pair the traceback touches
    // fits in one loaded segment; consecutive segments overlap by a column.
    std::vector<BlockState> cols;
    int64_t seg_lo = -1, seg_hi = -1;

    auto load_segment = [&](int64_t lo) {
        seg_lo = lo;
        seg_hi = std::min(n, lo + kCheckpoint);
        cols.assign(static_cast<size_t>(seg_hi - seg_lo + 1) * nb,
                    BlockState{});
        // start from snapshot at column lo (lo is a multiple of K)
        const BlockState* snap = snaps.data() + (lo / kCheckpoint) * nb;
        std::copy(snap, snap + nb, cols.begin());
        std::vector<BlockState> col(snap, snap + nb);
        for (int64_t j = lo + 1; j <= seg_hi; ++j) {
            const int c = code_of(t[j - 1]);
            int hin = 1;
            for (int64_t b = 0; b < nb; ++b) {
                const uint64_t Eq = peq[static_cast<size_t>(c) * nb + b];
                const int hout = block_step(Eq, hin, col[b].Pv, col[b].Mv);
                col[b].score += hout;
                hin = hout;
            }
            std::copy(col.begin(), col.end(),
                      cols.begin() + static_cast<size_t>(j - seg_lo) * nb);
        }
    };

    auto cell = [&](int64_t i, int64_t j) -> int32_t {
        // D[i][j] for j within the loaded segment; i is 0-based row count
        if (i == 0) {
            return static_cast<int32_t>(j);
        }
        const BlockState* col = cols.data() +
                                static_cast<size_t>(j - seg_lo) * nb;
        return score_at_row(col, i, nb);
    };

    std::vector<char> rev_ops;
    rev_ops.reserve(m + n);
    int64_t i = m, j = n;
    load_segment((n > 0 ? (n - 1) / kCheckpoint : 0) * kCheckpoint);
    while (i > 0 || j > 0) {
        if (i == 0) {
            rev_ops.push_back('D');
            --j;
            continue;
        }
        if (j == 0) {
            rev_ops.push_back('I');
            --i;
            continue;
        }
        // need both columns j-1 and j loaded
        if (j - 1 < seg_lo) {
            load_segment((j - 1) / kCheckpoint * kCheckpoint);
        }
        const int32_t v = cell(i, j);
        const int32_t diag = cell(i - 1, j - 1);
        const int sub = (q[i - 1] == t[j - 1]) ? 0 : 1;
        if (diag + sub == v) {
            rev_ops.push_back('M');
            --i;
            --j;
            continue;
        }
        if (cell(i - 1, j) + 1 == v) {
            rev_ops.push_back('I');
            --i;
            continue;
        }
        rev_ops.push_back('D');
        --j;
    }

    char last = 0;
    int64_t run = 0;
    for (int64_t s = static_cast<int64_t>(rev_ops.size()) - 1; s >= 0; --s) {
        if (rev_ops[s] == last) {
            ++run;
        } else {
            emit_cigar_run(*cigar, run, last);
            last = rev_ops[s];
            run = 1;
        }
    }
    emit_cigar_run(*cigar, run, last);
    return dist;
}

}  // namespace racon_host
