// Adaptive-band global alignment (edit distance + CIGAR) on the host.
//
// The host-exact-aligner role edlib plays in the reference
// (src/overlap.cpp:205-224: NW mode, unit costs, CIGAR path): used as the
// fallback for pairs the device aligner rejects (too long / band overflow),
// mirroring the reference's GPU->CPU fallback (src/cuda/cudapolisher.cpp:203-213).
//
// Algorithm: banded NW over a band of half-width `hw` centered on the main
// diagonal j == i. If the computed distance d satisfies d <= hw, every cell
// of an optimal path has |i - j| <= d <= hw, i.e. the path never leaves the
// band and the result is exact (Ukkonen's condition); otherwise the band is
// doubled and the DP re-run. 2-bit backpointers are stored per row for the
// traceback. Deterministic tie order: diagonal < up (I) < left (D).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace racon_host {

namespace {
constexpr int32_t kInf = 1 << 29;
enum : uint8_t { BP_DIAG = 0, BP_UP = 1, BP_LEFT = 2 };
}  // namespace

// Banded DP. Returns distance, or -1 if the band was inconclusive.
// When `bp` is non-null it receives packed 2-bit backpointers,
// (m + 1) rows x band cells (4 per byte).
static int64_t banded_pass(const uint8_t* q, int64_t m, const uint8_t* t,
                           int64_t n, int64_t hw, std::vector<uint8_t>* bp,
                           int64_t* band_out) {
    const int64_t band = 2 * hw + 1;
    if (band_out != nullptr) {
        *band_out = band;
    }
    const int64_t bpb = (band + 3) / 4;  // bytes per row
    if (bp != nullptr) {
        bp->assign(static_cast<size_t>(m + 1) * bpb, 0);
    }

    // row i covers columns j in [i - hw, i + hw]
    std::vector<int32_t> prev(band, kInf), cur(band, kInf);
    for (int64_t k = 0; k <= std::min(hw, n); ++k) {
        prev[hw + k] = static_cast<int32_t>(k);  // row 0: D[0][j] = j
        if (bp != nullptr && k > 0) {
            (*bp)[static_cast<size_t>(hw + k) >> 2] |=
                BP_LEFT << (((hw + k) & 3) * 2);
        }
    }

    for (int64_t i = 1; i <= m; ++i) {
        uint8_t* row_bp =
            bp != nullptr ? bp->data() + static_cast<size_t>(i) * bpb : nullptr;
        const int64_t lo = std::max<int64_t>(0, i - hw);
        const int64_t hi = std::min(n, i + hw);
        std::fill(cur.begin(), cur.end(), kInf);
        for (int64_t j = lo; j <= hi; ++j) {
            const int64_t k = j - i + hw;  // band cell for (i, j)
            // neighbors: diag (i-1, j-1) -> prev[k]; up (i-1, j) -> prev[k+1];
            // left (i, j-1) -> cur[k-1]
            int32_t best;
            uint8_t code;
            if (j > 0) {
                best = prev[k] + (q[i - 1] != t[j - 1] ? 1 : 0);
                code = BP_DIAG;
            } else {
                best = kInf;
                code = BP_UP;
            }
            if (k + 1 < band) {
                const int32_t up = prev[k + 1] + 1;
                if (up < best) {
                    best = up;
                    code = BP_UP;
                }
            }
            if (j > 0 && k >= 1) {
                const int32_t left = cur[k - 1] + 1;
                if (left < best) {
                    best = left;
                    code = BP_LEFT;
                }
            }
            cur[k] = best;
            if (row_bp != nullptr) {
                row_bp[k >> 2] |= code << ((k & 3) * 2);
            }
        }
        std::swap(prev, cur);
    }

    const int64_t k_end = n - m + hw;
    if (k_end < 0 || k_end >= band) {
        return -1;
    }
    const int64_t d = prev[k_end];
    if (d > hw) {
        return -1;  // band may have clipped the optimum
    }
    return d;
}

// Append "<len><op>" to dst.
static void emit_run(std::vector<char>& dst, int64_t len, char op) {
    if (len <= 0) return;
    char buf[24];
    int k = 0;
    while (len > 0) {
        buf[k++] = static_cast<char>('0' + len % 10);
        len /= 10;
    }
    while (k > 0) dst.push_back(buf[--k]);
    dst.push_back(op);
}

int64_t nw_align(const uint8_t* q, int64_t m, const uint8_t* t, int64_t n,
                 std::vector<char>* cigar) {
    if (m == 0 || n == 0) {
        if (cigar != nullptr) {
            cigar->clear();
            if (m > 0) emit_run(*cigar, m, 'I');
            if (n > 0) emit_run(*cigar, n, 'D');
        }
        return m + n;
    }

    int64_t hw = std::max<int64_t>({16, std::max(m, n) / 64,
                                    std::llabs(m - n) + 8});
    std::vector<uint8_t> bp;
    int64_t band = 0, d = -1;
    const int64_t hw_cap = m + n;
    while (true) {
        d = banded_pass(q, m, t, n, hw, cigar != nullptr ? &bp : nullptr,
                        &band);
        if (d >= 0 || hw >= hw_cap) {
            break;
        }
        hw = std::min(hw * 2, hw_cap);
    }
    if (d < 0) {
        return -1;  // cannot happen with hw == m + n, defensive
    }
    if (cigar == nullptr) {
        return d;
    }

    // traceback
    cigar->clear();
    const int64_t bpb = (band + 3) / 4;
    std::vector<char> rev_ops;
    rev_ops.reserve(m + n);
    int64_t i = m, j = n;
    while (i > 0 || j > 0) {
        uint8_t code;
        if (i == 0) {
            code = BP_LEFT;
        } else if (j == 0) {
            code = BP_UP;
        } else {
            const int64_t k = j - i + hw;
            code = (bp[static_cast<size_t>(i) * bpb + (k >> 2)] >>
                    ((k & 3) * 2)) & 3;
        }
        switch (code) {
            case BP_DIAG:
                rev_ops.push_back('M');
                --i;
                --j;
                break;
            case BP_UP:
                rev_ops.push_back('I');
                --i;
                break;
            default:
                rev_ops.push_back('D');
                --j;
                break;
        }
    }
    // run-length encode in forward order
    char last = 0;
    int64_t run = 0;
    for (int64_t s = static_cast<int64_t>(rev_ops.size()) - 1; s >= 0; --s) {
        if (rev_ops[s] == last) {
            ++run;
        } else {
            emit_run(*cigar, run, last);
            last = rev_ops[s];
            run = 1;
        }
    }
    emit_run(*cigar, run, last);
    return d;
}

int64_t edit_distance(const uint8_t* a, int64_t m, const uint8_t* b,
                      int64_t n) {
    return nw_align(a, m, b, n, nullptr);
}

}  // namespace racon_host
