// Native streaming FASTA/FASTQ loader — the data-loader role the reference
// gets from the vendored bioparser library (used at src/polisher.cpp:86-99,
// 202-203, 229-231 with 1 GiB chunking). zlib's gzFile layer reads both
// plain and gzipped files transparently; records are tokenized here and
// exposed to Python as flat byte buffers + offset arrays, so the Python
// side only slices (no per-line Python work on multi-GiB read sets).
//
// Contract details matched to the reference's bioparser: record name is
// the header's first whitespace-delimited token; FASTA data may wrap over
// any number of lines; FASTQ is the wrapped variant (sequence lines until
// the '+' separator, quality lines until their total length reaches the
// sequence length).

#include <zlib.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr int64_t kReadBuf = 1 << 20;

struct SeqFile {
    gzFile file = nullptr;
    std::string path;
    bool fastq = false;
    bool eof = false;
    bool failed = false;

    // line reader
    std::vector<char> buf;
    int64_t buf_pos = 0;
    int64_t buf_len = 0;
    std::string pending;   // pushed-back header line
    bool has_pending = false;

    // current chunk's record storage
    std::vector<uint8_t> names, seqs, quals;
    std::vector<int64_t> name_offs{0}, seq_offs{0}, qual_offs{0};

    bool fill() {
        if (buf.empty()) {
            buf.resize(kReadBuf);
        }
        const int n = gzread(file, buf.data(), static_cast<unsigned>(kReadBuf));
        if (n < 0) {
            failed = true;  // decompression error (corrupt stream)
            return false;
        }
        if (n == 0) {
            // distinguish clean EOF from a truncated gzip stream: zlib only
            // sets gzeof after the end-of-stream marker was seen
            if (!gzeof(file)) {
                failed = true;
            }
            return false;
        }
        buf_pos = 0;
        buf_len = n;
        return true;
    }

    // next line without trailing \r\n; false at EOF
    bool next_line(std::string& line) {
        if (has_pending) {
            line.swap(pending);
            has_pending = false;
            return true;
        }
        line.clear();
        while (true) {
            if (buf_pos >= buf_len) {
                if (!fill()) {
                    return !line.empty();
                }
            }
            const char* start = buf.data() + buf_pos;
            const char* nl = static_cast<const char*>(
                memchr(start, '\n', buf_len - buf_pos));
            if (nl == nullptr) {
                line.append(start, buf_len - buf_pos);
                buf_pos = buf_len;
                continue;
            }
            line.append(start, nl - start);
            buf_pos += (nl - start) + 1;
            while (!line.empty() &&
                   (line.back() == '\r' || line.back() == ' ' ||
                    line.back() == '\t')) {
                line.pop_back();
            }
            return true;
        }
    }

    void push_back_line(std::string& line) {
        pending.swap(line);
        has_pending = true;
    }
};

void append_name(SeqFile* h, const std::string& header) {
    // first whitespace-delimited token after the marker char
    size_t end = 1;
    while (end < header.size() && header[end] != ' ' && header[end] != '\t') {
        ++end;
    }
    h->names.insert(h->names.end(), header.begin() + 1, header.begin() + end);
    h->name_offs.push_back(static_cast<int64_t>(h->names.size()));
}

// Returns payload bytes appended, or -1 on malformed input, 0 at EOF.
int64_t read_record(SeqFile* h) {
    std::string line;
    do {
        if (!h->next_line(line)) {
            if (h->failed) {
                return -1;  // corrupt/truncated input, not a clean EOF
            }
            h->eof = true;
            return 0;
        }
    } while (line.empty());

    const char marker = h->fastq ? '@' : '>';
    if (line[0] != marker) {
        return -1;
    }
    append_name(h, line);
    const size_t seq_start = h->seqs.size();

    if (!h->fastq) {
        while (h->next_line(line)) {
            if (line.empty()) {
                continue;
            }
            if (line[0] == '>') {
                h->push_back_line(line);
                break;
            }
            h->seqs.insert(h->seqs.end(), line.begin(), line.end());
        }
        h->seq_offs.push_back(static_cast<int64_t>(h->seqs.size()));
        h->qual_offs.push_back(h->qual_offs.back());
        const int64_t n = static_cast<int64_t>(h->seqs.size() - seq_start);
        return n > 0 ? n : -1;
    }

    // FASTQ: sequence until '+', quality until length matches
    bool saw_plus = false;
    while (h->next_line(line)) {
        if (line.empty()) {
            continue;
        }
        if (line[0] == '+') {
            saw_plus = true;
            break;
        }
        h->seqs.insert(h->seqs.end(), line.begin(), line.end());
    }
    const int64_t seq_len = static_cast<int64_t>(h->seqs.size() - seq_start);
    if (!saw_plus || seq_len == 0) {
        return -1;
    }
    const size_t qual_start = h->quals.size();
    while (static_cast<int64_t>(h->quals.size() - qual_start) < seq_len) {
        if (!h->next_line(line)) {
            return -1;
        }
        h->quals.insert(h->quals.end(), line.begin(), line.end());
    }
    if (static_cast<int64_t>(h->quals.size() - qual_start) != seq_len) {
        return -1;
    }
    h->seq_offs.push_back(static_cast<int64_t>(h->seqs.size()));
    h->qual_offs.push_back(static_cast<int64_t>(h->quals.size()));
    return 2 * seq_len;
}

}  // namespace

extern "C" {

void* rh_sf_open(const char* path, int32_t is_fastq) {
    gzFile f = gzopen(path, "rb");
    if (f == nullptr) {
        return nullptr;
    }
    gzbuffer(f, 1 << 20);
    auto* h = new SeqFile();
    h->file = f;
    h->path = path;
    h->fastq = is_fastq != 0;
    return h;
}

// Parse up to ~max_bytes of payload (-1 = all). Returns the number of
// records in this chunk, or -1 on malformed input. *more = 1 when the file
// has further records. Buffer pointers stay valid until the next call.
int64_t rh_sf_chunk(void* handle, int64_t max_bytes, int32_t* more,
                    const uint8_t** names, const int64_t** name_offs,
                    const uint8_t** seqs, const int64_t** seq_offs,
                    const uint8_t** quals, const int64_t** qual_offs) {
    auto* h = static_cast<SeqFile*>(handle);
    h->names.clear();
    h->seqs.clear();
    h->quals.clear();
    h->name_offs.assign(1, 0);
    h->seq_offs.assign(1, 0);
    h->qual_offs.assign(1, 0);

    int64_t total = 0;
    int64_t n_records = 0;
    while (!h->eof && (max_bytes < 0 || total < max_bytes)) {
        const int64_t n = read_record(h);
        if (n < 0 || h->failed) {
            h->failed = true;
            return -1;
        }
        if (n == 0) {
            break;
        }
        total += n;
        ++n_records;
    }
    *more = h->eof ? 0 : 1;
    *names = h->names.data();
    *name_offs = h->name_offs.data();
    *seqs = h->seqs.data();
    *seq_offs = h->seq_offs.data();
    *quals = h->quals.data();
    *qual_offs = h->qual_offs.data();
    return n_records;
}

void rh_sf_close(void* handle) {
    auto* h = static_cast<SeqFile*>(handle);
    if (h == nullptr) {
        return;
    }
    if (h->file != nullptr) {
        gzclose(h->file);
    }
    delete h;
}

}  // extern "C"
