#include "poa.hpp"

#include <algorithm>
#include <cassert>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <limits>

namespace racon_host {

static uint8_t make_code(int c) {
    switch (c) {
        case 'A': return 0;
        case 'C': return 1;
        case 'G': return 2;
        case 'T': return 3;
        default: return 4;
    }
}

const uint8_t kBaseCode[256] = {
    4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,4, 4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,
    4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,4, 4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,
    4,0,4,1,4,4,4,2,4,4,4,4,4,4,4,4, 4,4,4,4,3,4,4,4,4,4,4,4,4,4,4,4,
    4,0,4,1,4,4,4,2,4,4,4,4,4,4,4,4, 4,4,4,4,3,4,4,4,4,4,4,4,4,4,4,4,
    4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,4, 4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,
    4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,4, 4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,
    4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,4, 4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,
    4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,4, 4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,4,
};
const char kCodeBase[6] = {'A', 'C', 'G', 'T', 'N', '-'};

int32_t Graph::add_node(uint8_t code, int32_t bpos) {
    nodes.push_back(Node{code, bpos, 0, {}, {}, {}});
    return static_cast<int32_t>(nodes.size()) - 1;
}

void Graph::add_edge(int32_t tail, int32_t head, int64_t weight) {
    // merge with an existing parallel edge (in-degrees are small)
    for (int32_t ei : nodes[head].in) {
        if (edges[ei].tail == tail) {
            edges[ei].weight += weight;
            return;
        }
    }
    int32_t ei = static_cast<int32_t>(edges.size());
    edges.push_back(Edge{tail, head, weight});
    nodes[tail].out.push_back(ei);
    nodes[head].in.push_back(ei);
}

void Graph::add_alignment(const Alignment& aln, const uint8_t* seq,
                          int32_t len, const uint32_t* weights,
                          bool anchored) {
    if (len <= 0) {
        return;
    }
    const bool backbone = nodes.empty();

    // Build the per-position node path, then connect consecutive path nodes
    // with edges weighted w[i-1] + w[i] (the endpoint-weight-sum convention
    // the reference GPU adapter mirrors with Phred int8 weights,
    // src/cuda/cudabatch.cpp:182-191).
    std::vector<int32_t> path(len, -1);

    int32_t first = -1, last = -1;
    for (const auto& p : aln) {
        if (p.pos >= 0) {
            if (first < 0) first = p.pos;
            last = p.pos;
        }
    }

    if (first < 0) {
        // no aligned bases: whole sequence becomes a fresh path
        for (int32_t i = 0; i < len; ++i) {
            path[i] = add_node(kBaseCode[seq[i]], backbone ? i : 0);
        }
    } else {
        // aligned middle
        int32_t col_bpos = 0;  // bpos of the last visited column
        bool col_seen = false;
        int32_t ins_offset = 0;  // consecutive insertions since last column
        for (const auto& p : aln) {
            if (p.pos < 0) {
                continue;
            }
            const uint8_t code = kBaseCode[seq[p.pos]];
            int32_t cur;
            if (p.node < 0) {
                if (anchored) {
                    // merge with identical insertions from earlier layers:
                    // key = (anchor column, run offset, base code)
                    const int64_t col_key =
                        ((static_cast<int64_t>(col_seen ? col_bpos : -1)
                          << 20) |
                         static_cast<int64_t>(ins_offset));
                    const int64_t key = (col_key << 8) | code;
                    auto it = ins_node_.find(key);
                    if (it != ins_node_.end()) {
                        cur = it->second;
                    } else {
                        cur = add_node(code, col_seen ? col_bpos : -1);
                        ins_node_.emplace(key, cur);
                        // register same-anchor different-code nodes as one
                        // column so coverage counting sees them together
                        std::vector<int32_t>& col = ins_col_[col_key];
                        for (int32_t a : col) {
                            nodes[a].aligned.push_back(cur);
                            nodes[cur].aligned.push_back(a);
                        }
                        col.push_back(cur);
                    }
                    ++ins_offset;
                } else {
                    // insertion relative to the graph
                    cur = add_node(code, col_seen ? col_bpos : -1);
                }
            } else {
                ins_offset = 0;
                Node& q = nodes[p.node];
                col_bpos = q.bpos;
                col_seen = true;
                if (q.code == code) {
                    cur = p.node;
                } else {
                    cur = -1;
                    for (int32_t a : q.aligned) {
                        if (nodes[a].code == code) {
                            cur = a;
                            break;
                        }
                    }
                    if (cur < 0) {
                        cur = add_node(code, q.bpos);
                        // register in the column: cur <-> node and all its
                        // aligned alternates
                        std::vector<int32_t> column = nodes[p.node].aligned;
                        column.push_back(p.node);
                        for (int32_t a : column) {
                            nodes[a].aligned.push_back(cur);
                            nodes[cur].aligned.push_back(a);
                        }
                    }
                }
            }
            path[p.pos] = cur;
        }
        // backfill bpos for leading insertions that preceded any column
        if (col_seen) {
            int32_t fill = -1;
            for (int32_t i = last; i >= first; --i) {
                if (path[i] >= 0 && nodes[path[i]].bpos >= 0) {
                    fill = nodes[path[i]].bpos;
                } else if (path[i] >= 0 && nodes[path[i]].bpos < 0) {
                    nodes[path[i]].bpos = fill;
                }
            }
        }
        // unaligned prefix / suffix become fresh chains inheriting the bpos
        // of the nearest aligned column
        int32_t pre_bpos = path[first] >= 0 ? nodes[path[first]].bpos : 0;
        for (int32_t i = 0; i < first; ++i) {
            path[i] = add_node(kBaseCode[seq[i]], pre_bpos);
        }
        int32_t suf_bpos = path[last] >= 0 ? nodes[path[last]].bpos : 0;
        for (int32_t i = last + 1; i < len; ++i) {
            path[i] = add_node(kBaseCode[seq[i]], suf_bpos);
        }
    }

    for (int32_t i = 0; i < len; ++i) {
        nodes[path[i]].n_seqs += 1;
    }
    for (int32_t i = 1; i < len; ++i) {
        const int64_t w = static_cast<int64_t>(weights[i - 1]) + weights[i];
        add_edge(path[i - 1], path[i], w);
    }
}

std::vector<int32_t> Graph::topo_order() const {
    const int32_t n = static_cast<int32_t>(nodes.size());
    std::vector<int32_t> indeg(n);
    for (int32_t i = 0; i < n; ++i) {
        indeg[i] = static_cast<int32_t>(nodes[i].in.size());
    }
    std::deque<int32_t> q;
    for (int32_t i = 0; i < n; ++i) {
        if (indeg[i] == 0) q.push_back(i);
    }
    std::vector<int32_t> order;
    order.reserve(n);
    while (!q.empty()) {
        int32_t v = q.front();
        q.pop_front();
        order.push_back(v);
        for (int32_t ei : nodes[v].out) {
            int32_t h = edges[ei].head;
            if (--indeg[h] == 0) q.push_back(h);
        }
    }
    assert(static_cast<int32_t>(order.size()) == n && "graph has a cycle");
    return order;
}

static constexpr int32_t kNegInf = std::numeric_limits<int32_t>::min() / 4;

// DP + traceback body, templated on the score cell type: int16_t halves
// the memory traffic and doubles the SIMD lane count of the hot loops when
// the score bounds allow it (checked by align_nw); int32_t otherwise. The
// clamp to neg_inf in the fold loops stops unreachable-cell drift from
// wrapping the narrow type; reachable scores and the traceback are
// bit-identical between the two instantiations.
template <typename S>
static Alignment align_nw_impl(const Graph& g, const uint8_t* seq,
                               int32_t len, int32_t match, int32_t mismatch,
                               int32_t gap, int32_t band,
                               int32_t bpos_origin, S neg_inf) {
    Alignment out;
    const std::vector<Node>& nodes = g.nodes;
    const std::vector<Edge>& edges = g.edges;
    const int32_t n = static_cast<int32_t>(nodes.size());

    const std::vector<int32_t> order = g.topo_order();
    std::vector<int32_t> rank_of(n);
    for (int32_t r = 0; r < n; ++r) {
        rank_of[order[r]] = r;
    }

    // H is (n + 1) x (len + 1); row 0 is the virtual source.
    const int64_t stride = len + 1;
    std::vector<S> H(static_cast<size_t>(n + 1) * stride);
    for (int32_t j = 0; j <= len; ++j) {
        H[j] = static_cast<S>(j * gap);
    }

    // per-code substitution profiles hoisted out of the DP loops (the
    // striped-profile idea SIMD POA engines use): profile[c][j] is the
    // diagonal score delta for aligning seq[j-1] to a code-c node, so the
    // inner loops below are branchless and auto-vectorize.
    std::vector<S> profile(static_cast<size_t>(5) * stride);
    for (int32_t c = 0; c < 5; ++c) {
        S* p = &profile[static_cast<size_t>(c) * stride];
        for (int32_t j = 1; j <= len; ++j) {
            p[j] = static_cast<S>((kBaseCode[seq[j - 1]] == c) ? match
                                                               : mismatch);
        }
    }
    const S sgap = static_cast<S>(gap);

    std::vector<int32_t> pred_rows;  // predecessor row indices, reused
    for (int32_t r = 1; r <= n; ++r) {
        const Node& node = nodes[order[r - 1]];
        S* row = &H[static_cast<size_t>(r) * stride];
        const S* prof = &profile[static_cast<size_t>(node.code) * stride];

        // banded: compute only columns near the node's expected diagonal;
        // everything else scores -inf (cheap vector fill vs DP compute)
        int32_t jlo = 1, jhi = len;
        if (band > 0) {
            const int32_t center = node.bpos - bpos_origin + 1;
            jlo = std::max<int32_t>(1, center - band / 2);
            jhi = std::min<int32_t>(len, center + band / 2);
            std::fill(row, row + stride, neg_inf);
        }

        pred_rows.clear();
        for (int32_t ei : node.in) {
            pred_rows.push_back(rank_of[edges[ei].tail] + 1);
        }
        if (pred_rows.empty()) {
            pred_rows.push_back(0);
        }

        // initialize from the first predecessor, then fold the rest in
        {
            const S* prow = &H[static_cast<size_t>(pred_rows[0]) * stride];
            row[0] = static_cast<S>(prow[0] + sgap);
            for (int32_t j = jlo; j <= jhi; ++j) {
                const S diag = static_cast<S>(prow[j - 1] + prof[j]);
                const S vert = static_cast<S>(prow[j] + sgap);
                const S best = diag > vert ? diag : vert;
                row[j] = best > neg_inf ? best : neg_inf;
            }
        }
        for (size_t pi = 1; pi < pred_rows.size(); ++pi) {
            const S* prow = &H[static_cast<size_t>(pred_rows[pi]) * stride];
            if (static_cast<S>(prow[0] + sgap) > row[0]) {
                row[0] = static_cast<S>(prow[0] + sgap);
            }
            for (int32_t j = jlo; j <= jhi; ++j) {
                const S diag = static_cast<S>(prow[j - 1] + prof[j]);
                const S vert = static_cast<S>(prow[j] + sgap);
                const S best = diag > vert ? diag : vert;
                if (best > row[j]) row[j] = best;
            }
        }
        // horizontal pass (sequence gap) — must run after all predecessors
        for (int32_t j = jlo; j <= jhi; ++j) {
            const S horiz = static_cast<S>(row[j - 1] + sgap);
            if (horiz > row[j]) row[j] = horiz;
        }
    }

    // best sink row at the final column (ties -> smallest rank)
    int32_t best_r = -1;
    S best_score = neg_inf;
    for (int32_t r = 1; r <= n; ++r) {
        if (!nodes[order[r - 1]].out.empty()) continue;
        const S s = H[static_cast<size_t>(r) * stride + len];
        if (s > best_score) {
            best_score = s;
            best_r = r;
        }
    }
    if (best_r < 0) {  // no sink (can't happen in a DAG with nodes)
        return out;
    }

    // traceback; preference: diagonal, vertical, horizontal (deterministic)
    int32_t r = best_r, j = len;
    while (r != 0 || j != 0) {
        const S cur = H[static_cast<size_t>(r) * stride + j];
        bool moved = false;
        if (r != 0) {
            const Node& node = nodes[order[r - 1]];
            pred_rows.clear();
            for (int32_t ei : node.in) {
                pred_rows.push_back(rank_of[edges[ei].tail] + 1);
            }
            if (pred_rows.empty()) {
                pred_rows.push_back(0);
            }
            if (j > 0) {
                const S sub = static_cast<S>(
                    (kBaseCode[seq[j - 1]] == node.code) ? match : mismatch);
                for (int32_t pr : pred_rows) {
                    if (static_cast<S>(
                            H[static_cast<size_t>(pr) * stride + j - 1] +
                            sub) == cur) {
                        out.push_back(AlnPair{order[r - 1], j - 1});
                        r = pr;
                        --j;
                        moved = true;
                        break;
                    }
                }
            }
            // RACON_TPU_TIEBREAK=dhv flips the equal-score indel
            // preference to horizontal-before-vertical (quality-gap
            // attribution experiment, PARITY.md); default dvh is the
            // order the device kernels replicate bit-for-bit
            static const bool kHorizFirst = [] {
                const char* e = std::getenv("RACON_TPU_TIEBREAK");
                return e != nullptr && std::strcmp(e, "dhv") == 0;
            }();
            if (!moved && kHorizFirst && j > 0 &&
                static_cast<S>(H[static_cast<size_t>(r) * stride + j - 1] +
                               sgap) == cur) {
                out.push_back(AlnPair{-1, j - 1});
                --j;
                moved = true;
            }
            if (!moved) {
                for (int32_t pr : pred_rows) {
                    if (static_cast<S>(
                            H[static_cast<size_t>(pr) * stride + j] +
                            sgap) == cur) {
                        out.push_back(AlnPair{order[r - 1], -1});
                        r = pr;
                        moved = true;
                        break;
                    }
                }
            }
        }
        if (!moved) {
            // horizontal (consume sequence base against no node)
            out.push_back(AlnPair{-1, j - 1});
            --j;
        }
    }
    std::reverse(out.begin(), out.end());
    return out;
}


Alignment Graph::align_nw(const uint8_t* seq, int32_t len, int32_t match,
                          int32_t mismatch, int32_t gap, int32_t band,
                          int32_t bpos_origin) const {
    const int32_t n = static_cast<int32_t>(nodes.size());
    if (n == 0 || len <= 0) {
        return Alignment();
    }
    // int16 cells when every reachable score fits with margin: the worst
    // real path magnitude is (n + len + 2) * max|score|, which must stay
    // above the -28000 unreachable sentinel (itself clear of INT16_MIN
    // after the per-row clamp)
    const int32_t maxabs = std::max(std::abs(match),
                                    std::max(std::abs(mismatch),
                                             std::abs(gap)));
    const int64_t bound =
        static_cast<int64_t>(n + len + 2) * std::max(maxabs, 1);
    if (bound < 27000) {
        return align_nw_impl<int16_t>(*this, seq, len, match, mismatch, gap,
                                      band, bpos_origin,
                                      static_cast<int16_t>(-28000));
    }
    return align_nw_impl<int32_t>(*this, seq, len, match, mismatch, gap,
                                  band, bpos_origin, kNegInf);
}

Graph Graph::subgraph(int32_t begin, int32_t end,
                      std::vector<int32_t>& mapping) const {
    const int32_t n = static_cast<int32_t>(nodes.size());
    std::vector<int32_t> full_to_sub(n, -1);
    mapping.clear();
    for (int32_t i = 0; i < n; ++i) {
        if (nodes[i].bpos >= begin && nodes[i].bpos <= end) {
            full_to_sub[i] = static_cast<int32_t>(mapping.size());
            mapping.push_back(i);
        }
    }

    Graph sub;
    sub.nodes.reserve(mapping.size());
    for (int32_t fi : mapping) {
        const Node& src = nodes[fi];
        Node dst;
        dst.code = src.code;
        dst.bpos = src.bpos;
        dst.n_seqs = src.n_seqs;
        for (int32_t a : src.aligned) {
            if (full_to_sub[a] >= 0) dst.aligned.push_back(full_to_sub[a]);
        }
        sub.nodes.push_back(std::move(dst));
    }
    for (const Edge& e : edges) {
        const int32_t t = full_to_sub[e.tail], h = full_to_sub[e.head];
        if (t >= 0 && h >= 0) {
            sub.add_edge(t, h, e.weight);
        }
    }
    return sub;
}

void Graph::update_alignment(Alignment& aln,
                             const std::vector<int32_t>& mapping) {
    for (auto& p : aln) {
        if (p.node >= 0) {
            p.node = mapping[p.node];
        }
    }
}

std::vector<uint8_t> Graph::consensus(std::vector<uint32_t>& coverages) const {
    coverages.clear();
    const int32_t n = static_cast<int32_t>(nodes.size());
    std::vector<uint8_t> out;
    if (n == 0) {
        return out;
    }

    const std::vector<int32_t> order = topo_order();
    std::vector<int64_t> score(n, 0);
    std::vector<int32_t> pred(n, -1);

    // heaviest bundle: per node pick the heaviest in-edge (ties -> the
    // predecessor with the larger accumulated score, later edge wins equal)
    int32_t max_node = order[0];
    for (int32_t v : order) {
        int64_t best_w = -1;
        int32_t best_p = -1;
        for (int32_t ei : nodes[v].in) {
            const Edge& e = edges[ei];
            if (e.weight > best_w ||
                (e.weight == best_w &&
                 (best_p < 0 || score[e.tail] >= score[best_p]))) {
                best_w = e.weight;
                best_p = e.tail;
            }
        }
        if (best_p >= 0) {
            score[v] = best_w + score[best_p];
            pred[v] = best_p;
        }
        if (score[v] > score[max_node]) {
            max_node = v;
        }
    }

    // extend to a sink so the consensus spans the full graph. Two modes:
    //   greedy (default): follow the heaviest out-edge step by step;
    //   branch (RACON_TPU_CONSENSUS_EXT=branch): spoa-style branch
    //     completion — re-run the accumulated-score pass on the subgraph
    //     beyond the current bundle end, restricted to paths leaving it,
    //     jump to the new best-scoring node, iterate. Measured on the
    //     reference fixtures for the quality-gap attribution (PARITY.md).
    static const bool kBranchExt = [] {
        const char* e = std::getenv("RACON_TPU_CONSENSUS_EXT");
        return e != nullptr && std::strcmp(e, "branch") == 0;
    }();
    int32_t tip = max_node;
    if (kBranchExt) {
        std::vector<int32_t> rank_of(n);
        for (int32_t r = 0; r < n; ++r) {
            rank_of[order[r]] = r;
        }
        while (!nodes[tip].out.empty()) {
            // restrict the re-scan to paths THROUGH the bundle end: every
            // node ranked at or before `tip` except `tip` itself becomes
            // unreachable, so deep nodes cannot attach to tails that
            // bypass the bundle
            for (int32_t r = 0; r <= rank_of[tip]; ++r) {
                if (order[r] != tip) {
                    score[order[r]] = -1;
                }
            }
            score[tip] = std::max<int64_t>(score[tip], 0);
            int64_t ext_best = -1;
            int32_t ext_node = -1;
            for (int32_t r = rank_of[tip] + 1; r < n; ++r) {
                const int32_t v = order[r];
                score[v] = -1;
                pred[v] = -1;
                int64_t best_w = -1;
                int32_t best_p = -1;
                for (int32_t ei : nodes[v].in) {
                    const Edge& e = edges[ei];
                    if (score[e.tail] < 0) {
                        continue;  // unreachable from the bundle end
                    }
                    if (e.weight > best_w ||
                        (e.weight == best_w &&
                         (best_p < 0 || score[e.tail] >= score[best_p]))) {
                        best_w = e.weight;
                        best_p = e.tail;
                    }
                }
                if (best_p >= 0) {
                    score[v] = best_w + score[best_p];
                    pred[v] = best_p;
                    if (score[v] > ext_best) {
                        ext_best = score[v];
                        ext_node = v;
                    }
                }
            }
            if (ext_node < 0) {
                break;  // no path forward (tip is effectively a sink)
            }
            tip = ext_node;
        }
    } else {
        while (!nodes[tip].out.empty()) {
            int64_t best_w = -1;
            int32_t best_h = -1;
            for (int32_t ei : nodes[tip].out) {
                const Edge& e = edges[ei];
                if (e.weight > best_w ||
                    (e.weight == best_w &&
                     (best_h < 0 || score[e.head] >= score[best_h]))) {
                    best_w = e.weight;
                    best_h = e.head;
                }
            }
            pred[best_h] = tip;
            tip = best_h;
        }
    }

    std::vector<int32_t> path;
    for (int32_t v = tip; v >= 0; v = pred[v]) {
        path.push_back(v);
    }
    std::reverse(path.begin(), path.end());

    out.reserve(path.size());
    coverages.reserve(path.size());
    for (int32_t v : path) {
        out.push_back(static_cast<uint8_t>(kCodeBase[nodes[v].code]));
        uint32_t cov = static_cast<uint32_t>(nodes[v].n_seqs);
        for (int32_t a : nodes[v].aligned) {
            cov += static_cast<uint32_t>(nodes[a].n_seqs);
        }
        coverages.push_back(cov);
    }
    return out;
}

std::vector<uint8_t> window_consensus(
    const uint8_t* const* seqs, const int32_t* lens,
    const uint8_t* const* quals, const int32_t* begins, const int32_t* ends,
    int32_t n_seqs, int32_t match, int32_t mismatch, int32_t gap,
    std::vector<uint32_t>& coverages, const Alignment* prealigned) {
    Graph graph;

    std::vector<uint32_t> weights;
    auto weights_of = [&](int32_t i) -> const uint32_t* {
        weights.assign(lens[i], 1);
        if (quals[i] != nullptr) {
            for (int32_t j = 0; j < lens[i]; ++j) {
                weights[j] = quals[i][j] >= 33 ? quals[i][j] - 33 : 0;
            }
        }
        return weights.data();
    };

    // backbone
    graph.add_alignment(Alignment(), seqs[0], lens[0], weights_of(0));

    // layers sorted by begin position, stable (reference window.cpp:84-85)
    std::vector<int32_t> rank;
    rank.reserve(n_seqs - 1);
    for (int32_t i = 1; i < n_seqs; ++i) {
        rank.push_back(i);
    }
    std::stable_sort(rank.begin(), rank.end(), [&](int32_t a, int32_t b) {
        return begins[a] < begins[b];
    });

    const int32_t backbone_len = lens[0];
    const int32_t offset = static_cast<int32_t>(0.01 * backbone_len);
    const bool anchored = prealigned != nullptr;
    // static band (the cudapoa band-256 contract, cudabatch.cpp:56-59);
    // a layer whose length diverges from its graph span by close to the
    // half-band cannot fit the band and gets the exact full DP instead.
    // RACON_TPU_HOST_BAND overrides the width (0 = exact full DP always,
    // the reference spoa behavior) — the accuracy/speed knob behind the
    // banding attribution measured in PARITY.md.
    static const int32_t kBand = [] {
        const char* e = std::getenv("RACON_TPU_HOST_BAND");
        return e != nullptr ? std::atoi(e) : 256;
    }();
    // banded-result sanity: if fewer than half the aligned columns match,
    // the in-band path is mismatch soup from band clipping (e.g. balanced
    // indels with small net length change) — redo with the exact full DP,
    // the same accept/reject discipline the device aligner applies
    auto band_clipped = [&](const Alignment& aln, const uint8_t* s,
                            const Graph& g) -> bool {
        int32_t aligned = 0, matched = 0;
        for (const auto& p : aln) {
            if (p.node >= 0 && p.pos >= 0) {
                ++aligned;
                matched += g.nodes[p.node].code == kBaseCode[s[p.pos]];
            }
        }
        return aligned == 0 || 2 * matched < aligned;
    };
    for (int32_t i : rank) {
        Alignment aln;
        if (anchored) {
            aln = prealigned[i];
        } else if (begins[i] < offset && ends[i] > backbone_len - offset) {
            const bool fits = std::abs(lens[i] - backbone_len) < kBand / 2 - 16;
            aln = graph.align_nw(seqs[i], lens[i], match, mismatch, gap,
                                 fits ? kBand : 0, 0);
            if (fits && band_clipped(aln, seqs[i], graph)) {
                aln = graph.align_nw(seqs[i], lens[i], match, mismatch, gap);
            }
        } else {
            const int32_t span = ends[i] - begins[i] + 1;
            const bool fits = std::abs(lens[i] - span) < kBand / 2 - 16;
            std::vector<int32_t> mapping;
            Graph sub = graph.subgraph(begins[i], ends[i], mapping);
            aln = sub.align_nw(seqs[i], lens[i], match, mismatch, gap,
                               fits ? kBand : 0, begins[i]);
            if (fits && band_clipped(aln, seqs[i], sub)) {
                aln = sub.align_nw(seqs[i], lens[i], match, mismatch, gap);
            }
            Graph::update_alignment(aln, mapping);
        }
        graph.add_alignment(aln, seqs[i], lens[i], weights_of(i), anchored);
    }

    return graph.consensus(coverages);
}

}  // namespace racon_host
