// Partial-order-alignment engine (host CPU).
//
// A from-scratch C++ implementation of the POA capabilities racon uses from
// the vendored spoa library (reference call sites: src/window.cpp:65-142,
// src/polisher.cpp:181-185): graph construction via add_alignment, global
// (NW) alignment of a sequence against the graph with linear gap scoring,
// subgraph extraction over a backbone position range, and heaviest-bundle
// consensus with per-base column coverages.
//
// The graph is a DAG. Nodes carry a base code; edges carry accumulated
// weights (sum of the Phred weights of their endpoint bases across all
// traversals). Nodes aligned to the same column but with different bases are
// linked through `aligned` lists. Each node remembers an approximate backbone
// position (`bpos`) — the backbone column it was aligned to or inserted
// after — which makes subgraph extraction a simple range filter instead of a
// graph traversal.
//
// Determinism: all tie-breaking rules are fixed (documented inline), so the
// same inputs produce byte-identical consensus on every run — the property
// the reference's golden CI diff demands (ci/gpu/cuda_test.sh:30-44).

#pragma once

#include <cstdint>
#include <unordered_map>
#include <vector>

namespace racon_host {

// base codes: A=0 C=1 G=2 T=3 other=4 (matches racon_tpu/ops/encode.py)
extern const uint8_t kBaseCode[256];
extern const char kCodeBase[6];

struct Edge {
    int32_t tail;
    int32_t head;
    int64_t weight;
};

struct Node {
    uint8_t code;
    int32_t bpos;     // approximate backbone column
    int32_t n_seqs;   // number of sequences whose path includes this node
    std::vector<int32_t> in;       // edge indices (tail -> this)
    std::vector<int32_t> out;      // edge indices (this -> head)
    std::vector<int32_t> aligned;  // node ids in the same column
};

// one aligned pair: (node_id, seq_pos); -1 on either side means gap
struct AlnPair {
    int32_t node;
    int32_t pos;
};
using Alignment = std::vector<AlnPair>;

class Graph {
public:
    std::vector<Node> nodes;
    std::vector<Edge> edges;

    bool empty() const { return nodes.empty(); }

    // Add `seq` (raw ASCII, uppercased) along `aln`. Empty alignment appends
    // the sequence as a fresh path. `weights[i]` is the per-base weight
    // (Phred quality - 33, or 1 when no quality). When the graph is empty the
    // sequence is the backbone and node bpos = base position; otherwise new
    // nodes inherit the bpos of their column / predecessor.
    //
    // `anchored`: the alignment's node ids refer to BACKBONE positions only
    // (the batched device prealign path, which cannot see nodes other layers
    // created). Insertions are then merged across layers by their anchor
    // (backbone column, offset within the insertion run, base code) so that
    // repeated insertions accumulate edge weight exactly as they would had
    // each layer been aligned against the evolving graph — without this,
    // backbone deletions could never win the heaviest-bundle consensus.
    void add_alignment(const Alignment& aln, const uint8_t* seq, int32_t len,
                       const uint32_t* weights, bool anchored = false);

    // Topological order of node ids (deterministic: Kahn's algorithm, FIFO
    // seeded in id order).
    std::vector<int32_t> topo_order() const;

    // Global (NW) alignment of seq against the whole graph with linear gap
    // scoring; maximizes score; alignment ends in a sink node column.
    // Tie order on traceback: diagonal > vertical (graph gap) > horizontal.
    //
    // band > 0 restricts each node row's DP to sequence columns within
    // band/2 of the node's expected diagonal (bpos - bpos_origin), the
    // static-band idea of cudapoa (src/cuda/cudabatch.cpp:56-59 band 256);
    // cells outside score -inf. band 0 = exact full DP. Callers pass
    // band 0 whenever |len - graph span| approaches band/2 (the band
    // cannot contain the path then).
    Alignment align_nw(const uint8_t* seq, int32_t len, int32_t match,
                       int32_t mismatch, int32_t gap, int32_t band = 0,
                       int32_t bpos_origin = 0) const;

    // Subgraph induced by nodes with begin <= bpos <= end (backbone column
    // range, inclusive — reference window.cpp:97-102 contract). `mapping`
    // gives sub node id -> full graph node id.
    Graph subgraph(int32_t begin, int32_t end,
                   std::vector<int32_t>& mapping) const;

    // Rewrite a subgraph alignment's node ids into full-graph ids.
    static void update_alignment(Alignment& aln,
                                 const std::vector<int32_t>& mapping);

    // Heaviest-bundle consensus. Returns base codes; `coverages[i]` = number
    // of sequences whose path passes through the consensus node's column
    // (node + aligned nodes) — used by the TGS trim (window.cpp:118-139).
    std::vector<uint8_t> consensus(std::vector<uint32_t>& coverages) const;

private:
    int32_t add_node(uint8_t code, int32_t bpos);
    void add_edge(int32_t tail, int32_t head, int64_t weight);

    // anchored-insertion registry: (bpos, offset, code) -> node id and
    // (bpos, offset) -> column members, used only by anchored additions
    std::unordered_map<int64_t, int32_t> ins_node_;
    std::unordered_map<int64_t, std::vector<int32_t>> ins_col_;
};

// Full per-window consensus: backbone + layers, mirroring the orchestration
// of reference window.cpp:65-142 (sort layers by begin, full-graph align for
// window-spanning layers, subgraph align otherwise). Caller guarantees
// n_seqs >= 3. Returns consensus ASCII bytes.
//
// seqs[i]/lens[i]: raw ASCII sequences, i = 0 is the backbone.
// quals[i]: Phred+33 bytes or nullptr.
// begins/ends[i]: layer positions on the backbone (inclusive end).
std::vector<uint8_t> window_consensus(
    const uint8_t* const* seqs, const int32_t* lens,
    const uint8_t* const* quals, const int32_t* begins, const int32_t* ends,
    int32_t n_seqs, int32_t match, int32_t mismatch, int32_t gap,
    std::vector<uint32_t>& coverages,
    const Alignment* prealigned /* nullable: per-layer backbone alignments */);

}  // namespace racon_host
