// Round-based POA session: the host half of the evolving-graph device
// consensus engine.
//
// The reference's GPU path (GenomeWorks cudapoa, src/cuda/cudabatch.cpp)
// runs the whole POA — graph DP and consensus — inside one CUDA block per
// window. The TPU engine splits it differently: the graph lives HERE (all
// the irregular bookkeeping: node/edge insertion, aligned-column merging,
// heaviest-bundle consensus), while the O(nodes x len) graph-banded NW DP
// — the hot loop — runs on the TPU as a batched fixed-shape XLA program
// (racon_tpu/ops/poa_graph.py). Each round, `prepare` densifies the
// *current* graph of every ready window (topo-ordered codes, predecessor
// rank lists, band centers, sink flags), the device aligns that window's
// next layer against it, and `commit` ingests the returned path with the
// exact same add_alignment the host engine uses. Because the layer is
// aligned against the evolving graph — not just the backbone — the device
// engine inherits the host engine's consensus quality by construction
// (unlike an anchored prealign, which cannot see other layers' insertions
// during alignment).
//
// Orchestration contracts mirror reference src/window.cpp:65-142 exactly:
// layers sorted stable by begin; window-spanning layers (within a 1%
// offset margin) align against the full graph, others against the
// [begin, end] bpos-subgraph; banded DP (band 256) when the layer fits the
// band, with a full-DP redo when the banded result is clipped. Windows the
// device cannot take (too many nodes, in-degree over the predecessor cap,
// layer too long, or a malformed device result) fall back to the host
// engine at finish() — the same per-window GPU->CPU fallback discipline as
// reference src/cuda/cudapolisher.cpp:354-383.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "poa.hpp"

namespace racon_host {

namespace {

constexpr int32_t kBand = 256;  // cudapoa static-band contract (cudabatch.cpp:56-59)

struct WindowState {
    // inputs (copied; index 0 = backbone)
    std::vector<std::vector<uint8_t>> seqs;
    std::vector<std::vector<uint8_t>> quals;  // empty = no quality
    std::vector<int32_t> begins, ends;

    Graph graph;
    std::vector<int32_t> layer_rank;  // layer visit order (begin-sorted)
    size_t next_layer = 0;            // index into layer_rank
    bool outstanding = false;         // a prepared job awaits commit
    bool redo_full = false;           // banded result clipped: redo band=0
    bool unfit = false;               // host fallback at finish()
    bool backbone_only = false;       // < 3 sequences

    // densification cached from prepare() for the matching commit() —
    // the graph is untouched while a job is outstanding, so the topo
    // order and subgraph mapping stay valid and are never re-derived
    bool pending_spanning = false;
    std::vector<int32_t> pending_order;    // topo rank -> (sub)graph node id
    std::vector<int32_t> pending_mapping;  // sub node id -> full node id
};

struct Session {
    std::vector<WindowState> windows;
    int32_t match, mismatch, gap;
    int32_t max_nodes, max_pred, max_len;
    // -b / banded-only mode: trust banded results (skip the clipped ->
    // full-DP retry) — the speed/accuracy trade the reference's
    // --cuda-banded-alignment flag selects via cudapoa's static_band mode
    // (cudabatch.cpp:56-59). Off by default, which keeps device output
    // byte-identical to the host engine.
    bool banded_only = false;
    size_t cursor = 0;  // round-robin scan position for prepare()
    // observability counters (SURVEY.md §5 metrics discipline)
    int64_t n_prepared = 0;   // jobs handed to the device
    int64_t n_committed = 0;  // layer alignments ingested
    int64_t n_redo = 0;       // banded results clipped -> full-DP requeue
};

std::mutex g_mutex;
std::unordered_map<int64_t, std::unique_ptr<Session>> g_sessions;
int64_t g_next_id = 1;

Session* get_session(int64_t handle) {
    std::lock_guard<std::mutex> lock(g_mutex);
    auto it = g_sessions.find(handle);
    return it == g_sessions.end() ? nullptr : it->second.get();
}

const uint32_t* weights_of(const WindowState& w, int32_t i,
                           std::vector<uint32_t>& buf) {
    const int32_t len = static_cast<int32_t>(w.seqs[i].size());
    buf.assign(len, 1);
    if (!w.quals[i].empty()) {
        for (int32_t j = 0; j < len; ++j) {
            buf[j] = w.quals[i][j] >= 33 ? w.quals[i][j] - 33 : 0;
        }
    }
    return buf.data();
}

// Decide full-graph vs subgraph and banded vs exact for this layer —
// the same rules as window_consensus (poa.cpp) / reference window.cpp:87-103.
struct JobPlan {
    bool spanning;
    int32_t band;    // 0 = exact full DP
    int32_t origin;  // bpos origin of the band centers
};

JobPlan plan_layer(const WindowState& w, int32_t i, bool redo_full) {
    const int32_t backbone_len = static_cast<int32_t>(w.seqs[0].size());
    const int32_t len = static_cast<int32_t>(w.seqs[i].size());
    const int32_t offset = static_cast<int32_t>(0.01 * backbone_len);
    JobPlan p;
    p.spanning = w.begins[i] < offset && w.ends[i] > backbone_len - offset;
    const int32_t span =
        p.spanning ? backbone_len : w.ends[i] - w.begins[i] + 1;
    const bool fits = std::abs(len - span) < kBand / 2 - 16;
    p.band = (fits && !redo_full) ? kBand : 0;
    p.origin = p.spanning ? 0 : w.begins[i];
    return p;
}

// Same acceptance rule as the host engine's banded retry (poa.cpp
// band_clipped): fewer than half the aligned columns matching means the
// in-band path is clipping artifact, not signal.
bool band_clipped(const Alignment& aln, const uint8_t* seq, const Graph& g) {
    int32_t aligned = 0, matched = 0;
    for (const auto& p : aln) {
        if (p.node >= 0 && p.pos >= 0) {
            ++aligned;
            matched += g.nodes[p.node].code == kBaseCode[seq[p.pos]];
        }
    }
    return aligned == 0 || 2 * matched < aligned;
}

}  // namespace
}  // namespace racon_host

using racon_host::Alignment;
using racon_host::AlnPair;
using racon_host::Graph;
using racon_host::Session;
using racon_host::WindowState;

extern "C" {

// Create a session over the same flat window layout rh_poa_batch takes
// (all sequences concatenated, per-window spans via win_off, first
// sequence of each window the backbone). max_nodes / max_pred / max_len
// are the device kernel's shape envelope: windows that exceed any of them
// fall back to the host engine at finish().
int64_t rh_poa_session_new(
    const uint8_t* seq_data, const int64_t* seq_off,
    const uint8_t* qual_data, const int64_t* qual_off,
    const int32_t* begins, const int32_t* ends,
    const int64_t* win_off, int64_t n_windows,
    int32_t match, int32_t mismatch, int32_t gap,
    int32_t max_nodes, int32_t max_pred, int32_t max_len,
    int32_t banded_only) {
    auto session = std::make_unique<Session>();
    session->match = match;
    session->mismatch = mismatch;
    session->gap = gap;
    session->max_nodes = max_nodes;
    session->max_pred = max_pred;
    session->max_len = max_len;
    session->banded_only = banded_only != 0;
    session->windows.resize(n_windows);

    std::vector<uint32_t> wbuf;
    for (int64_t w = 0; w < n_windows; ++w) {
        WindowState& ws = session->windows[w];
        const int64_t s0 = win_off[w], s1 = win_off[w + 1];
        const int64_t count = s1 - s0;
        for (int64_t s = s0; s < s1; ++s) {
            ws.seqs.emplace_back(seq_data + seq_off[s],
                                 seq_data + seq_off[s + 1]);
            ws.quals.emplace_back(qual_data + qual_off[s],
                                  qual_data + qual_off[s + 1]);
            ws.begins.push_back(begins[s]);
            ws.ends.push_back(ends[s]);
        }
        if (count < 3) {
            ws.backbone_only = true;
            continue;
        }
        // backbone seeds the graph
        ws.graph.add_alignment(Alignment(), ws.seqs[0].data(),
                               static_cast<int32_t>(ws.seqs[0].size()),
                               racon_host::weights_of(ws, 0, wbuf));
        // layer order: stable sort by begin (reference window.cpp:84-85)
        for (int64_t s = 1; s < count; ++s) {
            ws.layer_rank.push_back(static_cast<int32_t>(s));
        }
        std::stable_sort(ws.layer_rank.begin(), ws.layer_rank.end(),
                         [&](int32_t a, int32_t b) {
                             return ws.begins[a] < ws.begins[b];
                         });
        // a layer longer than the kernel envelope sinks the whole window
        for (int32_t i : ws.layer_rank) {
            if (static_cast<int32_t>(ws.seqs[i].size()) > max_len) {
                ws.unfit = true;
                break;
            }
        }
    }

    std::lock_guard<std::mutex> lock(racon_host::g_mutex);
    const int64_t id = racon_host::g_next_id++;
    racon_host::g_sessions.emplace(id, std::move(session));
    return id;
}

// Emit up to max_jobs ready jobs (windows with layers left and no
// outstanding job). Dense per-job buffers, caller-allocated:
//   job_win/job_layer/job_band/job_nnodes/job_len/job_origin: [max_jobs]
//   codes:   [max_jobs * max_nodes] int8  (topo-ordered node codes; pad 5)
//   preds:   [max_jobs * max_nodes * max_pred] int32 (H row index of each
//            predecessor: rank+1, 0 = virtual source; pad -1)
//   centers: [max_jobs * max_nodes] int32 (band center column per node)
//   sinks:   [max_jobs * max_nodes] uint8 (1 = sink node)
//   seqs:    [max_jobs * max_len] int8 (layer base codes; pad 5)
// Returns the number of jobs written (0 = no window is ready; the round is
// drained when this is 0 and no jobs are uncommitted).
int32_t rh_poa_session_prepare(
    int64_t handle, int32_t max_jobs, int32_t n_threads,
    int32_t* job_win, int32_t* job_layer, int32_t* job_band,
    int32_t* job_nnodes, int32_t* job_len, int32_t* job_origin,
    int32_t* job_maxpred,
    int8_t* codes, int16_t* preds, int16_t* centers, uint8_t* sinks,
    int8_t* seqs) {
    Session* s = racon_host::get_session(handle);
    if (s == nullptr || max_jobs <= 0) {
        return 0;
    }
    const int32_t N = s->max_nodes, P = s->max_pred, L = s->max_len;
    const size_t n_windows = s->windows.size();

    // pass 1 (serial): round-robin candidate selection — cheap flag checks
    std::vector<int32_t> cand;
    cand.reserve(max_jobs);
    for (size_t scanned = 0;
         scanned < n_windows &&
         static_cast<int32_t>(cand.size()) < max_jobs;
         ++scanned) {
        const size_t w = (s->cursor + scanned) % n_windows;
        WindowState& ws = s->windows[w];
        if (ws.backbone_only || ws.unfit || ws.outstanding ||
            ws.next_layer >= ws.layer_rank.size()) {
            continue;
        }
        cand.push_back(static_cast<int32_t>(w));
    }
    const int32_t n_cand = static_cast<int32_t>(cand.size());
    s->cursor = (s->cursor + n_cand) % (n_windows ? n_windows : 1);

    // pass 2 (parallel over candidates — distinct windows, no sharing):
    // plan, subgraph, topo order, densify into the candidate's slot
    std::vector<uint8_t> valid(n_cand, 0);
    std::atomic<int32_t> next(0);
    auto densify = [&]() {
        std::vector<int32_t> order, rank_of, mapping;
        while (true) {
            const int32_t c = next.fetch_add(1);
            if (c >= n_cand) {
                return;
            }
            WindowState& ws = s->windows[cand[c]];
            const int32_t li = ws.layer_rank[ws.next_layer];
            const racon_host::JobPlan plan =
                racon_host::plan_layer(ws, li, ws.redo_full);
            const Graph* g = &ws.graph;
            Graph sub;
            mapping.clear();
            if (!plan.spanning) {
                sub = ws.graph.subgraph(ws.begins[li], ws.ends[li],
                                        mapping);
                g = &sub;
            }
            const int32_t n = static_cast<int32_t>(g->nodes.size());
            if (n > N ||
                static_cast<int32_t>(ws.graph.nodes.size()) > N) {
                // graph outgrew the kernel envelope (possibly mid-build):
                // discard and host-polish the whole window at finish()
                ws.unfit = true;
                continue;
            }
            order = g->topo_order();
            rank_of.assign(n, 0);
            for (int32_t r = 0; r < n; ++r) {
                rank_of[order[r]] = r;
            }
            int8_t* jc = codes + static_cast<int64_t>(c) * N;
            int16_t* jp = preds + static_cast<int64_t>(c) * N * P;
            int16_t* jcen = centers + static_cast<int64_t>(c) * N;
            uint8_t* jsink = sinks + static_cast<int64_t>(c) * N;
            std::memset(jc, 5, N);
            std::fill(jp, jp + static_cast<int64_t>(N) * P,
                      static_cast<int16_t>(-1));
            std::memset(jcen, 0,
                        static_cast<int64_t>(N) * sizeof(int16_t));
            std::memset(jsink, 0, N);
            bool fits = true;
            int32_t max_indeg = 1;  // the virtual source counts as one
            for (int32_t r = 0; r < n && fits; ++r) {
                const racon_host::Node& node = g->nodes[order[r]];
                jc[r] = static_cast<int8_t>(node.code);
                jcen[r] = static_cast<int16_t>(node.bpos - plan.origin + 1);
                jsink[r] = node.out.empty() ? 1 : 0;
                if (node.in.empty()) {
                    jp[static_cast<int64_t>(r) * P] = 0;  // virtual source
                } else if (static_cast<int32_t>(node.in.size()) > P) {
                    fits = false;  // in-degree over the cap: host fallback
                } else {
                    for (size_t e = 0; e < node.in.size(); ++e) {
                        jp[static_cast<int64_t>(r) * P + e] =
                            static_cast<int16_t>(
                                rank_of[g->edges[node.in[e]].tail] + 1);
                    }
                    if (static_cast<int32_t>(node.in.size()) > max_indeg) {
                        max_indeg = static_cast<int32_t>(node.in.size());
                    }
                }
            }
            if (!fits) {
                ws.unfit = true;
                continue;
            }
            const int32_t len = static_cast<int32_t>(ws.seqs[li].size());
            int8_t* jq = seqs + static_cast<int64_t>(c) * L;
            std::memset(jq, 5, L);
            for (int32_t i = 0; i < len; ++i) {
                jq[i] = static_cast<int8_t>(
                    racon_host::kBaseCode[ws.seqs[li][i]]);
            }
            job_win[c] = cand[c];
            job_layer[c] = li;
            job_band[c] = plan.band;
            job_nnodes[c] = n;
            job_len[c] = len;
            job_origin[c] = plan.origin;
            job_maxpred[c] = max_indeg;
            ws.pending_spanning = plan.spanning;
            ws.pending_order = order;
            ws.pending_mapping = mapping;
            ws.outstanding = true;
            valid[c] = 1;
        }
    };
    int32_t nt = n_threads > 1 ? n_threads : 1;
    if (nt > n_cand) {
        nt = n_cand > 0 ? n_cand : 1;
    }
    if (nt <= 1) {
        densify();
    } else {
        std::vector<std::thread> pool;
        pool.reserve(nt);
        for (int32_t t = 0; t < nt; ++t) {
            pool.emplace_back(densify);
        }
        for (auto& th : pool) {
            th.join();
        }
    }

    // pass 3 (serial): compact over slots invalidated by unfit windows
    // (rare — at most once per window over the whole session)
    int32_t n_jobs = 0;
    for (int32_t c = 0; c < n_cand; ++c) {
        if (!valid[c]) {
            continue;
        }
        if (n_jobs != c) {
            std::memcpy(codes + static_cast<int64_t>(n_jobs) * N,
                        codes + static_cast<int64_t>(c) * N, N);
            std::memcpy(preds + static_cast<int64_t>(n_jobs) * N * P,
                        preds + static_cast<int64_t>(c) * N * P,
                        static_cast<int64_t>(N) * P * sizeof(int16_t));
            std::memcpy(centers + static_cast<int64_t>(n_jobs) * N,
                        centers + static_cast<int64_t>(c) * N,
                        static_cast<int64_t>(N) * sizeof(int16_t));
            std::memcpy(sinks + static_cast<int64_t>(n_jobs) * N,
                        sinks + static_cast<int64_t>(c) * N, N);
            std::memcpy(seqs + static_cast<int64_t>(n_jobs) * L,
                        seqs + static_cast<int64_t>(c) * L, L);
            job_win[n_jobs] = job_win[c];
            job_layer[n_jobs] = job_layer[c];
            job_band[n_jobs] = job_band[c];
            job_nnodes[n_jobs] = job_nnodes[c];
            job_len[n_jobs] = job_len[c];
            job_origin[n_jobs] = job_origin[c];
            job_maxpred[n_jobs] = job_maxpred[c];
        }
        ++n_jobs;
    }
    s->n_prepared += n_jobs;
    return n_jobs;
}

// Ingest device alignments. ranks[j * max_len + i] is, for job j and layer
// base i, the 0-based topo rank of the graph node base i aligned to, or
// -1 for an insertion (every i < job_len must be covered — global
// alignment consumes the whole layer). Banded jobs whose result is
// clipped are NOT ingested; they are re-queued for a full-DP redo (the
// band_clipped retry of the host engine). Malformed results mark the
// window unfit (host fallback).
void rh_poa_session_commit(
    int64_t handle, int32_t n_jobs, int32_t n_threads,
    const int32_t* job_win, const int32_t* job_layer,
    const int32_t* job_band, const int32_t* ranks) {
    Session* s = racon_host::get_session(handle);
    if (s == nullptr) {
        return;
    }
    const int32_t L = s->max_len;

    // parallel over jobs: each job's window is distinct within a batch
    // (one outstanding job per window), so graph ingest has no sharing
    std::atomic<int32_t> next(0);
    std::atomic<int64_t> committed(0), redos(0);
    auto ingest = [&]() {
        std::vector<uint32_t> wbuf;
        while (true) {
            const int32_t j = next.fetch_add(1);
            if (j >= n_jobs) {
                return;
            }
            WindowState& ws = s->windows[job_win[j]];
            const int32_t li = job_layer[j];
            ws.outstanding = false;
            // rank -> full-graph node id via the densification cached at
            // prepare() (the graph is untouched while outstanding)
            const std::vector<int32_t> order = std::move(ws.pending_order);
            const std::vector<int32_t> mapping =
                std::move(ws.pending_mapping);
            const bool spanning = ws.pending_spanning;
            ws.pending_order.clear();
            ws.pending_mapping.clear();
            if (ws.unfit) {
                continue;
            }
            const int32_t n = static_cast<int32_t>(order.size());

            const int32_t len = static_cast<int32_t>(ws.seqs[li].size());
            const int32_t* jr = ranks + static_cast<int64_t>(j) * L;
            Alignment aln;
            aln.reserve(len);
            bool ok = true;
            for (int32_t i = 0; i < len; ++i) {
                int32_t node = -1;
                if (jr[i] >= 0) {
                    if (jr[i] >= n) {
                        ok = false;
                        break;
                    }
                    node = order[jr[i]];
                    if (!spanning) {
                        node = mapping[node];
                    }
                } else if (jr[i] != -1) {
                    ok = false;  // -2 pad inside the sequence span
                    break;
                }
                aln.push_back(AlnPair{node, i});
            }
            if (!ok) {
                ws.unfit = true;
                continue;
            }
            if (job_band[j] > 0 && !s->banded_only &&
                racon_host::band_clipped(aln, ws.seqs[li].data(),
                                         ws.graph)) {
                ws.redo_full = true;  // re-queue this layer with band 0
                redos.fetch_add(1);
                continue;
            }
            ws.graph.add_alignment(aln, ws.seqs[li].data(), len,
                                   racon_host::weights_of(ws, li, wbuf));
            ws.redo_full = false;
            ++ws.next_layer;
            committed.fetch_add(1);
        }
    };
    int32_t nt = n_threads > 1 ? n_threads : 1;
    if (nt > n_jobs) {
        nt = n_jobs > 0 ? n_jobs : 1;
    }
    if (nt <= 1) {
        ingest();
    } else {
        std::vector<std::thread> pool;
        pool.reserve(nt);
        for (int32_t t = 0; t < nt; ++t) {
            pool.emplace_back(ingest);
        }
        for (auto& th : pool) {
            th.join();
        }
    }
    s->n_committed += committed.load();
    s->n_redo += redos.load();
}

// Counters: out[0] jobs prepared, out[1] layers committed, out[2] banded
// clipped->full-DP redos, out[3] unfit (host-fallback) windows so far.
void rh_poa_session_stats(int64_t handle, int64_t* out) {
    Session* s = racon_host::get_session(handle);
    if (s == nullptr) {
        out[0] = out[1] = out[2] = out[3] = 0;
        return;
    }
    out[0] = s->n_prepared;
    out[1] = s->n_committed;
    out[2] = s->n_redo;
    int64_t unfit = 0;
    for (const WindowState& ws : s->windows) {
        unfit += ws.unfit ? 1 : 0;
    }
    out[3] = unfit;
}

// Consensus for every window. Device-built graphs emit directly; unfit
// windows (and any with layers still pending) are host-polished from
// scratch; backbone-only windows copy their backbone (window.cpp:68-71).
// Output layout identical to rh_poa_batch. win_status[w]: 0 device,
// 1 host fallback, 2 backbone. Returns total bytes or -needed.
int64_t rh_poa_session_finish(
    int64_t handle, int32_t n_threads,
    uint8_t* cons_data, uint32_t* cov_data, int64_t cons_cap,
    int64_t* cons_off, int32_t* win_status) {
    Session* s = racon_host::get_session(handle);
    if (s == nullptr) {
        return -1;
    }
    const int64_t n_windows = static_cast<int64_t>(s->windows.size());
    std::vector<std::vector<uint8_t>> results(n_windows);
    std::vector<std::vector<uint32_t>> coverages(n_windows);

    std::atomic<int64_t> next(0);
    auto worker = [&]() {
        std::vector<const uint8_t*> seqs, quals;
        std::vector<int32_t> lens;
        while (true) {
            const int64_t w = next.fetch_add(1);
            if (w >= n_windows) {
                return;
            }
            WindowState& ws = s->windows[w];
            if (ws.backbone_only) {
                results[w] = ws.seqs[0];
                coverages[w].assign(ws.seqs[0].size(), 0);
                win_status[w] = 2;
            } else if (!ws.unfit &&
                       ws.next_layer == ws.layer_rank.size()) {
                results[w] = ws.graph.consensus(coverages[w]);
                win_status[w] = 0;
            } else {
                // host fallback: full window_consensus from the inputs
                const int32_t count = static_cast<int32_t>(ws.seqs.size());
                seqs.clear();
                quals.clear();
                lens.clear();
                for (int32_t i = 0; i < count; ++i) {
                    seqs.push_back(ws.seqs[i].data());
                    lens.push_back(static_cast<int32_t>(ws.seqs[i].size()));
                    quals.push_back(ws.quals[i].empty()
                                        ? nullptr
                                        : ws.quals[i].data());
                }
                results[w] = racon_host::window_consensus(
                    seqs.data(), lens.data(), quals.data(),
                    ws.begins.data(), ws.ends.data(), count, s->match,
                    s->mismatch, s->gap, coverages[w], nullptr);
                win_status[w] = 1;
            }
        }
    };
    int32_t nt = n_threads > 0 ? n_threads : 1;
    if (nt > n_windows) {
        nt = static_cast<int32_t>(n_windows > 0 ? n_windows : 1);
    }
    if (nt == 1) {
        worker();
    } else {
        std::vector<std::thread> pool;
        pool.reserve(nt);
        for (int32_t i = 0; i < nt; ++i) {
            pool.emplace_back(worker);
        }
        for (auto& th : pool) {
            th.join();
        }
    }

    int64_t total = 0;
    for (int64_t w = 0; w < n_windows; ++w) {
        total += static_cast<int64_t>(results[w].size());
    }
    if (total > cons_cap) {
        return -total;
    }
    int64_t at = 0;
    for (int64_t w = 0; w < n_windows; ++w) {
        cons_off[w] = at;
        std::memcpy(cons_data + at, results[w].data(), results[w].size());
        std::memcpy(cov_data + at, coverages[w].data(),
                    coverages[w].size() * sizeof(uint32_t));
        at += static_cast<int64_t>(results[w].size());
    }
    cons_off[n_windows] = at;
    return total;
}

void rh_poa_session_free(int64_t handle) {
    std::lock_guard<std::mutex> lock(racon_host::g_mutex);
    racon_host::g_sessions.erase(handle);
}

// Consensus from the fused device engine's fetched graph arrays
// (racon_tpu/ops/poa_fused.py): rebuild each window's Graph — nodes with
// codes and sequence counts, edges from the predecessor slots in slot
// order (the DP tie-break order), aligned lists from column membership —
// then run the exact host heaviest-bundle consensus. Output layout
// identical to rh_poa_batch; returns total bytes or -needed.
int64_t rh_poa_finish_arrays(
    const int8_t* codes, const int16_t* preds, const int32_t* predw,
    const int32_t* nseq, const int16_t* col_of,
    const int32_t* n_nodes, int64_t n_windows, int32_t N, int32_t P,
    int32_t n_threads,
    uint8_t* cons_data, uint32_t* cov_data, int64_t cons_cap,
    int64_t* cons_off) {
    std::vector<std::vector<uint8_t>> results(n_windows);
    std::vector<std::vector<uint32_t>> coverages(n_windows);

    std::atomic<int64_t> next_w(0);
    auto worker = [&]() {
        while (true) {
            const int64_t w = next_w.fetch_add(1);
            if (w >= n_windows) {
                return;
            }
            const int32_t n = n_nodes[w];
            const int8_t* wc = codes + w * N;
            const int16_t* wp = preds + static_cast<int64_t>(w) * N * P;
            const int32_t* ww = predw + static_cast<int64_t>(w) * N * P;
            const int32_t* wn = nseq + w * N;
            const int16_t* wcol = col_of + w * N;

            Graph g;
            g.nodes.resize(n);
            std::unordered_map<int32_t, std::vector<int32_t>> columns;
            for (int32_t v = 0; v < n; ++v) {
                racon_host::Node& node = g.nodes[v];
                node.code = static_cast<uint8_t>(wc[v]);
                node.bpos = 0;
                node.n_seqs = wn[v];
                columns[wcol[v]].push_back(v);
            }
            for (int32_t v = 0; v < n; ++v) {
                for (int32_t s = 0; s < P; ++s) {
                    const int32_t t = wp[static_cast<int64_t>(v) * P + s];
                    if (t < 0) {
                        continue;
                    }
                    const int32_t ei = static_cast<int32_t>(g.edges.size());
                    g.edges.push_back(racon_host::Edge{
                        t, v, ww[static_cast<int64_t>(v) * P + s]});
                    g.nodes[v].in.push_back(ei);
                    g.nodes[t].out.push_back(ei);
                }
            }
            for (const auto& kv : columns) {
                for (int32_t a : kv.second) {
                    for (int32_t b : kv.second) {
                        if (a != b) {
                            g.nodes[a].aligned.push_back(b);
                        }
                    }
                }
            }
            results[w] = g.consensus(coverages[w]);
        }
    };
    int32_t nt = n_threads > 1 ? n_threads : 1;
    if (nt > n_windows) {
        nt = static_cast<int32_t>(n_windows > 0 ? n_windows : 1);
    }
    if (nt <= 1) {
        worker();
    } else {
        std::vector<std::thread> pool;
        pool.reserve(nt);
        for (int32_t i = 0; i < nt; ++i) {
            pool.emplace_back(worker);
        }
        for (auto& th : pool) {
            th.join();
        }
    }

    int64_t total = 0;
    for (int64_t w = 0; w < n_windows; ++w) {
        total += static_cast<int64_t>(results[w].size());
    }
    if (total > cons_cap) {
        return -total;
    }
    int64_t at = 0;
    for (int64_t w = 0; w < n_windows; ++w) {
        cons_off[w] = at;
        std::memcpy(cons_data + at, results[w].data(), results[w].size());
        std::memcpy(cov_data + at, coverages[w].data(),
                    coverages[w].size() * sizeof(uint32_t));
        at += static_cast<int64_t>(results[w].size());
    }
    cons_off[n_windows] = at;
    return total;
}

}  // extern "C"
