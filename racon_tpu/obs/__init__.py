"""Unified observability layer: tracing, metrics, leveled logging.

Three pillars, all off (or invisible) by default so the clean run's
output and stderr stay byte-identical:

  1. SPAN TRACING (`obs.trace`): a thread-safe `TraceRecorder` armed by
     RACON_TPU_TRACE=<out.json> / `--tpu-trace`, emitting Chrome
     trace-event JSON for Perfetto — per-chunk pipeline stage spans,
     engine dispatch loops, XLA compiles, watchdog backoff, and instant
     events mirroring every resilience counter bump.
  2. METRICS REGISTRY (`obs.metrics.MetricsRegistry`): the pipeline /
     sched / resilience telemetry islands consolidated into one
     namespaced snapshot — bench JSON `"metrics"` field, `--tpu-metrics
     out.json` dump, end-of-run stderr table.
  3. LEVELED LOGGING (`utils/logger.py`, re-exported here):
     RACON_TPU_LOG_LEVEL=quiet|info|debug structured stderr logging
     with once-per-run deduplication of repeated per-chunk warnings.

`jax_profile(phase)` is the optional deep-dive hook: a context manager
bracketing a device phase with `jax.profiler` when RACON_TPU_PROFILE /
`--tpu-jax-profile <dir>` names a directory, and a silent no-op when the
profiler is unavailable on the backend.

The serve-grade additions (PR 6) build on the same pillars:

  4. LATENCY HISTOGRAMS (`obs.hist`): log-bucketed, thread-safe
     `Histogram` / `HistogramSet` — p50/p95/p99/max for pipeline stage
     durations, job latency, queue wait, gather wait, compiles.
  5. PROMETHEUS EXPOSITION (`obs.prom`): stdlib-only text-format
     rendering behind the serve layer's `scrape` RPC and optional
     localhost HTTP endpoint.
  6. FLIGHT RECORDER (`obs.flight`): an always-on bounded ring of
     recent spans (a `TraceRecorder` with deque buffers) the serve
     layer dumps as a Chrome-trace artifact when a job fails, times
     out, or misses its deadline."""

from __future__ import annotations

import os

from . import trace
from .hist import Histogram, HistogramSet
from .metrics import MetricsRegistry
from ..utils.logger import (log_debug, log_info, log_level, warn_dedup,
                            flush_dedup)

__all__ = ["trace", "MetricsRegistry", "Histogram", "HistogramSet",
           "jax_profile", "log_debug", "log_info", "log_level",
           "warn_dedup", "flush_dedup"]


class _SafeJaxProfile:
    """`jax.profiler.trace` bracket that degrades to a no-op — entering
    must never take a run down just because the backend (CPU tests, a
    shimmed tunnel) cannot profile."""

    def __init__(self, directory: str):
        self._dir = directory
        self._cm = None

    def __enter__(self) -> "_SafeJaxProfile":
        try:
            import jax

            cm = jax.profiler.trace(self._dir)
            cm.__enter__()
            self._cm = cm
        except Exception as exc:
            log_debug(f"[racon_tpu::obs] jax profiler unavailable "
                      f"({type(exc).__name__}: {exc}); phase runs "
                      "unprofiled")
            self._cm = None
        return self

    def __exit__(self, *exc_info) -> bool:
        if self._cm is not None:
            try:
                self._cm.__exit__(*exc_info)
            except Exception as exc:
                log_debug(f"[racon_tpu::obs] jax profiler stop failed "
                          f"({type(exc).__name__}: {exc})")
        return False


def jax_profile(phase: str = ""):
    """Context manager bracketing one device phase with a jax.profiler
    trace under RACON_TPU_PROFILE/<phase> (each phase gets its own
    capture directory so align and consensus don't clobber each other).
    A no-op context when the knob is unset."""
    import contextlib

    base = os.environ.get("RACON_TPU_PROFILE")
    if not base:
        return contextlib.nullcontext()
    return _SafeJaxProfile(os.path.join(base, phase) if phase else base)
