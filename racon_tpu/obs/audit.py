"""Online identity-audit sentinel: sampled shadow re-execution + SDC
detection for the live serve plane.

Every identity guarantee so far is a TEST-TIME artifact: byte-identity
pins across kernels/dtypes/meshes run in CI, the autotuner's veto runs
at profile time. Nothing checks identity WHILE SERVING — a chip that
starts silently corrupting int16 Pallas scores, a stale winner-table
entry, or one bad worker lane would ship wrong consensus bytes to every
client undetected, because wrong-but-well-formed FASTA trips no error
path. `WindowAuditor` closes that hole the way fleet-scale inference
services do, with a continuous sampled audit:

  - SAMPLING is content-keyed, not random: a window is audited iff the
    first 8 bytes of the SHA-256 over its content (backbone + layers +
    qualities + layer positions) fall under `rate` * 2^64. The decision
    is a pure function of the window bytes — reproducible across
    processes, replicas and reruns, test-pinnable, and un-gameable by
    scheduling (no RNG, no per-process seed).
  - SHADOW RE-EXECUTION runs the sampled windows through the ORACLE
    path (ops/oracle.py: XLA, int32, unpacked operands, split-chain —
    the same reference every identity pin and the profile-time veto
    compare against) on its own engines with its own telemetry, off the
    device hot path: the feeder audits AFTER releasing the lane's exec
    lock, so other lanes and the device never wait on an audit.
  - A MISMATCH is a confirmed silent-data-corruption event, and every
    consequence fires inside the same iteration:
      * the `racon_tpu_audit_mismatches{engine,kernel,dtype,bucket,
        lane}` labeled counter increments;
      * a flight artifact carrying BOTH byte streams (produced vs
        oracle) lands in the flight-dump directory, and the
        `audit.shadow` histogram's bucket exemplar names it — a fleet
        dashboard's mismatch click-through;
      * a typed `audit-mismatch` journal line lands in the owning job's
        timeline (an annotation event: obsreport renders it, `--check`
        ignores it);
      * the persisted autotuner winner entries for the implicated
        engine are ONLINE-DEMOTED to the oracle candidate (the same
        veto semantics as profile time, atomic table rewrite — a stale
        fast-but-wrong winner stops dispatching NOW and stays stopped
        across restarts);
      * the lane's health score drops and the lane is QUARANTINED
        (serve/batcher.py): it drains, solo re-probes with the
        known-good window (the mismatched content with its
        oracle-verified bytes), and either rejoins or stays quarantined
        — `racon_tpu_lane_health{lane}` is the scrape view;
      * the production window is REPAIRED with the oracle bytes before
        delivery, so the job's FASTA stays byte-identical to a clean
        run — detection protects the caught output, not just the
        dashboard;
      * the `racon_tpu_audit_alert` gauge flips (and a typed `alert`
        journal line fires); it stays up until an operator acknowledges
        via the debug RPC's `audit_ack` (serve/client.py
        `PolishClient.audit_ack()`).

  Telemetry isolation: the oracle executor keeps its own
  PipelineStats/OccupancyStats and never consults the winner table, so
  shadow executions surface ONLY under the `audit.*` scrape namespace —
  a sampled run's production `pipeline.*`/`sched.*` counters are
  identical to an unsampled one's (test-pinned).

Env knobs: RACON_TPU_AUDIT_RATE (sampled fraction, default 0 = off —
and with it off every serve surface is byte-identical to the pre-audit
code), RACON_TPU_AUDIT_DEMOTE (0 disables online demotion),
RACON_TPU_LANE_QUARANTINE (0 disables lane quarantine/re-probe)."""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import time

from ..utils.logger import log_info

#: 2^64, the denominator of the content-hash sampling fraction
_HASH_SPACE = float(1 << 64)


def window_sample_fraction(w) -> float:
    """The window's deterministic sample coordinate in [0, 1): the
    first 8 bytes of SHA-256 over its full content. A window is audited
    at rate R iff this fraction < R — so raising R only ADDS windows to
    the audited set (the R=1.0 set contains every smaller set)."""
    h = hashlib.sha256()
    for seq, qual, (begin, end) in zip(w.sequences, w.qualities,
                                       w.positions):
        h.update(struct.pack("<Iii", len(seq), begin, end))
        h.update(seq)
        if qual:
            h.update(qual)
    return int.from_bytes(h.digest()[:8], "big") / _HASH_SPACE


def _engine_label(p) -> str:
    """Which consensus engine produced the audited bytes: 'host' (the
    native C++ engine) or the device engine name."""
    if not p.tpu_poa_batches:
        return "host"
    return (p.tpu_engine or os.environ.get("RACON_TPU_ENGINE")
            or "session")


#: autotuner engines implicated per production engine label — the set
#: `demote()` sweeps on a mismatch. A host-engine mismatch implicates
#: no device winner (there is nothing to demote, only a lane to blame).
_DEMOTE_ENGINES = {"session": ("session",),
                   "fused": ("fused_loop", "fused", "session")}

#: the polisher attributes the lane re-probe needs (the batcher's
#: engine-key fields plus trim); the probe snapshots EXACTLY these so
#: it never pins the mismatched job's Polisher — and with it the job's
#: whole dataset — in memory for the rest of the server's life
_PARAM_FIELDS = ("match", "mismatch", "gap", "window_length", "trim",
                 "num_threads", "tpu_poa_batches",
                 "tpu_banded_alignment", "tpu_aligner_band_width",
                 "tpu_engine", "tpu_pipeline_depth",
                 "tpu_device_timeout")


def _slim_params(p):
    import types

    return types.SimpleNamespace(
        **{k: getattr(p, k) for k in _PARAM_FIELDS})


class AuditMismatch:
    """One confirmed silent-corruption event (diagnostics record)."""

    __slots__ = ("job", "trace", "lane", "iteration", "window_id",
                 "rank", "labels", "flight", "demoted", "t")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class WindowAuditor:
    """The sampling auditor (module docstring). One per PolishServer;
    the serve batcher calls `audit_windows` after every iteration
    (shared and solo) once the lane lock is released."""

    def __init__(self, rate: float, demote: bool = True,
                 quarantine: bool = True, hists=None,
                 flight_dir: str | None = None, journal=None,
                 on_alert=None):
        from ..ops.oracle import OracleExecutor

        self.rate = min(1.0, max(0.0, float(rate)))
        self.demote_enabled = bool(demote)
        self.quarantine_enabled = bool(quarantine)
        #: the server's lifetime HistogramSet: shadow durations observe
        #: as `audit.shadow`, whose mismatch-bucket exemplar names the
        #: dual-stream flight artifact
        self.hists = hists
        self.flight_dir = flight_dir
        #: obs.journal.Journal (or None): typed `audit-mismatch` /
        #: `audit-lane` / `alert` annotation lines
        self.journal = journal
        #: callable(state: str, detail: dict) — the server journals the
        #: typed alert and logs; state transitions only
        self.on_alert = on_alert
        self.oracle = OracleExecutor()
        self._lock = threading.Lock()
        self.counters = {"windows": 0, "sampled": 0, "audited": 0,
                         "clean": 0, "mismatches": 0, "repaired": 0,
                         "demotions": 0, "shadow_s": 0.0}
        #: labeled mismatch series: (engine, kernel, dtype, bucket,
        #: lane) -> count — the scrape's audit_mismatches family
        self.mismatch_series: dict[tuple, int] = {}
        self.recent: list[AuditMismatch] = []
        #: the lane re-probe's known-good input: the latest mismatched
        #: window's content with its ORACLE-verified bytes (always set
        #: by the time a quarantine exists)
        self._probe = None
        self._alert_firing = False
        self._acked = 0
        self._flight_seq = 0

    # ---------------------------------------------------------- sampling
    @property
    def armed(self) -> bool:
        return self.rate > 0.0

    def set_rate(self, rate: float) -> None:
        """Live re-rate (servebench's A/B uses it); sampling stays a
        pure function of (content, rate)."""
        self.rate = min(1.0, max(0.0, float(rate)))

    def sampled(self, w) -> bool:
        return window_sample_fraction(w) < self.rate

    # ------------------------------------------------------------- audit
    def audit_windows(self, pairs, lane_index: int, iteration: int,
                      batcher=None, wincache=None,
                      cache_keys=None) -> int:
        """Audit one finished iteration: `pairs` is [(window, polisher)]
        for every window the iteration completed. Samples by content
        hash, shadow re-executes the sampled set through the oracle,
        byte-compares, and fires the full mismatch consequence chain
        (module docstring) — including REPAIRING the production window
        — before the caller delivers the windows to their jobs. Returns
        the number of mismatches. Never raises: the batcher wraps it,
        and an audit bug must not fail production.

        CACHE-HIT audits (serve/wincache.py): when `wincache` and
        `cache_keys` (id(window) -> cache key) are given, a mismatched
        window came out of the content cache, not a device lane — the
        consequence chain redirects at the CACHE: the poisoned entry
        is evicted and its key quarantined, the window still repaired,
        but no engine is demoted and no lane quarantined (the
        populating iteration already had its own audit; blaming
        whatever lane the hit happened to ride would be noise)."""
        from ..ops.oracle import snapshot_window

        rate = self.rate
        chosen = [(w, p) for w, p in pairs
                  if window_sample_fraction(w) < rate]
        with self._lock:
            self.counters["windows"] += len(pairs)
            self.counters["sampled"] += len(chosen)
        if not chosen:
            return 0
        mismatches = 0
        exemplar = None
        t0 = time.perf_counter()
        # group by polisher: one oracle pass per job's parameter set
        by_polisher: dict[int, tuple] = {}
        for w, p in chosen:
            by_polisher.setdefault(id(p), (p, []))[1].append(w)
        for p, windows in by_polisher.values():
            snaps = [snapshot_window(w) for w in windows]
            clones = self.oracle.consensus(p, snaps)
            for w, snap, clone in zip(windows, snaps, clones):
                ok = (w.consensus == clone.consensus
                      and w.polished == clone.polished)
                with self._lock:
                    self.counters["audited"] += 1
                    if ok:
                        self.counters["clean"] += 1
                if not ok:
                    mismatches += 1
                    ck = (cache_keys.get(id(w))
                          if cache_keys is not None else None)
                    exemplar = self._on_mismatch(w, snap, clone, p,
                                                 lane_index, iteration,
                                                 batcher,
                                                 wincache=wincache,
                                                 cache_key=ck)
        shadow_s = time.perf_counter() - t0
        with self._lock:
            self.counters["shadow_s"] += shadow_s
        if self.hists is not None:
            # ONE real observation per shadow pass; when the pass caught
            # a mismatch, ITS bucket carries the exemplar naming the
            # dual-stream artifact (no phantom zero-duration samples)
            self.hists.observe("audit.shadow", shadow_s,
                               exemplar=exemplar)
        return mismatches

    def _on_mismatch(self, w, snap, clone, p, lane_index: int,
                     iteration: int, batcher, wincache=None,
                     cache_key=None) -> dict | None:
        """The full consequence chain for one confirmed mismatch;
        returns the exemplar labels the caller attaches to this shadow
        pass's `audit.shadow` observation. `cache_key` marks a CACHE
        mismatch (see audit_windows): the entry takes the blame, the
        device plane does not."""
        from ..ops.poa_pallas import pallas_mode

        from_cache = cache_key is not None
        engine = _engine_label(p)
        labels = {"engine": engine,
                  "kernel": pallas_mode(),
                  "dtype": _dtype_label(),
                  "bucket": f"{len(w.sequences)}x{len(w.sequences[0])}",
                  "lane": "cache" if from_cache else str(lane_index)}
        job = getattr(p, "serve_job_id", None)
        trace = getattr(p, "serve_trace_id", None)
        flight = self._dump_streams(w, clone, labels, job, iteration)
        demoted: list[str] = []
        if self.demote_enabled and not from_cache:
            demoted = self._demote(engine)
        if from_cache and wincache is not None:
            # evict the poisoned bytes and condemn the key: a repeat
            # of this content re-dispatches (and re-populates from a
            # fresh, audited iteration) instead of re-serving them
            wincache.quarantine(cache_key)
        with self._lock:
            self.counters["mismatches"] += 1
            key = tuple(sorted(labels.items()))
            self.mismatch_series[key] = self.mismatch_series.get(key,
                                                                 0) + 1
            self.counters["demotions"] += len(demoted)
            # known-good probe for the lane re-probe: this window's
            # content with its oracle-verified bytes (parameters
            # snapshotted slim — never the job's whole Polisher)
            self._probe = (_slim_params(p), snap, clone.consensus,
                           clone.polished)
            ev = AuditMismatch(job=job, trace=trace, lane=lane_index,
                               iteration=iteration, window_id=w.id,
                               rank=w.rank, labels=labels,
                               flight=flight, demoted=demoted,
                               t=round(time.time(), 6))
            self.recent.append(ev)
            del self.recent[:-16]
        if self.journal is not None:
            fields = dict(labels)  # carries the lane label already
            fields.update(iteration=iteration,
                          window=f"{w.id}:{w.rank}", flight=flight,
                          demoted=demoted or None,
                          cache=("entry-quarantined" if from_cache
                                 else None))
            self.journal.record("audit-mismatch", job=job, trace=trace,
                                **fields)
        log_info(f"[racon_tpu::audit] MISMATCH "
                 + ("cache entry"
                    if from_cache else f"lane {lane_index} "
                                       f"iteration {iteration}")
                 + f" window {w.id}:{w.rank} "
                 f"({labels['engine']}/{labels['kernel']}/"
                 f"{labels['dtype']} {labels['bucket']}): production "
                 f"bytes diverge from the oracle"
                 + ("; entry evicted and key quarantined"
                    if from_cache else "")
                 + (f"; demoted {len(demoted)} winner entr"
                    f"{'y' if len(demoted) == 1 else 'ies'}"
                    if demoted else "")
                 + (f"; dual-stream dump {flight}" if flight else ""))
        # REPAIR: the caught window ships the oracle bytes — detection
        # protects this job's output, not just the dashboards
        w.consensus = clone.consensus
        w.polished = clone.polished
        with self._lock:
            self.counters["repaired"] += 1
        self._update_alert()
        if demoted and batcher is not None:
            # a demotion must take effect on EVERY lane now: the
            # engines' per-bucket plan caches resolved the old winner,
            # so flag them all stale (rebuilt at each lane's next
            # iteration), not just the quarantined lane's
            batcher.flush_lane_engines()
        if (self.quarantine_enabled and batcher is not None
                and not from_cache):
            batcher.quarantine_lane(lane_index)
        return {k: v for k, v in
                (("trace_id", trace or job), ("job", job),
                 ("flight", flight)) if v} or None

    def _demote(self, engine: str) -> list[str]:
        from ..sched.autotune import get_autotuner

        demoted: list[str] = []
        try:
            at = get_autotuner()
            for eng in _DEMOTE_ENGINES.get(engine, ()):
                demoted += at.demote(engine=eng)
        except Exception as exc:  # noqa: BLE001 — demotion is a
            # consequence, never a second failure
            log_info(f"[racon_tpu::audit] warning: winner-table "
                     f"demotion failed ({type(exc).__name__}: {exc})")
        return demoted

    def _dump_streams(self, w, clone, labels: dict, job,
                      iteration: int) -> str | None:
        """The dual-stream flight artifact: a Chrome-trace-shaped JSON
        (indexable by tools/obsreport.py alongside the job dumps) whose
        `flight` object carries BOTH byte streams. Best-effort: a full
        disk loses the artifact, never the audit verdict."""
        if not self.flight_dir:
            return None
        try:
            os.makedirs(self.flight_dir, exist_ok=True)
            with self._lock:
                self._flight_seq += 1
                seq = self._flight_seq
            path = os.path.join(
                self.flight_dir,
                f"flight_{job or 'audit'}_audit-mismatch_{seq}.json")
            doc = {"traceEvents": [],
                   "displayTimeUnit": "ms",
                   "flight": {
                       "reason": "audit-mismatch",
                       "job_id": job, "iteration": iteration,
                       "window": {"id": w.id, "rank": w.rank},
                       "labels": labels,
                       "produced": w.consensus.decode("latin-1"),
                       "produced_polished": w.polished,
                       "oracle": clone.consensus.decode("latin-1"),
                       "oracle_polished": clone.polished}}
            with open(path, "w") as fh:
                json.dump(doc, fh)
            return path
        except Exception as exc:  # noqa: BLE001 — see docstring
            log_info(f"[racon_tpu::audit] warning: could not write "
                     f"dual-stream dump ({type(exc).__name__}: {exc})")
            return None

    # ---------------------------------------------------------- reprobe
    def probe(self):
        """The known-good re-probe input for a quarantined lane:
        (polisher_params, window_snapshot, expected_consensus,
        expected_polished) — the latest mismatched window with its
        oracle-verified bytes. None before any mismatch."""
        with self._lock:
            return self._probe

    def lane_event(self, lane_index: int, state: str, **fields) -> None:
        """Journal + log one lane health transition (the batcher calls
        this on quarantine / rejoin / degraded-rejoin)."""
        if self.journal is not None:
            self.journal.record("audit-lane", lane=lane_index,
                                state=state, **fields)
        log_info(f"[racon_tpu::audit] lane {lane_index} {state}"
                 + (f" ({', '.join(f'{k}={v}' for k, v in fields.items())})"
                    if fields else ""))

    # ------------------------------------------------------------ alert
    def _update_alert(self) -> None:
        with self._lock:
            firing = self.counters["mismatches"] > self._acked
            changed = firing != self._alert_firing
            self._alert_firing = firing
            detail = {"mismatches": self.counters["mismatches"],
                      "acked": self._acked}
        if changed and self.on_alert is not None:
            try:
                self.on_alert("firing" if firing else "clear", detail)
            except Exception:  # noqa: BLE001 — alerting is decoration
                pass

    @property
    def alert_firing(self) -> bool:
        with self._lock:
            return self._alert_firing

    def ack(self) -> dict:
        """Operator acknowledgement (the debug RPC's `audit_ack`): the
        alert clears and stays clear until the NEXT mismatch."""
        with self._lock:
            self._acked = self.counters["mismatches"]
        self._update_alert()
        with self._lock:
            return {"acked": self._acked,
                    "firing": self._alert_firing}

    # --------------------------------------------------------- exposure
    def mismatch_samples(self) -> list[tuple[dict, int]]:
        """Labeled samples for the scrape's audit_mismatches family."""
        with self._lock:
            items = sorted(self.mismatch_series.items())
        return [(dict(key), n) for key, n in items]

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["shadow_s"] = round(out["shadow_s"], 4)
            out["rate"] = self.rate
            out["alert_firing"] = self._alert_firing
            out["acked"] = self._acked
            out["recent"] = [m.as_dict() for m in self.recent[-4:]]
        out["shadow"] = self.oracle.stats()
        return out

    def close(self) -> None:
        self.oracle.close()


def _dtype_label() -> str:
    from ..ops.dtypes import dtype_mode

    return dtype_mode()
