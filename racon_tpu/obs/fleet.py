"""Fleet observability plane: scrape federation + SLO burn-rate alerts.

Every observability surface so far (histograms, the `scrape` RPC, the
flight recorder, the journal) is PROCESS-LOCAL: an operator of N
replicas has N disconnected dashboards and no way to trace a
fleet-level p99 spike to the one flight dump that explains it. This
module is the missing aggregation layer, and the groundwork the
multi-replica serve fabric (ROADMAP item 1) lands on:

  - `FleetAggregator` polls any number of replica endpoints — unix or
    TCP `scrape`/`healthz` RPC (serve/protocol.py frames) or an
    `http://` `/metrics`+`/healthz` pair — parses each body back into
    typed series via the STRICT obs/prom.py parser, and merges them:
    counters and gauges sum per (name, labels); histograms reconstruct
    through `Histogram.from_export` and fold through the SAME
    `Histogram.merge` the in-process path uses, so fleet quantiles are
    exactly the quantiles of the pooled per-replica buckets (with the
    exact min/max the `_min`/`_max` sidecars carry). Bucket exemplars
    survive the merge last-write-wins, so the fleet p99 bucket still
    names a real job's trace id and flight dump.
  - The merged view exposes three ways: a federated `/metrics` +
    `/healthz` HTTP endpoint (healthy = every replica reachable and
    not draining, per-replica detail in the JSON body), a
    machine-readable snapshot (`to_json()`, the `racon_tpu fleet
    --json` shape), and `tools/servetop.py`'s live console.
  - `BurnRateTracker` is the SLO alerting half: a fast/slow dual-window
    burn-rate monitor over the cumulative `deadline_hit` /
    `deadline_miss` counters (the SRE multiwindow shape: alert only
    when BOTH the fast and the slow window burn the error budget
    faster than `threshold`x, so a single straggler cannot page and a
    sustained breach cannot hide). The serve layer samples it on every
    deadline-carrying job (queue `on_slo` hook) and the aggregator on
    every poll; state transitions journal typed `alert` events and the
    scrape grows `racon_tpu_slo_burn_rate` / `racon_tpu_slo_burn_alert`
    gauges.

Env knobs (all optional): RACON_TPU_FLEET_ENDPOINTS (comma-separated
replica endpoints — the default for `racon_tpu fleet` / servetop),
RACON_TPU_SLO_BUDGET (allowed deadline-miss rate, default 0.01),
RACON_TPU_SLO_BURN_FAST_S / RACON_TPU_SLO_BURN_SLOW_S (window lengths,
default 60 / 600) and RACON_TPU_SLO_BURN_THRESHOLD (burn multiple that
fires, default 2.0)."""

from __future__ import annotations

import contextlib
import json
import os
import socket
import threading
import time
from collections import deque

from . import prom
from .hist import Histogram, HistogramSet

#: merged counter names the burn tracker reads
HIT_COUNTER = "racon_tpu_serve_jobs_deadline_hit_total"
MISS_COUNTER = "racon_tpu_serve_jobs_deadline_miss_total"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_endpoints() -> list[str]:
    raw = os.environ.get("RACON_TPU_FLEET_ENDPOINTS", "")
    return [e.strip() for e in raw.split(",") if e.strip()]


# ---------------------------------------------------------------- burn rate
class BurnRateTracker:
    """Fast/slow dual-window SLO burn-rate monitor (module docstring).

    Feed it CUMULATIVE deadline_hit/deadline_miss counter samples via
    `sample()`; it returns the windowed burn rates (window miss-rate /
    error budget), the firing state, and whether the state just
    changed (the journal-alert edge). `seed_zero` plants a (0, 0)
    baseline at construction — right for an in-process tracker born
    with its counters (the serve layer); an aggregator attaching to
    replicas mid-life leaves it False so pre-existing totals are the
    baseline, not a phantom flood."""

    def __init__(self, budget: float | None = None,
                 fast_s: float | None = None,
                 slow_s: float | None = None,
                 threshold: float | None = None,
                 seed_zero: bool = False):
        self.budget = max(1e-9, budget if budget is not None
                          else _env_float("RACON_TPU_SLO_BUDGET", 0.01))
        self.fast_s = (fast_s if fast_s is not None
                       else _env_float("RACON_TPU_SLO_BURN_FAST_S", 60.0))
        self.slow_s = (slow_s if slow_s is not None
                       else _env_float("RACON_TPU_SLO_BURN_SLOW_S",
                                       600.0))
        self.threshold = (threshold if threshold is not None
                          else _env_float("RACON_TPU_SLO_BURN_THRESHOLD",
                                          2.0))
        self._samples: deque = deque()
        self._lock = threading.Lock()
        self.firing = False
        self.fast = 0.0
        self.slow = 0.0
        #: planted lazily at the first sample's OWN clock, so callers
        #: that drive `t` explicitly (tests, replayed journals) get a
        #: coherent timeline
        self._seed_zero = seed_zero

    def _burn_locked(self, now: float, window: float) -> float:
        """Miss-rate over `window`, as a multiple of the budget. The
        baseline is the newest sample at or before the window start
        (falling back to the oldest), so short histories behave like
        their full length rather than reporting zero."""
        if len(self._samples) < 2:
            return 0.0
        cutoff = now - window
        base = self._samples[0]
        for s in self._samples:
            if s[0] > cutoff:
                break
            base = s
        latest = self._samples[-1]
        dh = latest[1] - base[1]
        dm = latest[2] - base[2]
        total = dh + dm
        if total <= 0 or dm <= 0:
            return 0.0
        return (dm / total) / self.budget

    def sample(self, hit: int, miss: int, t: float | None = None) -> dict:
        """Record one cumulative counter sample and re-evaluate. Returns
        {fast, slow, firing, changed, threshold}."""
        now = time.monotonic() if t is None else t
        with self._lock:
            if self._seed_zero:
                self._seed_zero = False
                self._samples.append((now - 1e-9, 0, 0))
            # a counter DECREASE means a replica restarted (summed
            # cumulative counters lost that replica's history): the
            # old samples are no longer comparable — rebase on the new
            # totals instead of letting negative deltas zero the burn
            # and mask an ongoing breach for up to a window length
            if self._samples and (hit < self._samples[-1][1]
                                  or miss < self._samples[-1][2]):
                self._samples.clear()
            self._samples.append((now, int(hit), int(miss)))
            # keep one sample at-or-before the slow window start as the
            # baseline; everything older is unreachable by any window
            while (len(self._samples) > 2
                   and self._samples[1][0] <= now - self.slow_s):
                self._samples.popleft()
            self.fast = self._burn_locked(now, self.fast_s)
            self.slow = self._burn_locked(now, self.slow_s)
            firing = (self.fast >= self.threshold
                      and self.slow >= self.threshold)
            changed = firing != self.firing
            self.firing = firing
            return {"fast": round(self.fast, 4),
                    "slow": round(self.slow, 4),
                    "firing": firing, "changed": changed,
                    "threshold": self.threshold}

    def state(self) -> dict:
        with self._lock:
            return {"fast": round(self.fast, 4),
                    "slow": round(self.slow, 4),
                    "firing": self.firing,
                    "threshold": self.threshold,
                    "budget": self.budget}


# ---------------------------------------------------------------- endpoints
class Endpoint:
    """One replica address. Three spellings:

      - `http://host:port[/base]` — HTTP: GET `<base>/metrics` and
        `<base>/healthz` (a `--metrics-port` replica, or another
        aggregator — federation composes);
      - `host:port` / `:port` / `port` — localhost-ish TCP RPC
        (`scrape` / `healthz` frames);
      - anything with a path separator — unix-socket RPC."""

    def __init__(self, spec: str):
        self.spec = spec.strip()
        if not self.spec:
            raise ValueError("empty fleet endpoint")
        if self.spec.startswith(("http://", "https://")):
            self.kind = "http"
            self.base = self.spec.rstrip("/")
            if self.base.endswith("/metrics"):
                self.base = self.base[: -len("/metrics")]
        elif "/" in self.spec or os.path.sep in self.spec:
            self.kind = "unix"
        else:
            self.kind = "tcp"
            host, _, port = self.spec.rpartition(":")
            try:
                self.port = int(port)
            except ValueError:
                raise ValueError(
                    f"fleet endpoint {spec!r}: expected host:port, a "
                    "unix socket path, or an http:// URL") from None
            self.host = host or "127.0.0.1"

    # ------------------------------------------------------------- probes
    def _rpc(self, req: dict, timeout: float) -> dict:
        from ..serve.protocol import recv_frame, send_frame

        if self.kind == "unix":
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            addr = self.spec
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            addr = (self.host, self.port)
        sock.settimeout(timeout)
        try:
            sock.connect(addr)
            send_frame(sock, req)
            resp = recv_frame(sock)
        finally:
            with contextlib.suppress(OSError):
                sock.close()
        if not isinstance(resp, dict):
            raise OSError("replica closed mid-request")
        if resp.get("type") == "error":
            raise OSError(f"replica error: {resp.get('message')}")
        return resp

    def _http_get(self, path: str, timeout: float) -> tuple[int, bytes]:
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(self.base + path,
                                        timeout=timeout) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as exc:
            # 503-with-body is a VALID healthz answer, not a failure
            return exc.code, exc.read()

    def scrape(self, timeout: float = 2.0) -> str:
        if self.kind == "http":
            status, body = self._http_get("/metrics", timeout)
            if status != 200:
                raise OSError(f"/metrics answered {status}")
            return body.decode("utf-8", "replace")
        return self._rpc({"type": "scrape"}, timeout)["text"]

    def healthz(self, timeout: float = 2.0) -> dict:
        """{ok, draining, ...} — transport-normalized."""
        if self.kind == "http":
            status, body = self._http_get("/healthz", timeout)
            try:
                doc = json.loads(body.decode("utf-8", "replace"))
            except ValueError:
                # pre-fleet replicas answered plain "ok\n"/"draining\n"
                text = body.decode("utf-8", "replace").strip()
                doc = {"draining": text == "draining"}
            doc["ok"] = status == 200 and not doc.get("draining")
            return doc
        resp = self._rpc({"type": "healthz"}, timeout)
        resp.setdefault("ok", not resp.get("draining"))
        return resp


# -------------------------------------------------------------- aggregation
class ReplicaSample:
    """One replica's poll result: parsed scrape + health, or the error
    that made it unreachable."""

    __slots__ = ("endpoint", "ok", "draining", "error", "scrape_s",
                 "parsed", "health")

    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        self.ok = False
        self.draining = False
        self.error: str | None = None
        self.scrape_s = 0.0
        self.parsed: prom.Scrape | None = None
        self.health: dict = {}


class FleetSnapshot:
    """One poll's merged view (see FleetAggregator.poll)."""

    __slots__ = ("t_wall", "poll_s", "replicas", "counters", "gauges",
                 "counter_series", "gauge_series", "hists", "burn")

    def __init__(self):
        self.t_wall = time.time()
        self.poll_s = 0.0
        self.replicas: list[ReplicaSample] = []
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.counter_series: dict[str, dict] = {}
        self.gauge_series: dict[str, dict] = {}
        self.hists = HistogramSet()
        self.burn: dict = {}

    @property
    def healthy(self) -> bool:
        return bool(self.replicas) and all(
            r.ok and not r.draining for r in self.replicas)


class FleetAggregator:
    """Polls replica endpoints, merges their expositions, and serves
    the federated view (module docstring)."""

    def __init__(self, endpoints: list[str] | None = None,
                 timeout_s: float = 2.0, journal=None,
                 burn: BurnRateTracker | None = None):
        specs = endpoints if endpoints is not None else default_endpoints()
        if not specs:
            raise ValueError(
                "no fleet endpoints (pass --endpoints or set "
                "RACON_TPU_FLEET_ENDPOINTS)")
        self.endpoints = [Endpoint(s) for s in specs]
        self.timeout_s = timeout_s
        self.burn = burn or BurnRateTracker()
        #: obs.journal.Journal (or any .record(event, **fields) sink)
        #: receiving typed `alert` events on burn-state transitions
        self.journal = journal
        self.polls = 0
        self._last: FleetSnapshot | None = None
        self._lock = threading.Lock()
        self._http = None
        self._poller: threading.Thread | None = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ polling
    # ------------------------------------------------ endpoint mutation
    def add_endpoint(self, spec: str) -> None:
        """Join one endpoint to the polled set mid-flight (the router
        autoscaler's scale-up seam). The list is REPLACED, not mutated:
        poll() snapshots it once per pass, so a concurrent poll sees
        either the old or the new set, never a half-edit."""
        with self._lock:
            if any(ep.spec == spec for ep in self.endpoints):
                return
            self.endpoints = self.endpoints + [Endpoint(spec)]

    def remove_endpoint(self, spec: str) -> None:
        with self._lock:
            self.endpoints = [ep for ep in self.endpoints
                              if ep.spec != spec]

    def poll(self) -> FleetSnapshot:
        snap = FleetSnapshot()
        t0 = time.perf_counter()
        for ep in list(self.endpoints):
            rs = ReplicaSample(ep.spec)
            t1 = time.perf_counter()
            try:
                text = ep.scrape(self.timeout_s)
                rs.parsed = prom.parse(text)
                rs.health = ep.healthz(self.timeout_s)
                rs.draining = bool(rs.health.get("draining"))
                rs.ok = bool(rs.health.get("ok", not rs.draining))
            except (OSError, ValueError, KeyError) as exc:
                rs.error = f"{type(exc).__name__}: {exc}"
            rs.scrape_s = time.perf_counter() - t1
            snap.replicas.append(rs)
        self._merge(snap)
        snap.poll_s = time.perf_counter() - t0
        hit = int(snap.counters.get(HIT_COUNTER, 0))
        miss = int(snap.counters.get(MISS_COUNTER, 0))
        snap.burn = self.burn.sample(hit, miss)
        if snap.burn["changed"] and self.journal is not None:
            with contextlib.suppress(Exception):
                self.journal.record(
                    "alert", kind="slo-burn", scope="fleet",
                    state="firing" if snap.burn["firing"] else "clear",
                    burn_fast=snap.burn["fast"],
                    burn_slow=snap.burn["slow"],
                    threshold=snap.burn["threshold"],
                    deadline_hit=hit, deadline_miss=miss)
        with self._lock:
            self._last = snap
            self.polls += 1
        return snap

    @staticmethod
    def _merge(snap: FleetSnapshot) -> None:
        for rs in snap.replicas:
            if rs.parsed is None:
                continue
            for name, v in rs.parsed.counters.items():
                snap.counters[name] = snap.counters.get(name, 0) + v
            for name, v in rs.parsed.gauges.items():
                snap.gauges[name] = snap.gauges.get(name, 0) + v
            for store, src in ((snap.counter_series,
                                rs.parsed.counter_series),
                               (snap.gauge_series,
                                rs.parsed.gauge_series)):
                for name, series in src.items():
                    dst = store.setdefault(name, {})
                    for key, (labels, v) in series.items():
                        old = dst.get(key)
                        dst[key] = (labels,
                                    (old[1] if old else 0) + v)
            for name in rs.parsed.hists:
                mine = snap.hists.get(name)
                theirs = rs.parsed.histogram(name)
                if mine is None:
                    snap.hists._hists[name] = theirs
                else:
                    mine.merge(theirs)

    def last(self) -> FleetSnapshot | None:
        with self._lock:
            return self._last

    # ----------------------------------------------------------- exposure
    def healthz(self) -> tuple[bool, dict]:
        """(healthy, detail): healthy = every replica reachable and not
        draining — the load-balancer contract, with per-replica detail
        for the operator behind it."""
        snap = self.last() or self.poll()
        detail = {
            "ok": snap.healthy,
            "replicas": [
                {"endpoint": r.endpoint, "ok": r.ok,
                 "draining": r.draining, "error": r.error}
                for r in snap.replicas],
            "burn": self.burn.state()}
        return snap.healthy, detail

    def prometheus_text(self) -> str:
        """The federated scrape body: every merged series under its
        original name, plus the fleet-meta and burn-rate gauges."""
        snap = self.last() or self.poll()
        counters: dict = dict(snap.counters)
        for name, series in snap.counter_series.items():
            counters[name] = prom.Labeled(
                [(labels, v) for labels, v in series.values()])
        gauges: dict = dict(snap.gauges)
        for name, series in snap.gauge_series.items():
            gauges[name] = prom.Labeled(
                [(labels, v) for labels, v in series.values()])
        # the replicas' own burn gauges merged by summation are
        # meaningless (and would DUPLICATE the fleet tracker's
        # families below — a real Prometheus server rejects a body
        # with a repeated metric family): the fleet-level burn view
        # below replaces them
        for name in ("racon_tpu_slo_burn_rate",
                     "racon_tpu_slo_burn_rate_slow",
                     "racon_tpu_slo_burn_alert"):
            gauges.pop(name, None)
        up = sum(1 for r in snap.replicas if r.ok)
        gauges["fleet.replicas"] = (
            len(snap.replicas), "configured replica endpoints")
        gauges["fleet.replicas_up"] = (
            up, "replicas reachable and not draining at the last poll")
        gauges["fleet.healthy"] = snap.healthy
        gauges["fleet.replica_up"] = prom.Labeled(
            [({"replica": r.endpoint}, r.ok) for r in snap.replicas])
        gauges["fleet.scrape_seconds"] = prom.Labeled(
            [({"replica": r.endpoint}, round(r.scrape_s, 6))
             for r in snap.replicas],
            "per-replica scrape+parse round-trip at the last poll")
        gauges["fleet.poll_seconds"] = round(snap.poll_s, 6)
        burn = self.burn.state()
        gauges["slo.burn_rate"] = (
            burn["fast"], "fast-window SLO burn rate (miss-rate / "
            "budget) over the merged fleet counters")
        gauges["slo.burn_rate_slow"] = burn["slow"]
        gauges["slo.burn_alert"] = (
            burn["firing"], "1 while both burn windows exceed the "
            "threshold")
        return prom.render(counters, gauges, snap.hists)

    def to_json(self) -> dict:
        """Machine-readable fleet snapshot (the `racon_tpu fleet
        --json` body): per-replica health + headline series, merged
        totals, merged latency quantiles, burn state."""
        snap = self.last() or self.poll()

        def headline(parsed: prom.Scrape | None) -> dict:
            if parsed is None:
                return {}
            g, c = parsed.gauges, parsed.counters
            return {
                "queue_depth": g.get("racon_tpu_serve_queue_depth"),
                "inflight": g.get("racon_tpu_serve_inflight"),
                "uptime_s": g.get("racon_tpu_serve_uptime_seconds"),
                "completed": c.get(
                    "racon_tpu_serve_jobs_completed_total"),
                "failed": c.get("racon_tpu_serve_jobs_failed_total"),
                "deadline_miss": c.get(MISS_COUNTER),
                "iterations": c.get(
                    "racon_tpu_serve_batch_iterations_total")}

        hists = {}
        for name, h in snap.hists.items():
            hists[name] = h.snapshot()
            ex = h.bucket_exemplars()
            if ex:
                hists[name]["exemplars"] = {
                    prom._le(le): e for le, e in sorted(ex.items())}
        return {
            "t": round(snap.t_wall, 3),
            "poll_s": round(snap.poll_s, 6),
            "healthy": snap.healthy,
            "replicas": [
                dict({"endpoint": r.endpoint, "ok": r.ok,
                      "draining": r.draining, "error": r.error,
                      "scrape_s": round(r.scrape_s, 6)},
                     **headline(r.parsed))
                for r in snap.replicas],
            "merged": {"counters": {k: snap.counters[k]
                                    for k in sorted(snap.counters)},
                       "gauges": {k: snap.gauges[k]
                                  for k in sorted(snap.gauges)}},
            "latency": hists,
            "burn": self.burn.state()}

    # --------------------------------------------------------------- serve
    def start_http(self, port: int) -> int:
        """Serve the federated `/metrics` + `/healthz` on localhost
        HTTP (0 = ephemeral; returns the bound port). Handler errors
        answer 500 and never kill the aggregator — the serve-layer
        discipline."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        agg = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path in ("/metrics", "/"):
                        body = agg.prometheus_text().encode()
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         prom.CONTENT_TYPE)
                    elif path == "/healthz":
                        ok, detail = agg.healthz()
                        body = (json.dumps(detail, sort_keys=True)
                                + "\n").encode()
                        self.send_response(200 if ok else 503)
                        self.send_header("Content-Type",
                                         "application/json")
                    else:
                        self.send_error(404)
                        return
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as exc:  # noqa: BLE001 — see docstring
                    with contextlib.suppress(Exception):
                        self.send_error(
                            500, f"{type(exc).__name__}: {exc}")

            def log_message(self, *args):
                pass

        httpd = ThreadingHTTPServer(("127.0.0.1", max(0, port)),
                                    _Handler)
        httpd.daemon_threads = True
        self._http = httpd
        t = threading.Thread(target=httpd.serve_forever,
                             name="racon-tpu-fleet-http", daemon=True)
        t.start()
        return httpd.server_address[1]

    def run(self, interval_s: float) -> None:
        """Background poll loop (daemon thread) at `interval_s`."""

        def loop():
            while not self._stop.is_set():
                with contextlib.suppress(Exception):
                    self.poll()
                self._stop.wait(interval_s)

        self._poller = threading.Thread(
            target=loop, name="racon-tpu-fleet-poll", daemon=True)
        self._poller.start()

    def close(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=2.0)
        if self._http is not None:
            with contextlib.suppress(Exception):
                self._http.shutdown()
                self._http.server_close()
            self._http = None


# --------------------------------------------------------------------- CLI
def fleet_main(argv: list[str]) -> int:
    """`racon_tpu fleet` entry point: one-shot `--json` snapshot, or a
    long-running federated `/metrics`+`/healthz` endpoint."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="racon_tpu fleet",
        description="fleet scrape aggregator: poll N replica "
                    "endpoints, merge their metrics, serve the "
                    "federated /metrics + /healthz view (README "
                    "'Fleet view')")
    ap.add_argument("--endpoints", default=None,
                    help="comma-separated replica endpoints — unix "
                         "socket paths, host:port RPC, or http:// "
                         "metrics bases (default: "
                         "RACON_TPU_FLEET_ENDPOINTS)")
    ap.add_argument("--port", type=int, default=None,
                    help="serve the federated /metrics + /healthz on "
                         "this localhost HTTP port (0 = ephemeral, "
                         "printed on start)")
    ap.add_argument("--interval", type=float, default=5.0,
                    help="poll interval seconds (default 5)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-replica scrape timeout seconds")
    ap.add_argument("--json", action="store_true",
                    help="poll once, print the machine-readable fleet "
                         "snapshot to stdout, exit (0 = healthy)")
    ap.add_argument("--journal", default=None,
                    help="journal path receiving fleet-scope `alert` "
                         "events on burn-rate transitions")
    args = ap.parse_args(argv)

    endpoints = ([e.strip() for e in args.endpoints.split(",")
                  if e.strip()] if args.endpoints else None)
    journal = None
    if args.journal:
        from .journal import Journal

        journal = Journal(args.journal)
    try:
        agg = FleetAggregator(endpoints, timeout_s=args.timeout,
                              journal=journal)
    except ValueError as exc:
        print(f"[racon_tpu::fleet] error: {exc}", file=sys.stderr)
        return 2
    if args.json:
        snap = agg.poll()
        print(json.dumps(agg.to_json(), indent=2, sort_keys=True))
        return 0 if snap.healthy else 1
    port = agg.start_http(args.port if args.port is not None else 0)
    print(f"[racon_tpu::fleet] federating {len(agg.endpoints)} "
          f"replica(s) on http://127.0.0.1:{port} "
          f"(/metrics, /healthz; poll every {args.interval:g}s)",
          file=sys.stderr)
    agg.run(args.interval)
    try:
        while True:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        agg.close()
        if journal is not None:
            journal.close()
    return 0
