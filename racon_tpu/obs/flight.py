"""Flight recorder: an always-on bounded ring of recent spans.

Span tracing (obs/trace.py) answers "where did the time go" — but only
when someone armed it BEFORE the interesting run, and its buffers grow
without bound, so a long-lived server cannot simply leave it on. The
flight recorder closes exactly that gap, the way aircraft FDRs and
inference servers' request recorders do:

  - `FlightRecorder` IS a `TraceRecorder` whose events land in ONE
    shared bounded ring (`collections.deque(maxlen=...)` — atomic
    appends under the GIL, no extra lock on the hot path): every
    existing instrumentation site — pipeline stage spans, engine
    rounds, XLA compiles, resilience instants — feeds it unchanged,
    spans keep the exact perf_counter endpoints the stage counters
    charge (so span sums still pin to stage_stats), and memory is a
    hard constant (`capacity` events total, RACON_TPU_FLIGHT_EVENTS,
    default 4096). Old events fall off the back; the recent past is
    always there. Unlike the base recorder, track ids are keyed by
    THREAD NAME, not per registration: a long-lived server spawns
    fresh pack/unpack/fallback threads per job, and per-registration
    buffers would accumulate one dead ring per thread forever — the
    name set (`racon-tpu-pack`, `racon-tpu-serve-worker-0`, ...) is
    small and stable, so both the ring and the track table stay
    bounded for the process lifetime.
  - The serve layer installs one at startup when no full trace is armed
    (server.py), leaves it on for the process lifetime — the measured
    recording overhead is the same <2% budget as tracing
    (`tools/synthbench.py --flight` A/Bs it) — and DUMPS it when a job
    fails, times out, or misses its deadline: `dump()` writes a valid
    Chrome trace-event JSON (loadable in Perfetto) windowed to the job,
    with the job's identity, error and stage_stats snapshot riding as a
    top-level `flight` object. The `debug` RPC returns the same recent
    events on demand for a live post-mortem.

`dump()` is a module function over ANY TraceRecorder, not a method:
when a full trace is armed (RACON_TPU_TRACE), the server reuses that
recorder as its flight source and dump/debug work identically."""

from __future__ import annotations

import json
import os
import threading
from collections import deque

from .trace import TraceRecorder, trace_matches

#: default total ring capacity (events); a span dict is ~200 bytes, so
#: the default bounds the recorder around ~1 MB for the process lifetime
DEFAULT_CAPACITY = 4096

#: default cap on events a single `trace_pull` RPC returns
#: (RACON_TPU_TRACE_PULL_EVENTS) — a routed job only needs its own
#: window of the ring, and the reply rides one length-prefixed frame
DEFAULT_PULL_EVENTS = 2048


def ring_capacity() -> int:
    try:
        n = int(os.environ.get("RACON_TPU_FLIGHT_EVENTS", 0))
    except ValueError:
        n = 0
    return n if n > 0 else DEFAULT_CAPACITY


def default_dump_dir() -> str | None:
    """RACON_TPU_FLIGHT_DIR: process-wide default directory for flight
    dump artifacts — keeps them out of whatever the working directory
    happens to be. The serve layer's own RACON_TPU_SERVE_FLIGHT_DIR /
    `serve --flight-dir` wins over it (ServeConfig), and the serve
    startup validates the resolved directory STRICTLY: an unwritable
    path fails the start instead of silently losing every post-mortem
    (serve/server.py, mirroring the --metrics-port strict-parse
    behavior). None when unset or empty."""
    return os.environ.get("RACON_TPU_FLIGHT_DIR") or None


class FlightRecorder(TraceRecorder):
    """TraceRecorder with one shared bounded ring (see module
    docstring): constant memory and constant `events()` cost no matter
    how many short-lived threads record into it."""

    def __init__(self, capacity: int | None = None):
        super().__init__(path=None)
        self.capacity = capacity if capacity else ring_capacity()
        # deque.append evicts the oldest event once full — O(1) and
        # atomic under the GIL, so concurrent recorders need no lock
        self._ring: deque = deque(maxlen=self.capacity)
        self._buffers.append(self._ring)  # base events() reads it
        self._name_tids: dict[str, int] = {}

    def _buf(self) -> deque:
        # tid keyed by thread NAME (bounded, stable set) instead of the
        # base class's per-registration tid (one dead buffer per thread
        # the server ever spawned — the leak this class exists to avoid)
        tid = getattr(self._local, "tid", None)
        if tid is None:
            name = threading.current_thread().name
            with self._lock:
                tid = self._name_tids.get(name)
                if tid is None:
                    tid = self._next_tid
                    self._next_tid += 1
                    self._name_tids[name] = tid
                    self._threads[tid] = name
            self._local.tid = tid
        return self._ring


def trace_pull_max_events() -> int:
    try:
        n = int(os.environ.get("RACON_TPU_TRACE_PULL_EVENTS", 0))
    except ValueError:
        n = 0
    return n if n > 0 else DEFAULT_PULL_EVENTS


def trace_events(recorder: TraceRecorder,
                 trace_id: str | list[str] | tuple[str, ...],
                 max_events: int | None = None) -> list[dict]:
    """The ring windowed to ONE distributed trace: spans/instants whose
    args carry `trace_id` (exact or dotted child `<trace>.s<k>` match,
    including lane-iteration `trace_ids` lists), plus every thread-name
    metadata event so track labels survive the pull. A list of ids
    selects the union — the router pulls each replica for exactly the
    child traces that completed there. Oldest events are trimmed past
    `max_events` (metadata kept) — the trace_pull RPC's bounded-reply
    guarantee."""
    cap = max_events if max_events and max_events > 0 else trace_pull_max_events()
    tids = ((trace_id,) if isinstance(trace_id, str) else
            tuple(trace_id))
    meta, hits = [], []
    for ev in recorder.events():
        if ev.get("ph") == "M":
            meta.append(ev)
        elif any(trace_matches(ev.get("args"), t) for t in tids):
            hits.append(ev)
    return meta + hits[-cap:]


def window_events(recorder: TraceRecorder,
                  since: float | None = None) -> list[dict]:
    """The recorder's events, keeping thread-name metadata but dropping
    spans/instants that START before `since` (a perf_counter timestamp,
    the clock every span already uses) — the "this job's window" filter
    for per-job dumps. None = everything still in the ring."""
    events = recorder.events()
    if since is None:
        return events
    cut = recorder._us(since)
    return [ev for ev in events
            if ev.get("ph") == "M" or ev.get("ts", 0.0) >= cut]


def dump(recorder: TraceRecorder, path: str,
         since: float | None = None,
         flight: dict | None = None) -> str:
    """Write the ring (optionally windowed to `since`) as Chrome
    trace-event JSON. `flight` rides as an extra top-level object
    (job id / reason / error / stage_stats) — Perfetto ignores unknown
    top-level keys, so the artifact stays loadable AND self-describing."""
    doc = {"traceEvents": window_events(recorder, since),
           "displayTimeUnit": "ms"}
    if flight:
        doc["flight"] = flight
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path
