"""Latency histograms: log-bucketed, thread-safe, allocation-free.

The metrics registry (obs/metrics.py) snapshots COUNTERS — totals that
answer "how much". A long-lived server needs DISTRIBUTIONS: "what is p99
job latency right now", "did queue wait grow a tail", "how long does a
compile stall a round". `Histogram` is the serve-grade primitive:

  - LOG-BUCKETED: bucket edges grow geometrically (default factor
    2**0.25, ~19% per bucket) from `lo` to `hi`, so one fixed ~110-slot
    array spans 0.1 ms .. 10 000 s with bounded RELATIVE quantile error
    (an estimate is off by at most one bucket width, ~19% worst case,
    ~9% at the geometric midpoint — tests/test_telemetry.py pins it
    against exact numpy percentiles);
  - EXACT where exactness is cheap: count, sum, min and max are tracked
    outside the buckets, so `max` (the SLO number people page on) is
    never an estimate;
  - THREAD-SAFE and allocation-free on the hot path: `observe` is a
    bisect into a prebuilt edge tuple plus integer adds under one lock —
    no per-observation allocation, no resizing, ever;
  - PROMETHEUS-SHAPED: `cumulative()` yields the classic
    `(le, cumulative_count)` bucket pairs (capped by `+Inf`) that
    obs/prom.py renders as `<name>_bucket{le="..."}` lines.

`HistogramSet` is the named get-or-create collection the polisher, the
job queue and the serve batcher share: `observe("pipeline.pack", dt)` is
the whole wiring surface, and `merge()` folds one set into another
(the server folds each finished job's per-run set into its lifetime
set — exact, because every default-constructed histogram shares the
same edge tuple)."""

from __future__ import annotations

import threading
import time
from bisect import bisect_left


def _edges(lo: float, hi: float, factor: float) -> tuple:
    out = [lo]
    while out[-1] < hi:
        out.append(out[-1] * factor)
    return tuple(out)


#: default bucket edges, shared by every default-constructed Histogram
#: (one tuple per process; sharing is what makes merge() exact)
_DEFAULT_EDGES = _edges(1e-4, 1e4, 2 ** 0.25)


class Histogram:
    """Log-bucketed latency histogram (see module docstring).

    Bucket i counts observations in (edges[i-1], edges[i]]; bucket 0 is
    the underflow bucket (0, edges[0]]; one overflow bucket catches
    values past `hi`. Negative observations clamp to 0 (a clock that ran
    backwards is recorded, not crashed on)."""

    __slots__ = ("edges", "counts", "count", "sum", "min", "max",
                 "exemplars", "_lock")

    def __init__(self, lo: float = 1e-4, hi: float = 1e4,
                 factor: float = 2 ** 0.25):
        if (lo, hi, factor) == (1e-4, 1e4, 2 ** 0.25):
            self.edges = _DEFAULT_EDGES
        else:
            if not (0 < lo < hi and factor > 1):
                raise ValueError(
                    f"Histogram: invalid layout lo={lo} hi={hi} "
                    f"factor={factor}")
            self.edges = _edges(lo, hi, factor)
        self.counts = [0] * (len(self.edges) + 1)  # + overflow
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        #: per-bucket OpenMetrics exemplar slot (bucket index -> dict
        #: with at least `value` and `t`, plus whatever labels the
        #: observer attached — the serve worker records trace_id and
        #: the flight-dump path). LAST-WRITE-WINS per bucket: the slot
        #: is a pointer to one representative observation, not a log.
        self.exemplars: dict[int, dict] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- record
    def observe(self, value: float, exemplar: dict | None = None) -> None:
        v = value if value > 0.0 else 0.0
        i = bisect_left(self.edges, v)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            if exemplar is not None:
                ex = dict(exemplar)
                ex.setdefault("value", v)
                ex.setdefault("t", round(time.time(), 6))
                self.exemplars[i] = ex

    def merge(self, other: "Histogram") -> None:
        """Fold `other` into this histogram (bucket layouts must match —
        default-constructed histograms always do). Exemplar slots merge
        last-write-wins per bucket, by the exemplar's own timestamp."""
        if other.edges is not self.edges and other.edges != self.edges:
            raise ValueError("Histogram.merge: bucket layouts differ")
        with other._lock:
            counts = list(other.counts)
            count, total = other.count, other.sum
            lo, hi = other.min, other.max
            exemplars = {i: dict(e) for i, e in other.exemplars.items()}
        if not count:
            return
        with self._lock:
            for i, c in enumerate(counts):
                self.counts[i] += c
            self.count += count
            self.sum += total
            if self.min is None or (lo is not None and lo < self.min):
                self.min = lo
            if self.max is None or (hi is not None and hi > self.max):
                self.max = hi
            for i, ex in exemplars.items():
                mine = self.exemplars.get(i)
                if mine is None or ex.get("t", 0) >= mine.get("t", 0):
                    self.exemplars[i] = ex

    @classmethod
    def from_export(cls, buckets: list, count: int, total: float,
                    lo: float | None = None, hi: float | None = None,
                    exemplars: dict | None = None) -> "Histogram":
        """Rebuild a Histogram from its `export()` shape — the inverse
        the fleet aggregator needs to merge SCRAPED histograms through
        the same `merge()` the in-process path uses. `buckets` are the
        cumulative `(le, cumulative_count)` pairs ending at `(inf,
        count)`; the finite edges must reproduce a valid layout (the
        default layout round-trips exactly because `repr(float)` is
        lossless). `exemplars` maps the le edge -> exemplar dict."""
        edges = tuple(le for le, _ in buckets if le != float("inf"))
        h = cls.__new__(cls)
        h.edges = _DEFAULT_EDGES if edges == _DEFAULT_EDGES else edges
        if not h.edges:
            raise ValueError("Histogram.from_export: no finite edges")
        h.counts = [0] * (len(h.edges) + 1)
        prev = 0
        for i, (_, cum) in enumerate(b for b in buckets
                                     if b[0] != float("inf")):
            if cum < prev:
                raise ValueError(
                    "Histogram.from_export: non-monotonic buckets")
            h.counts[i] = cum - prev
            prev = cum
        if count < prev:
            raise ValueError(
                "Histogram.from_export: count below last bucket")
        h.counts[len(h.edges)] = count - prev  # overflow
        h.count = count
        h.sum = total
        # a scrape without the _min/_max sidecars (pre-sidecar
        # replicas) still reconstructs USABLE: fall back to the
        # tightest bucket-derived bounds so quantile()/snapshot()/
        # re-rendering never trip over None on a non-empty histogram.
        # Exactness is only promised when the sidecars rode along.
        if count and lo is None:
            first = next(i for i, c in enumerate(h.counts) if c)
            lo = 0.0 if first == 0 else h.edges[first - 1]
        if count and hi is None:
            last = max(i for i, c in enumerate(h.counts) if c)
            hi = h.edges[min(last, len(h.edges) - 1)]
        h.min = lo
        h.max = hi
        h.exemplars = {}
        if exemplars:
            edge_index = {e: i for i, e in enumerate(h.edges)}
            edge_index[float("inf")] = len(h.edges)
            for le, ex in exemplars.items():
                i = edge_index.get(le)
                if i is not None:
                    h.exemplars[i] = dict(ex)
        h._lock = threading.Lock()
        return h

    def bucket_exemplars(self) -> dict[float, dict]:
        """{le_edge: exemplar} — each bucket's slot keyed by the same
        `le` its exposition line carries (inf for the overflow bucket)."""
        with self._lock:
            items = list(self.exemplars.items())
        out = {}
        for i, ex in items:
            le = self.edges[i] if i < len(self.edges) else float("inf")
            out[le] = dict(ex)
        return out

    # ------------------------------------------------------------ queries
    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0 <= q <= 1): linear interpolation
        inside the bucket holding rank ceil(q * count); 0.0 when empty.
        The exact min/max clamp the estimate, so p0/p100 are exact."""
        with self._lock:
            counts = list(self.counts)
            count = self.count
            lo, hi = self.min, self.max
        if not count:
            return 0.0
        rank = max(1, min(count, int(q * count + 0.9999999)))
        seen = 0
        for i, c in enumerate(counts):
            if not c:
                continue
            if seen + c >= rank:
                left = self.edges[i - 1] if 0 < i < len(self.edges) \
                    else (0.0 if i == 0 else self.edges[-1])
                right = self.edges[i] if i < len(self.edges) else hi
                frac = (rank - seen) / c
                est = left + (right - left) * frac
                return min(max(est, lo), hi)
            seen += c
        return hi  # unreachable; belt-and-braces

    def export(self) -> tuple[list[tuple[float, int]], int, float]:
        """One CONSISTENT (buckets, count, sum) snapshot under a single
        lock acquisition — the Prometheus invariant `bucket{le="+Inf"}
        == _count` must hold within one scrape body even while
        concurrent observers keep recording."""
        with self._lock:
            counts = list(self.counts)
            count = self.count
            total = self.sum
        out = []
        acc = 0
        for i, edge in enumerate(self.edges):
            acc += counts[i]
            out.append((edge, acc))
        out.append((float("inf"), count))
        return out, count, total

    def cumulative(self) -> list[tuple[float, int]]:
        """Prometheus bucket pairs: [(le_edge, cumulative_count), ...,
        (inf, count)] — counts are cumulative and end at the total."""
        return self.export()[0]

    def snapshot(self) -> dict:
        """JSON-ready summary: count/sum/min/max plus the p50/p95/p99
        the serve layer's SLO view reads."""
        with self._lock:
            count, total = self.count, self.sum
            lo, hi = self.min, self.max
        if not count:
            return {"count": 0}
        return {"count": count,
                "sum": round(total, 6),
                "mean": round(total / count, 6),
                "min": round(lo, 6),
                "max": round(hi, 6),
                "p50": round(self.quantile(0.50), 6),
                "p95": round(self.quantile(0.95), 6),
                "p99": round(self.quantile(0.99), 6)}


class HistogramSet:
    """Named get-or-create Histogram collection (one lock for the name
    map; each histogram carries its own)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: dict[str, Histogram] = {}

    def observe(self, name: str, value: float,
                exemplar: dict | None = None) -> None:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, Histogram())
        h.observe(value, exemplar=exemplar)

    def get(self, name: str) -> Histogram | None:
        return self._hists.get(name)

    def items(self) -> list[tuple[str, Histogram]]:
        with self._lock:
            return sorted(self._hists.items())

    def merge(self, other: "HistogramSet") -> None:
        for name, hist in other.items():
            mine = self._hists.get(name)
            if mine is None:
                with self._lock:
                    mine = self._hists.setdefault(name, Histogram())
            mine.merge(hist)

    def snapshot(self) -> dict:
        """{name: histogram summary} — the metrics registry's `latency`
        namespace and the serve stats' histogram view."""
        return {name: hist.snapshot() for name, hist in self.items()}
