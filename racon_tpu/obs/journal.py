"""Durable serve event journal: a size-bounded JSONL lifecycle log.

The flight recorder (obs/flight.py) answers "what was the process doing
around the failure" — spans, in memory, dumped on demand. What it cannot
answer is the auditor's question: "what happened to job X last Tuesday",
because the ring forgets and dumps only happen on failure. `Journal`
closes that gap the way inference servers' request logs do:

  - ONE LINE PER LIFECYCLE TRANSITION, as JSON (JSONL): received,
    admitted / rejected (with retry_after), started, one
    `part-streamed` per stitched contig (keyed by job + contig — the
    continuous batcher stitches every serve job incrementally), an
    `iterations` summary, finished / failed / deadline-miss, expired,
    drain — each keyed by job id and (when the client minted one)
    trace id, stamped with wall time. `jq` is a full query engine over
    it; `tools/obsreport.py` renders per-job timelines from it
    alongside flight dumps, and its `--check` verifies the
    parts-streamed count equals each successful job's contig count.
  - SIZE-BOUNDED, not append-forever: when the file would exceed
    `max_bytes` (RACON_TPU_JOURNAL_MAX_BYTES, default 8 MiB) it rotates
    to `<path>.1` (one older generation kept, previous `.1` replaced),
    so a long-lived server's journal is a hard ~2x`max_bytes` disk
    constant. `read_journal()` reads both generations in order.
  - STRICT AT OPEN, BEST-EFFORT AFTER: the constructor raises on an
    unwritable path (serve startup turns that into a failed start,
    mirroring the `--metrics-port` strict-parse discipline — an
    operator who asked for an audit trail must not silently run
    without one), but a mid-run write failure only bumps `dropped`:
    a full disk loses journal lines, never jobs.

Consistency is checkable, not assumed: `check_consistency()` verifies
every journaled job reaches exactly ONE terminal state and that
started/terminal pairs balance — `tools/servebench.py` runs it as part
of its gate, so a lifecycle path that forgets to journal its exit shows
up as a red bench cell, not a silent audit hole."""

from __future__ import annotations

import json
import os
import threading
import time

DEFAULT_MAX_BYTES = 8 << 20

#: events that end a job's lifecycle; `check_consistency` requires
#: exactly one per journaled job. `deadline-miss` is an annotation on a
#: finished-late job (it still terminates via `finished`), not terminal.
TERMINAL_EVENTS = frozenset((
    "finished", "failed", "expired", "rejected-full",
    "rejected-quota", "rejected-draining", "rejected-ingest"))

#: terminal states that imply the job actually ran (must pair with a
#: `started` event)
RAN_EVENTS = frozenset(("finished", "failed"))

#: every event type the lifecycle checker UNDERSTANDS. Anything outside
#: this set — `alert` lines from the SLO burn tracker, and whatever
#: event types future PRs add — is an annotation, not a lifecycle
#: transition: the consistency check must ignore it, never fail on it
#: (an old obsreport binary reading a newer server's journal would
#: otherwise turn every new event type into a red CI).
LIFECYCLE_EVENTS = TERMINAL_EVENTS | RAN_EVENTS | frozenset((
    "received", "admitted", "started", "deadline-miss", "iterations",
    "part-streamed"))


def journal_max_bytes() -> int:
    try:
        n = int(os.environ.get("RACON_TPU_JOURNAL_MAX_BYTES", 0))
    except ValueError:
        n = 0
    return n if n > 0 else DEFAULT_MAX_BYTES


class Journal:
    """Append-only JSONL event log with one-generation rotation (see
    module docstring). Thread-safe: one lock around write+rotate; every
    line is flushed so a crashed server's journal ends at the last
    completed transition, not mid-buffer."""

    def __init__(self, path: str, max_bytes: int | None = None,
                 fsync: bool | None = None):
        self.path = path
        self.max_bytes = max_bytes if max_bytes else journal_max_bytes()
        #: RACON_TPU_JOURNAL_FSYNC=1 upgrades flush-per-line to
        #: fsync-per-record: the line is on the PLATTER before record()
        #: returns, so a journal used as a retry ledger (serve/router)
        #: survives a host power cut with at most the final line torn —
        #: read_journal skips the torn tail. Off by default: fsync per
        #: line is orders of magnitude slower than flush.
        self.fsync = (fsync if fsync is not None
                      else os.environ.get("RACON_TPU_JOURNAL_FSYNC",
                                          "") == "1")
        self.events = 0
        self.dropped = 0
        self._lock = threading.Lock()
        #: lines queued by stage() — encoded but not yet on disk; any
        #: later record()/flush_staged()/close() writes them first, so
        #: relative order is fixed at stage time
        self._staged: list[str] = []
        self._closed = False
        # strict open: a bad path must fail the CALLER now, not lose
        # every line later (serve startup converts this to a failed
        # start)
        self._fh = open(path, "a", encoding="utf-8")
        self._size = self._fh.tell()

    def _encode(self, event: str, job: str | None,
                trace: str | None, fields: dict) -> str | None:
        doc: dict = {"t": round(time.time(), 6), "event": event}
        if job is not None:
            doc["job"] = job
        if trace is not None:
            doc["trace"] = trace
        for k, v in fields.items():
            if v is not None:
                doc[k] = v
        try:
            # ensure_ascii (the json default) is load-bearing: it keeps
            # every line pure ASCII, so len(line) == on-disk bytes and
            # the max_bytes accounting in _write_locked stays exact
            return json.dumps(doc, separators=(",", ":"),
                              default=str) + "\n"
        except ValueError:
            self.dropped += 1
            return None

    def record(self, event: str, job: str | None = None,
               trace: str | None = None, **fields) -> None:
        """Append one lifecycle line (draining any staged lines first,
        in order). Never raises: after a successful open, journal loss
        is accounted (`dropped`), not fatal."""
        line = self._encode(event, job, trace, fields)
        if line is None:
            return
        with self._lock:
            self._write_locked(line)

    def stage(self, event: str, job: str | None = None,
              trace: str | None = None, **fields) -> None:
        """Queue one line WITHOUT touching the disk — for callers
        holding a hot lock (the JobQueue fires admitted/expired under
        its mutex, and a stalled journal device must not stall every
        submit/pop/scrape behind it). Staged lines keep their relative
        order and are flushed by the next record()/flush_staged()/
        close(); until then they are memory-only (the one crash-
        durability exception to the flush-per-line rule)."""
        line = self._encode(event, job, trace, fields)
        if line is None:
            return
        with self._lock:
            self._staged.append(line)

    def flush_staged(self) -> None:
        """Write any staged lines now — called from lock-free contexts
        (the serve handler after its job resolves)."""
        with self._lock:
            self._write_locked(None)

    def _write_locked(self, line: str | None) -> None:
        if self._closed:
            self.dropped += len(self._staged) + (1 if line else 0)
            self._staged.clear()
            return
        pending, self._staged = self._staged, []
        if line is not None:
            pending.append(line)
        for ln in pending:
            try:
                if self._fh is None:
                    # a failed rotation (or transient reopen failure)
                    # dropped the handle; write failures are TRANSIENT
                    # by contract, so retry the open on every line —
                    # the journal heals when the condition clears
                    self._fh = open(self.path, "a", encoding="utf-8")
                    self._size = self._fh.tell()
                if self._size + len(ln) > self.max_bytes:
                    self._rotate_locked()
                self._fh.write(ln)
                self._fh.flush()
                if self.fsync:
                    os.fsync(self._fh.fileno())
                self._size += len(ln)
                self.events += 1
            except OSError:
                self.dropped += 1

    def _rotate_locked(self) -> None:
        # drop the handle FIRST: if replace/reopen raises, _fh is None
        # and the next write retries the open instead of writing into
        # a permanently-closed file
        try:
            self._fh.close()
        except OSError:
            pass
        self._fh = None
        os.replace(self.path, self.path + ".1")
        self._fh = open(self.path, "a", encoding="utf-8")
        self._size = 0

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                try:
                    self._write_locked(None)
                    if self._fh is not None:
                        self._fh.close()
                except OSError:
                    pass
                self._fh = None
                self._closed = True


def read_journal(path: str) -> list[dict]:
    """Entries from both generations (`<path>.1` first, then `<path>`),
    oldest first. Unparseable lines (a torn write at crash) are skipped,
    not fatal — the journal is evidence, and partial evidence beats an
    exception."""
    entries: list[dict] = []
    for p in (path + ".1", path):
        if not os.path.isfile(p):
            continue
        with open(p, encoding="utf-8", errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if isinstance(doc, dict):
                    entries.append(doc)
    return entries


def check_consistency(entries: list[dict]) -> list[str]:
    """Lifecycle invariants over journal entries; returns human-readable
    problem strings (empty = consistent):

      - every job reaches EXACTLY one terminal state;
      - finished/failed jobs have a `started` event (when their start of
        life — `received` — is inside the journal window; rotation may
        have cut older jobs' early events, which is not an error);
      - a `started` job never also terminates as expired/rejected.

    Events outside LIFECYCLE_EVENTS (e.g. `alert`) are annotations and
    are ignored; a job id that appears ONLY on annotation lines is
    skipped entirely — unknown event types must never fail the check.
    """
    jobs: dict[str, list[str]] = {}
    for e in entries:
        job = e.get("job")
        if job:
            jobs.setdefault(str(job), []).append(str(e.get("event")))
    problems: list[str] = []
    for job, all_events in sorted(jobs.items()):
        events = [e for e in all_events if e in LIFECYCLE_EVENTS]
        if not events:
            continue  # annotation-only job id (see docstring)
        terminal = [e for e in events if e in TERMINAL_EVENTS]
        if not terminal:
            problems.append(f"job {job}: no terminal state ({events})")
        elif len(terminal) > 1:
            problems.append(
                f"job {job}: {len(terminal)} terminal states {terminal}")
        started = "started" in events
        if started and terminal and terminal[0] not in RAN_EVENTS:
            problems.append(
                f"job {job}: started but terminated as {terminal[0]}")
        if (not started and terminal
                and terminal[0] in RAN_EVENTS
                and "received" in events):
            problems.append(
                f"job {job}: {terminal[0]} without a started event")
    return problems
