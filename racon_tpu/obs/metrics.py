"""Metrics registry: one namespaced snapshot for the whole run.

Before this module the run's telemetry lived on three disjoint islands —
`DispatchPipeline.stage_stats` wall-clock counters, the resilience
degradation counters riding the same snapshot, and the scheduler's
`OccupancyStats` — each with its own access path and emission format.
`MetricsRegistry` consolidates them behind namespaces (`pipeline.*`,
`sched.*`, `resilience.*`, plus whatever a caller registers), so the
bench JSON, the `--tpu-metrics out.json` dump and the end-of-run stderr
table all render the SAME snapshot.

Providers are callables returning a dict; they are invoked at snapshot
time, so registering is free and the registry always reflects current
counter values. The polisher wires the standard three namespaces in its
constructor (core/polisher.py)."""

from __future__ import annotations

import json


class MetricsRegistry:
    """Namespace -> provider mapping with nested/flat snapshot views."""

    def __init__(self):
        self._providers: dict[str, object] = {}

    def register(self, namespace: str, provider) -> None:
        """Register `provider()` (-> dict) under `namespace`. Re-registering
        a namespace replaces its provider (one source of truth each)."""
        if not namespace or "." in namespace:
            raise ValueError(
                f"MetricsRegistry.register: invalid namespace {namespace!r}")
        self._providers[namespace] = provider

    def namespaces(self) -> list[str]:
        return list(self._providers)

    # ------------------------------------------------------------ snapshots
    def snapshot(self) -> dict:
        """{namespace: provider()} — nested, JSON-ready (the bench JSON's
        `"metrics"` field and the --tpu-metrics dump)."""
        return {ns: provider() for ns, provider in self._providers.items()}

    def flat(self) -> dict:
        """Dotted scalar keys (`pipeline.pack_s`, `sched.aligner.
        occupancy_pct`, ...) — the stderr-table and test-assertion view."""
        out: dict = {}

        def walk(prefix: str, value) -> None:
            if isinstance(value, dict):
                for k, v in value.items():
                    walk(f"{prefix}.{k}", v)
            else:
                out[prefix] = value

        for ns, sub in self.snapshot().items():
            walk(ns, sub)
        return out

    # ------------------------------------------------------------- emission
    def dump(self, path: str) -> str:
        """Write the nested snapshot as indented JSON to `path`."""
        with open(path, "w") as fh:
            json.dump(self.snapshot(), fh, indent=2, sort_keys=True)
            fh.write("\n")
        return path

    def table(self) -> str:
        """One aligned key/value line per flat metric, sorted — the
        end-of-run stderr summary."""
        flat = self.flat()
        if not flat:
            return "(no metrics recorded)"
        width = max(len(k) for k in flat)
        lines = []
        for key in sorted(flat):
            v = flat[key]
            if isinstance(v, float):
                v = round(v, 3)
            lines.append(f"  {key:<{width}}  {v}")
        return "\n".join(lines)
