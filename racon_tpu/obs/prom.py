"""Prometheus text exposition (stdlib-only, text format 0.0.4).

The warm server's telemetry was reachable only as a JSON `stats`
snapshot — fine for a human with socat, invisible to a scrape-based
monitoring stack. This module renders counters, gauges and the
log-bucketed histograms (obs/hist.py) as the Prometheus text format
every scraper (Prometheus, VictoriaMetrics, Grafana agent, `curl`)
already speaks:

    # TYPE racon_tpu_serve_jobs_completed_total counter
    racon_tpu_serve_jobs_completed_total 42
    # TYPE racon_tpu_job_latency_seconds histogram
    racon_tpu_job_latency_seconds_bucket{le="0.25"} 12
    ...
    racon_tpu_job_latency_seconds_bucket{le="+Inf"} 42
    racon_tpu_job_latency_seconds_sum 13.9
    racon_tpu_job_latency_seconds_count 42

No client library, no registry singletons: callers hand `render()` the
numbers they already have (the serve stats snapshot, a HistogramSet) and
get back one scrape body. serve/server.py exposes it on the `scrape`
frame RPC and on the optional localhost HTTP port
(RACON_TPU_SERVE_METRICS_PORT / `racon_tpu serve --metrics-port`).

The fleet era (obs/fleet.py) made this a ROUND-TRIP format, not just an
emission format, so three extensions ride alongside the classic lines:

  - LABELED FAMILIES (`Labeled`): one TYPE line, one sample line per
    label set (`racon_tpu_serve_tenant_queue_depth{tenant="gold"} 3`) —
    per-tenant and per-replica series without name-mangling;
  - OPENMETRICS EXEMPLARS: a histogram bucket line may carry
    ` # {trace_id="...",flight="..."} <value> <ts>` — the one
    representative observation (obs/hist.py exemplar slots) that lets a
    fleet p99 bucket click through to the exact job's flight dump;
  - EXACT-STATS SIDECARS: `<hist>_min` / `<hist>_max` gauges ride next
    to each non-empty histogram so a scraped histogram reconstructs
    with the exact min/max the quantile estimator clamps to — without
    them a fleet-merged quantile could not equal the pooled one.

`parse()` is the STRICT inverse: it reads a scrape body back into typed
counters / gauges / labeled families / `ParsedHist` objects (which
`Scrape.histogram()` turns back into mergeable `Histogram`s), raising
`PromParseError` on any line it does not understand — a replica whose
exposition drifted must fail the aggregator loudly, not merge garbage.

Restart semantics (the process_start_time_seconds convention): every
counter here resets at process start, so the serve exposition pairs its
cumulative series with the `racon_tpu_serve_uptime_seconds` and
`racon_tpu_serve_start_time_seconds` gauges — a counter reset with a
CHANGED start_time is a restart, with an unchanged one a bug; a flat
queue-depth gauge plus advancing uptime is a quiet server, not a dead
one."""

from __future__ import annotations

import re

from .hist import Histogram, HistogramSet

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")

#: every exposed series is namespaced under this prefix
PREFIX = "racon_tpu_"

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def metric_name(name: str) -> str:
    """Sanitize a dotted internal name ("pipeline.pack") into a legal
    Prometheus metric name ("racon_tpu_pipeline_pack"). Names already
    carrying the prefix pass through unsanitized-prefix-free — that is
    what lets the fleet aggregator re-render PARSED series (full names)
    through the same `render()` the server uses."""
    if name.startswith(PREFIX):
        return _NAME_OK.sub("_", name)
    clean = _NAME_OK.sub("_", name.replace(".", "_")).strip("_")
    return PREFIX + clean


def _fmt(v) -> str:
    if v is None:
        return "0"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        if v != v:  # NaN
            return "NaN"
        if v == float("inf"):
            return "+Inf"
        return repr(v)
    return str(v)


def _le(edge: float) -> str:
    return "+Inf" if edge == float("inf") else repr(edge)


def escape_label_value(v) -> str:
    """Text-format label-value escaping: backslash, quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _unescape_label_value(v: str) -> str:
    out = []
    it = iter(v)
    for c in it:
        if c != "\\":
            out.append(c)
            continue
        nxt = next(it, "")
        out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
    return "".join(out)


def labels_str(labels: dict) -> str:
    """One canonical `{k="v",...}` rendering (sorted keys, escaped
    values) — canonical so a rendered-then-parsed label set compares
    equal to the original dict."""
    if not labels:
        return ""
    return ("{" + ",".join(
        f'{k}="{escape_label_value(v)}"'
        for k, v in sorted(labels.items())) + "}")


class Labeled:
    """A labeled metric family for `render()`: `samples` is a list of
    (labels_dict, value) pairs sharing one metric name and TYPE line."""

    __slots__ = ("samples", "help")

    def __init__(self, samples, help_: str | None = None):
        self.samples = list(samples)
        self.help = help_


def counter_lines(name: str, value, help_: str | None = None) -> list[str]:
    n = metric_name(name)
    if not n.endswith("_total"):
        n += "_total"
    out = []
    if help_ or (isinstance(value, Labeled) and value.help):
        out.append(f"# HELP {n} "
                   f"{help_ or value.help}")
    out.append(f"# TYPE {n} counter")
    if isinstance(value, Labeled):
        for labels, v in value.samples:
            out.append(f"{n}{labels_str(labels)} {_fmt(v)}")
    else:
        out.append(f"{n} {_fmt(value)}")
    return out


def gauge_lines(name: str, value, help_: str | None = None) -> list[str]:
    n = metric_name(name)
    out = []
    if help_ or (isinstance(value, Labeled) and value.help):
        out.append(f"# HELP {n} "
                   f"{help_ or value.help}")
    out.append(f"# TYPE {n} gauge")
    if isinstance(value, Labeled):
        for labels, v in value.samples:
            out.append(f"{n}{labels_str(labels)} {_fmt(v)}")
    else:
        out.append(f"{n} {_fmt(value)}")
    return out


def _exemplar_suffix(ex: dict) -> str:
    """OpenMetrics exemplar rendering: ` # {labels} value timestamp`.
    The `value`/`t` keys are positional; everything else is a label."""
    labels = {k: v for k, v in ex.items()
              if k not in ("value", "t") and v is not None}
    return (f" # {labels_str(labels) or '{}'} "
            f"{_fmt(float(ex.get('value', 0.0)))}"
            + (f" {_fmt(float(ex['t']))}" if ex.get("t") else ""))


def histogram_lines(name: str, hist: Histogram,
                    help_: str | None = None) -> list[str]:
    """Classic cumulative-bucket exposition; `_seconds` unit suffix is
    appended because every histogram in this codebase observes wall
    seconds. Buckets holding an exemplar slot render it OpenMetrics
    style, and non-empty histograms emit `_min`/`_max` gauge sidecars
    (exact stats the fleet reconstruction needs — see module
    docstring)."""
    n = metric_name(name)
    if not n.endswith("_seconds"):
        n += "_seconds"
    out = []
    if help_:
        out.append(f"# HELP {n} {help_}")
    out.append(f"# TYPE {n} histogram")
    # one atomic export: buckets/_sum/_count must be mutually
    # consistent within a scrape even under concurrent observe
    buckets, count, total = hist.export()
    exemplars = hist.bucket_exemplars()
    for edge, cum in buckets:
        line = f'{n}_bucket{{le="{_le(edge)}"}} {cum}'
        ex = exemplars.get(edge)
        if ex is not None:
            line += _exemplar_suffix(ex)
        out.append(line)
    out.append(f"{n}_sum {_fmt(total)}")
    out.append(f"{n}_count {count}")
    if count:
        lo, hi = hist.min, hist.max
        out.append(f"# TYPE {n}_min gauge")
        out.append(f"{n}_min {_fmt(float(lo))}")
        out.append(f"# TYPE {n}_max gauge")
        out.append(f"{n}_max {_fmt(float(hi))}")
    return out


def render(counters: dict | None = None, gauges: dict | None = None,
           hists: HistogramSet | None = None) -> str:
    """One scrape body. `counters` / `gauges` map dotted names to
    numbers (or to (value, help) pairs, or to `Labeled` families);
    `hists` contributes every histogram it holds. Ends with the
    trailing newline the text format requires."""
    lines: list[str] = []
    for name, value in sorted((counters or {}).items()):
        help_ = None
        if isinstance(value, tuple):
            value, help_ = value
        lines.extend(counter_lines(name, value, help_))
    for name, value in sorted((gauges or {}).items()):
        help_ = None
        if isinstance(value, tuple):
            value, help_ = value
        lines.extend(gauge_lines(name, value, help_))
    if hists is not None:
        for name, hist in hists.items():
            lines.extend(histogram_lines(name, hist))
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------- parsing
class PromParseError(ValueError):
    """A scrape body line the strict parser refuses (see module
    docstring: drifted expositions fail loudly)."""


class ParsedHist:
    """One scraped histogram: cumulative `(le, cum)` bucket pairs, the
    exact count/sum (and min/max when the sidecar gauges rode along),
    plus any OpenMetrics exemplars keyed by their bucket's le edge."""

    __slots__ = ("buckets", "sum", "count", "min", "max", "exemplars")

    def __init__(self):
        self.buckets: list[tuple[float, int]] = []
        self.sum = 0.0
        self.count = 0
        self.min: float | None = None
        self.max: float | None = None
        self.exemplars: dict[float, dict] = {}


class Scrape:
    """Typed view of one parsed scrape body. Unlabeled samples land in
    `counters` / `gauges` (metric name -> float); labeled samples in
    `counter_series` / `gauge_series` (name -> {labels_str: (labels,
    value)}); histograms in `hists` (base name -> ParsedHist)."""

    def __init__(self):
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.counter_series: dict[str, dict[str, tuple[dict, float]]] = {}
        self.gauge_series: dict[str, dict[str, tuple[dict, float]]] = {}
        self.hists: dict[str, ParsedHist] = {}

    def histogram(self, name: str) -> Histogram:
        """Reconstruct the named scraped histogram as a live, mergeable
        obs.hist.Histogram (exact counts; exact min/max when the
        exposition carried the sidecars)."""
        ph = self.hists[name]
        return Histogram.from_export(ph.buckets, ph.count, ph.sum,
                                     ph.min, ph.max, ph.exemplars)

    def histogram_set(self) -> HistogramSet:
        hs = HistogramSet()
        for name in self.hists:
            hs._hists[name] = self.histogram(name)
        return hs

    def series_sum(self, name: str, kind: str = "counter") -> float:
        """Sum of one labeled family's sample values across every label
        set — the 'family total' view cost-accounting invariants check
        (e.g. per-tenant device-seconds summing to total lane device
        seconds). Zero when the family is absent."""
        series = (self.counter_series if kind == "counter"
                  else self.gauge_series).get(name) or {}
        return sum(v for _, v in series.values())


_VALUE = r"[^\s#]+"
_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>" + _VALUE + r")"
    r"(?:\s+#\s+\{(?P<exlabels>[^}]*)\}\s+(?P<exvalue>" + _VALUE + r")"
    r"(?:\s+(?P<exts>" + _VALUE + r"))?)?\s*$")
_LABEL_RE = re.compile(
    r'\s*(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:\\.|[^"\\])*)"\s*(?:,|$)')


def _parse_labels(raw: str | None) -> dict:
    if not raw:
        return {}
    out: dict = {}
    pos = 0
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            raise PromParseError(f"bad label pair at {raw[pos:]!r}")
        out[m.group("k")] = _unescape_label_value(m.group("v"))
        pos = m.end()
    return out


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return float("inf")
    if raw == "-Inf":
        return float("-inf")
    try:
        return float(raw)
    except ValueError:
        raise PromParseError(f"bad sample value {raw!r}") from None


def parse(text: str) -> Scrape:
    """Strictly parse one scrape body (the `render()` output format)
    back into a typed `Scrape`. Every non-comment line must be a valid
    sample; every sample must follow a `# TYPE` declaration; histogram
    bucket cumulative counts must be monotone — violations raise
    `PromParseError` naming the line."""
    out = Scrape()
    types: dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                if parts[3] not in ("counter", "gauge", "histogram",
                                    "summary", "untyped"):
                    raise PromParseError(
                        f"line {lineno}: unknown metric type "
                        f"{parts[3]!r}")
                types[parts[2]] = parts[3]
            elif len(parts) >= 2 and parts[1] == "HELP":
                pass
            else:
                raise PromParseError(
                    f"line {lineno}: unrecognized comment {line!r}")
            continue
        m = _LINE_RE.match(line)
        if m is None:
            raise PromParseError(f"line {lineno}: unparseable sample "
                                 f"{line!r}")
        name = m.group("name")
        labels = _parse_labels(m.group("labels"))
        value = _parse_value(m.group("value"))
        # histogram component lines attach to their base family
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            cand = name[:-len(suffix)] if name.endswith(suffix) else None
            if cand and types.get(cand) == "histogram":
                base = cand
                break
        if base is not None:
            ph = out.hists.setdefault(base, ParsedHist())
            if name.endswith("_bucket"):
                if "le" not in labels:
                    raise PromParseError(
                        f"line {lineno}: histogram bucket without le")
                le = _parse_value(labels["le"])
                if ph.buckets and value < ph.buckets[-1][1]:
                    raise PromParseError(
                        f"line {lineno}: non-monotone bucket counts")
                ph.buckets.append((le, int(value)))
                if m.group("exlabels") is not None:
                    ex = _parse_labels(m.group("exlabels"))
                    ex["value"] = _parse_value(m.group("exvalue"))
                    if m.group("exts"):
                        ex["t"] = _parse_value(m.group("exts"))
                    ph.exemplars[le] = ex
            elif name.endswith("_sum"):
                ph.sum = value
            else:
                ph.count = int(value)
            continue
        # min/max sidecars attach to their histogram when one exists
        for suffix, attr in (("_min", "min"), ("_max", "max")):
            cand = name[:-len(suffix)] if name.endswith(suffix) else None
            if cand and types.get(cand) == "histogram":
                setattr(out.hists.setdefault(cand, ParsedHist()),
                        attr, value)
                base = cand
                break
        if base is not None:
            continue
        mtype = types.get(name)
        if mtype is None:
            raise PromParseError(
                f"line {lineno}: sample {name!r} without a TYPE line")
        if mtype == "counter":
            flat, series = out.counters, out.counter_series
        elif mtype == "gauge":
            flat, series = out.gauges, out.gauge_series
        else:
            raise PromParseError(
                f"line {lineno}: unsupported sample type {mtype!r}")
        if labels:
            series.setdefault(name, {})[labels_str(labels)] = (labels,
                                                               value)
        else:
            flat[name] = value
    return out
