"""Prometheus text exposition (stdlib-only, text format 0.0.4).

The warm server's telemetry was reachable only as a JSON `stats`
snapshot — fine for a human with socat, invisible to a scrape-based
monitoring stack. This module renders counters, gauges and the
log-bucketed histograms (obs/hist.py) as the Prometheus text format
every scraper (Prometheus, VictoriaMetrics, Grafana agent, `curl`)
already speaks:

    # TYPE racon_tpu_serve_jobs_completed_total counter
    racon_tpu_serve_jobs_completed_total 42
    # TYPE racon_tpu_job_latency_seconds histogram
    racon_tpu_job_latency_seconds_bucket{le="0.25"} 12
    ...
    racon_tpu_job_latency_seconds_bucket{le="+Inf"} 42
    racon_tpu_job_latency_seconds_sum 13.9
    racon_tpu_job_latency_seconds_count 42

No client library, no registry singletons: callers hand `render()` the
numbers they already have (the serve stats snapshot, a HistogramSet) and
get back one scrape body. serve/server.py exposes it on the `scrape`
frame RPC and on the optional localhost HTTP port
(RACON_TPU_SERVE_METRICS_PORT / `racon_tpu serve --metrics-port`).

Restart semantics (the process_start_time_seconds convention): every
counter here resets at process start, so the serve exposition pairs its
cumulative series with the `racon_tpu_serve_uptime_seconds` and
`racon_tpu_serve_start_time_seconds` gauges — a counter reset with a
CHANGED start_time is a restart, with an unchanged one a bug; a flat
queue-depth gauge plus advancing uptime is a quiet server, not a dead
one."""

from __future__ import annotations

import re

from .hist import Histogram, HistogramSet

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")

#: every exposed series is namespaced under this prefix
PREFIX = "racon_tpu_"

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def metric_name(name: str) -> str:
    """Sanitize a dotted internal name ("pipeline.pack") into a legal
    Prometheus metric name ("racon_tpu_pipeline_pack")."""
    clean = _NAME_OK.sub("_", name.replace(".", "_")).strip("_")
    return PREFIX + clean


def _fmt(v) -> str:
    if v is None:
        return "0"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        if v != v:  # NaN
            return "NaN"
        if v == float("inf"):
            return "+Inf"
        return repr(v)
    return str(v)


def _le(edge: float) -> str:
    return "+Inf" if edge == float("inf") else repr(edge)


def counter_lines(name: str, value, help_: str | None = None) -> list[str]:
    n = metric_name(name)
    if not n.endswith("_total"):
        n += "_total"
    out = []
    if help_:
        out.append(f"# HELP {n} {help_}")
    out.append(f"# TYPE {n} counter")
    out.append(f"{n} {_fmt(value)}")
    return out


def gauge_lines(name: str, value, help_: str | None = None) -> list[str]:
    n = metric_name(name)
    out = []
    if help_:
        out.append(f"# HELP {n} {help_}")
    out.append(f"# TYPE {n} gauge")
    out.append(f"{n} {_fmt(value)}")
    return out


def histogram_lines(name: str, hist: Histogram,
                    help_: str | None = None) -> list[str]:
    """Classic cumulative-bucket exposition; `_seconds` unit suffix is
    appended because every histogram in this codebase observes wall
    seconds."""
    n = metric_name(name)
    if not n.endswith("_seconds"):
        n += "_seconds"
    out = []
    if help_:
        out.append(f"# HELP {n} {help_}")
    out.append(f"# TYPE {n} histogram")
    # one atomic export: buckets/_sum/_count must be mutually
    # consistent within a scrape even under concurrent observe
    buckets, count, total = hist.export()
    for edge, cum in buckets:
        out.append(f'{n}_bucket{{le="{_le(edge)}"}} {cum}')
    out.append(f"{n}_sum {_fmt(total)}")
    out.append(f"{n}_count {count}")
    return out


def render(counters: dict | None = None, gauges: dict | None = None,
           hists: HistogramSet | None = None) -> str:
    """One scrape body. `counters` / `gauges` map dotted names to
    numbers (or to (value, help) pairs); `hists` contributes every
    histogram it holds. Ends with the trailing newline the text format
    requires."""
    lines: list[str] = []
    for name, value in sorted((counters or {}).items()):
        help_ = None
        if isinstance(value, tuple):
            value, help_ = value
        lines.extend(counter_lines(name, value, help_))
    for name, value in sorted((gauges or {}).items()):
        help_ = None
        if isinstance(value, tuple):
            value, help_ = value
        lines.extend(gauge_lines(name, value, help_))
    if hists is not None:
        for name, hist in hists.items():
            lines.extend(histogram_lines(name, hist))
    return "\n".join(lines) + "\n"
