"""Span tracing: Chrome trace-event recording for Perfetto.

The reference exposes only coarse phase timings (src/logger.cpp); a slow
or degraded run gives no way to see WHERE the time went. `TraceRecorder`
records per-event spans — pipeline pack/device/unpack/fallback stages
per chunk, engine dispatch loops, XLA compiles, watchdog backoff — plus
instant events for every resilience counter bump (faults, retries,
timeouts, breaker trips, quarantined windows, cancelled futures), and
writes them as Chrome trace-event JSON loadable in Perfetto
(https://ui.perfetto.dev) or chrome://tracing.

Design constraints, in order:

  1. OFF BY DEFAULT, zero overhead when off. The process-wide tracer is
     armed only by RACON_TPU_TRACE=<out.json> (mirrored by the CLI's
     `--tpu-trace`) or an explicit `configure()`; every hot-path hook is
     an `is None` check against the resolved-once singleton.
  2. Low overhead when ON: events append to per-thread buffers (no lock
     on the hot path — each pipeline worker owns its list; the shared
     lock is taken once per thread, at buffer registration), timestamps
     come from the monotonic `time.perf_counter` clock the pipeline's
     stage counters already use, and serialization happens once, at
     `save()`. Instrumentation sites reuse the exact perf_counter
     endpoints they feed into PipelineStats, so per-stage span-duration
     sums equal the stage wall-clock counters by construction
     (pinned by tests/test_obs.py).
  3. Thread-safe: concurrent pipeline threads (pack worker, dispatcher,
     unpack worker, fallback pool, watchdog workers) record freely;
     `events()` snapshots every buffer and sorts by timestamp.
"""

from __future__ import annotations

import json
import os
import threading
import time


class _Span:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_rec", "_name", "_args", "_t0")

    def __init__(self, rec: "TraceRecorder", name: str, args: dict | None):
        self._rec = rec
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc_info) -> None:
        self._rec.complete(self._name, self._t0, time.perf_counter(),
                           self._args)


class _NullSpan:
    """Shared no-op context for the disabled-tracer path."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc_info) -> None:
        pass


_NULL_SPAN = _NullSpan()


class TraceRecorder:
    """Append-only per-thread event buffers with one shared time base."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._pid = os.getpid()
        self._base = time.perf_counter()
        self._lock = threading.Lock()
        self._buffers: list[list] = []
        self._threads: dict[int, str] = {}
        self._next_tid = 1
        self._local = threading.local()

    # ------------------------------------------------------------ recording
    def _buf(self) -> list:
        buf = getattr(self._local, "buf", None)
        if buf is None:
            # synthetic per-registration tid, NOT threading.get_ident():
            # the OS reuses idents, so the consensus phase's workers
            # would land on (and relabel) the dead align-phase workers'
            # tracks — every registered thread gets its own track
            # (obs/flight.py overrides this with a shared bounded ring
            # and name-keyed tids)
            t = threading.current_thread()
            buf = self._local.buf = []
            with self._lock:
                tid = self._next_tid
                self._next_tid += 1
                self._buffers.append(buf)
                self._threads[tid] = t.name
            self._local.tid = tid
        return buf

    def _us(self, t: float) -> float:
        # clamp: a caller-supplied endpoint can predate this recorder
        # (env-armed tracer created lazily mid-phase); negative ts would
        # fail the faultcheck gate and misrender in Perfetto
        return round(max(0.0, t - self._base) * 1e6, 3)

    def rebase(self, base: float) -> None:
        """Move the recorder's time zero EARLIER, to perf_counter
        `base`, so spans that predate its creation — a serve job's
        queue wait — keep their real offsets instead of clamping to 0.
        Only valid before events are recorded with the old base (the
        serve layer calls it first thing inside a fresh per-job scope);
        later-or-equal bases are ignored."""
        if base < self._base:
            self._base = base

    def complete(self, name: str, t0: float, t1: float,
                 args: dict | None = None) -> None:
        """Record a finished span from its `time.perf_counter` endpoints
        — the idiom every stats-timed site uses, so span durations equal
        the wall seconds charged to the counters."""
        buf = self._buf()
        ev = {"name": name, "cat": "racon_tpu", "ph": "X",
              "ts": self._us(t0), "dur": round(max(0.0, t1 - t0) * 1e6, 3),
              "pid": self._pid, "tid": self._local.tid}
        if args:
            ev["args"] = args
        buf.append(ev)

    def instant(self, name: str, args: dict | None = None) -> None:
        buf = self._buf()
        ev = {"name": name, "cat": "racon_tpu", "ph": "i", "s": "t",
              "ts": self._us(time.perf_counter()),
              "pid": self._pid, "tid": self._local.tid}
        if args:
            ev["args"] = args
        buf.append(ev)

    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args or None)

    # ------------------------------------------------------------ emission
    def events(self) -> list[dict]:
        """Timestamp-sorted snapshot of every buffer, prefixed with the
        thread-name metadata events Perfetto uses to label tracks."""
        with self._lock:
            buffers = list(self._buffers)
            threads = dict(self._threads)
        meta = [{"name": "thread_name", "ph": "M", "pid": self._pid,
                 "tid": tid, "args": {"name": tname}}
                for tid, tname in sorted(threads.items())]
        evs: list[dict] = []
        for buf in buffers:
            evs.extend(list(buf))  # list() snapshots concurrent appends
        evs.sort(key=lambda e: e["ts"])
        return meta + evs

    def save(self, path: str | None = None) -> str:
        """Write the Chrome trace-event JSON object form (the format
        Perfetto and chrome://tracing both load)."""
        path = path or self.path
        if not path:
            raise ValueError("TraceRecorder.save: no output path")
        with open(path, "w") as fh:
            json.dump({"traceEvents": self.events(),
                       "displayTimeUnit": "ms"}, fh)
        return path


# ----------------------------------------------------------- module state
#: resolved-once process tracer: None (the common case — every hook is a
#: single `is None` check) or the armed recorder
_tracer: TraceRecorder | None = None
_resolved = False


def get_tracer() -> TraceRecorder | None:
    """The process tracer, armed lazily from RACON_TPU_TRACE on first
    call (None when unset — the zero-overhead clean path)."""
    global _tracer, _resolved
    if not _resolved:
        path = os.environ.get("RACON_TPU_TRACE")
        _tracer = TraceRecorder(path) if path else None
        _resolved = True
    return _tracer


def configure(path: str | None = None) -> TraceRecorder:
    """Explicitly arm (or re-arm) recording — tests and tools; the CLI
    path goes through the RACON_TPU_TRACE env so subprocesses inherit."""
    global _tracer, _resolved
    _tracer = TraceRecorder(path)
    _resolved = True
    return _tracer


def install(recorder: TraceRecorder) -> TraceRecorder:
    """Arm a caller-built recorder (e.g. the serve layer's bounded
    FlightRecorder, obs/flight.py) as the process tracer — every
    existing hook starts feeding it. Returns the recorder."""
    global _tracer, _resolved
    _tracer = recorder
    _resolved = True
    return recorder


def reset() -> None:
    """Drop the tracer and the env resolution (tests re-arm per case)."""
    global _tracer, _resolved
    _tracer = None
    _resolved = False


class _TeeRecorder:
    """Duck-typed recorder forwarding every event to several recorders
    — how a scoped per-job trace coexists with an already-armed
    process recorder (the serve layer's always-on flight ring,
    obs/flight.py): the job gets its own events AND the ring keeps
    recording, so a concurrent job's post-mortem dump has no blind
    window. Only the recording surface (`complete`/`instant`/`span`)
    fans out; `events`/`save` delegate to the primary recorder."""

    def __init__(self, primary: TraceRecorder, *others: TraceRecorder):
        self._recs = (primary,) + others
        self.path = primary.path

    def complete(self, name, t0, t1, args=None) -> None:
        for rec in self._recs:
            rec.complete(name, t0, t1, args)

    def instant(self, name, args=None) -> None:
        for rec in self._recs:
            rec.instant(name, args)

    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args or None)

    def events(self) -> list[dict]:
        return self._recs[0].events()

    def save(self, path: str | None = None) -> str:
        return self._recs[0].save(path)


class scoped:
    """Context manager arming a fresh in-memory recorder and restoring
    the previous tracer state (armed or unresolved) on exit — the serve
    layer's per-job trace scoping. The recorder is process-global for
    the duration, so spans from concurrent jobs sharing the process land
    in it too (one process, shared device: documented, not hidden).
    When a recorder is ALREADY armed (the always-on flight ring, or an
    RACON_TPU_TRACE trace), the scope installs a tee so the outer
    recorder keeps seeing every span — a traced job must not open a
    blind window in a concurrent job's flight dump.

    Scopes SERIALIZE on a module lock: the save/restore of the global
    tracer is not reentrant (overlapping scopes restoring out of order
    would leave the process tracer pointing at a dead per-job recorder),
    so a second traced job waits for the first to finish."""

    _lock = threading.Lock()

    def __enter__(self) -> TraceRecorder:
        global _tracer, _resolved
        self._lock.acquire()
        prev = get_tracer()  # resolve the env posture BEFORE saving it
        self._prev = (_tracer, _resolved)
        rec = TraceRecorder(None)
        _tracer = rec if prev is None else _TeeRecorder(rec, prev)
        _resolved = True
        return rec

    def __exit__(self, *exc_info) -> None:
        global _tracer, _resolved
        _tracer, _resolved = self._prev
        self._lock.release()


def save(path: str | None = None) -> str | None:
    """Write the armed tracer's events to its configured path (or
    `path`); None when tracing is off or has nowhere to write — callers
    use this as the unconditional end-of-run hook."""
    tr = get_tracer()
    if tr is None or not (path or tr.path):
        return None
    return tr.save(path)


def rebase_events(events: list[dict], pid: int, shift_us: float = 0.0,
                  name: str | None = None) -> list[dict]:
    """Re-stamp a snapshot of trace events onto process `pid`, shifting
    span/instant timestamps by `shift_us` — how a REMOTE recorder's
    events (the serve layer's per-job trace, whose clock is the
    server's perf_counter) merge into a local timeline as their own
    Perfetto process track. Returns fresh event dicts (inputs are not
    mutated), prefixed with a `process_name` metadata event when `name`
    is given; thread metadata ("M") keeps its original timestampless
    shape so track labels survive the move."""
    out: list[dict] = []
    if name is not None:
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": name}})
    for ev in events:
        ev = dict(ev)
        ev["pid"] = pid
        if ev.get("ph") != "M" and "ts" in ev:
            ev["ts"] = round(max(0.0, ev["ts"] + shift_us), 3)
        out.append(ev)
    return out


def trace_matches(args: dict | None, trace_id: str) -> bool:
    """True when a span/instant's args tie it to `trace_id` or to one of
    its descendants — the router's child shards carry dotted ids
    (`<trace>.s<k>`), so a match is exact OR by dotted prefix. Two arg
    shapes exist in the fabric: per-job spans carry a single `trace_id`
    string, batched lane iterations carry a `trace_ids` list (one entry
    per co-scheduled job); either side matching counts."""
    if not args:
        return False

    def _hit(t) -> bool:
        return isinstance(t, str) and (
            t == trace_id or t.startswith(trace_id + "."))

    if _hit(args.get("trace_id")):
        return True
    tids = args.get("trace_ids")
    return isinstance(tids, (list, tuple)) and any(_hit(t) for t in tids)


def span(name: str, **args):
    """Convenience span: a real recording context when tracing is armed,
    a shared no-op otherwise."""
    tr = get_tracer()
    return tr.span(name, **args) if tr is not None else _NULL_SPAN


def instant(name: str, **args) -> None:
    tr = get_tracer()
    if tr is not None:
        tr.instant(name, args or None)
