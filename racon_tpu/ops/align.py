"""Batched banded global alignment (edit distance + CIGAR path).

TPU-native replacement for both edlib (reference src/overlap.cpp:205-224) and
GenomeWorks cudaaligner (src/cuda/cudaaligner.cpp): many pairwise global
alignments are computed at once as one fixed-shape XLA program.

Design
------
Anti-diagonal wavefront DP: cells (i, j) with i+j == d depend only on
wavefronts d-1 and d-2, so each wavefront is a single vector op — no
horizontal dependency chain. A static band of width B tracks the main
diagonal: on wavefront d only query rows i in [offset[d], offset[d] + B) are
kept. Offsets are precomputed on the host per lane (they advance by 0/1 per
wavefront) and shared by the DP and the traceback, so the two can never
disagree. Unit costs (match 0, mismatch 1, indel 1, minimize), mirroring
edlib's edit-distance NW mode that the reference relies on.

The kernel emits 2-bit backpointers packed 4-per-byte; traceback runs on the
host, vectorized across lanes. Lengths are bucketed by the caller
(`BatchAligner`) into a handful of static shapes to avoid recompilation.

Determinism: tie-breaking is fixed (diagonal < up/I < left/D), so output is
bit-stable across runs and backends — the property the reference's golden
CI test demands (ci/gpu/cuda_test.sh:30-44).
"""

from __future__ import annotations

import functools

import numpy as np

INF = np.int32(1 << 28)

# backpointer codes
BP_DIAG, BP_UP, BP_LEFT = 0, 1, 2  # M, I (consume query), D (consume target)


def band_offsets(q_len: int, t_len: int, band: int, n_waves: int) -> np.ndarray:
    """Per-wavefront band start rows for one lane (host side).

    Wavefront d holds query rows i in [off[d], off[d]+band). The band tracks
    the ideal diagonal i ~= d * M / (M+N) and is clamped so (0,0) and (M,N)
    are always inside. Offsets are nondecreasing with steps in {0, 1}.
    """
    m, n = q_len, t_len
    d = np.arange(n_waves, dtype=np.int64)
    center = (d * m) // (m + n) if (m + n) else d * 0
    lo = np.maximum(0, d - n)
    hi = np.minimum(d, m)
    off = np.clip(center - band // 2, lo, np.maximum(lo, hi - band + 1))
    off = np.maximum.accumulate(off)            # enforce monotone
    off = np.minimum(off, np.maximum(0, m - 0))  # safety clamp
    # steps must be 0/1 for the DP gather to stay in-range; enforce
    steps = np.diff(off)
    if (steps > 1).any():
        # smooth: cumulative min walk backwards
        for idx in np.where(steps > 1)[0][::-1]:
            off[idx] = off[idx + 1] - 1
    return off.astype(np.int32)


@functools.lru_cache(maxsize=None)
def _kernel_for(band: int, n_waves: int, score_dtype: str = "int32",
                packed: bool = False):
    """jitted banded DP for one static (band, n_waves) shape; jax is
    imported lazily so the module loads without a device runtime.
    `score_dtype` narrows the wavefront state (legal only under
    ops/dtypes.aligner_int16_ok); `packed` takes 2-bit packed operands
    (encode.pack_2bit) and unpacks them on device — both variants are
    byte-identical to the int32/int8 program by construction."""
    import jax

    return jax.jit(functools.partial(_banded_nw_kernel, band=band,
                                     n_waves=n_waves,
                                     score_dtype=score_dtype,
                                     packed=packed))


def _banded_nw_kernel(q, t, q_len, t_len, offsets, band: int, n_waves: int,
                      score_dtype: str = "int32", packed: bool = False):
    """Batched banded edit-distance DP.

    Args:
      q, t: [B, Lq], [B, Lt] int8 codes (PAD beyond length), or 2-bit
        packed [B, Lq // 4] uint8 when `packed` (ACGT-only operands;
        PAD is restored from the lengths on device).
      q_len, t_len: [B] int32.
      offsets: [B, n_waves] int32 band starts.
      band: static band width (multiple of 4).
      n_waves: static number of wavefronts (>= max(q_len+t_len) + 1).
      score_dtype: 'int32' (sentinel 1<<28) or 'int16' (sentinel 1<<14,
        legal iff 2*edge+1 < 1<<14 — every cell is min-clamped at the
        sentinel per wavefront, so values never exceed sentinel + 1).

    Returns:
      bp_packed: [n_waves, B, band // 4] uint8 — 2-bit backpointers.
      distance: [B] score_dtype edit distance at (M, N).
    """
    import jax
    import jax.numpy as jnp

    DT = jnp.int16 if score_dtype == "int16" else jnp.int32
    INFD = jnp.asarray((1 << 14) if score_dtype == "int16" else INF, DT)
    if packed:
        from .encode import unpack_2bit_jax

        q = unpack_2bit_jax(q, q.shape[1] * 4, q_len)
        t = unpack_2bit_jax(t, t.shape[1] * 4, t_len)

    batch = q.shape[0]
    ks = jnp.arange(band, dtype=jnp.int32)

    def step(carry, d):
        s1, s2, a1, a2, dist = carry
        a0 = jax.lax.dynamic_slice_in_dim(offsets, d, 1, axis=1)[:, 0]  # [B]

        i = a0[:, None] + ks[None, :]              # [B, band] query row
        j = d - i                                  # target col
        valid = (i >= 0) & (i <= q_len[:, None]) & (j >= 0) & (j <= t_len[:, None])

        # gather neighbors from banded wavefronts
        k1 = ks[None, :] + (a0 - a1)[:, None]      # index into s1 for (d-1, i)
        k1m = k1 - 1                               # (d-1, i-1)
        k2m = ks[None, :] + (a0 - a2)[:, None] - 1  # (d-2, i-1)

        def gather(s, idx):
            ok = (idx >= 0) & (idx < band)
            return jnp.where(ok, jnp.take_along_axis(s, jnp.clip(idx, 0, band - 1),
                                                     axis=1), INFD)

        up = jnp.where(i >= 1, gather(s1, k1m), INFD)        # consume q[i-1]
        left = jnp.where(j >= 1, gather(s1, k1), INFD)       # consume t[j-1]
        diag = jnp.where((i >= 1) & (j >= 1), gather(s2, k2m), INFD)

        qi = jnp.take_along_axis(q, jnp.clip(i - 1, 0, q.shape[1] - 1), axis=1)
        tj = jnp.take_along_axis(t, jnp.clip(j - 1, 0, t.shape[1] - 1), axis=1)
        sub = jnp.where(qi == tj, 0, 1).astype(DT)

        cd = diag + sub
        cu = up + jnp.asarray(1, DT)
        cl = left + jnp.asarray(1, DT)

        # fixed tie order: diag, up, left
        score = cd
        bp = jnp.zeros_like(score, dtype=jnp.uint8) + BP_DIAG
        bp = jnp.where(cu < score, BP_UP, bp).astype(jnp.uint8)
        score = jnp.minimum(score, cu)
        bp = jnp.where(cl < score, BP_LEFT, bp).astype(jnp.uint8)
        score = jnp.minimum(score, cl)

        # seed origin
        origin = (i == 0) & (j == 0)
        score = jnp.where(origin, jnp.asarray(0, DT), score)
        score = jnp.where(valid, jnp.minimum(score, INFD), INFD)

        # record final distance when this wavefront crosses (M, N)
        at_end = (i == q_len[:, None]) & (j == t_len[:, None])
        dist = jnp.where(at_end.any(axis=1),
                         jnp.where(at_end, score, INFD).min(axis=1), dist)

        # pack 2-bit backpointers 4 per byte
        b4 = bp.reshape(batch, band // 4, 4).astype(jnp.uint8)
        packed_bp = (b4[..., 0] | (b4[..., 1] << 2) | (b4[..., 2] << 4)
                     | (b4[..., 3] << 6))

        return (score, s1, a0, a1, dist), packed_bp

    s_init = jnp.full((batch, band), INFD, dtype=DT)
    a_init = jnp.zeros((batch,), dtype=jnp.int32)
    dist_init = jnp.full((batch,), INFD, dtype=DT)

    (_, _, _, _, dist), bp_packed = jax.lax.scan(
        step, (s_init, s_init, a_init, a_init, dist_init),
        jnp.arange(n_waves, dtype=jnp.int32))
    return bp_packed, dist


def _unpack_bp(bp_packed: np.ndarray) -> np.ndarray:
    """[n_waves, B, band/4] uint8 -> [n_waves, B, band] uint8 of 2-bit codes."""
    nw, b, b4 = bp_packed.shape
    out = np.empty((nw, b, b4, 4), dtype=np.uint8)
    out[..., 0] = bp_packed & 3
    out[..., 1] = (bp_packed >> 2) & 3
    out[..., 2] = (bp_packed >> 4) & 3
    out[..., 3] = (bp_packed >> 6) & 3
    return out.reshape(nw, b, b4 * 4)


def _traceback(bp: np.ndarray, offsets: np.ndarray, q_lens: np.ndarray,
               t_lens: np.ndarray):
    """Vectorized-across-lanes traceback.

    Walks all lanes simultaneously from (M, N) to (0, 0); each numpy step
    advances every unfinished lane by one op. Returns (per-lane op runs in
    forward order, per-lane touched_edge flags). A lane whose optimal
    in-band path rides the band boundary may have been clipped away from
    the true optimum — the caller treats those as rejections and re-aligns
    on the host (the cudaaligner status -> CPU fallback pattern,
    src/cuda/cudaaligner.cpp:63-71).
    """
    n_lanes = bp.shape[1]
    band = bp.shape[2]
    i = q_lens.astype(np.int64).copy()
    j = t_lens.astype(np.int64).copy()
    active = (i > 0) | (j > 0)
    max_steps = int((q_lens + t_lens).max()) if n_lanes else 0

    ops = np.zeros((n_lanes, max_steps), dtype=np.uint8)
    counts = np.zeros(n_lanes, dtype=np.int64)
    touched = np.zeros(n_lanes, dtype=bool)

    lanes = np.arange(n_lanes)
    ql = q_lens.astype(np.int64)
    tl = t_lens.astype(np.int64)
    step = 0
    while active.any() and step < max_steps:
        d = i + j
        off = offsets[lanes, np.minimum(d, offsets.shape[1] - 1)].astype(np.int64)
        k = i - off
        # a band-boundary cell marks possible clipping, but only when the
        # matrix actually continues past the boundary on that side
        row_lo = np.maximum(0, d - tl)
        row_hi = np.minimum(d, ql)
        touched |= active & (k <= 0) & (off > row_lo)
        touched |= active & (k >= band - 1) & (off + band - 1 < row_hi)
        k = np.clip(k, 0, band - 1)
        code = bp[np.minimum(d, bp.shape[0] - 1), lanes, k]
        # boundary overrides: on i==0 only D possible; on j==0 only I
        code = np.where(i == 0, BP_LEFT, code)
        code = np.where(j == 0, BP_UP, code)

        di = np.where(code != BP_LEFT, 1, 0)
        dj = np.where(code != BP_UP, 1, 0)
        i = np.where(active, i - di, i)
        j = np.where(active, j - dj, j)
        ops[active, counts[active]] = code[active]
        counts[active] += 1
        active = (i > 0) | (j > 0)
        step += 1

    out = [_runs_of(ops[lane, :counts[lane]][::-1])
           for lane in range(n_lanes)]
    return out, touched


_CODE_TO_OP = {BP_DIAG: "M", BP_UP: "I", BP_LEFT: "D"}


def _runs_of(seq: np.ndarray) -> list[tuple[int, str]]:
    """Forward-order op codes -> CIGAR-style run list — the ONE decoding
    shared by the host traceback and the Pallas kernel's in-kernel path,
    so both kernels' outputs compare (and render) identically."""
    runs: list[tuple[int, str]] = []
    if len(seq):
        change = np.nonzero(np.diff(seq))[0]
        starts = np.concatenate(([0], change + 1))
        ends = np.concatenate((change + 1, [len(seq)]))
        runs = [(int(e - s), _CODE_TO_OP[int(seq[s])])
                for s, e in zip(starts, ends)]
    return runs


class BatchAligner:
    """Buckets (query, target) pairs into static shapes and aligns each bucket
    on the device — the orchestration analogue of CUDABatchAligner
    (src/cuda/cudaaligner.cpp) with XLA instead of CUDA streams.

    band_width=0 means auto: 10% of the mean pair length, forced even —
    the reference's auto band rule (src/cuda/cudapolisher.cpp:158-174) —
    quantized up to a multiple of 128 so each bucket compiles exactly once.

    Rejection statuses mirror cudaaligner (src/cuda/cudaaligner.cpp:63-71):
    pairs beyond the largest bucket, pairs whose traceback rode the band
    boundary, and pairs whose in-band cost is beyond what a <=30%-error
    overlap can produce (both signs of band clipping) return None, and the
    caller host-aligns them (the GPU->CPU fallback,
    cudapolisher.cpp:203-213) — no overlap is ever dropped.
    """

    #: length bucket edges (sequences are padded to the bucket edge)
    BUCKETS = (512, 1024, 2048, 4096, 8192, 16384, 32768, 65536)
    #: target bytes of packed backpointers per device batch
    MAX_BP_BYTES = 192 * 1024 * 1024

    def __init__(self, band_width: int = 0, max_length: int | None = None,
                 runner=None, scheduler=None,
                 use_pallas: bool | None = None):
        import os

        from ..sched import BatchScheduler

        self.band_width = band_width
        #: Pallas wavefront-kernel posture: True/False force it on/off
        #: (tests), None defers to RACON_TPU_PALLAS (`1` = always when
        #: the VMEM envelope fits, `auto` = per-bucket winner table,
        #: unset/0 = XLA programs only — today's behavior)
        self.use_pallas = use_pallas
        # the cudaaligner max-length envelope (exceeded_max_length ->
        # CPU, cudaaligner.cpp:63-68); RACON_TPU_ALIGNER_MAXLEN trims it
        # e.g. for time-capped smoke runs on slow links
        if max_length is None:
            max_length = int(os.environ.get("RACON_TPU_ALIGNER_MAXLEN",
                                            65536))
        self.max_length = max_length
        self.runner = runner
        # occupancy-aware scheduler (sched/): adaptive length ladder +
        # sorted packing when armed, per-bucket occupancy telemetry always
        self.sched = (scheduler if scheduler is not None
                      else BatchScheduler.from_env())
        #: pairs whose banded distance hit the band-adequacy limit and were
        #: sent back for exact host alignment (observability, SURVEY.md §5)
        self.n_band_rejects = 0

    def _bucket_of(self, length: int) -> int | None:
        for edge in self.BUCKETS:
            if length <= edge and edge <= self.max_length:
                return edge
        return None

    def _band_for(self, pairs, idxs) -> int:
        """Auto band for one bucket: 10% of the bucket's mean pair length
        (the reference's auto rule, cudapolisher.cpp:158-174), quantized up
        to a multiple of 128 — one compiled shape per bucket, cached across
        runs. An explicit band_width is honored as given (rounded up to a
        multiple of 4 for backpointer packing).

        Length differences need no band floor: band_offsets tracks the
        (0,0)->(M,N) ideal line, so a uniformly-stretched skewed pair fits
        a narrow band, and a pair with concentrated indels is caught by the
        edge-touch/cost signals and host-realigned. A floor keyed to the
        bucket's worst pair would let one chimeric outlier balloon the
        whole bucket's backpointer memory."""
        if self.band_width > 0:
            return (self.band_width + 3) // 4 * 4
        mean_len = sum(max(len(pairs[i][0]), len(pairs[i][1]))
                       for i in idxs) / len(idxs)
        return max(128, (int(mean_len * 0.1) + 127) // 128 * 128)

    def align(self, pairs: list[tuple[bytes, bytes]], progress=None,
              pipeline=None,
              on_reject=None) -> list[list[tuple[int, str]] | None]:
        """Globally align each (query, target) pair. Returns per-pair op runs,
        or None for rejected pairs (see class docstring).

        `pipeline` (pipeline.DispatchPipeline) overlaps host pack (operand
        encoding + band offsets) and unpack (backpointer traceback) with
        device compute; omitted, the stages run synchronously as before.
        `on_reject(idx_list)` fires as soon as pairs are known to need the
        host aligner — unbucketable pairs up front, band-clipped pairs per
        chunk as tracebacks land — so the caller can start fallback work
        concurrently with the device pass instead of scanning for None
        afterwards. With `on_reject` armed and strict mode off, a device
        chunk that still fails after the pipeline's watchdog/retry policy
        is routed the same way — its pairs host-align and the device pass
        continues (chunk-granularity GPU->CPU discipline,
        cudapolisher.cpp:354-383) instead of aborting the whole phase.
        """
        import jax

        from . import align_pallas
        from .dtypes import aligner_int16_ok, kernel_plan
        from .encode import (encode_padded, pack_2bit, pack_bases_enabled,
                             packable)
        from ..parallel.mesh import BatchRunner
        from ..pipeline import DispatchPipeline
        from ..resilience import strict_mode
        from ..utils.logger import warn_dedup

        runner = self.runner if self.runner is not None else BatchRunner()
        pl = pipeline if pipeline is not None else DispatchPipeline(depth=0)
        results: list[list[tuple[int, str]] | None] = [None] * len(pairs)

        def shape_of(idx: int) -> int:
            return max(len(pairs[idx][0]), len(pairs[idx][1]))

        # device eligibility and the AUTO band are ALWAYS decided by the
        # static ladder, adaptive mode included. The band is algorithmic,
        # not padding — it changes which equal-cost path the banded DP
        # can see — so it must not move when the scheduler regroups jobs;
        # pinning both to the static rule makes scheduler-on vs -off
        # byte-identity structural, not a fixture property.
        static_groups: dict[int, list[int]] = {}
        unbucketed: list[int] = []
        for idx, (qs, ts) in enumerate(pairs):
            edge = self._bucket_of(max(len(qs), len(ts)))
            if edge is None or not qs or not ts:
                unbucketed.append(idx)  # host aligner handles these
                continue
            static_groups.setdefault(edge, []).append(idx)
        if on_reject is not None and unbucketed:
            on_reject(unbucketed)

        band_of: dict[int, int] = {}  # pair -> band, the static rule's
        for edge, idxs in static_groups.items():
            band = self._band_for(pairs, idxs)
            for i in idxs:
                band_of[i] = band

        # regroup by (compiled edge, band). Static mode: the original
        # one-band-per-bucket grouping, unchanged. Adaptive mode: a
        # sub-ladder INSIDE each occupied static bucket (the run's
        # length histogram, compile budget K = len(BUCKETS) split across
        # buckets by job count), so jobs move to a tighter edge but keep
        # their static band — the per-lane DP (band + offsets) is
        # bit-identical, only the compiled wavefront count shrinks, and
        # the total (edge, band) combo count stays <= K because band is
        # constant within a static bucket. Static edges are multiples of
        # the ladder quantum, so a derived edge never exceeds its static
        # bucket's. All derivation state is local: a reused aligner
        # starts every align() from the static ladder again.
        groups: dict[tuple[int, int], list[int]] = {}
        if self.sched.adaptive and static_groups:
            k_of = {edge: 1 for edge in static_groups}
            spare = len(self.BUCKETS) - len(static_groups)
            by_load = sorted(static_groups,
                             key=lambda e: -len(static_groups[e]))
            i = 0
            while spare > 0:
                k_of[by_load[i % len(by_load)]] += 1
                spare -= 1
                i += 1
            for edge, idxs in static_groups.items():
                sub = self.sched.aligner_ladder(
                    [shape_of(i) for i in idxs], k=k_of[edge],
                    max_length=self.max_length) or (edge,)
                for i in idxs:
                    e = next((x for x in sub if x >= shape_of(i)), edge)
                    groups.setdefault((e, band_of[i]), []).append(i)
        else:
            for edge, idxs in static_groups.items():
                for i in idxs:
                    groups.setdefault((edge, band_of[i]), []).append(i)

        from ..sched import shard_interleave

        chunks: list[tuple[int, int, int, list[int]]] = []
        n_dev = runner.n_devices
        for (edge, band), idxs in sorted(groups.items()):
            # sorted packing: shape-homogeneous chunks instead of arrival
            # order (results land by original index, so output order is
            # unaffected); identity when the scheduler is off
            idxs = self.sched.order(idxs, key=shape_of)
            n_waves = 2 * edge + 1
            lane_bytes = n_waves * (band // 4)
            max_lanes = max(n_dev, self.MAX_BP_BYTES // lane_bytes)
            if n_dev > 1:
                # device-aware chunking: BODY chunks are multiples of
                # the mesh width (zero round_batch padding lanes, rows
                # interleaved so each shard carries an even share of
                # the sorted lengths) and the remainder dispatches as
                # its own small chunk on a sub-mesh (for_batch) instead
                # of padding whole lanes up to the full device count
                stride = max(n_dev, (max_lanes // n_dev) * n_dev)
                body = (len(idxs) // n_dev) * n_dev
                for s in range(0, body, stride):
                    part = idxs[s:s + min(stride, body - s)]
                    chunks.append((edge, band, n_waves,
                                   shard_interleave(part, n_dev)))
                if body < len(idxs):
                    chunks.append((edge, band, n_waves, idxs[body:]))
            else:
                for s in range(0, len(idxs), max_lanes):
                    chunks.append((edge, band, n_waves,
                                   idxs[s:s + max_lanes]))

        # per-bucket kernel/dtype plan, resolved once: the Pallas posture
        # (constructor override, else RACON_TPU_PALLAS incl. the `auto`
        # winner-table consult), the score dtype (int16 iff the bucket's
        # overflow proof holds — ops/dtypes), and the VMEM envelope gate
        # with fallback to the XLA program
        if self.use_pallas is True:
            mode = "on"
        elif self.use_pallas is False:
            mode = "off"
        else:
            from .poa_pallas import pallas_mode

            mode = pallas_mode()
        plans: dict[tuple[int, int], tuple[str, str]] = {}

        def plan_for(edge: int, band: int) -> tuple[str, str]:
            plan = plans.get((edge, band))
            if plan is None:
                use, dtype = kernel_plan(
                    mode, "aligner", (edge, band), (),
                    aligner_int16_ok(edge),
                    lambda dt: align_pallas.fits_vmem(edge, band, dt))
                plan = plans[(edge, band)] = (
                    "pallas" if use else "xla", dtype)
            return plan

        def pack(chunk):
            edge, band, n_waves, idx = chunk
            kern, dtype = plan_for(edge, band)
            qs = [pairs[i][0] for i in idx]
            ts = [pairs[i][1] for i in idx]
            # tail batches smaller than the mesh dispatch on a SUB-MESH
            # (largest device count <= batch) instead of padding whole
            # lanes up to the full device count; for_batch is
            # deterministic in len(idx), so dispatch() resolves the
            # same runner
            lanes = runner.for_batch(len(idx)).round_batch(len(idx))
            q_arr, q_lens = encode_padded(qs + [b"A"] * (lanes - len(idx)),
                                          edge)
            t_arr, t_lens = encode_padded(ts + [b"A"] * (lanes - len(idx)),
                                          edge)
            offs = np.stack([band_offsets(int(ql), int(tl), band, n_waves)
                             for ql, tl in zip(q_lens, t_lens)])
            # 2-bit base packing: ACGT-only chunks ship a quarter of the
            # sequence bytes and unpack on device (byte-identical; any N
            # in the chunk keeps the int8 operands)
            do_pack = (pack_bases_enabled() and packable(q_arr, q_lens)
                       and packable(t_arr, t_lens))
            if kern == "pallas":
                q_op, t_op = align_pallas.build_ext(q_arr, t_arr, band)
                if do_pack:
                    q_op, t_op = pack_2bit(q_op), pack_2bit(t_op)
            elif do_pack:
                q_op, t_op = pack_2bit(q_arr), pack_2bit(t_arr)
            else:
                q_op, t_op = q_arr, t_arr
            return kern, dtype, do_pack, q_op, t_op, q_lens, t_lens, offs

        def dispatch(chunk, ops):
            import time

            edge, band, n_waves, idx = chunk
            kern, dtype, do_pack, q_op, t_op, q_lens, t_lens, offs = ops
            # the sub-mesh pack() sized the lanes for (zero padding
            # lanes on tails smaller than the mesh)
            r = runner.for_batch(len(idx))
            # compile telemetry: the first dispatch of a new shape blocks
            # through trace + XLA build (near-zero when the persistent
            # compile cache is warm) — charge that wall to the shape.
            # The lane count is part of the program identity: a tail
            # chunk narrower than its siblings compiles separately.
            t0 = time.perf_counter()
            if kern == "pallas":
                fn = align_pallas.wavefront_align(
                    edge, band, dtype, do_pack,
                    interpret=jax.default_backend() == "cpu")
                out = r.run_split(fn, q_op, t_op,
                                  q_lens.astype(np.int32),
                                  t_lens.astype(np.int32), offs)
            else:
                kernel = _kernel_for(band, n_waves, dtype, do_pack)
                out = r.run(
                    kernel, q_op, t_op, q_lens.astype(np.int32),
                    t_lens.astype(np.int32), offs,
                    out_batch_axes=(1, 0))  # bp is [n_waves, B, band//4]
            self.sched.stats.record_compile_once(
                "aligner",
                (band, n_waves, offs.shape[0], kern, dtype, do_pack),
                time.perf_counter() - t0)
            # occupancy telemetry, recorded at dispatch (a chunk killed
            # by a fault or the circuit breaker must not be accounted as
            # device work): useful DP cells = per-pair wave count x band
            # vs the batch's full n_waves x band x lanes — plus the mesh
            # view (per-shard useful split; what full-mesh round_batch
            # rounding would have dispatched)
            from .device_program import shard_useful_split

            row_cells = [(len(pairs[i][0]) + len(pairs[i][1]) + 1) * band
                         for i in idx]
            self.sched.stats.record(
                "aligner", (edge, band), jobs=len(idx),
                lanes=offs.shape[0],
                useful_cells=sum(row_cells),
                total_cells=offs.shape[0] * n_waves * band,
                kernel=kern, dtype=dtype, n_devices=r.n_devices,
                shard_useful=shard_useful_split(row_cells, offs.shape[0],
                                                r.n_devices),
                full_mesh_cells=(runner.round_batch(len(idx))
                                 * n_waves * band))
            pl.stats.bump("launches")
            return kern, out, q_lens, t_lens, offs

        def wait(handle):
            kern, out, q_lens, t_lens, offs = handle
            if kern == "pallas":
                shards = out if isinstance(out, list) else [out]
                op_arr = np.concatenate(
                    [np.asarray(jax.device_get(s[0])) for s in shards])
                meta = np.concatenate(
                    [np.asarray(jax.device_get(s[1])) for s in shards])
                return kern, (op_arr, meta), q_lens, t_lens, offs
            bp_packed, dist = out
            dist = np.asarray(dist).astype(np.int64)
            bp = np.asarray(jax.device_get(bp_packed))
            return kern, (bp, dist), q_lens, t_lens, offs

        def unpack(chunk, res):
            breaker.ok()  # a chunk came all the way back: device alive
            edge, band, n_waves, idx = chunk
            kern, out, q_lens, t_lens, offs = res
            if kern == "pallas":
                # in-kernel traceback: decode each lane's op path with
                # the same RLE the host traceback uses
                op_arr, meta = out
                counts = meta[:, 0]
                dist = meta[:, 1].astype(np.int64)
                touched = meta[:, 2] > 0
                runs = [_runs_of(op_arr[lane, :counts[lane]][::-1])
                        for lane in range(len(idx))]
            else:
                bp_packed, dist = out
                bp = _unpack_bp(bp_packed)
                runs, touched = _traceback(bp, offs, q_lens, t_lens)
            # second clipping signal: an in-band cost far above what a
            # <=30%-error overlap can produce means the true (off-band)
            # path was clipped — e.g. a large balanced indel whose
            # in-band "alignment" is a run of mismatches
            suspicious = dist > 0.4 * np.maximum(q_lens, t_lens)
            accepted = 0
            rejected: list[int] = []
            for lane, i_pair in enumerate(idx):
                if touched[lane] or suspicious[lane]:
                    self.n_band_rejects += 1  # clipped: host re-aligns
                    rejected.append(i_pair)
                else:
                    results[i_pair] = runs[lane]
                    accepted += 1
            if on_reject is not None and rejected:
                on_reject(rejected)
            if progress is not None:
                # rejected pairs tick when the host fallback aligns them
                progress(accepted)

        #: consecutive-chunk-failure circuit breaker — the shared seam
        #: implementation (ops/device_program.ChunkBreaker): one flaky
        #: chunk degrades to the host fallback, but a wedged device must
        #: not cost a watchdog deadline + retry per chunk for the whole
        #: phase — past the streak the pass aborts and the polisher's
        #: whole-phase host fallback runs
        from .device_program import ChunkBreaker

        breaker = ChunkBreaker("BatchAligner", pl.stats,
                               "the device alignment pass")

        def chunk_error(chunk, exc):
            # a chunk dead after watchdog/retry: its pairs host-align via
            # the reject protocol; results stay complete, never crash.
            # Deduplicated: on a wedged device this fires once per chunk
            # with near-identical text — the first prints, repeats are
            # counted (RACON_TPU_LOG_LEVEL=debug shows each)
            edge, band, n_waves, idx = chunk
            breaker.failed(exc, f"{len(idx)} pairs to host fallback")
            on_reject(list(idx))

        pl.run(chunks, pack, dispatch, wait, unpack,
               on_error=(chunk_error if on_reject is not None
                         and not strict_mode() else None),
               label="aligner",
               describe=lambda c: {"engine": "aligner",
                                   "bucket": f"{c[0]}x{c[1]}",
                                   "jobs": len(c[3])})
        return results


def edit_distance(a: bytes, b: bytes) -> int:
    """Plain (unbanded) edit distance on host — numpy row DP. Used by tests
    as the reference metric (the reference uses edlib in
    test/racon_test.cpp:16-25)."""
    if not a:
        return len(b)
    if not b:
        return len(a)
    pa = np.frombuffer(a, dtype=np.uint8)
    pb = np.frombuffer(b, dtype=np.uint8)
    prev = np.arange(len(pb) + 1, dtype=np.int32)
    for i in range(1, len(pa) + 1):
        cur = np.empty_like(prev)
        cur[0] = i
        # vertical + diagonal candidates
        np.minimum(prev[:-1] + (pb != pa[i - 1]), prev[1:] + 1, out=cur[1:])
        # horizontal propagation: cur[j] = min_k<=j (cand[k] + (j - k))
        ar = np.arange(len(cur), dtype=np.int32)
        cur = np.minimum.accumulate(cur - ar) + ar
        prev = cur
    return int(prev[-1])
