"""Pallas TPU kernel for banded global alignment with in-kernel traceback.

The alignment-side half of the device-kernel plane (the POA side is
ops/poa_pallas.window_sweep): the anti-diagonal wavefront of
ops/align._banded_nw_kernel as a hand-tiled kernel, one pair per
sequential grid step with the WHOLE job resident in VMEM:

  - the two rolling wavefronts live in VMEM scratch as `score_dtype`
    rows ([1, band]); the XLA program instead carries them through a
    `lax.scan` whose state round-trips HBM every anti-diagonal;
  - the backpointer plane ([n_waves, band] int8 — codes are 2-bit
    values, stored one per byte because byte rows keep every store a
    plain vector op; the XLA program's packed uint8 plane must leave
    the chip, ~n_waves*band/4 bytes per lane, while this one never
    does) lives in VMEM scratch;
  - the traceback runs in-kernel (scalar pointer chase over the VMEM
    backpointers, mirroring window_sweep), so the kernel's outputs are
    only the op-code path (<= m+n entries), its length, the final
    distance and the band-edge flag — a ~band/4-fold cut in
    device->host traffic.

DP values, band tracking and tie order replicate _banded_nw_kernel
EXACTLY (same formulas, same INF clamp, same diag < up < left order),
and the band-shifted neighbour reads are plain dynamic slices because
the host pre-extends the operands (`build_ext`): q_ext[p] =
q[clip(p-1, 0, edge-1)] and t_ext[p] = t[clip(2*edge-1-p, 0, edge-1)],
so wavefront d of lane offset a0 reads q at slice start a0 and t at
slice start 2*edge + a0 - d — including the exact clip values the XLA
program's `take_along_axis(clip(...))` produces, cell for cell.
tests/test_pallas_align.py fuzzes the kernel against the XLA program in
interpret mode; `BatchAligner` dispatches it per bucket under
RACON_TPU_PALLAS=1 (always, when the envelope fits) or =auto (when the
persisted autotuner table says it measured faster), with the XLA
program as the fallback for shapes the VMEM budget cannot hold.
"""

from __future__ import annotations

import functools

import numpy as np

#: VMEM the resident job may use — shared budget with the POA kernel
from .poa_pallas import VMEM_BUDGET

BP_DIAG, BP_UP, BP_LEFT = 0, 1, 2  # ops/align.py's codes


def _round128(n: int) -> int:
    return (n + 127) // 128 * 128


def ext_widths(edge: int, band: int) -> tuple[int, int]:
    """(q_ext, t_ext) operand widths for one bucket (128-padded)."""
    return _round128(1 + edge + band), _round128(2 * edge + band)


def fits_vmem(edge: int, band: int, dtype: str = "int32") -> bool:
    """True when one lane of bucket (edge, band) is resident-VMEM
    feasible: the backpointer plane, the rolling wavefronts, AND the
    per-grid-step operand blocks (offsets, extended q/t, outputs — all
    int32 in VMEM; the original fits_vmem bug of budgeting only the
    scratch is not repeated here) fit the shared budget with slack."""
    n_waves = 2 * edge + 1
    lq, lt = ext_widths(edge, band)
    nw_pad = _round128(n_waves)
    # int8 bp rows are tiled to >= 128 lanes on chip
    bp = _round128(n_waves + 32) * max(_round128(band), 128)
    dbytes = 2 if dtype == "int16" else 4
    waves = 2 * max(_round128(band), 128) * dbytes * 8  # 8-sublane tiles
    operands = (nw_pad + lq + lt + nw_pad + 128) * 4
    return bp + waves + operands + (1 << 20) <= VMEM_BUDGET


def build_ext(q_arr: np.ndarray, t_arr: np.ndarray,
              band: int) -> tuple[np.ndarray, np.ndarray]:
    """Host-side operand extension (see module docstring): [B, edge]
    int8 code arrays (PAD beyond length, from encode_padded) ->
    (q_ext [B, Lq], t_ext [B, Lt]) int8 such that every wavefront's
    neighbour reads become contiguous dynamic slices that reproduce the
    XLA program's clipped gathers exactly."""
    edge = q_arr.shape[1]
    lq, lt = ext_widths(edge, band)
    qi = np.clip(np.arange(lq) - 1, 0, edge - 1)
    ti = np.clip(2 * edge - 1 - np.arange(lt), 0, edge - 1)
    return np.ascontiguousarray(q_arr[:, qi]), \
        np.ascontiguousarray(t_arr[:, ti])


@functools.lru_cache(maxsize=None)
def wavefront_align(edge: int, band: int, score_dtype: str = "int32",
                    packed: bool = False, interpret: bool = False):
    """Jitted fn(q_ext, t_ext, q_lens, t_lens, offsets) ->
    (ops [B, nw_pad] i32, meta [B, 128] i32), one pair per grid step.

    `ops[k, :meta[k, 0]]` is lane k's backpointer path in traceback
    order (reverse it for the forward CIGAR); meta[k] = (count, dist,
    touched_edge, 0...). `packed` takes 2-bit packed q_ext/t_ext
    ([B, Lx//4] uint8, from encode.pack_2bit over build_ext's output)
    and unpacks + PAD-restores them with XLA ops before the kernel —
    a 4x cut in host->device sequence traffic, byte-identical by
    construction. `score_dtype` picks the wavefront dtype; int16 is
    only legal under ops/dtypes.aligner_int16_ok's envelope proof.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_waves = 2 * edge + 1
    nw_pad = _round128(n_waves)
    lq, lt = ext_widths(edge, band)
    DT = jnp.int16 if score_dtype == "int16" else jnp.int32
    INF = (1 << 14) if score_dtype == "int16" else (1 << 28)

    def kernel(scal_ref, offs_ref, qx_ref, tx_ref, ops_ref, meta_ref,
               s1, s2, bps):
        m = scal_ref[0, 0]
        n = scal_ref[0, 1]
        INFD = jnp.asarray(INF, DT)
        ks = jax.lax.broadcasted_iota(jnp.int32, (1, band), 1)
        s1[0:1, :] = jnp.full((1, band), INF, DT)
        s2[0:1, :] = jnp.full((1, band), INF, DT)
        ops_ref[0:1, :] = jnp.zeros((1, nw_pad), jnp.int32)
        pad = jnp.full((1, 1), INF, DT)

        def wave(d, carry):
            # the loop index arrives as int64 when another kernel build
            # (poa_fused) has flipped jax_enable_x64 for the process;
            # every index expression below must stay int32
            d = jnp.asarray(d, jnp.int32)
            z = jnp.int32(0)
            a1, a2, dist = carry
            a0 = offs_ref[0, d]
            ext1 = jnp.concatenate([pad, s1[0:1, :], pad], axis=1)
            ext2 = jnp.concatenate([pad, s2[0:1, :], pad], axis=1)
            da = a0 - a1
            db = a0 - a2
            i = a0 + ks
            j = d - i
            # neighbour reads as shifted slices of the rolling rows:
            # up (d-1, i-1) = s1[k + da - 1], left (d-1, i) = s1[k + da],
            # diag (d-2, i-1) = s2[k + db - 1]; the INF border of ext*
            # reproduces the XLA gather's out-of-band INF exactly
            up = jnp.where(i >= 1,
                           jax.lax.dynamic_slice(ext1, (z, da), (1, band)),
                           INFD)
            left = jnp.where(j >= 1,
                             jax.lax.dynamic_slice(ext1, (z, da + 1),
                                                   (1, band)), INFD)
            diag = jnp.where((i >= 1) & (j >= 1),
                             jax.lax.dynamic_slice(ext2, (z, db),
                                                   (1, band)), INFD)
            qi = jax.lax.dynamic_slice(qx_ref[0:1, :], (z, a0), (1, band))
            tj = jax.lax.dynamic_slice(tx_ref[0:1, :],
                                       (z, 2 * edge + a0 - d), (1, band))
            sub = jnp.where(qi == tj, 0, 1).astype(DT)

            cd = diag + sub
            cu = up + jnp.asarray(1, DT)
            cl = left + jnp.asarray(1, DT)
            # fixed tie order: diag, up, left (ops/align.py)
            score = cd
            bp = jnp.zeros((1, band), jnp.int32) + BP_DIAG
            bp = jnp.where(cu < score, BP_UP, bp)
            score = jnp.minimum(score, cu)
            bp = jnp.where(cl < score, BP_LEFT, bp)
            score = jnp.minimum(score, cl)
            origin = (i == 0) & (j == 0)
            score = jnp.where(origin, jnp.asarray(0, DT), score)
            valid = (i >= 0) & (i <= m) & (j >= 0) & (j <= n)
            score = jnp.where(valid, jnp.minimum(score, INFD), INFD)

            at_end = (i == m) & (j == n)
            dist = jnp.where(
                jnp.any(at_end),
                jnp.min(jnp.where(at_end, score, INFD)).astype(jnp.int32),
                dist)

            bps[pl.ds(d, 1), :] = bp.astype(jnp.int8)
            s2[0:1, :] = s1[0:1, :]
            s1[0:1, :] = score
            return a0, a1, dist

        _, _, dist = jax.lax.fori_loop(
            0, n_waves, wave,
            (jnp.int32(0), jnp.int32(0), jnp.int32(INF)))

        # in-kernel traceback: the host _traceback's walk, one lane
        def tb_cond(st):
            i, j, cnt, touched = st
            return (i > 0) | (j > 0)

        def tb_body(st):
            i, j, cnt, touched = st
            d = i + j
            off = offs_ref[0, d]
            k = i - off
            row_lo = jnp.maximum(0, d - n)
            row_hi = jnp.minimum(d, m)
            # band-boundary marks (possible clipping) only when the
            # matrix continues past the boundary on that side
            touched = jnp.where((k <= 0) & (off > row_lo), 1, touched)
            touched = jnp.where((k >= band - 1)
                                & (off + band - 1 < row_hi), 1, touched)
            kc = jnp.clip(k, 0, band - 1)
            code = bps[d, kc].astype(jnp.int32)
            # boundary overrides: on i==0 only D possible; on j==0 only I
            code = jnp.where(i == 0, BP_LEFT, code)
            code = jnp.where(j == 0, BP_UP, code)
            di = jnp.where(code != BP_LEFT, 1, 0)
            dj = jnp.where(code != BP_UP, 1, 0)
            ops_ref[0, cnt] = code
            return i - di, j - dj, cnt + 1, touched

        i, j, cnt, touched = jax.lax.while_loop(
            tb_cond, tb_body, (m, n, jnp.int32(0), jnp.int32(0)))
        meta_ref[0:1, :] = jnp.zeros((1, 128), jnp.int32)
        meta_ref[0, 0] = cnt
        meta_ref[0, 1] = dist
        meta_ref[0, 2] = touched

    def call(q_ext, t_ext, q_lens, t_lens, offsets):
        B = offsets.shape[0]
        if packed:
            from .encode import PAD, unpack_2bit_jax

            pos_q = jnp.arange(lq, dtype=jnp.int32)[None, :]
            pos_t = jnp.arange(lt, dtype=jnp.int32)[None, :]
            ql = q_lens.astype(jnp.int32)[:, None]
            tl = t_lens.astype(jnp.int32)[:, None]
            qx = unpack_2bit_jax(q_ext, lq)
            tx = unpack_2bit_jax(t_ext, lt)
            # PAD restore along the clip maps build_ext baked in:
            # q_ext[p] = q[clip(p-1, 0, edge-1)] is PAD iff that clipped
            # index lands at or past q_len (only possible when the pair
            # does not fill its bucket), and symmetrically for t_ext
            qx = jnp.where((pos_q >= 1 + ql) & (ql < edge),
                           jnp.int8(PAD), qx)
            tx = jnp.where((pos_t <= 2 * edge - 1 - tl) & (tl < edge),
                           jnp.int8(PAD), tx)
        else:
            qx, tx = q_ext, t_ext
        scal = jnp.stack([q_lens.astype(jnp.int32),
                          t_lens.astype(jnp.int32)], axis=1)      # [B, 2]
        offs = jnp.pad(offsets.astype(jnp.int32),
                       ((0, 0), (0, nw_pad - offsets.shape[1])))
        vmem = pltpu.VMEM
        return pl.pallas_call(
            kernel,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, 2), lambda b: (b, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, nw_pad), lambda b: (b, 0),
                             memory_space=vmem),
                pl.BlockSpec((1, lq), lambda b: (b, 0),
                             memory_space=vmem),
                pl.BlockSpec((1, lt), lambda b: (b, 0),
                             memory_space=vmem),
            ],
            out_specs=(
                pl.BlockSpec((1, nw_pad), lambda b: (b, 0),
                             memory_space=vmem),
                pl.BlockSpec((1, 128), lambda b: (b, 0),
                             memory_space=vmem),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((B, nw_pad), jnp.int32),
                jax.ShapeDtypeStruct((B, 128), jnp.int32),
            ),
            scratch_shapes=[
                pltpu.VMEM((1, band), DT),          # wavefront d-1
                pltpu.VMEM((1, band), DT),          # wavefront d-2
                pltpu.VMEM((n_waves, band), jnp.int8),  # backpointers
            ],
            interpret=interpret,
        )(scal, offs, qx.astype(jnp.int32), tx.astype(jnp.int32))

    return jax.jit(call)
