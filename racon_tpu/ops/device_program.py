"""Shared device-program seam (ROADMAP item 5, first slice).

Every device engine has so far privately re-wired the same chunk-loop
plumbing: a consecutive-failure circuit breaker, per-shard occupancy
splits, and the declared-fallback discipline around a failed chunk.
This module hosts the pieces the engines can share TODAY without any
behavior change — the aligner and the fused POA engine bind to it
instead of keeping private copies, and the fused align→window→POA
program (ops/poa_fused.py's single-launch path) is wired through it
rather than growing a fifth private copy. The full
shapes → ladder → dtype-plan → pack → dispatch → unpack interface
extraction is the rest of item 5; this slice deliberately starts with
the parts whose unification cannot move a byte.
"""

from __future__ import annotations


class ChunkBreaker:
    """Consecutive-chunk-failure circuit breaker for a device chunk
    loop (one implementation of the FusedPOA/BatchAligner discipline):
    one flaky chunk degrades to the engine's declared fallback, but a
    device that fails every chunk (dead tunnel, OOM) must not burn a
    pack+dispatch attempt — or a watchdog deadline — per chunk for the
    whole phase. After `max_streak` consecutive failures the pass
    aborts with a DeviceError chained to the last cause, restoring the
    old first-exception whole-phase fallback.
    """

    def __init__(self, engine: str, stats, abort_what: str,
                 max_streak: int = 3):
        #: `engine` names the loop in warnings/errors (BatchAligner /
        #: FusedPOA); `stats` is the pipeline's PipelineStats (or None)
        #: for the breaker_trips counter; `abort_what` finishes the
        #: abort message ("the device alignment pass" / "the device
        #: pass")
        self.engine = engine
        self.stats = stats
        self.abort_what = abort_what
        self.max_streak = max_streak
        self.n = 0

    def ok(self) -> None:
        """A chunk came all the way back: the device is alive."""
        self.n = 0

    def failed(self, exc: BaseException, detail: str) -> None:
        """Count one failed chunk (warning deduplicated per engine —
        on a wedged device this fires once per chunk with
        near-identical text); raises DeviceError past the streak
        limit. `detail` says where the chunk's items went
        ("N pairs to host fallback")."""
        from ..errors import DeviceError
        from ..utils.logger import warn_dedup

        self.n += 1
        warn_dedup(
            f"{self.engine}.device_chunk_failed",
            f"[racon_tpu::{self.engine}] warning: device chunk failed "
            f"({type(exc).__name__}: {exc}); {detail}")
        if self.n >= self.max_streak:
            if self.stats is not None:
                self.stats.bump("breaker_trips")
            err = DeviceError(
                self.engine,
                f"{self.n} consecutive device chunk failures; aborting "
                f"{self.abort_what}")
            err.__cause__ = exc
            raise err


def shard_useful_split(row_cells, lanes: int, n_devices: int) -> list:
    """Per-shard useful-cell sums for a contiguously-sharded batch of
    `lanes` rows (rows s*per .. (s+1)*per land on device s) — the
    occupancy mesh view every engine records. `row_cells` is the
    per-row useful-cell list for the REAL rows only; the padding rows
    at the batch tail contribute zero wherever they land."""
    per = lanes // max(1, n_devices)
    return [sum(row_cells[s * per:(s + 1) * per])
            for s in range(n_devices)]
