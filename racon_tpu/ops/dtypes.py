"""Score-dtype shrinking: per-bucket int16 eligibility proofs.

Every DP kernel in ops/ historically carried scores as int32. For most
buckets that is 2x the bytes the arithmetic needs: the score magnitude a
bucket can produce is bounded by its shape and the scoring params, and
when that envelope provably fits int16 the whole DP state (the H carry,
the wavefronts, the sentinel comparisons) can run narrow — half the VMEM
footprint for the resident Pallas kernels, half the HBM traffic for the
XLA programs. int32 stays the fallback and the identity oracle: the
narrow program is only ever selected when overflow is IMPOSSIBLE, so its
results are bit-identical by construction (and fuzzed at the envelope
boundary in tests/test_pallas_align.py / test_pallas_poa.py).

The proofs the predicates encode:

- aligner (unit-cost edit distance, minimize, sentinel INF): every
  stored cell is min-clamped at INF each wavefront, so values live in
  [0, INF + 1]. Real path costs are bounded by the anti-diagonal index
  d <= 2*edge. With INF16 = 1 << 14, int16 is safe iff 2*edge + 1 < INF16
  (INF must exceed every real score; INF + 1 = 16385 <= 32767 always).

- POA graph-NW (maximize, sentinel NEG): real scores are bounded by
  (N + L + 1) * mp with mp = max(|match|, |mismatch|, |gap|). Unlike the
  aligner there is no per-row clamp, so unreachable in-band cells can
  drift below NEG by at most mp per node row (stored row k values are
  >= NEG - k * mp by induction); intermediates add at most one more op
  plus the Hillis/cummax offset of |L * gap|. With NEG16 = -(1 << 14),
  every value and intermediate fits int16 iff
  (N + L + 2) * mp <= (1 << 15) - 1 - (1 << 14) = 16383
  (which also implies the real-score bound (N+L+1)*mp < 1 << 14).

RACON_TPU_DTYPE selects the posture: `auto` (default — shrink whenever
the proof holds, except where a persisted autotuner winner measured the
wide program faster), `int32` (force the oracle everywhere; the
bisection / identity-pin knob), `int16` (shrink wherever provable,
ignoring the winner table). A bucket whose envelope fails the proof
ALWAYS runs int32, whatever the knob says.
"""

from __future__ import annotations

import os

#: int16 sentinel magnitudes (the int32 kernels keep their historical
#: 1 << 28 / -(1 << 29) sentinels)
INF16 = 1 << 14
NEG16 = -(1 << 14)

_I16_MAX = (1 << 15) - 1


def dtype_mode() -> str:
    """RACON_TPU_DTYPE posture: 'auto' | 'int32' | 'int16'. Invalid
    values fall back to auto (never crash a run over a typo'd knob).
    Inside an audit oracle_scope (ops/oracle.py) the posture is pinned
    'int32' on that thread — the shadow oracle always runs wide."""
    from .oracle import oracle_active

    if oracle_active():
        return "int32"
    raw = (os.environ.get("RACON_TPU_DTYPE") or "auto").strip().lower()
    return raw if raw in ("auto", "int32", "int16") else "auto"


def aligner_int16_ok(edge: int) -> bool:
    """True when the banded edit-distance DP at bucket `edge` provably
    fits int16 (see module docstring)."""
    return 2 * edge + 1 < INF16


def poa_int16_ok(n_nodes: int, seq_len: int, match: int, mismatch: int,
                 gap: int) -> bool:
    """True when the graph-NW DP at bucket (n_nodes, seq_len) with these
    scoring params provably fits int16 (see module docstring)."""
    mp = max(abs(match), abs(mismatch), abs(gap))
    return (n_nodes + seq_len + 2) * mp <= _I16_MAX - INF16


def kernel_plan(posture: str, engine: str, bucket, params,
                envelope_ok: bool, fits) -> tuple[bool, str]:
    """The ONE kernel-plane dispatch decision, shared by all three
    engine dispatchers (align.BatchAligner, poa_graph.DeviceGraphPOA,
    poa_fused.FusedPOA): consult the persisted autotuner winner table
    under the `auto` posture, resolve the score dtype against the
    bucket's overflow proof, and gate the Pallas choice on the VMEM
    envelope. Returns (use_pallas, score_dtype).

    `posture` is pallas_mode()'s 'off'|'on'|'auto' (or a constructor
    override already folded to on/off); `fits` is the engine's VMEM
    predicate `fits(dtype) -> bool` (pass `lambda dt: False` for an
    engine with no Pallas variant — the dtype half still applies)."""
    ent = None
    if posture == "auto":
        from ..sched.autotune import get_autotuner

        ent = get_autotuner().winner(engine, bucket, params)
    dtype = resolve_dtype(envelope_ok, ent)
    wants = posture == "on" or (ent or {}).get("kernel") == "pallas"
    return bool(wants and fits(dtype)), dtype


def resolve_dtype(envelope_ok: bool, winner: dict | None = None) -> str:
    """The per-bucket score dtype: 'int16' or 'int32'.

    `envelope_ok` is the bucket's overflow proof — False always means
    int32. `winner` is an optional autotuner table entry whose measured
    `dtype` wins under the auto posture (a bucket where narrow measured
    slower stays wide)."""
    if not envelope_ok:
        return "int32"
    mode = dtype_mode()
    if mode == "int32":
        return "int32"
    if mode == "auto" and winner and winner.get("dtype") in ("int16",
                                                            "int32"):
        return winner["dtype"]
    return "int16"
