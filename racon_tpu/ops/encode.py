"""Base-space encoding for device kernels.

Sequences live as ASCII bytes on the host; device kernels work on small int8
codes so comparisons vectorize and tensors stay narrow: A=0, C=1, G=2, T=3,
everything else (N, IUPAC) = 4. Code 4 compares equal to itself, matching the
reference's char-equality semantics ('N' vs 'N' is a match for spoa/edlib).
PAD=5 never matches anything, including itself.
"""

from __future__ import annotations

import numpy as np

A, C, G, T, N, PAD = 0, 1, 2, 3, 4, 5

_LUT = np.full(256, N, dtype=np.int8)
for i, b in enumerate(b"ACGT"):
    _LUT[b] = i
_DECODE = np.frombuffer(b"ACGTN-", dtype=np.uint8)


def encode(seq: bytes) -> np.ndarray:
    """ASCII bytes -> int8 codes."""
    return _LUT[np.frombuffer(seq, dtype=np.uint8)]


def decode(codes: np.ndarray) -> bytes:
    """int8 codes -> ASCII bytes (PAD renders as '-')."""
    return _DECODE[np.asarray(codes, dtype=np.int64)].tobytes()


def encode_padded(seqs: list[bytes], length: int) -> tuple[np.ndarray, np.ndarray]:
    """Encode a batch of sequences into a [len(seqs), length] int8 array
    padded with PAD; returns (codes, lengths)."""
    out = np.full((len(seqs), length), PAD, dtype=np.int8)
    lens = np.empty(len(seqs), dtype=np.int32)
    for i, s in enumerate(seqs):
        n = min(len(s), length)
        out[i, :n] = _LUT[np.frombuffer(s, dtype=np.uint8)[:n]]
        lens[i] = n
    return out, lens


def packable(codes: np.ndarray, lens: np.ndarray) -> bool:
    """True when a [B, L] code batch is exactly reconstructible from its
    2-bit packing: every in-length code is ACGT (< 4) and every
    beyond-length position is PAD. N/IUPAC operands (code 4) stay int8 —
    2 bits cannot carry them."""
    pos = np.arange(codes.shape[1])[None, :]
    valid = pos < np.asarray(lens).reshape(-1, 1)
    return bool(np.all(np.where(valid, codes < 4, codes == PAD)))


def pack_2bit(codes: np.ndarray) -> np.ndarray:
    """[B, L] int8 codes -> [B, ceil(L/4)] uint8, 4 bases per byte
    (base i in bits 2i..2i+1). Codes >= 4 pack as their low 2 bits —
    callers gate with `packable` (PAD positions are restored from
    lengths on unpack, so their packed value is immaterial)."""
    b, l = codes.shape
    l4 = (l + 3) // 4 * 4
    arr = np.zeros((b, l4), dtype=np.uint8)
    arr[:, :l] = codes.astype(np.uint8) & 3
    arr = arr.reshape(b, l4 // 4, 4)
    return (arr[..., 0] | (arr[..., 1] << 2) | (arr[..., 2] << 4)
            | (arr[..., 3] << 6))


def unpack_2bit_jax(packed, length: int, lens=None, pad: int = PAD):
    """Device-side inverse of `pack_2bit` (jax ops, runs inside the
    jitted program before the DP kernel): [B, W] uint8 -> [B, length]
    int8 codes, with positions >= lens restored to `pad` when `lens`
    is given — byte-identical to the int8 operand the kernel would
    otherwise have received. The unpack is a handful of vector shifts,
    while the host->device transfer it replaces shrinks 4x."""
    import jax.numpy as jnp

    shifts = jnp.arange(4, dtype=jnp.uint8) * 2
    v = (packed[:, :, None] >> shifts[None, None, :]) & 3     # [B, W, 4]
    v = v.reshape(packed.shape[0], -1)[:, :length].astype(jnp.int8)
    if lens is not None:
        pos = jnp.arange(length, dtype=jnp.int32)[None, :]
        v = jnp.where(pos < lens.astype(jnp.int32)[:, None], v,
                      jnp.int8(pad))
    return v


def pack_bases_enabled() -> bool:
    """2-bit operand packing posture: on unless RACON_TPU_PACK_BASES=0
    (the bisection knob — packing is byte-identical by construction,
    this exists to A/B the transfer win and to pin identity in tests).
    Inside an audit oracle_scope (ops/oracle.py) packing is pinned OFF
    on that thread — the shadow oracle ships unpacked operands."""
    import os

    from .oracle import oracle_active

    if oracle_active():
        return False
    return os.environ.get("RACON_TPU_PACK_BASES", "auto") not in ("0",)


def phred_weights(quality: bytes | None, length: int, pad_to: int) -> np.ndarray:
    """Phred+33 quality -> int32 weights (char - 33), like the reference GPU
    path (src/cuda/cudabatch.cpp:182-191). None -> weight 1 per base (spoa's
    qual-less default)."""
    out = np.zeros(pad_to, dtype=np.int32)
    if quality is None:
        out[:length] = 1
    else:
        q = np.frombuffer(quality, dtype=np.uint8).astype(np.int32) - 33
        out[: len(q)] = q
    return out
