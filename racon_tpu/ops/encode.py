"""Base-space encoding for device kernels.

Sequences live as ASCII bytes on the host; device kernels work on small int8
codes so comparisons vectorize and tensors stay narrow: A=0, C=1, G=2, T=3,
everything else (N, IUPAC) = 4. Code 4 compares equal to itself, matching the
reference's char-equality semantics ('N' vs 'N' is a match for spoa/edlib).
PAD=5 never matches anything, including itself.
"""

from __future__ import annotations

import numpy as np

A, C, G, T, N, PAD = 0, 1, 2, 3, 4, 5

_LUT = np.full(256, N, dtype=np.int8)
for i, b in enumerate(b"ACGT"):
    _LUT[b] = i
_DECODE = np.frombuffer(b"ACGTN-", dtype=np.uint8)


def encode(seq: bytes) -> np.ndarray:
    """ASCII bytes -> int8 codes."""
    return _LUT[np.frombuffer(seq, dtype=np.uint8)]


def decode(codes: np.ndarray) -> bytes:
    """int8 codes -> ASCII bytes (PAD renders as '-')."""
    return _DECODE[np.asarray(codes, dtype=np.int64)].tobytes()


def encode_padded(seqs: list[bytes], length: int) -> tuple[np.ndarray, np.ndarray]:
    """Encode a batch of sequences into a [len(seqs), length] int8 array
    padded with PAD; returns (codes, lengths)."""
    out = np.full((len(seqs), length), PAD, dtype=np.int8)
    lens = np.empty(len(seqs), dtype=np.int32)
    for i, s in enumerate(seqs):
        n = min(len(s), length)
        out[i, :n] = _LUT[np.frombuffer(s, dtype=np.uint8)[:n]]
        lens[i] = n
    return out, lens


def phred_weights(quality: bytes | None, length: int, pad_to: int) -> np.ndarray:
    """Phred+33 quality -> int32 weights (char - 33), like the reference GPU
    path (src/cuda/cudabatch.cpp:182-191). None -> weight 1 per base (spoa's
    qual-less default)."""
    out = np.zeros(pad_to, dtype=np.int32)
    if quality is None:
        out[:length] = 1
    else:
        q = np.frombuffer(quality, dtype=np.uint8).astype(np.int32) - 33
        out[: len(q)] = q
    return out
