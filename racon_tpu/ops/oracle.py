"""Oracle re-execution: ground-truth consensus for the audit sentinel.

Every kernel plane in this repo is pinned byte-identical to one ORACLE
configuration: the XLA programs at int32 with unpacked operands, and —
for the fused engine — the SPLIT chained-call path (the declared
fallback of the single-launch program, PR-11). The autotuner's identity
veto already compares against exactly that configuration at PROFILE
time (sched/autotune.py `_pick`); this module makes the same oracle
available at SERVE time, so the online auditor (obs/audit.py) can
shadow re-execute a sampled production window and byte-compare.

Two pieces:

  - `oracle_scope()` — a THREAD-LOCAL posture override consulted by the
    four kernel-plane posture functions (`pallas_mode`, `dtype_mode`,
    `pack_bases_enabled`, `fused_mode`). Inside the scope, on the
    entering thread only, every dispatch decision resolves to the
    oracle: XLA, int32, unpacked, split-chain. Thread-local (not
    os.environ) because the auditor runs INSIDE a live server whose
    feeder threads are concurrently resolving the production posture —
    a process-wide env flip would corrupt their dispatch mid-iteration.
  - `OracleExecutor` — cached per-engine-parameter oracle engines
    (BatchPOA at pipeline depth 0, every stage inline on the calling
    thread so the scope override is seen everywhere) with their OWN
    PipelineStats/OccupancyStats: shadow executions never pollute the
    production `pipeline.*`/`sched.*` telemetry (they surface as the
    `audit.*` namespace instead, test-pinned) and never consult the
    autotuner (forced postures skip the winner table entirely), so a
    poisoned winner entry cannot poison its own audit. Fault injection
    is disabled on the oracle pipeline (`faults=False`) — the oracle
    must reproduce ground truth, not re-fire the injected corruption it
    exists to detect.

The oracle is deliberately NOT pinned to the production lane's
sub-mesh: a bad lane is exactly what the comparison must be independent
of (lane-level blame is the re-probe's job, serve/batcher.py)."""

from __future__ import annotations

import contextlib
import threading

_tls = threading.local()


def oracle_active() -> bool:
    """True on a thread currently inside `oracle_scope()` — consulted
    by the kernel-plane posture functions (one thread-local attribute
    read; the production hot path pays only that)."""
    return getattr(_tls, "depth", 0) > 0


@contextlib.contextmanager
def oracle_scope():
    """Enter the oracle posture on THIS thread: XLA kernels, int32
    scores, unpacked operands, split-chain fused dispatch. Reentrant."""
    _tls.depth = getattr(_tls, "depth", 0) + 1
    try:
        yield
    finally:
        _tls.depth -= 1


# ------------------------------------------------------------ snapshots
def snapshot_window(w) -> tuple:
    """An immutable content snapshot of one production window — the
    bytes the consensus is a pure function of. The sequences/qualities
    are immutable `bytes`, so this is reference-copying, not data
    copying; safe to hold across iterations and processes."""
    return (w.id, w.rank, w.type, tuple(w.sequences),
            tuple(w.qualities), tuple(w.positions))


def rebuild_window(snap):
    """A fresh Window carrying exactly the snapshot's content, with no
    consensus yet — the oracle's (and the lane re-probe's) input."""
    from ..core.window import Window

    wid, rank, wtype, seqs, quals, positions = snap
    w = Window(wid, rank, wtype, seqs[0], quals[0])
    w.sequences = list(seqs)
    w.qualities = list(quals)
    w.positions = list(positions)
    return w


def engine_params_key(p) -> tuple:
    """The consensus-engine identity of a polisher's parameters — every
    knob that can influence a window's consensus bytes (the serve
    batcher's iteration-sharing key minus the job-only fields)."""
    import os

    return (p.match, p.mismatch, p.gap, p.window_length,
            p.tpu_poa_batches, p.tpu_banded_alignment,
            p.tpu_aligner_band_width,
            p.tpu_engine or os.environ.get("RACON_TPU_ENGINE")
            or "session")


class OracleExecutor:
    """Cached oracle engines, one per engine-parameter key (see module
    docstring). `consensus()` serializes on one lock — the auditor is a
    sampling sidecar, not a second serving plane — and runs everything
    inline (pipeline depth 0) on the calling thread so `oracle_scope`
    covers every posture read."""

    def __init__(self):
        from ..pipeline import PipelineStats
        from ..sched import BatchScheduler, OccupancyStats

        #: audit-namespace telemetry: the oracle's own stage counters
        #: and compile/occupancy stats, never mixed into production
        self.pipeline_stats = PipelineStats()
        self.scheduler = BatchScheduler(adaptive=False,
                                        stats=OccupancyStats())
        self._engines: dict = {}
        self._lock = threading.Lock()

    def _engine(self, key: tuple, p):
        from ..pipeline import DispatchPipeline
        from .poa import BatchPOA

        ent = self._engines.get(key)
        if ent is None:
            pipeline = DispatchPipeline(depth=0,
                                        stats=self.pipeline_stats,
                                        faults=False)
            ent = self._engines[key] = BatchPOA(
                p.match, p.mismatch, p.gap, p.window_length,
                num_threads=1,
                device_batches=p.tpu_poa_batches,
                banded=p.tpu_banded_alignment,
                band_width=p.tpu_aligner_band_width,
                engine=p.tpu_engine,
                pipeline=pipeline,
                scheduler=self.scheduler)
        return ent

    def consensus(self, p, snaps: list) -> list:
        """Re-execute the snapshotted windows through the oracle path
        for polisher-parameters `p`; returns the rebuilt windows, each
        carrying the ground-truth `consensus`/`polished`."""
        key = engine_params_key(p)
        clones = [rebuild_window(s) for s in snaps]
        with self._lock, oracle_scope():
            engine = self._engine(key, p)
            engine.logger = None
            engine.generate_consensus(clones, p.trim)
        return clones

    def stats(self) -> dict:
        """The audit.* telemetry view: the oracle's own stage counters
        plus its compile totals."""
        snap = self.pipeline_stats.snapshot()
        occ = self.scheduler.stats.snapshot()
        return {"launches": snap["launches"],
                "chunks": snap["chunks"],
                "device_s": round(snap["device_s"], 4),
                "compiles": sum(e.get("compiles", 0)
                                for e in occ.values()),
                "compile_s": round(sum(e.get("compile_s", 0.0)
                                       for e in occ.values()), 3)}

    def close(self) -> None:
        with self._lock:
            engines, self._engines = self._engines, {}
        for engine in engines.values():
            if engine.pipeline is not None:
                engine.pipeline.close()
