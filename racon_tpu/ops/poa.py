"""Batched POA consensus over windows.

The consensus role spoa (CPU) and GenomeWorks cudapoa (GPU) play in the
reference. Two engines:

  - host: the native C++ POA graph engine (racon_tpu/native), threaded over
    windows — the spoa-equivalent path (reference src/polisher.cpp:491-504).
  - device (`device_batches > 0`): the evolving-graph engine
    (ops/poa_graph.py + native/src/session.cpp). The graph-NW DP — the hot
    loop — runs on the TPU as batched fixed-shape XLA programs while the
    graph bookkeeping stays in the C++ session; every layer is aligned
    against the *evolving* graph with host-identical DP and tie-breaking,
    so device consensus is byte-identical to the host engine (unlike the
    reference, which pins diverging GPU numbers separately,
    racon_test.cpp:292-496). Windows outside the kernel's shape envelope
    fall back to the host engine per window, the reference's GPU->CPU
    fallback discipline (cudapolisher.cpp:354-383).

Windows with fewer than 3 sequences keep their backbone (reference
window.cpp:68-71); TGS windows are coverage-trimmed (window.cpp:118-139).
"""

from __future__ import annotations

import os

from ..native import poa_batch
from ..utils.logger import Logger


class BatchPOA:
    def __init__(self, match: int, mismatch: int, gap: int,
                 window_length: int, num_threads: int = 1,
                 device_batches: int = 0, banded: bool = False,
                 band_width: int = 0, logger: Logger | None = None,
                 engine: str | None = None, pipeline=None):
        self.match = match
        self.mismatch = mismatch
        self.gap = gap
        self.window_length = window_length
        self.num_threads = num_threads
        self.device_batches = device_batches
        # the reference's -b / cuda-banded-alignment flag selects cudapoa's
        # static-band mode as a speed/accuracy trade (cudabatch.cpp:56-59)
        # that only affects the GPU path. Mirrored here: with -b the device
        # session trusts banded DP results (skips the clipped -> full-DP
        # retry), trading the byte-identity-with-host guarantee for fewer
        # device round trips — exactly the reference's GPU-only divergence
        # pattern (racon_test.cpp:292-496 pins GPU numbers separately).
        self.banded_only = banded
        self.logger = logger
        # the polisher's async dispatch pipeline (pipeline.DispatchPipeline
        # or None): overlaps host pack/unpack with compute in both the
        # fused device path and the host chunk loop; None keeps every
        # stage synchronous (direct callers, tests)
        self.pipeline = pipeline
        # device engine selection: explicit parameter (the CLI's
        # --tpu-engine) wins over the RACON_TPU_ENGINE env var; an empty
        # env value means unset (the `VAR= cmd` idiom), not a typo
        self.engine = (engine or os.environ.get("RACON_TPU_ENGINE")
                       or "session")
        # the CLI validates --tpu-engine; the env-var path must too, or a
        # typo like RACON_TPU_ENGINE=Fused silently measures the session
        # engine while the user believes they measured the fused one
        if self.engine not in ("session", "fused"):
            raise ValueError(
                f"[racon_tpu::BatchPOA] invalid TPU engine "
                f"{self.engine!r} (expected 'session' or 'fused'; set via "
                "--tpu-engine or RACON_TPU_ENGINE)")

    #: windows per host batch call (bounds peak packed-buffer memory)
    HOST_CHUNK = 4096

    def generate_consensus(self, windows, trim: bool) -> None:
        """Fill `window.consensus` / `window.polished` for every window."""
        todo = []
        for w in windows:
            if len(w.sequences) < 3:
                w.backbone_fallback()
            else:
                todo.append(w)
        if not todo:
            return

        host = todo
        if self.device_batches > 0:
            import sys

            try:
                self._device_consensus(todo, trim)
                host = []
            except Exception as exc:  # device init/OOM: host completes all
                if os.environ.get("RACON_TPU_STRICT"):
                    raise
                print("[racon_tpu::BatchPOA] warning: device consensus "
                      f"failed ({type(exc).__name__}: {exc}); falling back "
                      "to host engine", file=sys.stderr)
                host = [w for w in todo if not w.polished]

        if not host:
            return
        bar = self.logger.bar if self.logger is not None else None
        if self.logger is not None:
            self.logger.bar_total(len(host))

        # the host engine runs through the same staged pipeline: the
        # native POA call (GIL released inside the C++ batch entry point)
        # computes chunk k on the dispatch thread while a pack worker
        # builds chunk k+1's window lists and the unpack worker trims
        # chunk k-1
        from ..pipeline import DispatchPipeline

        pl = (self.pipeline if self.pipeline is not None
              else DispatchPipeline(depth=0))
        chunks = [host[s:s + self.HOST_CHUNK]
                  for s in range(0, len(host), self.HOST_CHUNK)]

        def pack(chunk):
            return [_pack(w) for w in chunk]

        def dispatch(chunk, packed):
            results = poa_batch(packed, self.match, self.mismatch,
                                self.gap, n_threads=self.num_threads)
            pl.stats.bump("launches")
            return results

        def wait(results):
            return results

        def unpack(chunk, results):
            for w, (cons, cov) in zip(chunk, results):
                w.apply_trim(cons, cov, trim)
            if bar is not None:
                for _ in chunk:
                    bar("[racon_tpu::Polisher.polish] generating consensus")

        pl.run(chunks, pack, dispatch, wait, unpack)

    def _device_consensus(self, todo, trim):
        """Device consensus over all of `todo`; unfit/failed windows are
        host-polished internally, so nothing is left over.

        `self.engine` selects the device engine — the explicit
        constructor/CLI choice, falling back to RACON_TPU_ENGINE:
        "session" (default, the per-layer evolving-graph engine —
        byte-identical to the host engine) or "fused" (whole-window
        single-launch engine, ops/poa_fused.py — the cudapoa-shaped
        design; equal aggregate quality, rare topo-order tie divergence
        possible on deep windows — see its module docstring)."""
        import sys

        from .poa_graph import DeviceGraphPOA

        packed = [_pack(w) for w in todo]
        if self.engine == "fused":
            from .poa_fused import FusedPOA

            fused = FusedPOA(self.match, self.mismatch, self.gap,
                             num_threads=self.num_threads,
                             logger=self.logger,
                             banded_only=self.banded_only)
            # RACON_TPU_FUSED_FALLBACK picks who polishes the windows the
            # fused engine cannot take (graph overflowed its envelope):
            # "session" (default) keeps the whole batch on device via the
            # per-layer session engine; "host" uses the C++ engine — the
            # reference's per-window GPU->CPU fallback discipline
            # (cudapolisher.cpp:354-383), no second device engine compile
            to_host = (os.environ.get("RACON_TPU_FUSED_FALLBACK",
                                      "session") == "host")
            results, statuses = fused.consensus(packed, fallback=to_host,
                                                pipeline=self.pipeline)
            rest = [i for i, r in enumerate(results) if r is None]
            fs = fused.last_stats
            print(f"[racon_tpu::BatchPOA] fused engine built "
                  f"{int((statuses == 0).sum())} windows "
                  f"({fs['chunks']} chunks, {fs['launches']} device "
                  f"launches, pack {fs['pack_s']:.2f}s, device "
                  f"{fs['device_s']:.2f}s, finalize {fs['unpack_s']:.2f}s); "
                  f"{fused.n_fallback} to "
                  f"{'host' if to_host else 'session'} engine",
                  file=sys.stderr)
            if rest:
                engine = DeviceGraphPOA(self.match, self.mismatch,
                                        self.gap,
                                        num_threads=self.num_threads,
                                        logger=self.logger,
                                        banded_only=self.banded_only)
                sub_res, sub_st = engine.consensus(
                    [packed[i] for i in rest])
                for i, r, st in zip(rest, sub_res, sub_st):
                    results[i] = r
                    statuses[i] = st
            else:
                engine = fused
        else:
            engine = DeviceGraphPOA(self.match, self.mismatch, self.gap,
                                    num_threads=self.num_threads,
                                    logger=self.logger,
                                    banded_only=self.banded_only)
            results, statuses = engine.consensus(packed)
        for w, (cons, cov) in zip(todo, results):
            w.apply_trim(cons, cov, trim)
        stats = getattr(engine, "last_stats", None) or {}
        if "committed" in stats:
            print(f"[racon_tpu::BatchPOA] device layer alignments: "
                  f"{stats['committed']} committed, {stats['redos']} "
                  "banded-clip full-DP retries", file=sys.stderr)
        n_fallback = int((statuses == 1).sum())
        if n_fallback:
            # the reference logs GPU-skipped work the same way
            # (cudapolisher.cpp:204-206)
            print(f"[racon_tpu::BatchPOA] {n_fallback} windows polished on "
                  "host (outside device kernel envelope)", file=sys.stderr)


def _pack(w):
    return [(w.sequences[i], w.qualities[i], w.positions[i][0],
             w.positions[i][1]) for i in range(len(w.sequences))]
