"""Batched POA consensus over windows.

The consensus role spoa (CPU) and GenomeWorks cudapoa (GPU) play in the
reference. Two engines:

  - host: the native C++ POA graph engine (racon_tpu/native), threaded over
    windows — the spoa-equivalent path (reference src/polisher.cpp:491-504).
  - device (`device_batches > 0`): the alignment hot loop moves to the TPU —
    every layer is globally aligned against its window backbone as one
    batched fixed-shape XLA program (ops/align kernel), and the resulting
    paths are fed to the native graph builder as prealigned inputs (backbone
    node ids are 0..L-1 by construction). This mirrors cudapoa's batched
    window processing (src/cuda/cudabatch.cpp:77-270) while keeping the
    irregular graph bookkeeping on the host where it is cheap.

Windows with fewer than 3 sequences keep their backbone (reference
window.cpp:68-71); TGS windows are coverage-trimmed (window.cpp:118-139).
"""

from __future__ import annotations

from ..native import poa_batch
from ..utils.logger import Logger


class BatchPOA:
    def __init__(self, match: int, mismatch: int, gap: int,
                 window_length: int, num_threads: int = 1,
                 device_batches: int = 0, banded: bool = False,
                 band_width: int = 0, logger: Logger | None = None):
        self.match = match
        self.mismatch = mismatch
        self.gap = gap
        self.window_length = window_length
        self.num_threads = num_threads
        self.device_batches = device_batches
        # the reference's -b / cuda-banded-alignment: static-band device
        # DP (band 256 unless overridden), trading accuracy for speed
        self.band = (band_width or 256) if banded else 0
        self.logger = logger

    #: windows per host batch call (bounds peak packed-buffer memory)
    HOST_CHUNK = 4096
    #: anchored-alignment passes on the device path (pass N re-anchors the
    #: layers on pass N-1's consensus; see _device_consensus). Measured on
    #: the sample data (PAF+qual w=500, truth distance; host engine 1352):
    #: 1 pass 2370, 2 passes 1759, 3 passes 1642, 4 passes 1626 — the same
    #: kind of backend divergence the reference pins separately for its GPU
    #: engine (racon_test.cpp:312: GPU 1385 vs CPU 1312; 4168 vs 1289 at
    #: w=1000).
    device_passes = 3

    def generate_consensus(self, windows, trim: bool) -> None:
        """Fill `window.consensus` / `window.polished` for every window."""
        todo = []
        for w in windows:
            if len(w.sequences) < 3:
                w.backbone_fallback()
            else:
                todo.append(w)
        if not todo:
            return

        if self.device_batches > 0:
            import sys

            try:
                host = self._device_consensus(todo, trim)
            except Exception as exc:  # device init/OOM: host completes all
                print("[racon_tpu::BatchPOA] warning: device consensus "
                      f"failed ({type(exc).__name__}: {exc}); falling back "
                      "to host engine", file=sys.stderr)
                host = [w for w in todo if not w.polished]
        else:
            host = todo

        bar = self.logger.bar if self.logger is not None else None
        if self.logger is not None:
            self.logger.bar_total(len(todo))
            for _ in range(len(todo) - len(host)):
                bar("[racon_tpu::Polisher.polish] generating consensus")

        for s in range(0, len(host), self.HOST_CHUNK):
            chunk = host[s:s + self.HOST_CHUNK]
            packed = [_pack(w) for w in chunk]
            results = poa_batch(packed, self.match, self.mismatch, self.gap,
                                n_threads=self.num_threads)
            for w, (cons, cov) in zip(chunk, results):
                w.apply_trim(cons, cov, trim)
            if bar is not None:
                for _ in chunk:
                    bar("[racon_tpu::Polisher.polish] generating consensus")

    def _device_consensus(self, todo, trim):
        """Multi-pass device consensus (`device_passes` rounds); returns
        the windows that must fall back to the host engine.

        Pass 1 aligns every layer against the raw window backbone on device
        and builds an anchored POA consensus. Because anchored alignments
        cannot see other layers' insertions during alignment (only at graph
        ingest), pass-1 consensus underperforms evolving-graph alignment —
        so pass 2 re-aligns all layers against the pass-1 consensus (which
        already contains the recovered indels) and rebuilds. This converges
        to within a few percent of the host engine while keeping all
        O(len^2) DP work on device (cudapoa runs the whole graph algorithm
        on device instead — see ops/poa_device.py for why that design does
        not fit XLA).
        """
        from .poa_device import device_prealign

        pre1 = device_prealign(todo, self.match, self.mismatch, self.gap,
                               self.device_batches, self.band,
                               logger=self.logger)
        dev = [(i, w) for i, w in enumerate(todo) if pre1[i] is not None]
        fallback = [w for i, w in enumerate(todo) if pre1[i] is None]
        if not dev:
            return fallback

        best = poa_batch([_pack(w) for _, w in dev],
                         self.match, self.mismatch, self.gap,
                         n_threads=self.num_threads,
                         prealigned=[pre1[i] for i, _ in dev])

        # later passes: same layers re-anchored on the previous consensus
        for _ in range(self.device_passes - 1):
            rewins = [_Rewindow(cons, w)
                      for (_, w), (cons, _cov) in zip(dev, best)]
            pre = device_prealign(rewins, self.match, self.mismatch,
                                  self.gap, self.device_batches,
                                  self.band, logger=self.logger)
            idx = [k for k in range(len(rewins)) if pre[k] is not None]
            if not idx:
                break
            redo = poa_batch([_pack(rewins[k]) for k in idx],
                             self.match, self.mismatch, self.gap,
                             n_threads=self.num_threads,
                             prealigned=[pre[k] for k in idx])
            for k, res in zip(idx, redo):
                best[k] = res

        for (_, w), (cons, cov) in zip(dev, best):
            w.apply_trim(cons, cov, trim)
        return fallback


def _pack(w):
    return [(w.sequences[i], w.qualities[i], w.positions[i][0],
             w.positions[i][1]) for i in range(len(w.sequences))]


class _Rewindow:
    """Pass-2 device-alignment view of a window: the pass-1 consensus as
    backbone, original layers with positions rescaled (and slightly
    widened) into consensus coordinates."""

    __slots__ = ("sequences", "qualities", "positions")

    def __init__(self, consensus: bytes, w):
        backbone_len = len(w.sequences[0])
        scale = len(consensus) / backbone_len if backbone_len else 1.0
        end = len(consensus) - 1
        self.sequences = [consensus] + w.sequences[1:]
        # the new backbone keeps dummy weight-0 quality, like the window
        # backbone itself (reference polisher.cpp:393 dummy quality)
        self.qualities = [b"!" * len(consensus)] + list(w.qualities[1:])
        self.positions = [(0, end)]
        # linear rescale can misplace a span by up to the total indel count
        # when indels are unevenly distributed — widen by that bound so the
        # true region is always inside the aligned slice
        slack = 16 + abs(len(consensus) - backbone_len)
        for b, e in w.positions[1:]:
            nb = max(0, int(b * scale) - slack)
            ne = min(end, int(e * scale) + slack + 1)
            self.positions.append((nb, max(ne, nb + 1)))
