"""Batched POA consensus over windows.

The consensus role spoa (CPU) and GenomeWorks cudapoa (GPU) play in the
reference. Two engines:

  - host: the native C++ POA graph engine (racon_tpu/native), threaded over
    windows — the spoa-equivalent path (reference src/polisher.cpp:491-504).
  - device (`device_batches > 0`): the alignment hot loop moves to the TPU —
    every layer is globally aligned against its window backbone as one
    batched fixed-shape XLA program (ops/align kernel), and the resulting
    paths are fed to the native graph builder as prealigned inputs (backbone
    node ids are 0..L-1 by construction). This mirrors cudapoa's batched
    window processing (src/cuda/cudabatch.cpp:77-270) while keeping the
    irregular graph bookkeeping on the host where it is cheap.

Windows with fewer than 3 sequences keep their backbone (reference
window.cpp:68-71); TGS windows are coverage-trimmed (window.cpp:118-139).
"""

from __future__ import annotations

from ..native import poa_batch
from ..utils.logger import Logger


class BatchPOA:
    def __init__(self, match: int, mismatch: int, gap: int,
                 window_length: int, num_threads: int = 1,
                 device_batches: int = 0, band_width: int = 0,
                 logger: Logger | None = None):
        self.match = match
        self.mismatch = mismatch
        self.gap = gap
        self.window_length = window_length
        self.num_threads = num_threads
        self.device_batches = device_batches
        self.band_width = band_width
        self.logger = logger

    #: windows per host batch call (bounds peak packed-buffer memory)
    HOST_CHUNK = 4096

    def generate_consensus(self, windows, trim: bool) -> None:
        """Fill `window.consensus` / `window.polished` for every window."""
        todo = []
        for w in windows:
            if len(w.sequences) < 3:
                w.backbone_fallback()
            else:
                todo.append(w)
        if not todo:
            return

        if self.device_batches > 0:
            from .poa_device import device_prealign
            prealign = device_prealign(
                todo, self.match, self.mismatch, self.gap,
                self.device_batches, self.band_width, logger=self.logger)
            dev = [(w, prealign[i]) for i, w in enumerate(todo)
                   if prealign[i] is not None]
            host = [w for i, w in enumerate(todo) if prealign[i] is None]
        else:
            dev = []
            host = todo

        bar = self.logger.bar if self.logger is not None else None
        if self.logger is not None:
            self.logger.bar_total(len(todo))

        def consume(chunk, pre):
            packed = [
                [(w.sequences[i], w.qualities[i], w.positions[i][0],
                  w.positions[i][1])
                 for i in range(len(w.sequences))]
                for w in chunk
            ]
            results = poa_batch(packed, self.match, self.mismatch, self.gap,
                                n_threads=self.num_threads, prealigned=pre)
            for w, (cons, cov) in zip(chunk, results):
                w.apply_trim(cons, cov, trim)
            if bar is not None:
                for _ in chunk:
                    bar("[racon_tpu::Polisher.polish] generating consensus")

        for s in range(0, len(dev), self.HOST_CHUNK):
            part = dev[s:s + self.HOST_CHUNK]
            consume([w for w, _ in part], [p for _, p in part])
        for s in range(0, len(host), self.HOST_CHUNK):
            consume(host[s:s + self.HOST_CHUNK], None)
