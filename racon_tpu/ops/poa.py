"""Batched POA consensus over windows.

The consensus role spoa (CPU) and GenomeWorks cudapoa (GPU) play in the
reference. Two engines:

  - host: the native C++ POA graph engine (racon_tpu/native), threaded over
    windows — the spoa-equivalent path (reference src/polisher.cpp:491-504).
  - device (`device_batches > 0`): the evolving-graph engine
    (ops/poa_graph.py + native/src/session.cpp). The graph-NW DP — the hot
    loop — runs on the TPU as batched fixed-shape XLA programs while the
    graph bookkeeping stays in the C++ session; every layer is aligned
    against the *evolving* graph with host-identical DP and tie-breaking,
    so device consensus is byte-identical to the host engine (unlike the
    reference, which pins diverging GPU numbers separately,
    racon_test.cpp:292-496). Windows outside the kernel's shape envelope
    fall back to the host engine per window, the reference's GPU->CPU
    fallback discipline (cudapolisher.cpp:354-383).

Windows with fewer than 3 sequences keep their backbone (reference
window.cpp:68-71); TGS windows are coverage-trimmed (window.cpp:118-139).

Failure ladder (racon_tpu/resilience/): device consensus falls back to
the host engine (whole-batch or, in the fused path, per chunk); a HOST
chunk that fails is retried window by window; a window that still fails
alone is QUARANTINED — it keeps its draft backbone as consensus, counts
as unpolished (so the XC ratio reflects it, mirroring the reference's
`ratio > 0` handling, polisher.cpp:515) and bumps the `quarantined`
degradation counter. Only strict mode turns any of these back into a
raise. The run never aborts on a single poisoned window.
"""

from __future__ import annotations

import os

from ..native import poa_batch
from ..resilience import strict_mode
from ..utils.logger import Logger, log_info, warn_dedup


class BatchPOA:
    def __init__(self, match: int, mismatch: int, gap: int,
                 window_length: int, num_threads: int = 1,
                 device_batches: int = 0, banded: bool = False,
                 band_width: int = 0, logger: Logger | None = None,
                 engine: str | None = None, pipeline=None,
                 scheduler=None, runner=None):
        self.match = match
        # the occupancy-aware batch scheduler (sched/), threaded into
        # whichever device engine runs; None lets each engine default
        # from the environment posture
        self.scheduler = scheduler
        # an explicit parallel.mesh.BatchRunner pins the device engines
        # to a sub-mesh — the serve layer's worker lanes each dispatch
        # through their own device partition; None = the full mesh
        self.runner = runner
        self.mismatch = mismatch
        self.gap = gap
        self.window_length = window_length
        self.num_threads = num_threads
        self.device_batches = device_batches
        # the reference's -b / cuda-banded-alignment flag selects cudapoa's
        # static-band mode as a speed/accuracy trade (cudabatch.cpp:56-59)
        # that only affects the GPU path. Mirrored here: with -b the device
        # session trusts banded DP results (skips the clipped -> full-DP
        # retry), trading the byte-identity-with-host guarantee for fewer
        # device round trips — exactly the reference's GPU-only divergence
        # pattern (racon_test.cpp:292-496 pins GPU numbers separately).
        self.banded_only = banded
        self.logger = logger
        # the polisher's async dispatch pipeline (pipeline.DispatchPipeline
        # or None): overlaps host pack/unpack with compute in both the
        # fused device path and the host chunk loop; None keeps every
        # stage synchronous (direct callers, tests)
        self.pipeline = pipeline
        # device engine selection: explicit parameter (the CLI's
        # --tpu-engine) wins over the RACON_TPU_ENGINE env var; an empty
        # env value means unset (the `VAR= cmd` idiom), not a typo
        self.engine = (engine or os.environ.get("RACON_TPU_ENGINE")
                       or "session")
        # the CLI validates --tpu-engine; the env-var path must too, or a
        # typo like RACON_TPU_ENGINE=Fused silently measures the session
        # engine while the user believes they measured the fused one
        if self.engine not in ("session", "fused"):
            raise ValueError(
                f"[racon_tpu::BatchPOA] invalid TPU engine "
                f"{self.engine!r} (expected 'session' or 'fused'; set via "
                "--tpu-engine or RACON_TPU_ENGINE)")
        # device engines cached across generate_consensus calls: the
        # serve feeder's persistent dispatch loop reuses ONE BatchPOA
        # per lane+engine-key, so per-iteration engine construction
        # (kernel plans, batch-width pinning, runner lookups) drops out
        # of the iteration hot path. Everything in an engine's identity
        # is fixed at BatchPOA construction; only the logger is rebound
        # per call.
        self._device_engine = None
        self._session_net = None

    #: windows per host batch call (bounds peak packed-buffer memory);
    #: RACON_TPU_HOST_POA_CHUNK overrides it — chunk granularity never
    #: changes output (windows are independent), only pipeline batching,
    #: so the fleet benches shrink it to pace per-chunk device latency
    #: proportionally to a job's window count
    HOST_CHUNK = 4096

    def _host_chunk(self) -> int:
        raw = os.environ.get("RACON_TPU_HOST_POA_CHUNK", "")
        if not raw:
            return self.HOST_CHUNK
        try:
            n = int(raw)
        except ValueError:
            n = 0
        if n <= 0:
            from ..errors import RaconError
            raise RaconError(
                "BatchPOA",
                f"invalid RACON_TPU_HOST_POA_CHUNK {raw!r} (expected a "
                "positive integer)!")
        return n

    def generate_consensus(self, windows, trim: bool) -> None:
        """Fill `window.consensus` / `window.polished` for every window.

        After the pass, any armed `sdc` fault (resilience/faults.py) is
        consumed against the finished consensus — the silent-corruption
        injection the audit sentinel (obs/audit.py) exists to catch. A
        plan-less run (the universal default) pays one None check."""
        from ..resilience import get_fault_plan

        self._generate_consensus(windows, trim)
        plan = (self.pipeline.faults if self.pipeline is not None
                else get_fault_plan())
        if plan is not None:
            plan.corrupt_consensus(
                windows, stats=(self.pipeline.stats
                                if self.pipeline is not None else None))

    def _generate_consensus(self, windows, trim: bool) -> None:
        todo = []
        for w in windows:
            if len(w.sequences) < 3:
                w.backbone_fallback()
            else:
                todo.append(w)
        if not todo:
            return

        host = todo
        if self.device_batches > 0:
            from ..errors import DeviceError, RaconError

            def degrade(msg):
                # the device pass died mid-flight: before the host pass
                # reruns the unpolished windows, empty the shared
                # fallback pool — a queued/running prefall job would
                # keep polishing those same windows underneath it
                if self.pipeline is not None:
                    self.pipeline.cancel_fallback()
                log_info(f"[racon_tpu::BatchPOA] warning: device consensus "
                         f"failed ({msg}); falling back to host engine")
                return [w for w in todo if not w.polished]

            try:
                host = self._device_consensus(todo, trim)
            except RaconError as exc:
                # device failures degrade; genuine user-facing errors
                # (bad input discovered late) propagate regardless
                if not isinstance(exc, DeviceError) or strict_mode():
                    raise
                host = degrade(str(exc))
            except Exception as exc:  # device init/OOM: host completes all
                if strict_mode():
                    raise
                host = degrade(f"{type(exc).__name__}: {exc}")

        if not host:
            return
        bar = self.logger.bar if self.logger is not None else None
        if self.logger is not None:
            self.logger.bar_total(len(host))

        # the host engine runs through the same staged pipeline: the
        # native POA call (GIL released inside the C++ batch entry point)
        # computes chunk k on the dispatch thread while a pack worker
        # builds chunk k+1's window lists and the unpack worker trims
        # chunk k-1
        from ..pipeline import DispatchPipeline

        pl = (self.pipeline if self.pipeline is not None
              else DispatchPipeline(depth=0))
        host_chunk = self._host_chunk()
        chunks = [host[s:s + host_chunk]
                  for s in range(0, len(host), host_chunk)]

        def pack(chunk):
            return [_pack(w) for w in chunk]

        def dispatch(chunk, packed):
            results = poa_batch(packed, self.match, self.mismatch,
                                self.gap, n_threads=self.num_threads)
            pl.stats.bump("launches")
            return results

        def wait(results):
            return results

        def unpack(chunk, results):
            for w, (cons, cov) in zip(chunk, results):
                w.apply_trim(cons, cov, trim)
            if bar is not None:
                for _ in chunk:
                    bar("[racon_tpu::Polisher.polish] generating consensus")

        def chunk_error(chunk, exc):
            # host-chunk failure: retry each window on its own; a window
            # that fails alone is poisoned — quarantine it (draft
            # backbone as consensus, counted) and keep the run alive
            warn_dedup(
                "BatchPOA.host_chunk_failed",
                f"[racon_tpu::BatchPOA] warning: host consensus chunk "
                f"failed ({type(exc).__name__}: {exc}); retrying "
                f"{len(chunk)} windows individually")
            for w in chunk:
                try:
                    (cons, cov), = poa_batch([_pack(w)], self.match,
                                             self.mismatch, self.gap,
                                             n_threads=1)
                    w.apply_trim(cons, cov, trim)
                except Exception as wexc:
                    w.backbone_fallback()
                    pl.stats.bump("quarantined")
                    warn_dedup(
                        "BatchPOA.window_quarantined",
                        "[racon_tpu::BatchPOA] warning: window "
                        f"quarantined (kept draft backbone; "
                        f"{type(wexc).__name__}: {wexc})")
                if bar is not None:
                    bar("[racon_tpu::Polisher.polish] generating consensus")

        pl.run(chunks, pack, dispatch, wait, unpack,
               on_error=None if strict_mode() else chunk_error,
               label="host_poa",
               describe=lambda c: {"engine": "host", "jobs": len(c)})

    def _device_consensus(self, todo, trim) -> list:
        """Device consensus over `todo`; unfit/failed windows are
        host-polished internally when possible. Returns the windows left
        unbuilt (normally none) — the caller routes them through the
        host chunk loop, whose per-window quarantine is the last rung of
        the failure ladder.

        `self.engine` selects the device engine — the explicit
        constructor/CLI choice, falling back to RACON_TPU_ENGINE:
        "session" (default, the per-layer evolving-graph engine —
        byte-identical to the host engine) or "fused" (whole-window
        single-launch engine, ops/poa_fused.py — the cudapoa-shaped
        design; equal aggregate quality, rare topo-order tie divergence
        possible on deep windows — see its module docstring)."""
        from .poa_graph import DeviceGraphPOA

        packed = [_pack(w) for w in todo]
        if self.engine == "fused":
            from .poa_fused import FusedPOA

            if self._device_engine is None:
                self._device_engine = FusedPOA(
                    self.match, self.mismatch, self.gap,
                    num_threads=self.num_threads,
                    banded_only=self.banded_only,
                    scheduler=self.scheduler,
                    runner=self.runner)
            fused = self._device_engine
            fused.logger = self.logger
            # RACON_TPU_FUSED_FALLBACK picks who polishes the windows the
            # fused engine cannot take (graph overflowed its envelope):
            # "session" (default) keeps the whole batch on device via the
            # per-layer session engine; "host" uses the C++ engine — the
            # reference's per-window GPU->CPU fallback discipline
            # (cudapolisher.cpp:354-383), no second device engine compile
            to_host = (os.environ.get("RACON_TPU_FUSED_FALLBACK",
                                      "session") == "host")
            results, statuses = fused.consensus(packed, fallback=to_host,
                                                pipeline=self.pipeline)
            rest = [i for i, r in enumerate(results) if r is None]
            fs = fused.last_stats
            log_info(f"[racon_tpu::BatchPOA] fused engine built "
                     f"{int((statuses == 0).sum())} windows "
                     f"({fs['chunks']} chunks, {fs['launches']} device "
                     f"launches, pack {fs['pack_s']:.2f}s, device "
                     f"{fs['device_s']:.2f}s, finalize "
                     f"{fs['unpack_s']:.2f}s); {fused.n_fallback} to "
                     f"{'host' if to_host else 'session'} engine")
            if rest:
                # leftover windows are a handful of envelope-tail cases:
                # adapting a grid to THEM would compile throwaway
                # programs mid-run (the stall precompile exists to
                # prevent), so this engine pins the static grid —
                # telemetry still flows into the shared counters
                from ..sched import BatchScheduler

                if self._session_net is None:
                    static_sched = BatchScheduler(
                        adaptive=False,
                        stats=(self.scheduler.stats
                               if self.scheduler is not None else None))
                    self._session_net = DeviceGraphPOA(
                        self.match, self.mismatch, self.gap,
                        num_threads=self.num_threads,
                        banded_only=self.banded_only,
                        scheduler=static_sched,
                        runner=self.runner)
                engine = self._session_net
                engine.logger = self.logger
                sub_res, sub_st = engine.consensus(
                    [packed[i] for i in rest])
                for i, r, st in zip(rest, sub_res, sub_st):
                    results[i] = r
                    statuses[i] = st
            else:
                engine = fused
        else:
            if self._device_engine is None:
                self._device_engine = DeviceGraphPOA(
                    self.match, self.mismatch, self.gap,
                    num_threads=self.num_threads,
                    banded_only=self.banded_only,
                    scheduler=self.scheduler,
                    runner=self.runner)
            engine = self._device_engine
            engine.logger = self.logger
            results, statuses = engine.consensus(packed)
        leftover = []
        for w, r in zip(todo, results):
            if r is None:  # neither engine built it: host loop's turn
                leftover.append(w)
            else:
                w.apply_trim(r[0], r[1], trim)
        stats = getattr(engine, "last_stats", None) or {}
        if "committed" in stats:
            log_info(f"[racon_tpu::BatchPOA] device layer alignments: "
                     f"{stats['committed']} committed, {stats['redos']} "
                     "banded-clip full-DP retries")
        n_fallback = int((statuses == 1).sum())
        if n_fallback:
            # the reference logs GPU-skipped work the same way
            # (cudapolisher.cpp:204-206)
            log_info(f"[racon_tpu::BatchPOA] {n_fallback} windows polished "
                     "on host (outside device kernel envelope)")
        return leftover


def _pack(w):
    return [(w.sequences[i], w.qualities[i], w.positions[i][0],
             w.positions[i][1]) for i in range(len(w.sequences))]
