"""Batched device prealignment for POA consensus — the cudapoa role.

GenomeWorks cudapoa (reference src/cuda/cudabatch.cpp) runs the whole POA —
graph-banded DP plus consensus — inside one CUDA block per window. That
design is pointer-heavy and irregular: a poor fit for the TPU's dense
vector/matrix units and XLA's static-shape compilation model. The TPU-first
split used here keeps the *regular* 95% of the work on device and the
irregular 5% on the host:

  - device: every layer is globally aligned (NW, linear gap) against its
    window's backbone slice as one fixed-shape batched XLA program —
    dense int8 code tensors, a `lax.scan` over DP rows, and a second
    `lax.scan` for the traceback, all vectorized over the batch. This is
    where the O(len^2 * depth) FLOPs live.
  - host: the POA graph builder (native/src/poa.cpp) ingests the resulting
    paths as *anchored* alignments. Because every path is expressed in
    backbone coordinates, identical insertions from different layers are
    merged by (backbone column, run offset, base code) — preserving the
    evolving-graph property that repeated insertions accumulate consensus
    weight (see Graph::add_alignment(anchored=true)).

Batches are padded to a small set of static (Q, T) shape buckets so XLA
compiles a handful of programs, and the batch axis is sharded across every
available device through parallel/mesh.py — the TPU analogue of cudapoa's
multi-GPU batch loop (src/cuda/cudapolisher.cpp:228-345). Layers that
exceed the largest bucket (beyond the cudapoa contract of ~1023 bp,
cudabatch.cpp:56-59) are returned as None and the caller host-aligns those
windows — the same device->host fallback the reference uses for oversized
windows (cudapolisher.cpp:354-383).
"""

from __future__ import annotations

import functools

import numpy as np

from .encode import encode_padded
from ..utils.logger import Logger

# (Q, T) shape buckets: Q = padded layer length, T = padded backbone span.
# w=500 windows fill the first two buckets; w=1000 the last.
_BUCKETS = ((320, 512), (640, 512), (1280, 1024))
#: elements budget per batch (bp tensor is B*Q*(T+1) int8)
_BATCH_BUDGET = 48 * 1024 * 1024


def _batch_size(q: int, t: int) -> int:
    b = _BATCH_BUDGET // (q * (t + 1))
    return max(8, 1 << (int(b).bit_length() - 1))


@functools.lru_cache(maxsize=None)
def _aligner(q_len: int, t_len: int, match: int, mismatch: int, gap: int,
             band: int = 0):
    """Build the jitted batched NW align+traceback program for one shape.

    band == 0: full Q x T DP (cudapoa full_band mode). band > 0: each layer
    row computes only `band` target columns centered on the lane's own
    ideal diagonal (cudapoa static_band mode, cudabatch.cpp:56-59 band 256
    — the `-b/--tpu-banded-alignment` flag) — ~T/band less compute and
    backpointer memory; out-of-band cells score -inf, so a clipped path
    shows up as poor consensus the same way cudapoa's banded mode does.
    """
    import jax
    import jax.numpy as jnp

    K = q_len + t_len  # max path length
    NEG = jnp.int32(-(1 << 28))

    def full_align(q, ql, t, tl):
        # q: [B, Q] int8 codes, ql: [B] int32; t: [B, T], tl: [B]
        B = q.shape[0]
        idx = jnp.arange(t_len + 1, dtype=jnp.int32)

        h0 = idx * gap  # row 0: D[0][j] = j*gap
        h0 = jnp.broadcast_to(h0, (B, t_len + 1)).astype(jnp.int32)

        def row_step(h_prev, qi_i):
            qi, i = qi_i  # qi: [B] this row's base codes; i: row number
            sub = jnp.where(t == qi[:, None], match, mismatch)  # [B, T]
            diag = h_prev[:, :-1] + sub
            up = h_prev[:, 1:] + gap
            tmp = jnp.maximum(diag, up)
            lead = jnp.full((B, 1), i * gap, dtype=jnp.int32)
            full = jnp.concatenate([lead, tmp], axis=1)  # [B, T+1]
            # resolve the left-gap dependency with a running max:
            # H[j] = max_k<=j full[k] + (j-k)*gap
            h_row = jax.lax.cummax(full - idx * gap, axis=1) + idx * gap
            # backpointers; tie priority matches the host graph traceback
            # (poa.cpp align_nw): diagonal > backbone-consume > layer-consume
            diag_ok = h_row[:, 1:] == diag
            left_ok = h_row[:, 1:] == h_row[:, :-1] + gap
            bp_tail = jnp.where(diag_ok, 0, jnp.where(left_ok, 2, 1))
            bp = jnp.concatenate(
                [jnp.ones((B, 1), dtype=jnp.int8), bp_tail.astype(jnp.int8)],
                axis=1)
            return h_row, bp

        rows_i = jnp.arange(1, q_len + 1, dtype=jnp.int32)
        _, bp = jax.lax.scan(row_step, h0, (q.T, rows_i))
        # bp: [Q, B, T+1] -> flat per-batch for gathered traceback reads
        bp_flat = bp.transpose(1, 0, 2).reshape(B, q_len * (t_len + 1))

        def tb_step(state, _):
            i, j = state
            on_q = i > 0
            on_t = j > 0
            lin = jnp.clip(i - 1, 0, q_len - 1) * (t_len + 1) + j
            code = jnp.take_along_axis(bp_flat, lin[:, None], axis=1)[:, 0]
            code = jnp.where(on_q & on_t, code, jnp.where(on_q, 1, 2))
            done = ~on_q & ~on_t
            take_q = ~done & (code != 2)   # diag or up consume a layer base
            take_t = ~done & (code != 1)   # diag or left consume a backbone col
            node = jnp.where(take_t, j - 1, -1)
            pos = jnp.where(take_q, i - 1, -1)
            node = jnp.where(done, -2, node)
            pos = jnp.where(done, -2, pos)
            return ((i - take_q.astype(jnp.int32),
                     j - take_t.astype(jnp.int32)),
                    (node.astype(jnp.int32), pos.astype(jnp.int32)))

        _, (nodes, poss) = jax.lax.scan(
            tb_step, (ql.astype(jnp.int32), tl.astype(jnp.int32)), None,
            length=K)
        # emitted back-to-front: [K, B] -> [B, K]
        return nodes.T, poss.T

    def band_start(i, ql, tl):
        # leftmost target column of row i's band (integer, replicated by
        # the traceback so DP and walk can never disagree)
        center = (i * tl) // jnp.maximum(ql, 1)
        return jnp.clip(center - band // 2, 0,
                        jnp.maximum(0, tl + 1 - band))

    def banded_align(q, ql, t, tl):
        B = q.shape[0]
        ks = jnp.arange(band, dtype=jnp.int32)
        ql32 = ql.astype(jnp.int32)
        tl32 = tl.astype(jnp.int32)

        # row 0: band starts at column 0 (band_start(0) == 0), D[0][j]=j*gap
        h0 = jnp.broadcast_to(ks * gap, (B, band)).astype(jnp.int32)

        def row_step(carry, qi_i):
            h_prev, s_prev = carry   # [B, band], [B]
            qi, i = qi_i
            s = band_start(jnp.full((B,), i, jnp.int32), ql32, tl32)  # [B]
            j = s[:, None] + ks[None, :]        # [B, band] target col of cell
            # gather this row's target codes
            tj = jnp.take_along_axis(
                t, jnp.clip(j - 1, 0, t_len - 1).astype(jnp.int32), axis=1)
            sub = jnp.where(tj == qi[:, None], match, mismatch)
            # neighbors live in h_prev at shifted positions
            shift = (s - s_prev)[:, None]
            k_up = ks[None, :] + shift          # (i-1, j)
            k_diag = k_up - 1                   # (i-1, j-1)

            def gather(h, kk):
                ok = (kk >= 0) & (kk < band)
                return jnp.where(
                    ok, jnp.take_along_axis(h, jnp.clip(kk, 0, band - 1),
                                            axis=1), NEG)

            valid_j = j <= tl32[:, None]
            diag = jnp.where(j >= 1, gather(h_prev, k_diag), NEG) + sub
            up = gather(h_prev, k_up) + gap
            # j == 0 boundary: D[i][0] = i*gap
            tmp = jnp.maximum(diag, up)
            tmp = jnp.where(j == 0, i * gap, tmp)
            tmp = jnp.where(valid_j, tmp, NEG)
            # left-gap within the band via running max
            h_row = jax.lax.cummax(tmp - ks * gap, axis=1) + ks * gap
            diag_ok = (h_row == diag) & (j >= 1)
            left_shift = jnp.concatenate(
                [jnp.full((B, 1), NEG), h_row[:, :-1] + gap], axis=1)
            left_ok = h_row == left_shift
            bp = jnp.where(diag_ok, 0, jnp.where(left_ok, 2, 1)).astype(
                jnp.int8)
            return (h_row, s), bp

        rows_i = jnp.arange(1, q_len + 1, dtype=jnp.int32)
        s0 = jnp.zeros((B,), dtype=jnp.int32)
        _, bp = jax.lax.scan(row_step, (h0, s0), (q.T, rows_i))
        bp_flat = bp.transpose(1, 0, 2).reshape(B, q_len * band)

        def tb_step(state, _):
            i, j = state
            on_q = i > 0
            on_t = j > 0
            s = band_start(jnp.maximum(i, 1), ql32, tl32)
            k = jnp.clip(j - s, 0, band - 1)
            lin = jnp.clip(i - 1, 0, q_len - 1) * band + k
            code = jnp.take_along_axis(bp_flat, lin[:, None], axis=1)[:, 0]
            code = jnp.where(on_q & on_t, code, jnp.where(on_q, 1, 2))
            done = ~on_q & ~on_t
            take_q = ~done & (code != 2)
            take_t = ~done & (code != 1)
            node = jnp.where(take_t, j - 1, -1)
            pos = jnp.where(take_q, i - 1, -1)
            node = jnp.where(done, -2, node)
            pos = jnp.where(done, -2, pos)
            return ((i - take_q.astype(jnp.int32),
                     j - take_t.astype(jnp.int32)),
                    (node.astype(jnp.int32), pos.astype(jnp.int32)))

        _, (nodes, poss) = jax.lax.scan(
            tb_step, (ql32, tl32), None, length=K)
        return nodes.T, poss.T

    return jax.jit(banded_align if band > 0 else full_align)


def device_prealign(windows, match: int, mismatch: int, gap: int,
                    device_batches: int = 1, band: int = 0,
                    logger: Logger | None = None):
    """Align every layer of every window against its backbone slice on
    device. band > 0 selects the static-band kernel (see _aligner).

    Returns a list parallel to `windows`; each entry is either a list
    (parallel to window.sequences, [0] = None) of (nodes, poss) int32 array
    pairs, or None when any layer of that window exceeded the largest shape
    bucket (caller falls back to host alignment for the whole window, like
    the reference's GPU->CPU window fallback, cudapolisher.cpp:354-383).
    """
    from ..parallel.mesh import BatchRunner

    band = max(0, (band + 7) // 8 * 8)

    max_q, max_t = _BUCKETS[-1]
    jobs: dict[tuple[int, int], list] = {}
    results: list = []
    for w_idx, w in enumerate(windows):
        spans = [(w.sequences[i],) + w.positions[i]
                 for i in range(1, len(w.sequences))]
        if any(len(s) > max_q or e - b + 1 > max_t for s, b, e in spans):
            results.append(None)  # whole window falls back to host
            continue
        results.append([None] * len(w.sequences))
        for l_idx, (seq, b, e) in enumerate(spans, start=1):
            t_span = e - b + 1
            bucket = next(qt for qt in _BUCKETS
                          if len(seq) <= qt[0] and t_span <= qt[1])
            jobs.setdefault(bucket, []).append((w_idx, l_idx, seq, b, e))

    runner = BatchRunner()
    total = sum(len(v) for v in jobs.values())
    if logger is not None and total:
        logger.bar_total(total)

    for (q_len, t_len), items in sorted(jobs.items()):
        eff_band = band if 0 < band < t_len else 0
        fn = _aligner(q_len, t_len, match, mismatch, gap, eff_band)
        batch = _batch_size(q_len, eff_band if eff_band else t_len)
        batch = runner.round_batch(batch)
        for s in range(0, len(items), batch):
            part = items[s:s + batch]
            q_codes, q_lens = encode_padded([it[2] for it in part], q_len)
            t_codes, t_lens = encode_padded(
                [windows[it[0]].sequences[0][it[3]:it[4] + 1] for it in part],
                t_len)
            pad = batch - len(part)
            if pad:
                q_codes = np.pad(q_codes, ((0, pad), (0, 0)),
                                 constant_values=5)
                t_codes = np.pad(t_codes, ((0, pad), (0, 0)),
                                 constant_values=5)
                q_lens = np.pad(q_lens, (0, pad), constant_values=1)
                t_lens = np.pad(t_lens, (0, pad), constant_values=1)
            nodes, poss = runner.run(fn, q_codes, q_lens, t_codes, t_lens)
            nodes = np.asarray(nodes)
            poss = np.asarray(poss)
            for k, (w_idx, l_idx, _seq, b, _e) in enumerate(part):
                nd, ps = nodes[k], poss[k]
                keep = ps >= 0  # drop pads and backbone-skip steps
                nd = nd[keep][::-1].copy()
                ps = ps[keep][::-1].copy()
                nd[nd >= 0] += b  # slice -> window backbone coordinates
                results[w_idx][l_idx] = (nd.astype(np.int32),
                                         ps.astype(np.int32))
                if logger is not None:
                    logger.bar("[racon_tpu::Polisher.polish] "
                               "aligning layers on device")
    return results
