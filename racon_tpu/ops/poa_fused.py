"""Single-launch whole-window POA on device (experimental engine).

The cudapoa-shaped design (reference src/cuda/cudabatch.cpp:77-270: add
windows until the batch is full, then ONE generate_poa() builds every
window's whole graph on device) rebuilt TPU-first. Where the session
engine (ops/poa_graph.py) round-trips host<->device once per layer wave,
this engine runs ALL layers of a window batch in a single jitted call —
the POA graph itself lives in fixed-shape device arrays and is mutated by
vectorized scatters:

  - the graph's topological order is maintained WITHOUT graph traversal:
    every aligned column owns a 64-bit ORDER KEY; node order is
    `argsort(column key, node id)` — one vectorized sort per layer instead
    of a sequential topo walk. Insertion columns get keys strictly between
    their path neighbours' keys (run-partitioned equal spacing), with the
    low 8 bits salted by layer index so keys are globally unique (equal
    keys would let node-id tie-breaking reorder columns under later
    in-column allocations);
  - per layer: graph-NW DP + traceback on device (the same formulation as
    ops/poa_graph.graph_aligner, full DP), then a fully VECTORIZED ingest
    — target resolution (same base -> existing node, mismatch -> aligned
    alternate or new node in column, insertion -> new node + new column),
    prefix-sum node allocation, and conflict-free scatter wiring of edges,
    edge weights (w[i-1] + w[i], the endpoint-sum convention of
    native/src/poa.cpp add_alignment) and sequence counts.
    No sequential walk anywhere in the ingest;
  - windows that exceed any envelope (nodes, columns, in-degree P, key
    spacing) raise a per-window `failed` flag and fall back to the host
    engine — the per-window GPU->CPU fallback discipline
    (cudapolisher.cpp:354-383);
  - consensus runs on host from the fetched arrays via the SAME C++
    heaviest-bundle the host engine uses (native rh_poa_finish_arrays).

Accuracy contract: the engine replicates the host's layer order
(begin-sorted, window.cpp:84-85), band rule (256 when the layer fits,
exact DP otherwise), the banded clipped->full-DP retry (the host
band_clipped rule, run on device under `lax.cond` so unclipped layers —
the typical case — pay nothing) and ingest semantics; tests assert
BYTE-IDENTITY to the host engine on spanning, non-spanning and
band-clipping synthetic windows. On real data the guarantee is
measurably weaker than the session engine's: deep windows can hit
topo-order tie cases where the argsort-key order and the host graph's
walk order rank equal-scoring paths differently (lambda sample: 95/96
windows byte-equal, 1 diverges with identical aggregate quality —
distance 1352 == host; pinned by tests/test_fused_poa.py). The session
engine (ops/poa_graph.py) remains the byte-identical-everywhere engine;
the reference itself pins diverging GPU numbers separately
(racon_test.cpp:292-496). With `banded_only` (-b) the retry is skipped,
the reference's GPU-only speed/accuracy trade (cudabatch.cpp:56-59).

Non-spanning layers (reference window.cpp:87-103's subgraph case) are
handled by MASKING, not extraction: every node carries its backbone
position (`bpos`, inherited exactly like the host engine's), and a layer
with range [begin, end] aligns against only the in-range nodes — preds
filtered to in-range (a node with no in-range pred becomes a subgraph
source), sinks recomputed as in-range nodes without in-range successors.
This reproduces the host's bpos-range-induced subgraph
(native/src/poa.cpp Graph::subgraph) without materializing it.

Depth is bucketed ((8, 16, 32, 64) layers per call) and deeper windows
CHAIN calls: the state arrays stream out of one call and into the next
with a layer-index base, so arbitrary depth costs no extra host work
beyond the fetch/feed of the fixed-size state.

FUSED single-launch mode (RACON_TPU_FUSED=auto|0|1, default auto):
instead of the chained per-bucket calls with host-side window slicing,
one device program runs a chunk's WHOLE chain — banded graph alignment,
the window-slicing decisions (spanning / bpos-range subgraph bounds /
the static-band rule, derived on device from the raw layer coordinates)
and the POA row-update ingest — as one jitted scan with donated state
buffers, so aligned coordinates never leave the chip between stages and
per-chunk Python dispatch collapses to one launch + one fetch.
Bit-identical to the split path by construction (integer-exact slicing,
same layer scan); `auto` arbitrates fused-vs-split per depth bucket via
the persisted autotuner winner table (sched/autotune, engine
"fused_loop") under the same identity veto as the kernel plane, and a
fused chunk that faults falls back to the split chained path — its
DECLARED fallback — byte-identically before anything reaches the host
engine tail.

Requires jax x64 (the order keys are int64); enabled at kernel build.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from ..resilience import strict_mode
from ..utils.logger import Logger, log_info, warn_dedup
#: envelope shared with the session engine (ONE source of truth, incl.
#: the construction-time RACON_TPU_MAX_NODES override; measured: ~2000
#: nodes at depth 38 on the lambda sample, and the default envelope
#: device-builds 98.7% of windows at 30x coverage — see
#: poa_graph.MAX_NODES and PARITY.md)
from .poa_graph import (MAX_LEN, MAX_NODES, MAX_PRED, RING,
                        env_max_nodes)

#: layers per call; deeper windows chain calls with carried state
DEPTH_BUCKETS = (8, 16, 32, 64)

#: deepest chunk the FUSED single-launch program takes (beyond it the
#: split chained path runs — one compiled program per distinct chunk
#: total-depth must stay bounded, and chain-sums past this are rare
#: tails, not the hot path)
FUSED_LOOP_MAX_DEPTH = 128

_NEG = -(1 << 29)


def fused_mode() -> str:
    """RACON_TPU_FUSED posture for the single-launch fused
    align→window-slice→POA program: '1' = fused whenever the chunk
    fits FUSED_LOOP_MAX_DEPTH, '0' = always the split chained path
    (the pre-fusion behavior), 'auto' (default) = per-bucket via the
    persisted autotuner winner table (sched/autotune engine
    "fused_loop"; a cold table dispatches split). Invalid values fall
    back to auto — never crash a run over a typo'd knob. Inside an
    audit oracle_scope (ops/oracle.py) the posture is pinned '0' on
    that thread — the shadow oracle runs the split chained path, the
    fused program's declared byte-identical fallback."""
    from .oracle import oracle_active

    if oracle_active():
        return "0"
    raw = (os.environ.get("RACON_TPU_FUSED") or "auto").strip().lower()
    return raw if raw in ("auto", "0", "1") else "auto"


@functools.lru_cache(maxsize=None)
def fused_raw(n_nodes: int, seq_len: int, depth: int, max_pred: int,
              match: int, mismatch: int, gap: int,
              banded_only: bool = False, score_dtype: str = "int32",
              device_slice: bool = False):
    """Raw (traceable, un-jitted) whole-window POA builder for one
    (N, L, D, P) shape — `fused_builder` jits it for single-device
    dispatch; FusedPOA's BatchRunner shard_maps it for multi-chip
    dispatch (the batch-per-GPU loop of cudapolisher.cpp:228-240, as one
    batch-sharded program per chip over the mesh).

    State arrays (leading dim B): codes [B,N] i8 (-1 free), preds [B,N,P]
    i16 node ids (-1 empty), predw [B,N,P] i32, nseq [B,N] i32,
    col_of [B,N] i16, colkey [B,N] i64, colnodes [B,N,5] i16,
    bpos [B,N] i16, n_nodes/n_cols [B] i32. Layer inputs: seqs [B,D,L] i8
    (pad 5), lens [B,D] i32 (0 = no layer), wts [B,D,L] i8 (Phred-33
    weights <= 93; upcast on device — a quarter of the host->device
    bytes), rlo/rhi [B,D] i16 (the layer's bpos range; -32768/32767 =
    spanning, full graph), lbase [B] i32 (per-row layer-index base, so
    every operand is batch-leading and shardable). Returns the updated
    state + failed [B] bool.
    """
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_enable_x64", True)

    N, L, D, P = n_nodes, seq_len, depth, max_pred
    C = N  # column capacity
    #: DP score dtype — int16 halves the per-layer DP carry when the
    #: envelope proof holds (ops/dtypes.poa_int16_ok; the graph/ingest
    #: arrays keep their own dtypes — only the alignment DP narrows)
    DT = jnp.int16 if score_dtype == "int16" else jnp.int32
    NEG = jnp.asarray(-(1 << 14) if score_dtype == "int16" else _NEG, DT)
    MAXKEY = jnp.int64(1) << 44  # composite (key << 11 | id) must fit i64

    def dp_align(codes_r, preds_r, sinks_r, centers_r, band, seq, slen, B,
                 kmax):
        # ring carry: only the last RING DP rows stay resident (slot 0 =
        # virtual source) — valid because the caller fails any lane whose
        # predecessor distance exceeds the ring (measured: 29 on the
        # lambda sample, 72 on synthbench 250 kb — see poa_graph.RING);
        # the score at each lane's sink column is collected
        # into a side carry as rows retire
        W = RING
        jidx = jnp.arange(L + 1, dtype=jnp.int32)
        jg = (jidx * gap).astype(DT)
        h0 = jnp.where(jidx[None, :] <= slen[:, None], jg[None, :], NEG)
        H = jnp.full((B, W + 1, L + 1), NEG, dtype=DT)
        H = H.at[:, 0, :].set(h0)
        scores0 = jnp.full((B, N), NEG, dtype=DT)
        band2 = (band // 2).astype(jnp.int32)

        def step(carry, xs):
            H, scores = carry
            code_k, preds_k, center_k, k = xs
            pk = jnp.where(preds_k > 0,
                           1 + jax.lax.rem(preds_k - 1, jnp.int32(W)), 0)
            pk = jnp.clip(pk, 0, W)
            rows = jnp.take_along_axis(H, pk[:, :, None], axis=1)
            rows = jnp.where((preds_k >= 0)[:, :, None], rows, NEG)
            sub = jnp.where(seq == code_k[:, None], match,
                            mismatch).astype(DT)
            diag = rows[:, :, :-1] + sub[:, None, :]
            vert = rows[:, :, 1:] + gap
            best = jnp.max(jnp.maximum(diag, vert), axis=1)
            row0 = jnp.max(rows[:, :, 0], axis=1) + gap
            # static-band masking around each node's expected diagonal,
            # exactly like the host engine (band 0 = full DP)
            use_band = band > 0
            jlo = jnp.where(use_band, jnp.maximum(1, center_k - band2), 1)
            jhi = jnp.where(use_band, jnp.minimum(slen, center_k + band2),
                            slen)
            inb = ((jidx[None, 1:] >= jlo[:, None]) &
                   (jidx[None, 1:] <= jhi[:, None]))
            pre = jnp.where(inb, best, NEG)
            seed0 = jnp.where(jlo == 1, row0, NEG)
            cat = jnp.concatenate([seed0[:, None], pre], axis=1)
            run = jax.lax.cummax(cat - jg, axis=1) + jg
            hrow = jnp.where(inb, run[:, 1:], pre)
            new_row = jnp.concatenate([row0[:, None], hrow], axis=1)

            nr = new_row[:, 1:]
            is_diag = nr[:, None, :] == diag
            is_vert = nr[:, None, :] == vert
            pd = jnp.argmax(is_diag, axis=1).astype(jnp.int32)
            pv = jnp.argmax(is_vert, axis=1).astype(jnp.int32)
            bpc = jnp.where(jnp.any(is_diag, axis=1), pd,
                            jnp.where(jnp.any(is_vert, axis=1), P + pv,
                                      2 * P))
            is_v0 = row0[:, None] == rows[:, :, 0] + gap
            bp0 = P + jnp.argmax(is_v0, axis=1).astype(jnp.int32)
            bp_row = jnp.concatenate([bp0[:, None], bpc],
                                     axis=1).astype(jnp.int8)
            slot = 1 + jax.lax.rem(k - 1, jnp.int32(W))
            H = jax.lax.dynamic_update_slice(
                H, new_row[:, None, :], (jnp.int32(0), slot, jnp.int32(0)))
            sc = jnp.take_along_axis(new_row, slen[:, None], axis=1)
            scores = jax.lax.dynamic_update_slice(
                scores, sc, (jnp.int32(0), k - 1))
            return (H, scores), bp_row

        # row loop bounded by the batch's real node count (graphs start at
        # backbone size ~N/4 and grow layer by layer — a static N-step
        # scan would pay for every pad row on every layer)
        bps0 = jnp.zeros((N, B, L + 1), dtype=jnp.int8)

        def row(k, carry):
            hs, bps = carry
            code_k = jax.lax.dynamic_slice_in_dim(
                codes_r, k - 1, 1, axis=1)[:, 0]
            preds_k = jax.lax.dynamic_slice_in_dim(
                preds_r, k - 1, 1, axis=1)[:, 0]
            center_k = jax.lax.dynamic_slice_in_dim(
                centers_r, k - 1, 1, axis=1)[:, 0]
            hs, bp_row = step(hs, (code_k, preds_k, center_k, k))
            bps = jax.lax.dynamic_update_slice(
                bps, bp_row[None], (k - 1, jnp.int32(0), jnp.int32(0)))
            return hs, bps

        (_, scores), bps = jax.lax.fori_loop(
            jnp.int32(1), kmax + 1, row, ((H, scores0), bps0))

        cand = jnp.where(sinks_r, scores, NEG)
        best_rank = jnp.argmax(cand, axis=1).astype(jnp.int32)

        bp_flat = bps.transpose(1, 0, 2).reshape(B, N * (L + 1))
        preds_flat = preds_r.reshape(B, N * P)
        rows_b = jnp.arange(B)

        def cond(st):
            r, j, _ = st
            return jnp.any((r > 0) | (j > 0))

        def body(st):
            r, j, out = st
            active = (r > 0) | (j > 0)
            lin = (jnp.clip(r - 1, 0, N - 1) * (L + 1) + jnp.clip(j, 0, L))
            code = jnp.take_along_axis(
                bp_flat, lin[:, None], axis=1)[:, 0].astype(jnp.int32)
            code = jnp.where(r > 0, code, 2 * P)
            is_diag = code < P
            is_vert = (code >= P) & (code < 2 * P)
            p = jnp.where(is_diag, code, code - P)
            plin = (jnp.clip(r - 1, 0, N - 1) * P + jnp.clip(p, 0, P - 1))
            pr = jnp.take_along_axis(preds_flat, plin[:, None],
                                     axis=1)[:, 0]
            consume = active & ~is_vert
            jc = jnp.clip(j - 1, 0, L - 1)
            cur = jnp.take_along_axis(out, jc[:, None], axis=1)[:, 0]
            emit = jnp.where(is_diag, r - 1, -1)
            out = out.at[rows_b, jc].set(jnp.where(consume, emit, cur))
            r = jnp.where(active & (is_diag | is_vert), pr, r)
            j = jnp.where(consume, j - 1, j)
            return r, j, out

        out0 = jnp.full((B, L), -2, dtype=jnp.int32)
        _, _, ranks = jax.lax.while_loop(cond, body,
                                         (best_rank + 1, slen, out0))
        return ranks

    def fwd(a, b):
        return jnp.where(b[1], b[0], a[0]), (a[1] | b[1])

    def bwd_seg(a, b):
        return (jnp.where(b[1], b[0], jnp.maximum(a[0], b[0])),
                (a[1] | b[1]))

    def one_layer(state, layer):
        (codes, preds, predw, nseq, col_of, colkey, colnodes,
         bpos, n_nodes, n_cols, failed) = state
        seq, slen, wts, rlo, rhi, band, lidx = layer
        B = codes.shape[0]
        rows_b = jnp.arange(B)
        active = (slen > 0) & ~failed

        # topo order from column keys (argsort; node-id tiebreak)
        alloc = codes >= 0
        nkey = jnp.where(
            alloc,
            (jnp.take_along_axis(
                colkey, jnp.clip(col_of, 0, C - 1).astype(jnp.int32),
                axis=1) << 11) | jnp.arange(N, dtype=jnp.int64)[None, :],
            jnp.int64(1) << 62)
        order = jnp.argsort(nkey, axis=1).astype(jnp.int32)
        rank_of = jnp.zeros((B, N), dtype=jnp.int32)
        rank_of = rank_of.at[rows_b[:, None], order].set(
            jnp.arange(N, dtype=jnp.int32)[None, :])

        # the layer's bpos-range-induced subgraph, by masking (the host's
        # Graph::subgraph semantics): out-of-range nodes become dead rows,
        # in-range nodes keep only in-range preds (none left -> subgraph
        # source), sinks = in-range nodes with no in-range successor
        in_range = (alloc & (bpos >= rlo[:, None]) &
                    (bpos <= rhi[:, None]))
        in_range_r = jnp.take_along_axis(in_range, order, axis=1)

        codes_r = jnp.take_along_axis(codes, order, axis=1)
        codes_r = jnp.where(in_range_r, codes_r, 5).astype(jnp.int8)
        pr_nodes = jnp.take_along_axis(preds, order[:, :, None], axis=1)
        pr_clip = jnp.clip(pr_nodes, 0, N - 1).reshape(B, -1)
        pr_ok = (pr_nodes >= 0) & jnp.take_along_axis(
            in_range, pr_clip, axis=1).reshape(B, N, P)
        pr_rank = jnp.where(
            pr_ok,
            jnp.take_along_axis(rank_of, pr_clip,
                                axis=1).reshape(B, N, P) + 1,
            -1).astype(jnp.int32)
        no_pred = (~pr_ok).all(axis=2) & in_range_r
        pr_rank = pr_rank.at[:, :, 0].set(
            jnp.where(no_pred, 0, pr_rank[:, :, 0]))
        # dp_align's carry holds only the last RING rows — a lane with a
        # longer predecessor reach would read retired rows; fail it to
        # the host engine (measured: 29 lambda / 72 synthbench, both
        # within RING=128 — see poa_graph.RING)
        kk1 = jnp.arange(1, N + 1, dtype=jnp.int32)[None, :, None]
        ring_fail = ((pr_rank > 0) &
                     (kk1 - pr_rank > RING)).any(axis=(1, 2))

        has_succ = jnp.zeros((B, N + 2), dtype=bool)
        succ_pos = jnp.where(pr_ok & in_range_r[:, :, None],
                             pr_clip.reshape(B, N, P), N + 1)
        has_succ = has_succ.at[
            rows_b[:, None, None], succ_pos].set(True, mode="drop")
        sinks_r = in_range_r & ~jnp.take_along_axis(
            has_succ[:, :N], order, axis=1)

        # band centers: bpos relative to the layer's range origin
        origin = jnp.maximum(rlo.astype(jnp.int32), 0)
        centers_r = (jnp.take_along_axis(bpos, order, axis=1).astype(
            jnp.int32) - origin[:, None] + 1)

        kmax = jnp.max(n_nodes).astype(jnp.int32)
        ranks = dp_align(codes_r, pr_rank, sinks_r, centers_r,
                         band.astype(jnp.int32), seq, slen, B, kmax)

        if not banded_only:
            # banded clipped -> full-DP retry, the host engine's rule
            # (native/src/poa.cpp band_clipped): fewer than half the
            # aligned columns matching means the in-band path is mismatch
            # soup from band clipping; redo those lanes with the exact
            # full DP. lax.cond skips the redo entirely on the (typical)
            # layer where nothing clipped.
            node_c = jnp.take_along_axis(
                codes_r, jnp.clip(ranks, 0, N - 1), axis=1)
            al = ranks >= 0
            n_al = al.sum(axis=1)
            n_ma = (al & (node_c == seq)).sum(axis=1)
            clipped = (active & (band > 0) &
                       ((n_al == 0) | (2 * n_ma < n_al)))

            def _redo(_):
                full = dp_align(codes_r, pr_rank, sinks_r, centers_r,
                                jnp.zeros_like(band, jnp.int32), seq,
                                slen, B, kmax)
                return jnp.where(clipped[:, None], full, ranks)

            ranks = jax.lax.cond(jnp.any(clipped), _redo,
                                 lambda _: ranks, None)

        # ---- vectorized ingest
        iidx = jnp.arange(L, dtype=jnp.int32)
        inlen = (iidx[None, :] < slen[:, None]) & active[:, None]
        base = seq.astype(jnp.int32)
        aligned = (ranks >= 0) & inlen
        node_at = jnp.where(
            aligned,
            jnp.take_along_axis(order, jnp.clip(ranks, 0, N - 1), axis=1),
            -1)
        col0 = jnp.where(
            aligned,
            jnp.take_along_axis(col_of, jnp.clip(node_at, 0, N - 1),
                                axis=1).astype(jnp.int32),
            -1)
        same = aligned & (jnp.take_along_axis(
            codes, jnp.clip(node_at, 0, N - 1), axis=1) == base)
        alt = jnp.where(
            aligned,
            colnodes.reshape(B, -1)[
                rows_b[:, None],
                jnp.clip(col0, 0, C - 1) * 5 + jnp.clip(base, 0, 4)],
            -1).astype(jnp.int32)
        use_alt = aligned & ~same & (alt >= 0)
        new_in_col = aligned & ~same & (alt < 0)
        insertion = inlen & ~aligned

        # per-run anchor keys: prev (forward) / next (backward); anchor
        # bpos propagated the same way for insertion-node bpos inheritance
        # (host: insertions take the previous column's bpos, leading
        # insertions backfill from the next aligned column)
        akey = jnp.where(
            aligned,
            jnp.take_along_axis(
                colkey, jnp.clip(col0, 0, C - 1).astype(jnp.int32),
                axis=1),
            0)
        abpos = jnp.where(
            aligned,
            jnp.take_along_axis(bpos, jnp.clip(node_at, 0, N - 1),
                                axis=1).astype(jnp.int64),
            0)
        pkey, pflag = jax.lax.associative_scan(fwd, (akey, aligned),
                                               axis=1)
        pkey_prev = jnp.concatenate(
            [jnp.zeros((B, 1), jnp.int64), pkey[:, :-1]], axis=1)
        has_prev = jnp.concatenate(
            [jnp.zeros((B, 1), bool), pflag[:, :-1]], axis=1)
        pbp = jax.lax.associative_scan(fwd, (abpos, aligned), axis=1)[0]
        pbp_prev = jnp.concatenate(
            [jnp.zeros((B, 1), jnp.int64), pbp[:, :-1]], axis=1)
        nk = jax.lax.associative_scan(
            fwd, (jnp.flip(akey, 1), jnp.flip(aligned, 1)), axis=1)[0]
        nkey_next = jnp.flip(nk, 1)
        nbp_next = jnp.flip(jax.lax.associative_scan(
            fwd, (jnp.flip(abpos, 1), jnp.flip(aligned, 1)), axis=1)[0], 1)
        nkey_next = jnp.where(
            jnp.flip(jax.lax.associative_scan(
                jnp.logical_or, jnp.flip(aligned, 1), axis=1), 1),
            nkey_next, MAXKEY)
        ins_bpos = jnp.where(has_prev, pbp_prev, nbp_next).astype(
            jnp.int16)

        # position within insertion run and run length
        ins_i = jnp.cumsum(insertion.astype(jnp.int32), axis=1)
        run_start_ins = jax.lax.associative_scan(
            fwd, (ins_i.astype(jnp.int64), aligned), axis=1)[0]
        run_start_ins = jnp.concatenate(
            [jnp.zeros((B, 1), jnp.int64), run_start_ins[:, :-1]],
            axis=1).astype(jnp.int32)
        jrun = jnp.where(insertion, ins_i - run_start_ins, 0)
        mrev = jax.lax.associative_scan(
            bwd_seg, (jnp.flip(jnp.where(insertion, jrun, 0), 1),
                      jnp.flip(aligned, 1)), axis=1)[0]
        mrun = jnp.flip(mrev, 1)

        # insertion column keys: run-partitioned equal spacing, low 8 bits
        # replaced with the layer salt for global uniqueness (lidx is per
        # row, [B])
        span = nkey_next - pkey_prev
        spacing = span // (mrun.astype(jnp.int64) + 1)
        grid = pkey_prev + span * jrun.astype(jnp.int64) // (
            mrun.astype(jnp.int64) + 1)
        salt = ((lidx.astype(jnp.int64) + 1) & 0xFF)[:, None]
        ikey = (grid & ~jnp.int64(0xFF)) | salt
        key_bad = insertion & ((spacing <= 512) |
                               (ikey <= pkey_prev) | (ikey >= nkey_next))

        new_node = new_in_col | insertion
        nid = (n_nodes[:, None] +
               jnp.cumsum(new_node.astype(jnp.int32), axis=1) - 1)
        cid = (n_cols[:, None] +
               jnp.cumsum(insertion.astype(jnp.int32), axis=1) - 1)
        overflow = (new_node & (nid >= N)) | (insertion & (cid >= C))
        layer_fail = (key_bad.any(axis=1) | overflow.any(axis=1)
                      | ring_fail)
        ok = active & ~layer_fail
        okm = ok[:, None]

        target = jnp.where(same, node_at,
                           jnp.where(use_alt, alt,
                                     jnp.where(new_node, nid, -1)))
        tcol = jnp.where(insertion, cid, col0)

        sn = jnp.where(new_node & okm, nid, N + 1)
        codes = codes.at[rows_b[:, None], sn].set(
            base.astype(jnp.int8), mode="drop")
        col_of = col_of.at[rows_b[:, None], sn].set(
            tcol.astype(col_of.dtype), mode="drop")
        tbpos = jnp.where(insertion, ins_bpos,
                          jnp.take_along_axis(
                              bpos, jnp.clip(node_at, 0, N - 1),
                              axis=1)).astype(jnp.int16)
        bpos = bpos.at[rows_b[:, None], sn].set(tbpos, mode="drop")
        sc = jnp.where(insertion & okm, cid, C + 1)
        colkey = colkey.at[rows_b[:, None], sc].set(ikey, mode="drop")
        flat_cn = colnodes.reshape(B, C * 5)
        cnpos = jnp.where(new_node & okm,
                          jnp.clip(tcol, 0, C - 1) * 5 + base, C * 5 + 1)
        flat_cn = flat_cn.at[rows_b[:, None], cnpos].set(
            nid.astype(colnodes.dtype), mode="drop")
        colnodes = flat_cn.reshape(B, C, 5)

        st = jnp.where((inlen & (target >= 0)) & okm, target, N + 1)
        nseq = nseq.at[rows_b[:, None], st].add(1, mode="drop")

        # edges between consecutive path positions
        tails = target[:, :-1]
        heads = target[:, 1:]
        epresent = inlen[:, 1:] & inlen[:, :-1] & okm
        w32 = wts.astype(jnp.int32)
        ew = w32[:, :-1] + w32[:, 1:]
        hclip = jnp.clip(heads, 0, N - 1)
        hpred = jnp.take_along_axis(preds, hclip[:, :, None], axis=1)
        match_slot = (hpred == tails[:, :, None]) & (tails[:, :, None] >= 0)
        empty_slot = hpred < 0
        has_match = match_slot.any(axis=2)
        slot = jnp.where(has_match, jnp.argmax(match_slot, axis=2),
                         jnp.argmax(empty_slot, axis=2))
        slot_ok = has_match | empty_slot.any(axis=2)
        edge_fail = (epresent & ~slot_ok).any(axis=1)
        failed = failed | (active & (layer_fail | edge_fail))
        eok = epresent & slot_ok & (~edge_fail)[:, None]

        flat_p = preds.reshape(B, N * P)
        flat_w = predw.reshape(B, N * P)
        ppos = jnp.where(eok, hclip * P + slot, N * P + 1)
        flat_p = flat_p.at[rows_b[:, None], ppos].set(
            tails.astype(preds.dtype), mode="drop")
        flat_w = flat_w.at[rows_b[:, None], ppos].add(ew, mode="drop")
        preds = flat_p.reshape(B, N, P)
        predw = flat_w.reshape(B, N, P)
        n_nodes = jnp.where(
            ok, n_nodes + new_node.sum(axis=1, dtype=jnp.int32), n_nodes)
        n_cols = jnp.where(
            ok, n_cols + insertion.sum(axis=1, dtype=jnp.int32), n_cols)
        return ((codes, preds, predw, nseq, col_of, colkey,
                 colnodes, bpos, n_nodes, n_cols, failed), None)

    def run(codes, preds, predw, nseq, col_of, colkey, colnodes,
            bpos, n_nodes, n_cols, failed, seqs, lens, wts, rlo, rhi,
            band, lbase):
        state = (codes, preds, predw, nseq, col_of, colkey,
                 colnodes, bpos, n_nodes, n_cols, failed)
        # per-step layer indices [D, B]: row base + step offset
        lidx_all = (lbase[None, :].astype(jnp.int32)
                    + jnp.arange(D, dtype=jnp.int32)[:, None])
        state, _ = jax.lax.scan(
            one_layer, state,
            (seqs.transpose(1, 0, 2), lens.T, wts.transpose(1, 0, 2),
             rlo.T, rhi.T, band.T, lidx_all))
        return state

    def run_sliced(codes, preds, predw, nseq, col_of, colkey, colnodes,
                   bpos, n_nodes, n_cols, failed, seqs, lens, wts,
                   begins, ends, bblen, offs, lbase):
        """The FUSED variant: window slicing runs ON DEVICE. Layers
        arrive as raw (begin, end) backbone coordinates plus per-row
        backbone length / spanning offset, and each scan step derives
        the bpos-range subgraph bounds (rlo/rhi) and the static-band
        rule exactly as the host packer does (`_pack_chunk`) — integer
        arithmetic only, so the derived operands are bit-identical to
        the host-sliced ones and the aligned coordinates never leave
        the chip between the slicing, alignment and ingest stages."""
        state = (codes, preds, predw, nseq, col_of, colkey,
                 colnodes, bpos, n_nodes, n_cols, failed)
        lidx_all = (lbase[None, :].astype(jnp.int32)
                    + jnp.arange(D, dtype=jnp.int32)[:, None])
        bb32 = bblen.astype(jnp.int32)
        of32 = offs.astype(jnp.int32)

        def sliced(state, xs):
            seq, slen, w, b, e, lidx = xs
            b32 = b.astype(jnp.int32)
            e32 = e.astype(jnp.int32)
            # the host packer's spanning rule (reference
            # window.cpp:97-102): offset precomputed per row on host
            # (int(0.01 * bb_len) — float-truncation-exact)
            spanning = (b32 < of32) & (e32 > bb32 - of32)
            span = jnp.where(spanning, bb32, e32 - b32 + 1)
            rlo = jnp.where(spanning, -32768, b32).astype(jnp.int16)
            rhi = jnp.where(spanning, 32767, e32).astype(jnp.int16)
            # the host engine's static-band rule: band 256 when the
            # layer fits, exact DP otherwise
            band = jnp.where(jnp.abs(slen - span) < 256 // 2 - 16,
                             256, 0).astype(jnp.int32)
            return one_layer(state, (seq, slen, w, rlo, rhi, band,
                                     lidx))

        state, _ = jax.lax.scan(
            sliced, state,
            (seqs.transpose(1, 0, 2), lens.T, wts.transpose(1, 0, 2),
             begins.T, ends.T, lidx_all))
        return state

    return run_sliced if device_slice else run


@functools.lru_cache(maxsize=None)
def fused_builder(n_nodes: int, seq_len: int, depth: int, max_pred: int,
                  match: int, mismatch: int, gap: int,
                  banded_only: bool = False, score_dtype: str = "int32",
                  device_slice: bool = False):
    """Single-device jitted variant of `fused_raw` (multi-chip dispatch
    goes through BatchRunner.run on the raw function instead)."""
    import jax

    run = fused_raw(n_nodes, seq_len, depth, max_pred, match, mismatch,
                    gap, banded_only=banded_only, score_dtype=score_dtype,
                    device_slice=device_slice)
    # donate the state buffers on accelerators so chained calls mutate in
    # place instead of allocating a second copy of the graph arrays (the
    # CPU test backend can't donate and would warn on every call)
    donate = () if jax.default_backend() == "cpu" else tuple(range(11))
    return jax.jit(run, donate_argnums=donate)


@functools.lru_cache(maxsize=None)
def _pinned_rows(n_nodes: int, seq_len: int, max_pred: int) -> int:
    """ONE pinned batch width per envelope from the device free-memory
    query (the 90%-of-free-VRAM rule, cudapolisher.cpp:169-173,230-239).
    Wider batches are nearly free on the VPU — the whole workload should
    fit ONE chunk when memory allows, because sequential depth (layers x
    graph rows) and launch count are the real costs; /3 keeps two
    pipelined chunks' DP state plus slack in flight. Cached per process:
    jit programs are shape-keyed on B, so the width the bench precompiles
    must be the width the polish run uses even though precompile's own
    buffers shrink the free-memory reading in between."""
    import jax

    from .poa_graph import _device_budget, pin_pow2_rows

    h = (n_nodes + 1) * (seq_len + 1) * 4       # DP score carry, per row
    bps = n_nodes * (seq_len + 1)               # backpointer stack, per row
    state = n_nodes * (2 * max_pred * 3 + 30)   # graph arrays, per row
    return pin_pow2_rows(_device_budget(jax.devices()) // 3,
                         h + bps + state)


def _weights_of(qual, length):
    if qual:
        w = np.frombuffer(qual, np.uint8).astype(np.int32) - 33
        return np.clip(w, 0, 127)  # Phred <= 93; int8-safe by contract
    return np.ones(length, dtype=np.int32)


class FusedPOA:
    """Whole-window device POA engine (see module docstring).

    consensus(windows) has the same contract as DeviceGraphPOA.consensus:
    windows are lists of (seq, qual|None, begin, end) with element 0 the
    backbone; returns (results, statuses) with statuses 0 = device-built,
    1 = host fallback, 2 = backbone-only.
    """

    def __init__(self, match: int, mismatch: int, gap: int,
                 num_threads: int = 1, logger: Logger | None = None,
                 max_nodes: int | None = None, max_len: int = MAX_LEN,
                 max_pred: int = MAX_PRED, batch_rows: int | None = None,
                 depth_buckets=DEPTH_BUCKETS, banded_only: bool = False,
                 runner=None, scheduler=None,
                 use_fused: bool | None = None):
        from ..parallel.mesh import BatchRunner
        from ..sched import BatchScheduler

        if max_nodes is None:
            max_nodes = env_max_nodes()
        # occupancy-aware scheduler (sched/): adaptive depth ladder when
        # armed, per-depth-bucket occupancy telemetry always
        self.sched = (scheduler if scheduler is not None
                      else BatchScheduler.from_env())
        self.match = match
        self.mismatch = mismatch
        self.gap = gap
        self.num_threads = num_threads
        self.logger = logger
        self.N = max_nodes
        self.L = max_len
        self.P = max_pred
        # batch axis sharded over every device (the reference's
        # batch-per-GPU loop, cudapolisher.cpp:228-240): B is sized PER
        # DEVICE from the free-memory pin, times the mesh width, so each
        # chip carries the width one chip's memory affords
        self.runner = runner if runner is not None else BatchRunner()
        if batch_rows:
            self.B = self.runner.round_batch(batch_rows)
        else:
            self.B = self._pin_rows() * self.runner.n_devices
        self.depth_buckets = tuple(depth_buckets)
        #: compile budget for the adaptive depth ladder — pinned to the
        #: construction-time ladder size so adapt() is idempotent (a
        #: precompile-then-consensus double derivation must yield the
        #: SAME ladder, or the precompiled programs would be discarded)
        self._depth_k = len(self.depth_buckets)
        self.last_stats = {"chunks": 0, "launches": 0, "pack_s": 0.0,
                           "device_s": 0.0, "unpack_s": 0.0,
                           "fused_chunks": 0, "fused_fallbacks": 0}
        # -b / banded-only: trust banded DP results (skip the clipped ->
        # full-DP retry), the reference's GPU-only speed/accuracy trade
        self.banded_only = banded_only
        #: fused single-launch posture (see fused_mode): the constructor
        #: bool forces it on/off for tests, None defers to
        #: RACON_TPU_FUSED; per-depth-bucket winner lookups cache here
        if use_fused is None:
            self.fused_posture = fused_mode()
        else:
            self.fused_posture = "1" if use_fused else "0"
        self._fused_plans: dict[int, bool] = {}
        # score-dtype plan for this engine's single (N, L) envelope:
        # int16 when the overflow proof holds (ops/dtypes; the third
        # engine dispatcher consulting the autotuner table — the fused
        # engine has no pallas variant, so only the dtype half applies)
        from .dtypes import kernel_plan, poa_int16_ok
        from .poa_pallas import pallas_mode

        _, self.score_dtype = kernel_plan(
            pallas_mode(), "fused", (self.N, self.L),
            (self.match, self.mismatch, self.gap, self.P),
            poa_int16_ok(self.N, self.L, self.match, self.mismatch,
                         self.gap),
            lambda dt: False)  # no pallas variant: dtype half only
        self._code_of = np.full(256, 4, dtype=np.int8)
        for i, b in enumerate(b"ACGT"):
            self._code_of[b] = i

    def _pin_rows(self) -> int:
        return _pinned_rows(self.N, self.L, self.P)

    def _call(self, d: int, state, seqs, lens, wts, rlo, rhi, band,
              done: int):
        """One chained builder call for depth bucket `d`: shard_mapped
        over the mesh when one exists, plain donated jit on one device."""
        import time

        t0 = time.perf_counter()
        lbase = np.full(self.B, done, dtype=np.int32)
        if self.runner.sharding is not None:
            raw = fused_raw(self.N, self.L, d, self.P, self.match,
                            self.mismatch, self.gap,
                            banded_only=self.banded_only,
                            score_dtype=self.score_dtype)
            out = self.runner.run(raw, *state, seqs, lens, wts, rlo,
                                  rhi, band, lbase,
                                  donate_argnums=tuple(range(11)))
        else:
            fn = fused_builder(self.N, self.L, d, self.P, self.match,
                               self.mismatch, self.gap,
                               banded_only=self.banded_only,
                               score_dtype=self.score_dtype)
            out = fn(*state, seqs, lens, wts, rlo, rhi, band, lbase)
        # first-dispatch compile telemetry (shared record_compile_once
        # idiom); the key is the full program identity
        self.sched.stats.record_compile_once(
            "fused",
            (self.N, self.L, d, self.P, self.match, self.mismatch,
             self.gap, self.banded_only, self.B,
             self.runner.sharding is not None, self.score_dtype),
            time.perf_counter() - t0)
        return out

    def _call_fused(self, D: int, state, seqs, lens, wts, begins, ends,
                    bblen, offs):
        """ONE single-launch fused align→window-slice→POA call covering
        a chunk's whole chain depth `D`: window slicing (spanning /
        bpos-range / band rule) runs on device from the raw layer
        coordinates, and the layer loop is one device-resident scan —
        no chained Python dispatch, no intermediate state fetch.
        Bit-identical to the split chained path by construction (the
        slicing arithmetic is integer-exact; pinned by tests)."""
        import time

        t0 = time.perf_counter()
        lbase = np.zeros(self.B, dtype=np.int32)
        if self.runner.sharding is not None:
            raw = fused_raw(self.N, self.L, D, self.P, self.match,
                            self.mismatch, self.gap,
                            banded_only=self.banded_only,
                            score_dtype=self.score_dtype,
                            device_slice=True)
            out = self.runner.run(raw, *state, seqs, lens, wts, begins,
                                  ends, bblen, offs, lbase,
                                  donate_argnums=tuple(range(11)))
        else:
            fn = fused_builder(self.N, self.L, D, self.P, self.match,
                               self.mismatch, self.gap,
                               banded_only=self.banded_only,
                               score_dtype=self.score_dtype,
                               device_slice=True)
            out = fn(*state, seqs, lens, wts, begins, ends, bblen, offs,
                     lbase)
        self.sched.stats.record_compile_once(
            "fused",
            (self.N, self.L, D, self.P, self.match, self.mismatch,
             self.gap, self.banded_only, self.B,
             self.runner.sharding is not None, self.score_dtype,
             "loop"),
            time.perf_counter() - t0)
        return out

    def _fused_plan(self, plan) -> bool:
        """Arbitrate FUSED single-launch vs SPLIT chained dispatch for
        a chunk whose chain plan is `plan` (see fused_mode): forced
        postures win; `auto` consults the persisted autotuner winner
        table per depth bucket (engine "fused_loop", keyed by the
        chunk's leading — largest — chain bucket at this engine's
        envelope and scoring; a cold table dispatches split, exactly
        the pre-fusion behavior). Chunks deeper than
        FUSED_LOOP_MAX_DEPTH always split: one compiled program per
        distinct total depth must stay bounded."""
        if not plan or sum(plan) > FUSED_LOOP_MAX_DEPTH:
            return False
        if self.fused_posture == "0":
            return False
        if self.fused_posture == "1":
            return True
        key = plan[0]
        cached = self._fused_plans.get(key)
        if cached is None:
            from ..sched.autotune import get_autotuner

            ent = get_autotuner().winner(
                "fused_loop", (self.N, self.L, key),
                (self.match, self.mismatch, self.gap, self.P))
            cached = self._fused_plans[key] = (
                (ent or {}).get("kernel") == "fused")
        return cached

    def _eligible(self, win) -> bool:
        bb_len = len(win[0][0])
        if bb_len + 1 > self.N:
            return False
        for seq, _, b, e in win[1:]:
            if not seq or len(seq) > self.L:
                return False
        return True

    def _adapt_depths(self, windows, fused_idx) -> None:
        """Adaptive depth ladder from the ACTUAL chunk-max depths — known
        exactly once windows are depth-sorted, since chunks are carved
        from that list in B-strides; every padded layer costs B * L
        device work, so tight edges are the whole occupancy story.
        No-op when the scheduler is off."""
        if not self.sched.adaptive or not fused_idx:
            return
        maxima = [len(windows[fused_idx[s]]) - 1
                  for s in range(0, len(fused_idx), self.B)]
        ladder = self.sched.depth_ladder(maxima, k=self._depth_k)
        if ladder:
            self.depth_buckets = ladder

    def _fused_order(self, windows) -> list[int]:
        """Eligible window indices, deepest first — the ONE definition of
        which windows the device pass takes and in what order, shared by
        consensus() and adapt() so a precompile-derived depth ladder is
        always the ladder the run dispatches."""
        idx = [i for i, w in enumerate(windows)
               if len(w) >= 3 and self._eligible(w)]
        idx.sort(key=lambda i: -len(windows[i]))
        return idx

    def adapt(self, windows) -> None:
        """Derive the adaptive depth ladder ahead of consensus(), so
        precompile(windows=...) warms exactly the programs the run will
        dispatch (the ladder is a pure function of the window set)."""
        self._adapt_depths(windows, self._fused_order(windows))

    def _chain_plan(self, depth: int) -> list[int]:
        """The greedy chained-call depth sequence for one chunk depth."""
        plan, done = [], 0
        while done < depth:
            rem = depth - done
            fits = [b for b in self.depth_buckets if b <= rem]
            d = max(fits) if fits else min(
                b for b in self.depth_buckets if b >= rem)
            plan.append(d)
            done += d
        return plan

    def precompile(self, max_depth: int | None = None,
                   windows=None) -> None:
        """Compile the depth-bucket programs up front. `max_depth` (the
        deepest window that will be polished) restricts compilation to the
        buckets the chaining algorithm can actually pick — the caller
        knows the windows, so the bench/polisher need not pay for unused
        programs. With the adaptive scheduler armed, pass `windows` (the
        packed window set) so the DERIVED depth ladder is what gets
        compiled instead of the static one the run would then discard."""
        if windows is not None:
            self.adapt(windows)
        fused_totals: set[int] = set()
        if max_depth is None:
            needed = set(self.depth_buckets)
            plans = [self._chain_plan(b) for b in self.depth_buckets]
        else:
            needed = set()
            plans = [self._chain_plan(depth)
                     for depth in range(1, max(1, max_depth) + 1)]
        for plan in plans:
            if self._fused_plan(plan):
                fused_totals.add(sum(plan))
            needed.update(plan)  # split programs stay warm: they are
            # the fused program's declared fallback
        for d in sorted(needed):
            state = self._init_state([b"AC"], [np.ones(2, np.int32)])
            seqs = np.full((self.B, d, self.L), 5, np.int8)
            lens = np.zeros((self.B, d), np.int32)
            wts = np.zeros((self.B, d, self.L), np.int8)
            rlo = np.full((self.B, d), -32768, np.int16)
            rhi = np.full((self.B, d), 32767, np.int16)
            band = np.zeros((self.B, d), np.int32)
            out = self._call(d, state, seqs, lens, wts, rlo, rhi, band, 0)
            np.asarray(out[0])  # block
        for D in sorted(fused_totals):
            state = self._init_state([b"AC"], [np.ones(2, np.int32)])
            seqs = np.full((self.B, D, self.L), 5, np.int8)
            lens = np.zeros((self.B, D), np.int32)
            wts = np.zeros((self.B, D, self.L), np.int8)
            begins = np.zeros((self.B, D), np.int32)
            ends = np.zeros((self.B, D), np.int32)
            bblen = np.full(self.B, 2, np.int32)
            offs = np.zeros(self.B, np.int32)
            out = self._call_fused(D, state, seqs, lens, wts, begins,
                                   ends, bblen, offs)
            np.asarray(out[0])  # block

    def _init_state(self, backbones, bweights):
        B, N, P, C = self.B, self.N, self.P, self.N
        codes = np.full((B, N), -1, dtype=np.int8)
        preds = np.full((B, N, P), -1, dtype=np.int16)
        predw = np.zeros((B, N, P), dtype=np.int32)
        nseq = np.zeros((B, N), dtype=np.int32)
        col_of = np.full((B, N), -1, dtype=np.int16)
        colkey = np.zeros((B, C), dtype=np.int64)
        colnodes = np.full((B, C, 5), -1, dtype=np.int16)
        bpos = np.zeros((B, N), dtype=np.int16)
        n_nodes = np.zeros(B, dtype=np.int32)
        n_cols = np.zeros(B, dtype=np.int32)
        failed = np.zeros(B, dtype=bool)
        for k, (bb, w) in enumerate(zip(backbones, bweights)):
            m = len(bb)
            codes[k, :m] = self._code_of[np.frombuffer(bb, np.uint8)]
            col_of[k, :m] = np.arange(m)
            colkey[k, :m] = (np.arange(m, dtype=np.int64) + 1) << 32
            colnodes[k, np.arange(m), codes[k, :m]] = np.arange(m)
            bpos[k, :m] = np.arange(m)
            preds[k, 1:m, 0] = np.arange(m - 1)
            predw[k, 1:m, 0] = w[:-1] + w[1:]
            nseq[k, :m] = 1
            n_nodes[k] = m
            n_cols[k] = m
        return (codes, preds, predw, nseq, col_of, colkey,
                colnodes, bpos, n_nodes, n_cols, failed)

    def consensus(self, windows, fallback: bool = True, pipeline=None):
        """fallback=False leaves ineligible/failed windows as (None,
        status 1) for the caller to polish (e.g. with the session engine,
        which handles non-spanning layers via subgraphs).

        `pipeline` (pipeline.DispatchPipeline) drives the chunk loop:
        while chunk k's chained calls compute on device, a pack worker
        builds chunk k+1's layer operands, an unpack worker fetches and
        C++-finalizes chunk k-1, and fused-ineligible windows are host-
        polished on the fallback pool concurrently with the device pass —
        the stream-overlap role of the reference's per-batch CUDA streams
        (cudapolisher.cpp:165-199). Omitted, an internal depth-1 pipeline
        reproduces the engine's historical one-chunk lookahead. A chunk
        whose device call raises is routed to the host fallback (per-chunk
        GPU->CPU discipline, cudapolisher.cpp:354-383) unless
        RACON_TPU_STRICT is set, in which case the error propagates.
        """
        from ..native import poa_batch
        from ..pipeline import DispatchPipeline

        n = len(windows)
        results: list = [None] * n
        statuses = np.ones(n, dtype=np.int32)
        for i, w in enumerate(windows):
            if len(w) < 3:
                statuses[i] = 2
                results[i] = (w[0][0], np.zeros(len(w[0][0]), np.uint32))
        # windows are processed deepest-first so each batch chunk chains
        # a similar number of calls (padding layers are not free);
        # _fused_order is the one shared definition of the device set
        fused_idx = self._fused_order(windows)
        fused_set = set(fused_idx)
        self._adapt_depths(windows, fused_idx)

        bar = self.logger.bar if self.logger is not None else None
        if self.logger is not None and fused_idx:
            self.logger.bar_total(len(fused_idx))

        self.last_stats = stats = {"chunks": 0, "launches": 0,
                                   "pack_s": 0.0, "device_s": 0.0,
                                   "unpack_s": 0.0, "fused_chunks": 0,
                                   "fused_fallbacks": 0}
        own_pipeline = pipeline is None
        pl = pipeline if pipeline is not None else DispatchPipeline(depth=1)

        # upfront-known host work overlaps the device pass: windows the
        # fused engine cannot take are submitted to the fallback pool NOW
        # instead of serialized after every device chunk retires;
        # concurrent jobs split the thread budget so the pool never
        # oversubscribes the host beyond num_threads
        prefall: list[tuple[list[int], object]] = []
        if fallback and pl.depth > 0:
            ineligible = [i for i in range(n)
                          if statuses[i] == 1 and i not in fused_set]
            fb_threads = max(1, self.num_threads // pl.fallback_workers)
            prefall = pl.map_fallback(
                ineligible,
                lambda sub: poa_batch([windows[i] for i in sub],
                                      self.match, self.mismatch, self.gap,
                                      n_threads=fb_threads))

        def chunk_plan(chunk):
            # deterministic in the chunk (env/posture/table stable for
            # the run), so pack and on_error always agree on which
            # path a chunk took
            return self._chain_plan(max(len(windows[i]) - 1
                                        for i in chunk))

        def pack(chunk):
            plan = chunk_plan(chunk)
            if self._fused_plan(plan):
                D = sum(plan)
                return ("fused", D) + self._pack_chunk_fused(
                    windows, chunk, D)
            return ("split",) + self._pack_chunk(windows, chunk)

        def dispatch(chunk, packed):
            from .device_program import shard_useful_split

            depths = [len(windows[i]) - 1 for i in chunk]
            n_dev = self.runner.n_devices
            if packed[0] == "fused":
                # the FUSED single-launch program: window slicing +
                # every chained layer step in ONE device-resident scan
                # — one launch, one fetch per chunk
                _, D, state, ops = packed
                state = self._call_fused(D, state, *ops)
                row_layers = [min(dep, D) for dep in depths]
                self.sched.stats.record(
                    "fused", D, jobs=len(chunk), lanes=self.B,
                    useful_cells=sum(row_layers),
                    total_cells=self.B * D,
                    kernel="fused", dtype=self.score_dtype,
                    n_devices=n_dev,
                    shard_useful=shard_useful_split(row_layers, self.B,
                                                    n_dev),
                    full_mesh_cells=self.B * D)
                pl.stats.bump("launches")
                stats["fused_chunks"] += 1
                return state
            _, state, calls = packed
            # state stays on device across chained calls (a fetch here
            # would round-trip ~5 MB of graph arrays per call); only the
            # final state is materialized for the host finalizer
            for d, ops, done in calls:
                state = self._call(d, state, *ops, done)
                # occupancy in LAYER units, recorded AFTER the call
                # returned (a faulted chunk must not be accounted as
                # device work): every lane pays all d layer steps of
                # every chained call, real or padded. Each window counts
                # as a job ONCE (on its chunk's first call) so jobs
                # totals stay comparable across engines. The mesh view
                # splits the chunk's rows into per-device shards; B is
                # pinned (no sub-mesh tails), so the full-mesh baseline
                # equals the dispatched capacity.
                row_layers = [min(max(0, dep - done), d)
                              for dep in depths]
                self.sched.stats.record(
                    "fused", d, jobs=len(chunk) if done == 0 else 0,
                    lanes=self.B,
                    useful_cells=sum(row_layers),
                    total_cells=self.B * d,
                    kernel="xla", dtype=self.score_dtype,
                    n_devices=n_dev,
                    shard_useful=shard_useful_split(row_layers, self.B,
                                                    n_dev),
                    full_mesh_cells=self.B * d)
            pl.stats.bump("launches", len(calls))
            return state

        def wait(state):
            return tuple(np.asarray(x) for x in state)

        def _tick(chunk):
            if bar is not None:
                for _ in chunk:
                    bar("[racon_tpu::Polisher.polish] "
                        "building whole-window POA graphs on device")

        def unpack(chunk, np_state):
            self._finalize_chunk(chunk, np_state, results, statuses)
            breaker.ok()
            _tick(chunk)

        # consecutive-chunk-failure circuit breaker — the shared seam
        # implementation (ops/device_program.ChunkBreaker)
        from .device_program import ChunkBreaker

        breaker = ChunkBreaker("FusedPOA", pl.stats, "the device pass")

        def on_error(chunk, exc):
            # a FUSED single-launch chunk gets its DECLARED fallback
            # first: re-run through the split chained path, which is
            # byte-identical by construction (the host tail is not —
            # the host engine may resolve topo-order ties differently,
            # so falling past split would move bytes under a fault)
            if self._fused_plan(chunk_plan(chunk)):
                try:
                    self._split_chunk_inline(windows, chunk, results,
                                             statuses,
                                             watchdog=pl.watchdog,
                                             stats=pl.stats)
                except Exception as split_exc:  # noqa: BLE001 — both
                    # paths dead: count the streak on the SPLIT failure
                    # and leave the windows to the host tail below
                    exc = split_exc
                else:
                    stats["fused_fallbacks"] += 1
                    breaker.ok()
                    warn_dedup(
                        "FusedPOA.fused_chunk_fell_back",
                        "[racon_tpu::FusedPOA] warning: fused program "
                        f"failed ({type(exc).__name__}: {exc}); chunk "
                        "re-ran on the split chained path")
                    _tick(chunk)
                    return
            # the chunk's windows stay unbuilt; the fallback tail below
            # polishes every one of them on host
            breaker.failed(exc, f"{len(chunk)} windows to fallback")
            _tick(chunk)

        # mesh balance: within each FULL chunk, windows round-robin
        # across the per-device row shards (the chunk list IS the row
        # order and B/n_dev rows per shard align exactly with the
        # strided groups), so the depth-sorted deep windows spread over
        # the mesh instead of loading the first shard; pure permutation
        # — per-window results are row-position-independent. The tail
        # chunk keeps sorted order: its graph-state rows are contiguous
        # from row 0, so a strided reorder would NOT line up with the
        # shard boundaries anyway (the padding rows are pinned to the
        # end of the batch by _init_state).
        from ..sched import shard_interleave

        n_dev = self.runner.n_devices
        chunk_items = [
            (shard_interleave(chunk, n_dev) if len(chunk) == self.B
             else chunk)
            for chunk in (fused_idx[s:s + self.B]
                          for s in range(0, len(fused_idx), self.B))]
        strict = strict_mode()
        try:
            # the pipeline already counts and times every stage callback;
            # this run's share is the delta against the (possibly
            # phase-shared) counters — nothing else runs on the pipeline
            # meanwhile
            base = pl.stats.snapshot()
            pl.run(chunk_items, pack, dispatch, wait, unpack,
                   on_error=None if strict else on_error,
                   label="fused",
                   describe=lambda c: {"engine": "fused",
                                       "jobs": len(c)})
            after = pl.stats.snapshot()
            for key in ("pack_s", "device_s", "unpack_s", "chunks",
                        "launches"):
                stats[key] = after[key] - base[key]

            pl.drain_fallback(ignore_errors=not strict)
            for sub, fut in prefall:
                try:
                    sub_res = fut.result()
                except Exception as exc:
                    # this fallback job died even after its bounded
                    # retry: its windows stay None for the caller's
                    # per-window quarantine path
                    warn_dedup(
                        "FusedPOA.fallback_job_failed",
                        "[racon_tpu::FusedPOA] warning: fallback job "
                        f"failed ({type(exc).__name__}: {exc}); "
                        f"{len(sub)} windows left to the caller")
                    continue
                for i, r in zip(sub, sub_res):
                    results[i] = r
                    statuses[i] = 1
        finally:
            if own_pipeline:
                pl.close()

        # everything left is ineligible (depth-0 path) or device-failed
        rest = [i for i in range(n) if results[i] is None]
        self.n_fallback = len(rest) + sum(len(s) for s, _ in prefall)
        if rest and fallback:
            try:
                host = poa_batch([windows[i] for i in rest], self.match,
                                 self.mismatch, self.gap,
                                 n_threads=self.num_threads)
            except Exception as exc:
                # the host batch itself died: leave the unbuilt windows
                # as None for the caller's per-window quarantine path
                # instead of losing the whole device pass's results
                if strict:
                    raise
                log_info("[racon_tpu::FusedPOA] warning: host fallback "
                         f"batch failed ({type(exc).__name__}: {exc}); "
                         f"{len(rest)} windows left to the caller")
            else:
                for i, r in zip(rest, host):
                    results[i] = r
                    statuses[i] = 1
        return results, statuses

    def _pack_chunk(self, windows, chunk):
        """Host-only packing for one window chunk: the init state plus
        every chained call's padded layer operands. Returns (state,
        [(depth_bucket, operand_arrays, layer_base), ...]) — no device
        interaction, so a pipeline pack worker can run it while an older
        chunk computes."""
        backbones = [windows[i][0][0] for i in chunk]
        bweights = [_weights_of(windows[i][0][1], len(windows[i][0][0]))
                    for i in chunk]
        state = self._init_state(backbones, bweights)
        depth = max(len(windows[i]) - 1 for i in chunk)
        done = 0
        plan = self._chain_plan(depth)
        # per-window constants, hoisted out of the chained-call loop:
        # layer order is a stable sort by begin, the host engine's visit
        # order (reference window.cpp:84-85)
        metas = [(sorted(windows[i][1:], key=lambda s: s[2]),
                  len(windows[i][0][0])) for i in chunk]
        calls = []
        for d in plan:
            seqs = np.full((self.B, d, self.L), 5, np.int8)
            lens = np.zeros((self.B, d), np.int32)
            wts = np.zeros((self.B, d, self.L), np.int8)
            rlo = np.full((self.B, d), -32768, np.int16)
            rhi = np.full((self.B, d), 32767, np.int16)
            band = np.zeros((self.B, d), np.int32)
            for k, (layers, bb_len) in enumerate(metas):
                offset = int(0.01 * bb_len)
                for dd in range(d):
                    li = done + dd
                    if li >= len(layers):
                        break
                    seq, qual, b, e = layers[li]
                    seqs[k, dd, :len(seq)] = self._code_of[
                        np.frombuffer(seq, np.uint8)]
                    lens[k, dd] = len(seq)
                    wts[k, dd, :len(seq)] = _weights_of(qual, len(seq))
                    spanning = b < offset and e > bb_len - offset
                    span = bb_len if spanning else e - b + 1
                    if not spanning:
                        # non-spanning: bpos-range subgraph (reference
                        # window.cpp:97-102)
                        rlo[k, dd] = b
                        rhi[k, dd] = e
                    # the host engine's static-band rule (band 256 when
                    # the layer fits, exact DP otherwise)
                    if abs(len(seq) - span) < 256 // 2 - 16:
                        band[k, dd] = 256
            calls.append((d, (seqs, lens, wts, rlo, rhi, band), done))
            done += d
        return state, calls

    def _pack_chunk_fused(self, windows, chunk, D: int):
        """Host packing for one FUSED single-launch chunk: the init
        state plus ONE set of layer operands covering the whole chain
        depth `D` — raw (begin, end) coordinates and the per-row
        backbone length / spanning offset instead of host-derived
        rlo/rhi/band (that slicing now runs on device, `_call_fused`).
        Cheaper than the split packer by construction: no per-layer
        band/spanning Python work and one operand set instead of one
        per chained call."""
        backbones = [windows[i][0][0] for i in chunk]
        bweights = [_weights_of(windows[i][0][1], len(windows[i][0][0]))
                    for i in chunk]
        state = self._init_state(backbones, bweights)
        seqs = np.full((self.B, D, self.L), 5, np.int8)
        lens = np.zeros((self.B, D), np.int32)
        wts = np.zeros((self.B, D, self.L), np.int8)
        begins = np.zeros((self.B, D), np.int32)
        ends = np.zeros((self.B, D), np.int32)
        bblen = np.zeros(self.B, np.int32)
        offs = np.zeros(self.B, np.int32)
        for k, i in enumerate(chunk):
            layers = sorted(windows[i][1:], key=lambda s: s[2])
            bb_len = len(windows[i][0][0])
            bblen[k] = bb_len
            # float truncation kept bit-exact with the split packer
            offs[k] = int(0.01 * bb_len)
            for dd, (seq, qual, b, e) in enumerate(layers[:D]):
                seqs[k, dd, :len(seq)] = self._code_of[
                    np.frombuffer(seq, np.uint8)]
                lens[k, dd] = len(seq)
                wts[k, dd, :len(seq)] = _weights_of(qual, len(seq))
                begins[k, dd] = b
                ends[k, dd] = e
        return state, (seqs, lens, wts, begins, ends, bblen, offs)

    def _split_chunk_inline(self, windows, chunk, results, statuses,
                            watchdog=None, stats=None) -> None:
        """The DECLARED fallback of the fused single-launch program: a
        chunk whose fused dispatch failed (injected fault, watchdog
        timeout, real device error) is re-run through the SPLIT chained
        path — byte-identical to the fused program by construction,
        unlike the host-engine tail (which may resolve topo-order ties
        differently). Runs synchronously on the calling (pipeline
        error-handler) thread, with the pipeline's `watchdog` deadline
        (single attempt, no retry) guarding every device interaction —
        a chunk whose fused dispatch DeadlineTimed-out on a wedged
        device must not hang forever in its own fallback. Compile
        telemetry still flows through `_call`; occupancy is not
        recorded for the retry (the existing discipline: a faulted
        chunk is never accounted as clean device work)."""
        state, calls = self._pack_chunk(windows, chunk)
        for d, ops, done in calls:
            dispatch = functools.partial(self._call, d, state, *ops,
                                         done)
            state = (watchdog.call(dispatch, stats=stats, retry=False,
                                   deadline=True)
                     if watchdog is not None else dispatch())

        def fetch():
            return tuple(np.asarray(x) for x in state)

        np_state = (watchdog.call(fetch, stats=stats, retry=False)
                    if watchdog is not None else fetch())
        self._finalize_chunk(chunk, np_state, results, statuses)

    def _finalize_chunk(self, chunk, state, results, statuses):
        from ..native import poa_finish_arrays

        (codes, preds, predw, nseq, col_of, colkey, colnodes,
         bpos, n_nodes, n_cols, failed) = (np.asarray(x) for x in state)
        okrows = [k for k in range(len(chunk)) if not failed[k]]
        if okrows:
            sel = np.asarray(okrows)
            fin = poa_finish_arrays(
                codes[sel], preds[sel], predw[sel], nseq[sel],
                col_of[sel], colkey[sel], n_nodes[sel],
                n_threads=self.num_threads)
            for k, r in zip(okrows, fin):
                results[chunk[k]] = r
                statuses[chunk[k]] = 0
