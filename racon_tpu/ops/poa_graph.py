"""Evolving-graph POA consensus with the graph DP on device.

The consensus role of GenomeWorks cudapoa (reference src/cuda/cudabatch.cpp)
rebuilt TPU-first. cudapoa keeps the whole POA — graph storage, DP, and
consensus — inside one CUDA block per window; that pointer-chasing design
has no good mapping onto the TPU's dense vector units or XLA's static-shape
model. The split here keeps the *irregular* graph bookkeeping on the host
(C++ session, native/src/session.cpp) and moves the *regular* hot loop — the
O(nodes x len) graph-banded NW DP plus traceback — onto the device as one
batched fixed-shape XLA program:

  - the host densifies each window's current graph into topo-ordered arrays
    (node codes, predecessor rank lists, band centers, sink flags);
  - the device kernel scans nodes in topological order (`lax.scan`), each
    step computing one DP row for the whole batch: gather at most P
    predecessor rows, diagonal/vertical maxima, then the in-row gap
    recurrence as a running max (`lax.cummax`) — a formulation with no
    sequential dependence along the row, so every step is a wide vector op
    over [batch, len] lanes;
  - backpointers are derived from score equalities with the same tie order
    as the host engine (diagonal > vertical > horizontal, predecessors in
    edge order), and the traceback runs on device as a `lax.while_loop`
    (it exits as soon as every lane's path is complete rather than paying
    the worst-case node-count bound);
  - the resulting per-base node ranks are committed back into the C++
    session, which ingests them with the exact evolving-graph add_alignment
    the host engine uses.

Because each layer is aligned against the *evolving* graph — seeing every
earlier layer's insertions — and both DP and tie-breaking replicate the host
engine bit-for-bit (including the static-band masking and the clipped-band
full-DP retry), the device engine produces byte-identical consensus to the
host engine (tests/test_device_poa.py asserts this window-for-window). The
reference accepts backend divergence and pins its GPU numbers separately
(test/racon_test.cpp:292-496); this design does not have to.

Shape discipline (the cudapoa BatchConfig role, cudabatch.cpp:56-59): the
envelope is sized to what w=500 polishing actually needs — graphs beyond it
fall back to the host engine per window, the reference's GPU->CPU fallback
(cudapolisher.cpp:354-383). Jobs are padded into a FIXED set of
(nodes, len) buckets, each with ONE pinned batch size derived from the
device's free-memory query (the 90%-of-free-VRAM rule of
cudapolisher.cpp:169-173,230-239), and every program is compiled up front
by `precompile()` — so the steady-state loop never compiles.

The scheduling loop is pipelined: each round's batches are dispatched
asynchronously, and the host commits round k's results (mutating the POA
graphs) while round k+1 computes on device — the stream-overlap role of
cudapolisher.cpp:165-199. The batch axis is sharded across every device via
parallel/mesh.py — the multi-chip analogue of cudapoa's batch-per-GPU loop
(src/cuda/cudapolisher.cpp:228-345).
"""

from __future__ import annotations

import functools
import os
from collections import deque

import numpy as np

from ..obs import trace
from ..utils.logger import Logger, log_info

#: kernel shape envelope: max graph nodes, max layer len, max node
#: in-degree. Sized from measurement so w=500 ONT polishing fits entirely
#: (lambda sample, depth <= 38: graphs grow to ~2000 nodes with layer
#: insertions, layer slices <= 634 bp, in-degree <= 8 — envelope sweep in
#: round 4 gave 0/96 host fallbacks at 2048/640/8 vs 39/96 at 1280);
#: larger windows host-fallback per window. Round-5 measurement: at 30x
#: coverage the default envelope device-builds 98.7% of windows (500 kb
#: x 30x with exact overlap coordinates; a 2048-vs-3072 sweep changed
#: NOTHING — the once-suspected "node envelope binds at 30x" was a
#: synthbench coordinate-drift artifact, see PARITY.md). For workloads
#: whose graphs genuinely exceed the envelope, RACON_TPU_MAX_NODES
#: overrides it at ~linear per-row memory cost; the override resolves
#: at ENGINE CONSTRUCTION (like every other RACON_TPU_* knob), not at
#: import.
MAX_NODES = 2048
MAX_LEN = 640
MAX_PRED = 8


def env_max_nodes(default: int = MAX_NODES) -> int:
    """The node envelope both engines use when the caller doesn't pass
    one: RACON_TPU_MAX_NODES when set to a sane positive integer, else
    `default`. Invalid values warn and fall back instead of crashing
    the import or silently emptying the bucket ladder."""
    raw = os.environ.get("RACON_TPU_MAX_NODES")
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        v = -1
    # upper bound: beyond 32k nodes a single DP row costs ~100 MB and a
    # typo'd extra digit should warn, not OOM the device
    if v < 512 or v > 32768:
        log_info(f"[racon_tpu::env_max_nodes] warning: ignoring invalid "
                 f"RACON_TPU_MAX_NODES={raw!r} (want an integer in "
                 "[512, 32768])")
        return default
    return v

#: the full (nodes, len) bucket grid — every job shape is padded up into
#: one of these four compiled programs (plus one batch size each). Graphs
#: start at backbone size (~500) and grow as layers commit, so jobs climb
#: the ladder over a window's lifetime; (320, 256) catches NGS reads and
#: small subgraphs.
BUCKETS = ((320, 256), (768, 640), (1280, 640), (MAX_NODES, MAX_LEN))

#: jobs requested from the session per scheduling round (enough that every
#: ready window contributes a layer even on large inputs)
_CYCLE_JOBS = 1024

_NEG = -(1 << 29)  # matches the host engine's kNegInf (INT32_MIN / 4)


def _materialize(out) -> np.ndarray:
    """Block on one dispatched batch's results; multi-device pallas
    dispatches come back as a per-device list of shards."""
    if isinstance(out, list):
        return np.concatenate([np.asarray(o) for o in out])
    return np.asarray(out)


def _bytes_per_row(n_nodes: int, seq_len: int, max_pred: int) -> int:
    """Peak device bytes one batch row costs while its program runs: the
    H score carry, the backpointer stack (plus its traceback copy), and
    the densified inputs."""
    h = (n_nodes + 1) * (seq_len + 1) * 4
    bp = 2 * n_nodes * (seq_len + 1)
    inputs = n_nodes * (2 * max_pred + 4) + seq_len
    return h + bp + inputs


def pin_pow2_rows(budget: int, per_row: int, lo: int = 8,
                  hi: int = 128) -> int:
    """Shared batch-width pinning policy: the largest power of two whose
    rows fit `budget`, clamped to [lo, hi] — ONE size per program so the
    compile count stays fixed."""
    b = 1 << max(0, (budget // max(per_row, 1)).bit_length() - 1)
    return max(lo, min(hi, b))


def _device_budget(devices) -> int:
    """Free device memory to size batches from — queried from the chip
    like the reference's cudaMemGetInfo 90% rule
    (cudapolisher.cpp:169-173,230-239); conservative fallback when the
    backend exposes no stats (CPU test backend).

    RACON_TPU_DEVICE_MEM (bytes) overrides everything — the operator
    escape hatch for backends whose memory_stats() is missing or wrong
    (round-4 verdict #8: the axon shim may expose no stats, and nothing
    recorded which path sized the batches). The chosen branch is logged
    on stderr once per process so every run's artifact shows whether a
    real free-memory reading drove the batch widths."""
    dev = devices[0]
    budget = 0
    override = os.environ.get("RACON_TPU_DEVICE_MEM")
    if override:
        try:
            budget = int(override)
        except ValueError:
            budget = 0
        if budget > 0:
            kind = "override"
            branch = f"RACON_TPU_DEVICE_MEM override ({budget} bytes)"
        else:
            log_info(f"[racon_tpu::device_budget] warning: ignoring invalid "
                     f"RACON_TPU_DEVICE_MEM={override!r} (want a positive "
                     "byte count)")
    if budget <= 0:
        branch = ""
        kind = ""
        try:
            stats = dev.memory_stats()
            free = int(stats["bytes_limit"]) - int(stats["bytes_in_use"])
            if free > 0:
                budget = int(free * 0.9)
                kind = "memory_stats"
                branch = (f"memory_stats query (limit {stats['bytes_limit']},"
                          f" in_use {stats['bytes_in_use']}, 90% of free ="
                          f" {budget})")
        except Exception as exc:
            kind = f"unavailable:{type(exc).__name__}"
            branch = f"memory_stats unavailable ({type(exc).__name__})"
        if budget <= 0:
            # any accelerator (the axon TPU shim reports its own platform
            # name) gets the TPU-sized default; CPU test backend stays small
            budget = (64 << 20) if dev.platform == "cpu" else (4 << 30)
            kind += f";default:{dev.platform}"
            branch += f"; hardcoded default for platform={dev.platform!r}"
    # dedup on the branch KIND, not the volatile byte readings, so a run
    # logs each sizing path once rather than once per query
    if kind not in _budget_logged:
        _budget_logged.add(kind)
        log_info(f"[racon_tpu::device_budget] {branch} -> {budget} bytes "
                 f"(platform {dev.platform})")
    return budget


_budget_logged: set = set()


#: DP-carry ring depth for the ringed program variant: covers the
#: measured max predecessor rank distance across BOTH measured datasets
#: (lambda sample: 29, 99.95% of edges within 16; synthbench 250 kb x
#: 20x ONT-like: 72 — measured via RACON_TPU_ENVELOPE_STATS in round 5)
#: with ~1.8x headroom over the worst observation. Round 4 shipped
#: RING=64, which the second dataset EXCEEDED — that would have fired
#: the round-3 failure mode (lazy mid-run full-carry compile) on chip.
#: Batches that still exceed it are routed to the full-carry program —
#: compiled lazily on first occurrence (one-time, cache-persisted).
#: The fused engine fails >RING lanes to the host engine per window, so
#: this constant bounds its real-data eligibility too.
RING = 128


def max_pred_distance(preds: np.ndarray) -> int:
    """Max topological back-reach of any predecessor in densified job
    arrays ([B, N, P] DP-row indices, rank+1; 0 = virtual source, -1
    pad). Row k+1 reading row r is ring-safe iff k+1-r <= RING."""
    k1 = np.arange(1, preds.shape[1] + 1, dtype=np.int32)[None, :, None]
    return int(np.where(preds > 0, k1 - preds, 0).max(initial=0))


def _mark_compiled(eng, nb: int, lb: int, ring_ok: bool, seconds: float,
                   kernel: str = "xla", dtype: str = "int32",
                   packed: bool = False) -> None:
    """First-dispatch compile telemetry (the shared OccupancyStats
    record_compile_once idiom): the key is the full program identity —
    bucket shape, pinned batch width, ring variant, scoring, engine,
    and the kernel-plane choices (pallas/xla, score dtype, packed
    operands) that each compile a distinct program."""
    eng.sched.stats.record_compile_once(
        "session",
        (nb, lb, eng.batch_rows.get((nb, lb)), bool(ring_ok),
         eng.match, eng.mismatch, eng.gap, eng.max_pred, kernel, dtype,
         packed),
        seconds)


@functools.lru_cache(maxsize=None)
def graph_aligner(n_nodes: int, seq_len: int, max_pred: int, match: int,
                  mismatch: int, gap: int, ring: int = 0,
                  score_dtype: str = "int32", packed_seq: bool = False):
    """Jitted batched graph-NW align + traceback for one shape bucket.

    Args (all leading dim B = batch; preds/centers ship as int16 — half
    the host->device bytes, upcast on device):
      codes   [B, N] int8   topo-ordered node base codes (pad 5)
      preds   [B, N, P] int16  predecessor DP-row indices (rank+1; 0 is the
                               virtual source row; -1 pad)
      centers [B, N] int16  band center column per node (bpos - origin + 1)
      sinks   [B, N] uint8  1 = sink node
      seq     [B, L] int8   layer base codes (pad 5)
      lens    [B]    int32  layer lengths
      band    [B]    int32  static band width (0 = exact full DP)

    Returns ranks [B, L] int16: for layer base i, the 0-based topo rank of
    the node it aligned to, or -1 for an insertion (-2 beyond lens).

    `ring > 0` carries only the last `ring` DP rows (plus the virtual
    source row) instead of all N+1 — a ~N/ring reduction of the scan
    carry's footprint — and is valid ONLY when every predecessor is
    within `ring` ranks of its node (the dispatcher checks the densified
    preds and falls back to the full-carry program otherwise). Results
    are bit-identical between the two variants; per-node sink scores are
    collected into a side carry as rows retire.

    `score_dtype='int16'` halves the DP carry and backpointer-source
    rows (legal only under ops/dtypes.poa_int16_ok's per-bucket
    overflow proof; bit-identical by construction). `packed_seq` takes
    the layer bases 2-bit packed ([B, L//4] uint8, encode.pack_2bit)
    and unpacks + pad-restores them on device from `lens` — a 4x cut in
    per-layer sequence traffic for ACGT-only windows.
    """
    import jax
    import jax.numpy as jnp

    N, L, P = n_nodes, seq_len, max_pred
    DT = jnp.int16 if score_dtype == "int16" else jnp.int32
    NEG = jnp.asarray(-(1 << 14) if score_dtype == "int16" else _NEG, DT)
    W = ring

    def align(codes, preds, centers, sinks, seq, lens, band):
        B = codes.shape[0]
        if packed_seq:
            from .encode import unpack_2bit_jax

            seq = unpack_2bit_jax(seq, L, lens)
        preds = preds.astype(jnp.int32)
        centers = centers.astype(jnp.int32)
        jidx = jnp.arange(L + 1, dtype=jnp.int32)
        jg = (jidx * gap).astype(DT)
        l32 = lens.astype(jnp.int32)
        band2 = (band // 2).astype(jnp.int32)

        # virtual source row: D[0][j] = j*gap within the layer, NEG beyond
        h0 = jnp.where(jidx[None, :] <= l32[:, None], jg[None, :], NEG)
        if W:
            # ring carry: slot 0 = virtual source (always resident), slot
            # 1 + (r-1) % W = DP row r; scores side-carry collects each
            # row's sink-column value as it is produced
            H = jnp.full((B, W + 1, L + 1), NEG, dtype=DT)
            H = H.at[:, 0, :].set(h0)
            scores0 = jnp.full((B, N), NEG, dtype=DT)
        else:
            H = jnp.full((B, N + 1, L + 1), NEG, dtype=DT)
            H = H.at[:, 0, :].set(h0)

        def step(carry, xs):
            if W:
                H, scores = carry
            else:
                H = carry
            code_k, preds_k, center_k, k = xs  # [B], [B,P], [B], scalar
            if W:
                pk = jnp.where(preds_k > 0,
                               1 + jax.lax.rem(preds_k - 1,
                                               jnp.int32(W)), 0)
                pk = jnp.clip(pk, 0, W)
            else:
                pk = jnp.clip(preds_k, 0, N)
            rows = jnp.take_along_axis(H, pk[:, :, None], axis=1)
            rows = jnp.where((preds_k >= 0)[:, :, None], rows, NEG)
            sub = jnp.where(seq == code_k[:, None], match,
                            mismatch).astype(DT)                 # [B, L]
            diag = rows[:, :, :-1] + sub[:, None, :]             # [B, P, L]
            vert = rows[:, :, 1:] + gap                          # [B, P, L]
            best = jnp.max(jnp.maximum(diag, vert), axis=1)      # [B, L]
            row0 = jnp.max(rows[:, :, 0], axis=1) + gap          # [B]

            # static-band masking, replicating the host engine exactly:
            # out-of-band cells are NEG, and the in-row gap recurrence only
            # propagates within the band (seeded from column 0 only when
            # the band touches it)
            use_band = band > 0
            jlo = jnp.where(use_band, jnp.maximum(1, center_k - band2), 1)
            jhi = jnp.where(use_band, jnp.minimum(l32, center_k + band2),
                            l32)
            inband = ((jidx[None, 1:] >= jlo[:, None]) &
                      (jidx[None, 1:] <= jhi[:, None]))          # [B, L]
            pre = jnp.where(inband, best, NEG)
            seed0 = jnp.where(jlo == 1, row0, NEG)
            cat = jnp.concatenate([seed0[:, None], pre], axis=1)
            run = jax.lax.cummax(cat - jg, axis=1) + jg
            hrow = jnp.where(inband, run[:, 1:], pre)
            new_row = jnp.concatenate([row0[:, None], hrow], axis=1)

            # backpointers from score equalities against the final row;
            # tie order matches the host traceback (poa.cpp align_nw):
            # diagonal first (predecessors in edge order), then vertical,
            # then horizontal. Encoding: p = diag via pred p; P+p = vert
            # via pred p; 2P = horizontal.
            nr = new_row[:, 1:]
            is_diag = nr[:, None, :] == diag
            is_vert = nr[:, None, :] == vert
            pd = jnp.argmax(is_diag, axis=1).astype(jnp.int32)
            pv = jnp.argmax(is_vert, axis=1).astype(jnp.int32)
            bpc = jnp.where(jnp.any(is_diag, axis=1), pd,
                            jnp.where(jnp.any(is_vert, axis=1), P + pv,
                                      2 * P))
            is_v0 = row0[:, None] == rows[:, :, 0] + gap         # [B, P]
            bp0 = P + jnp.argmax(is_v0, axis=1).astype(jnp.int32)
            bp_row = jnp.concatenate([bp0[:, None], bpc],
                                     axis=1).astype(jnp.int8)

            if W:
                slot = 1 + jax.lax.rem(k - 1, jnp.int32(W))
                H = jax.lax.dynamic_update_slice(
                    H, new_row[:, None, :],
                    (jnp.int32(0), slot, jnp.int32(0)))
                sc = jnp.take_along_axis(new_row, l32[:, None], axis=1)
                scores = jax.lax.dynamic_update_slice(
                    scores, sc, (jnp.int32(0), k - 1))
                return (H, scores), bp_row
            H = jax.lax.dynamic_update_slice(
                H, new_row[:, None, :], (jnp.int32(0), k, jnp.int32(0)))
            return H, bp_row

        ks = jnp.arange(1, N + 1, dtype=jnp.int32)
        # unroll on TPU: the scan body is small relative to the While-loop
        # iteration overhead at N=2048 steps; CPU (tests) keeps compiles fast
        # (the axon TPU shim reports a non-"tpu" platform name, so key off
        # not-cpu rather than equality)
        unroll = 1 if jax.default_backend() == "cpu" else 4
        carry, bps = jax.lax.scan(
            step, (H, scores0) if W else H,
            (codes.T, preds.transpose(1, 0, 2), centers.T, ks),
            unroll=unroll)
        # bps: [N, B, L+1] int8

        # best sink at the layer's final column; ties -> smallest rank
        # (host: ascending scan keeping strict improvements)
        if W:
            scores = carry[1]                                    # [B, N]
        else:
            H = carry
            flat_h = H.reshape(B, (N + 1) * (L + 1))
            ridx = (jnp.arange(1, N + 1, dtype=jnp.int32)[None, :]
                    * (L + 1) + l32[:, None])
            scores = jnp.take_along_axis(flat_h, ridx, axis=1)   # [B, N]
        cand = jnp.where(sinks > 0, scores, NEG)
        best_rank = jnp.argmax(cand, axis=1).astype(jnp.int32)

        bp_flat = bps.transpose(1, 0, 2).reshape(B, N * (L + 1))
        preds_flat = preds.reshape(B, N * P)
        rows_b = jnp.arange(B)

        def cond(st):
            r, j, _ = st
            return jnp.any((r > 0) | (j > 0))

        def body(st):
            r, j, out = st
            active = (r > 0) | (j > 0)
            lin = (jnp.clip(r - 1, 0, N - 1) * (L + 1)
                   + jnp.clip(j, 0, L))
            code = jnp.take_along_axis(
                bp_flat, lin[:, None], axis=1)[:, 0].astype(jnp.int32)
            code = jnp.where(r > 0, code, 2 * P)  # source row: horizontal
            is_diag = code < P
            is_vert = (code >= P) & (code < 2 * P)
            p = jnp.where(is_diag, code, code - P)
            plin = (jnp.clip(r - 1, 0, N - 1) * P
                    + jnp.clip(p, 0, P - 1))
            pr = jnp.take_along_axis(preds_flat, plin[:, None],
                                     axis=1)[:, 0]
            consume = active & ~is_vert                # diag or horizontal
            jc = jnp.clip(j - 1, 0, L - 1)
            cur = jnp.take_along_axis(out, jc[:, None], axis=1)[:, 0]
            emit = jnp.where(is_diag, r - 1, -1).astype(jnp.int16)
            out = out.at[rows_b, jc].set(jnp.where(consume, emit, cur))
            r = jnp.where(active & (is_diag | is_vert), pr, r)
            j = jnp.where(consume, j - 1, j)
            return r, j, out

        # int16 output: rank < N <= 32767; halves the device->host bytes
        out0 = jnp.full((B, L), -2, dtype=jnp.int16)
        _, _, ranks = jax.lax.while_loop(
            cond, body, (best_rank + 1, l32, out0))
        return ranks

    return jax.jit(align)


class DeviceGraphPOA:
    """Orchestrates the session <-> device scheduling loop.

    Each round: ask the C++ session for the next ready layer of up to
    `_CYCLE_JOBS` windows, bucket the jobs by (graph size, layer length),
    pad each bucket to its pinned batch size and dispatch (async), then
    commit the OLDEST in-flight batch — so the host's graph ingest always
    overlaps the device's compute on the younger batches.

    The envelope/bucket/batch-size knobs exist so tests can force tiny
    shapes (and the unfit-fallback paths) without a real chip.
    """

    def __init__(self, match: int, mismatch: int, gap: int,
                 num_threads: int = 1, logger: Logger | None = None,
                 max_nodes: int | None = None, max_len: int = MAX_LEN,
                 max_pred: int = MAX_PRED, buckets=None,
                 batch_rows: int | None = None, cycle_jobs: int = _CYCLE_JOBS,
                 banded_only: bool = False, use_pallas: bool | None = None,
                 scheduler=None, runner=None):
        from ..parallel.mesh import BatchRunner
        from ..sched import BatchScheduler

        if max_nodes is None:
            max_nodes = env_max_nodes()
        # occupancy-aware scheduler (sched/): adaptive (nodes, len) grid
        # + sorted packing when armed, occupancy telemetry always
        self.sched = (scheduler if scheduler is not None
                      else BatchScheduler.from_env())
        #: RACON_TPU_PALLAS routes VMEM-sized buckets through the
        #: resident pallas window-sweep kernel (ops/poa_pallas.py)
        #: instead of the XLA scan program: `1` = always (when the VMEM
        #: envelope fits), `auto` = per-bucket via the persisted
        #: autotuner winner table (sched/autotune; no entry -> XLA,
        #: today's default), unset/0 = off. The constructor bool forces
        #: on/off for tests.
        from .poa_pallas import pallas_mode

        if use_pallas is None:
            self.pallas_posture = pallas_mode()
        else:
            self.pallas_posture = "on" if use_pallas else "off"
        self.use_pallas = self.pallas_posture != "off"
        #: per-bucket (use_pallas, score_dtype) dispatch plans, resolved
        #: lazily (the autotuner table / envelope proofs don't change
        #: within a run)
        self._plans: dict = {}

        self.match = match
        self.mismatch = mismatch
        self.gap = gap
        self.num_threads = num_threads
        self.logger = logger
        self.banded_only = banded_only
        # an explicit runner pins this engine to a sub-mesh (the serve
        # layer's worker lanes each pass their own); default is the full
        # auto-discovered mesh
        self.runner = runner if runner is not None else BatchRunner()
        self.max_nodes = max_nodes
        self.max_len = max_len
        self.max_pred = max_pred
        self.cycle_jobs = cycle_jobs
        self._forced_batch_rows = batch_rows
        self._set_buckets(tuple(buckets) if buckets is not None else tuple(
            b for b in BUCKETS if b[0] <= max_nodes and b[1] <= max_len))
        #: RACON_TPU_ENVELOPE_STATS=1: collect observed envelope maxima
        #: (nodes, len, pred distance, in-degree, depth) across the run —
        #: the measurement that justifies RING/MAX_* on new datasets
        self._env_stats = (
            {"max_nodes": 0, "max_len": 0, "max_pred_distance": 0,
             "max_in_degree": 0, "max_depth": 0}
            if os.environ.get("RACON_TPU_ENVELOPE_STATS") else None)

    def _set_buckets(self, buckets) -> None:
        """Install a bucket grid (envelope bucket appended as the safety
        net — every in-envelope job always fits SOME bucket) and pin one
        batch width per bucket."""
        self.buckets = tuple(buckets)
        if (not self.buckets or self.buckets[-1][0] < self.max_nodes
                or self.buckets[-1][1] < self.max_len):
            self.buckets = self.buckets + ((self.max_nodes, self.max_len),)
        self.batch_rows = {
            b: self._pin_batch(b, self._forced_batch_rows)
            for b in self.buckets}

    #: predicted graph growth per committed layer base: graphs start at
    #: backbone size and gain ~GROWTH nodes per aligned layer bp from
    #: insertions (lambda sample measurement: ~500 -> ~2000 nodes over
    #: 37 layers of ~550 bp, PARITY.md). The prediction only shapes the
    #: adaptive grid — a job outgrowing it first-fits a larger bucket or
    #: the envelope, so a wrong GROWTH costs padding, never correctness.
    GROWTH = 0.08

    def adapt(self, windows) -> None:
        """Derive the adaptive (nodes, len) grid from the window set (the
        job-shape histogram at run start: one predicted job per layer).
        No-op when the scheduler is off. Called by consensus() and by
        precompile(windows=...) so the bench can warm the same shapes the
        polish run will use."""
        if not self.sched.adaptive:
            return
        shapes: list[tuple[int, int]] = []
        for w in windows:
            if len(w) < 3:
                continue
            nodes = len(w[0][0]) + 1
            # host-engine visit order (begin-sorted, window.cpp:84-85):
            # early layers align small graphs, late ones the grown graph
            for seq, _, _, _ in sorted(w[1:], key=lambda s: s[2]):
                shapes.append((min(self.max_nodes, int(nodes)), len(seq)))
                nodes += self.GROWTH * len(seq)
        grid = self.sched.poa_grid(shapes, k=len(BUCKETS),
                                   max_nodes=self.max_nodes,
                                   max_len=self.max_len)
        if grid:
            self._set_buckets(grid)

    def _pin_batch(self, bucket, forced) -> int:
        """ONE batch size per bucket: the largest power of two whose peak
        footprint fits a quarter of the device budget (several batches are
        in flight while the pipeline is full), rounded to the device count."""
        n_dev = self.runner.n_devices
        if forced is not None:
            b = forced
        else:
            budget = _device_budget(self.runner.devices) // 4
            row = _bytes_per_row(bucket[0], bucket[1], self.max_pred)
            b = pin_pow2_rows(budget, row)
        return max(n_dev, (b // n_dev) * n_dev)

    def precompile(self, windows=None) -> None:
        """Compile every (bucket, pinned batch size) program up front so
        the scheduling loop never stalls on XLA (VERDICT r3: mid-run
        compiles were the prime suspect in the on-chip failure).

        With the adaptive scheduler armed, pass the window set so the
        DERIVED grid is what gets compiled — the ladder is a pure
        function of the windows, so a later engine instance adapting to
        the same windows reuses these programs via the jit cache."""
        import time

        if windows is not None:
            self.adapt(windows)
        for (nb, lb) in self.buckets:
            B = self.batch_rows[(nb, lb)]
            # a valid tiny problem: linear 2-node chain, 2-base layer
            codes = np.full((B, nb), 5, dtype=np.int8)
            codes[:, :2] = 0
            preds = np.full((B, nb, self.max_pred), -1, dtype=np.int16)
            preds[:, 0, 0] = 0
            preds[:, 1, 0] = 1
            centers = np.zeros((B, nb), dtype=np.int16)
            centers[:, :2] = (1, 2)
            sinks = np.zeros((B, nb), dtype=np.uint8)
            sinks[:, 1] = 1
            seq = np.full((B, lb), 5, dtype=np.int8)
            seq[:, :2] = 0
            lens = np.full(B, 2, dtype=np.int32)
            band = np.zeros(B, dtype=np.int32)
            # through the run's own dispatch entry point, so the warmed
            # program (kernel choice, dtype, packing) is EXACTLY the one
            # the scheduling loop will request
            nnodes = np.full(B, 2, dtype=np.int32)
            out = self._run_bucket(nb, lb, codes, preds, centers, sinks,
                                   seq, lens, band, nnodes)
            _materialize(out)  # block
            from .encode import pack_bases_enabled

            if pack_bases_enabled():
                # the ACGT-only job above warmed the packed-operand
                # program; real data carries N/IUPAC windows whose
                # batches request the UNPACKED variant — a distinct
                # program that must not compile cold mid-run
                seq_n = seq.copy()
                seq_n[:, 1] = 4
                out = self._run_bucket(nb, lb, codes, preds, centers,
                                       sinks, seq_n, lens, band, nnodes)
                _materialize(out)

    def _bucket(self, n_nodes: int, length: int) -> tuple[int, int]:
        return next((nb, lb) for nb, lb in self.buckets
                    if n_nodes <= nb and length <= lb)

    def consensus(self, windows):
        """windows: list of lists of (seq, qual|None, begin, end), element 0
        the backbone. Returns (results, statuses): results like poa_batch's
        [(consensus bytes, coverages)], statuses int array (0 device,
        1 host fallback, 2 backbone-only)."""
        from ..native import PoaSession

        # adaptive grid from the run's own job-shape histogram (no-op
        # when the scheduler is off — the static grid stays)
        self.adapt(windows)
        session = PoaSession(windows, self.match, self.mismatch, self.gap,
                             self.max_nodes, self.max_pred, self.max_len,
                             max_jobs=self.cycle_jobs,
                             banded_only=self.banded_only,
                             n_threads=self.num_threads)
        bar = self.logger.bar if self.logger is not None else None
        total_layers = sum(max(0, len(w) - 1) for w in windows)
        if self.logger is not None and total_layers:
            self.logger.bar_total(total_layers)

        # split-half pipelining: each prepare() pulls at most HALF the
        # active windows (round-robin), so while half A's results are
        # committed (mutating graphs), half B computes on device — and
        # every batch stays large (few device calls, few round trips)
        # instead of fragmenting to whatever the last commit freed.
        import os

        n_active = sum(1 for w in windows if len(w) >= 3)
        # RACON_TPU_SCHED_HALVES: windows per prepare = active/H. H=2
        # overlaps host ingest with device compute; H=1 minimizes device
        # round trips (serial rounds) — tune per link latency
        halves = max(1, int(os.environ.get("RACON_TPU_SCHED_HALVES", "2")))
        half = max(8, min(self.cycle_jobs, max(1, n_active // halves)))
        # how many dispatched batches to keep queued: enough to hide the
        # host's commit+prepare time behind device compute, small enough
        # to bound queued transfers on large inputs
        depth = 4
        # prepare only in BURSTS — once enough windows have been freed by
        # commits to fill a decent batch — otherwise each commit's handful
        # of freed windows would round-trip as a tiny fragment batch
        threshold = 1
        freed = 1
        inflight: deque = deque()
        while True:
            if freed >= threshold or not inflight:
                burst = 0
                while len(inflight) < depth:
                    jobs = session.prepare(half)
                    if jobs is None:
                        break
                    burst += jobs["n"]
                    inflight.extend(self._dispatch_round(jobs))
                if burst:
                    freed = 0
                    threshold = max(8, burst // 2)
            if not inflight:
                break
            # commit the oldest batch (blocks only on ITS device result;
            # younger batches keep computing via async dispatch)
            win, layer, band, npart, lb, out, rows = inflight.popleft()
            with trace.span("session.commit", engine="session",
                            jobs=npart):
                # gather by the dispatch scatter's row map (job j is on
                # row rows[j], not row j)
                ranks = _materialize(out)[rows][:, :lb]
                session.commit(win, layer, band, ranks)
            freed += npart
            if bar is not None:
                for _ in range(npart):
                    bar("[racon_tpu::Polisher.polish] "
                        "aligning layers to graphs on device")
        self.last_stats = session.stats()
        if self._env_stats is not None:
            self._env_stats["max_depth"] = max(
                (len(w) - 1 for w in windows), default=0)
            log_info(f"[racon_tpu::DeviceGraphPOA] envelope stats: "
                     f"{self._env_stats} (envelope: nodes {self.max_nodes}, "
                     f"len {self.max_len}, pred {self.max_pred}, "
                     f"RING {RING})")
        return session.finish(self.num_threads)

    #: bucket groups smaller than this merge upward into the next larger
    #: nonempty bucket: a slightly longer scan for a few jobs beats paying
    #: another device round trip for a nearly-empty batch
    MIN_FILL = 16

    def _dispatch_round(self, jobs):
        """Bucket one prepare() round and dispatch every batch async.
        Returns [(win, layer, band, n_jobs, len_bucket, device_out)] —
        everything needed for commit is snapshotted so the session's
        prepare buffers can be reused immediately."""
        n = jobs["n"]
        if self._env_stats is not None:
            # RACON_TPU_ENVELOPE_STATS: record the run's observed maxima
            # so the RING/MAX_NODES/MAX_LEN/MAX_PRED envelope constants
            # can be justified against more datasets than the lambda
            # sample (round-4 verdict #7)
            st = self._env_stats
            st["max_nodes"] = max(st["max_nodes"],
                                  int(jobs["nnodes"][:n].max(initial=0)))
            st["max_len"] = max(st["max_len"],
                                int(jobs["len"][:n].max(initial=0)))
            st["max_pred_distance"] = max(
                st["max_pred_distance"],
                max_pred_distance(jobs["preds"][:n]))
            st["max_in_degree"] = max(
                st["max_in_degree"],
                int((jobs["preds"][:n] >= 0).sum(axis=2).max(initial=0)))
        groups: dict[tuple[int, int], list[int]] = {}
        for i in range(n):
            b = self._bucket(int(jobs["nnodes"][i]), int(jobs["len"][i]))
            groups.setdefault(b, []).append(i)

        # merge under-filled groups upward (jobs always fit any larger
        # bucket) so each round dispatches few, well-filled batches
        order = sorted(groups)
        for gi, b in enumerate(order[:-1]):
            if len(groups.get(b, ())) < self.MIN_FILL:
                for nb in order[gi + 1:]:
                    if groups.get(nb) and nb[0] >= b[0] and nb[1] >= b[1]:
                        groups[nb] = groups.pop(b) + groups[nb]
                        break

        batches = []
        for (nb, lb), idx in sorted(groups.items()):
            # sorted packing: shape-homogeneous batches within the bucket
            # (commits key on (win, layer), so cross-window dispatch
            # order is free); identity when the scheduler is off
            idx = self.sched.order(
                idx, key=lambda i: (int(jobs["nnodes"][i]),
                                    int(jobs["len"][i])))
            B = self.batch_rows[(nb, lb)]
            for s in range(0, len(idx), B):
                part = idx[s:s + B]
                sel = np.asarray(part, dtype=np.int64)
                meta = (jobs["win"][sel].copy(), jobs["layer"][sel].copy(),
                        jobs["band"][sel].copy())
                with trace.span("session.dispatch", engine="session",
                                bucket=f"{nb}x{lb}", jobs=len(part)):
                    out, rows = self._dispatch(jobs, sel, nb, lb, B)
                # occupancy recorded AFTER the dispatch call returned
                # (the aligner's discipline: a batch killed before the
                # device saw it must not be accounted as device work)
                use_pallas, dtype = self._plan(nb, lb)
                # mesh view: job j landed on shard j % n_devices (the
                # _dispatch round-robin scatter), so per-shard useful
                # cells — the balance the scale curve gates on — come
                # from strided sums. The batch is always padded to the
                # pinned width B (a per-tail program shape would
                # compile cold mid-run), so the full-mesh baseline
                # equals the dispatched capacity.
                n_dev = self.runner.n_devices
                row_cells = (jobs["nnodes"][sel].astype(np.int64)
                             * (jobs["len"][sel].astype(np.int64) + 1))
                shard_useful = [int(row_cells[s::n_dev].sum())
                                for s in range(n_dev)]
                self.sched.stats.record(
                    "session", (nb, lb), jobs=len(part), lanes=B,
                    useful_cells=int(row_cells.sum()),
                    total_cells=B * nb * (lb + 1),
                    kernel="pallas" if use_pallas else "xla", dtype=dtype,
                    n_devices=n_dev, shard_useful=shard_useful,
                    full_mesh_cells=B * nb * (lb + 1))
                batches.append(meta + (len(part), lb, out, rows))
        return batches

    def _plan(self, nb, lb) -> tuple[bool, str]:
        """(use_pallas, score_dtype) for one bucket — the kernel-plane
        dispatch decision: the Pallas posture (forced / env / the
        persisted autotuner winner table under `auto`), the corrected
        VMEM envelope gate, and the dtype-shrinking proof
        (ops/dtypes.poa_int16_ok; int32 whenever it fails)."""
        plan = self._plans.get((nb, lb))
        if plan is None:
            from .dtypes import kernel_plan, poa_int16_ok
            from .poa_pallas import fits_vmem

            plan = self._plans[(nb, lb)] = kernel_plan(
                self.pallas_posture, "session", (nb, lb),
                (self.match, self.mismatch, self.gap, self.max_pred),
                poa_int16_ok(nb, lb, self.match, self.mismatch, self.gap),
                lambda dt: fits_vmem(nb, lb, self.max_pred, dt))
        return plan

    def _scan_kernel(self, nb, lb, ring_ok: bool = True,
                     score_dtype: str = "int32",
                     packed_seq: bool = False):
        """The XLA scan program for a bucket: ring-carried (last RING rows
        only, ~nb/RING smaller carry) when every predecessor in the batch
        is within RING ranks, full-carry otherwise (lazy-compiled; see
        RING)."""
        ring = RING if (ring_ok and nb > RING) else 0
        if not ring_ok and not getattr(self, "_warned_full", False):
            self._warned_full = True
            log_info("[racon_tpu::DeviceGraphPOA] long back-edge batch: "
                     "using the full-carry DP program")
        # default-valued kwargs are omitted so the lru key (and thus the
        # jit cache entry) is shared with plain graph_aligner(...) calls
        kwargs: dict = {}
        if score_dtype != "int32":
            kwargs["score_dtype"] = score_dtype
        if packed_seq:
            kwargs["packed_seq"] = True
        return graph_aligner(nb, lb, self.max_pred, self.match,
                             self.mismatch, self.gap, ring=ring, **kwargs)

    def _run_bucket(self, nb, lb, codes, preds, centers, sinks, seqs,
                    lens, band, nnodes):
        """Dispatch ONE padded batch through the bucket's planned
        program — the single device entry point shared by precompile()
        and the scheduling loop, so the programs warmed up front are
        exactly the programs the run requests. Handles the kernel
        choice (pallas/XLA), the score dtype, 2-bit operand packing
        (ACGT-only batches; the XLA path packs the layer bases, the
        pallas path additionally packs the node codes — it carries the
        per-job node counts the restore needs) and the first-dispatch
        compile telemetry."""
        import time

        import jax

        from .encode import pack_2bit, pack_bases_enabled, packable

        use_pallas, dtype = self._plan(nb, lb)
        can_pack = pack_bases_enabled() and packable(seqs, lens)
        t0 = time.perf_counter()
        if use_pallas:
            from .poa_pallas import window_sweep

            packed = can_pack and packable(codes, nnodes)
            # default kwargs omitted: lru/jit keys shared with direct
            # window_sweep(...) calls (profiling, tests)
            kwargs: dict = {}
            if dtype != "int32":
                kwargs["score_dtype"] = dtype
            if packed:
                kwargs["packed"] = True
            fn = window_sweep(nb, lb, self.max_pred, self.match,
                              self.mismatch, self.gap,
                              interpret=jax.default_backend() == "cpu",
                              **kwargs)
            c = pack_2bit(codes) if packed else codes
            s = pack_2bit(seqs) if packed else seqs
            # pallas path: per-job real node count bounds its row sweep
            out = self._run_pallas(fn, c, preds, centers, sinks, s,
                                   lens, band, nnodes)
            _mark_compiled(self, nb, lb, True,
                           time.perf_counter() - t0, kernel="pallas",
                           dtype=dtype, packed=packed)
            return out
        # ring validity: every predecessor within RING ranks of its node
        # (measured: 29 lambda / 72 synthbench, see RING; the full-carry
        # program covers the rare batch that exceeds it)
        ring_ok = max_pred_distance(preds) <= RING
        fn = self._scan_kernel(nb, lb, ring_ok=ring_ok, score_dtype=dtype,
                               packed_seq=can_pack)
        s = pack_2bit(seqs) if can_pack else seqs
        out = self.runner.run(fn, codes, preds, centers, sinks, s,
                              lens, band)
        _mark_compiled(self, nb, lb, ring_ok,
                       seconds=time.perf_counter() - t0, dtype=dtype,
                       packed=can_pack)
        return out

    def _dispatch(self, jobs, sel, nb, lb, B):
        """Pad/scatter one bucket batch and dispatch it. Returns
        (device_out, rows): `rows[j]` is the batch row job j landed on —
        round-robin across the mesh's per-device shards, so each device
        carries an even share of the real (and of the padding) rows
        instead of the last shard eating all the pad. Per-row results
        are position-independent; commit gathers by `rows`."""
        n_dev = self.runner.n_devices
        per = B // n_dev
        j = np.arange(len(sel), dtype=np.int64)
        rows = (j % n_dev) * per + j // n_dev

        def take(arr, fill):
            out = np.full((B,) + arr.shape[1:], fill, dtype=arr.dtype)
            out[rows] = arr[sel]
            return out

        return self._run_bucket(
            nb, lb, take(jobs["codes"][:, :nb], 5),
            take(jobs["preds"][:, :nb, :self.max_pred], -1),
            take(jobs["centers"][:, :nb], 0),
            take(jobs["sinks"][:, :nb], 0),
            take(jobs["seqs"][:, :lb], 5),
            take(jobs["len"], 0), take(jobs["band"], 0),
            take(jobs["nnodes"], 0)), rows

    def _run_pallas(self, fn, *args):
        """Run the pallas sweep across every device (the batch width is
        already a multiple of n_devices, _pin_batch) — the shared
        per-device split both kernel planes use."""
        return self.runner.run_split(fn, *args)
