"""Pallas TPU kernel for whole-window graph-banded NW + traceback.

The within-kernel half of GenomeWorks cudapoa, TPU-shaped. cudapoa runs
one POA group per CUDA block with the working set in shared memory
(SURVEY.md §2c-6); this kernel runs one (window, layer) job per
sequential grid step with the ENTIRE job resident in VMEM:

  - the full score matrix H [N+1, L+1] i32 (~5.3 MB at the largest
    bucket) and the backpointer matrix live in VMEM scratch — the row
    sweep never touches HBM;
  - the virtual source is H row 0, and predecessor rows are scalar
    dynamic slices (one window per step means predecessor ranks are
    scalars — no per-lane gather problem);
  - the row loop runs to THIS job's real node count (dynamic bound), not
    the bucket's padded N;
  - the traceback is in-kernel (scalar pointer chase over the VMEM
    backpointers), so the kernel's only output is the final per-base
    node ranks — nothing else leaves the chip.

DP values, band masking and tie-breaking replicate
ops/poa_graph.graph_aligner exactly (same formulas, same int32
arithmetic), so consensus byte-identity is preserved;
tests/test_pallas_poa.py fuzzes this kernel against the XLA one in
interpret mode. The trade against the XLA kernel: the XLA program
vectorizes one DP row across the whole batch ([B, L] per step) but pays
HBM for every row and ~N+L while-loop steps of traceback per batch; this
kernel's vectors are [L]-wide but every access is VMEM and the whole
sweep is one fused loop. Which wins is a hardware question — the kernel
is enabled with RACON_TPU_PALLAS=1 (default off until profiled on chip),
and the dispatcher falls back to the XLA program for shapes the VMEM
budget cannot hold.
"""

from __future__ import annotations

import functools
import os

_NEG = -(1 << 29)
_NEG16 = -(1 << 14)

#: VMEM the resident job may use (scores + backpointers + operand
#: blocks + slack); the largest session bucket (2048, 640) needs
#: ~10.6 MB of the ~16 MB
VMEM_BUDGET = 14 << 20


def pallas_mode() -> str:
    """RACON_TPU_PALLAS posture shared by every engine dispatcher:
    'off' (unset/0 — XLA programs only, today's default), 'on' (`1` —
    the Pallas kernel whenever the VMEM envelope fits), or 'auto'
    (consult the persisted per-bucket winner table, sched/autotune;
    buckets without a measured entry dispatch XLA exactly as off).
    Inside an audit oracle_scope (ops/oracle.py) the posture is pinned
    'off' on that thread — the shadow re-execution's ground truth is
    the XLA program whatever the environment says."""
    from .oracle import oracle_active

    if oracle_active():
        return "off"
    raw = (os.environ.get("RACON_TPU_PALLAS") or "").strip().lower()
    if not raw or raw == "0":
        return "off"
    if raw == "auto":
        return "auto"
    return "on"


def fits_vmem(n_nodes: int, seq_len: int, max_pred: int = 8,
              score_dtype: str = "int32") -> bool:
    """True when one (window, layer) job is resident-VMEM feasible.

    Budgets EVERYTHING `window_sweep` places in VMEM, not only the
    scratch: the H score matrix (at the chosen dtype), the int8
    backpointer matrix, AND the per-grid-step operand blocks — codes,
    preds [1, N, P], centers, sinks, seq, the rank output — which the
    BlockSpecs stage as int32 (the original accounting omitted the
    operands entirely, under-budgeting the envelope bucket by ~15%%).
    The aligner kernel's envelope check (ops/align_pallas.fits_vmem)
    shares this discipline and the same budget constant."""
    dbytes = 2 if score_dtype == "int16" else 4
    h = (n_nodes + 1) * (seq_len + 1) * dbytes
    bps = n_nodes * (seq_len + 1)                     # int8 plane
    operands = (3 * n_nodes                           # codes/centers/sinks
                + n_nodes * max_pred                  # preds
                + 2 * seq_len) * 4                    # seq + rank output
    return h + bps + operands + (1 << 20) <= VMEM_BUDGET


@functools.lru_cache(maxsize=None)
def window_sweep(n_nodes: int, seq_len: int, max_pred: int, match: int,
                 mismatch: int, gap: int, interpret: bool = False,
                 score_dtype: str = "int32", packed: bool = False):
    """Jitted fn(codes, preds, centers, sinks, seq, lens, band, nnodes)
    -> ranks [B, L] i32, one grid step per batch row.

    Argument layouts match graph_aligner's (codes [B,N] i8, preds
    [B,N,P] i16 rank+1 with 0 = virtual source / -1 pad, centers [B,N]
    i16, sinks [B,N] u8, seq [B,L] i8, lens/band [B] i32) plus nnodes
    [B] i32 — the per-job real node count. Returns graph_aligner's rank
    encoding (node rank, -1 insertion, -2 beyond lens).

    `score_dtype='int16'` halves the resident H matrix (legal only
    under ops/dtypes.poa_int16_ok's per-bucket overflow proof —
    bit-identical results by construction). `packed` takes 2-bit packed
    codes/seq ([B, N//4] / [B, L//4] uint8, encode.pack_2bit) and
    unpacks + pad-restores them with XLA ops before the kernel — a 4x
    cut in node/sequence transfer for ACGT-only windows.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, L, P = n_nodes, seq_len, max_pred
    DT = jnp.int16 if score_dtype == "int16" else jnp.int32

    def kernel(scal_ref, codes_ref, preds_ref, centers_ref, sinks_ref,
               seq_ref, out_ref, H, bps):
        NEG = jnp.asarray(_NEG16 if score_dtype == "int16" else _NEG, DT)
        slen = scal_ref[0, 0]
        band = scal_ref[0, 1]
        nn = scal_ref[0, 2]
        jidx = jax.lax.broadcasted_iota(jnp.int32, (1, L + 1), 1)
        jg = (jidx * gap).astype(DT)

        # virtual source row: D[0][j] = j*gap within the layer
        H[0:1, :] = jnp.where(jidx <= slen, jg, NEG)

        seq2 = seq_ref[0:1, :]                                  # [1, L]
        band2 = band // 2
        use_band = band > 0

        def row(k, carry):
            code_k = codes_ref[0, k - 1]
            center_k = centers_ref[0, k - 1]

            rows = jnp.full((P, L + 1), NEG, dtype=DT)
            for p in range(P):                       # static P, unrolled
                pr = preds_ref[0, k - 1, p]
                r2 = H[pl.ds(jnp.maximum(pr, 0), 1), :]         # [1, L+1]
                rows = jax.lax.dynamic_update_slice(
                    rows, jnp.where(pr >= 0, r2, NEG), (p, 0))

            sub = jnp.where(seq2 == code_k, match,
                            mismatch).astype(DT)                # [1, L]
            diag = rows[:, :-1] + sub                           # [P, L]
            vert = rows[:, 1:] + gap
            best = jnp.max(jnp.maximum(diag, vert), axis=0,
                           keepdims=True)                       # [1, L]
            row0 = jnp.max(rows[:, 0]) + gap                    # scalar

            jlo = jnp.where(use_band, jnp.maximum(1, center_k - band2), 1)
            jhi = jnp.where(use_band, jnp.minimum(slen, center_k + band2),
                            slen)
            j1 = jidx[:, 1:]                                    # [1, L]
            inb = (j1 >= jlo) & (j1 <= jhi)
            pre = jnp.where(inb, best, NEG)
            seed0 = jnp.where(jlo == 1, row0, NEG).reshape(1, 1)
            cat = jnp.concatenate([seed0, pre], axis=1)         # [1, L+1]
            # in-row gap recurrence: running max via Hillis-Steele
            # doubling (deterministic TPU lowering; log2(L+1) steps)
            x = cat - jg
            s = 1
            while s <= L:
                shifted = jnp.concatenate(
                    [jnp.full((1, s), NEG, DT), x[:, :-s]], axis=1)
                x = jnp.maximum(x, shifted)
                s <<= 1
            run = x + jg
            hrow = jnp.where(inb, run[:, 1:], pre)              # [1, L]
            new_row = jnp.concatenate(
                [jnp.full((1, 1), row0, DT), hrow], axis=1)

            # backpointers, graph_aligner's encoding and tie order:
            # diagonal via pred p -> p; vertical via pred p -> P+p;
            # horizontal -> 2P
            nr = new_row[:, 1:]                                 # [1, L]
            is_diag = nr == diag                                # [P, L]
            is_vert = nr == vert
            pd = jnp.argmax(is_diag, axis=0)[None, :]           # [1, L]
            pv = jnp.argmax(is_vert, axis=0)[None, :]
            bpc = jnp.where(jnp.any(is_diag, axis=0)[None, :], pd,
                            jnp.where(jnp.any(is_vert, axis=0)[None, :],
                                      P + pv, 2 * P)).astype(jnp.int32)
            is_v0 = (row0 == rows[:, 0:1] + gap)                # [P, 1]
            bp0 = (P + jnp.argmax(is_v0, axis=0)).reshape(1, 1)
            H[pl.ds(k, 1), :] = new_row
            # codes <= 2P <= 16: an int8 plane, a quarter of the int32
            # footprint the first cut of this kernel budgeted
            bps[pl.ds(k - 1, 1), :] = jnp.concatenate(
                [bp0, bpc], axis=1).astype(jnp.int8)
            return carry

        jax.lax.fori_loop(1, nn + 1, row, 0)

        # best sink at the layer's final column; ties -> smallest rank
        kidx = jax.lax.broadcasted_iota(jnp.int32, (N, 1), 0)
        col = H[:, pl.ds(slen, 1)]                              # [N+1, 1]
        cand = jnp.where((sinks_ref[0:1, :].T > 0) & (kidx < nn),
                         col[1:, :], NEG)                       # [N, 1]
        best_rank = jnp.argmax(cand[:, 0]).astype(jnp.int32)

        out_ref[0:1, :] = jnp.full((1, L), -2, dtype=jnp.int32)

        def tb_cond(st):
            r, j = st
            return (r > 0) | (j > 0)

        def tb_body(st):
            r, j = st
            code = jnp.where(r > 0,
                             bps[jnp.maximum(r - 1, 0),
                                 jnp.maximum(j, 0)].astype(jnp.int32),
                             2 * P)
            is_diag = code < P
            is_vert = (code >= P) & (code < 2 * P)
            p = jnp.where(is_diag, code, code - P)
            pr = preds_ref[0, jnp.maximum(r - 1, 0),
                           jnp.clip(p, 0, P - 1)].astype(jnp.int32)
            consume = jnp.logical_not(is_vert)     # diag or horizontal
            jc = jnp.maximum(j - 1, 0)
            old = out_ref[0, jc]
            emit = jnp.where(is_diag, r - 1, -1)
            out_ref[0, jc] = jnp.where(consume & (j > 0), emit, old)
            r = jnp.where(is_diag | is_vert, pr, r)
            j = jnp.where(consume, j - 1, j)
            return r, j

        # empty rows (nnodes == 0: batch padding) wrote no bps rows — the
        # traceback must not start, or it would chase uninitialized
        # scratch; start it pre-terminated instead
        jax.lax.while_loop(tb_cond, tb_body,
                           (jnp.where(nn > 0, best_rank + 1, 0),
                            jnp.where(nn > 0, slen, 0)))

    def call(codes, preds, centers, sinks, seq, lens, band, nnodes):
        if packed:
            from .encode import unpack_2bit_jax

            codes = unpack_2bit_jax(codes, N, nnodes)
            seq = unpack_2bit_jax(seq, L, lens)
        B = codes.shape[0]
        scal = jnp.stack([lens.astype(jnp.int32),
                          band.astype(jnp.int32),
                          nnodes.astype(jnp.int32)], axis=1)    # [B, 3]
        vmem = pltpu.VMEM
        return pl.pallas_call(
            kernel,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, 3), lambda b: (b, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, N), lambda b: (b, 0), memory_space=vmem),
                pl.BlockSpec((1, N, P), lambda b: (b, 0, 0),
                             memory_space=vmem),
                pl.BlockSpec((1, N), lambda b: (b, 0), memory_space=vmem),
                pl.BlockSpec((1, N), lambda b: (b, 0), memory_space=vmem),
                pl.BlockSpec((1, L), lambda b: (b, 0), memory_space=vmem),
            ],
            out_specs=pl.BlockSpec((1, L), lambda b: (b, 0),
                                   memory_space=vmem),
            out_shape=jax.ShapeDtypeStruct((B, L), jnp.int32),
            scratch_shapes=[
                pltpu.VMEM((N + 1, L + 1), DT),          # H
                pltpu.VMEM((N, L + 1), jnp.int8),        # backpointers
            ],
            interpret=interpret,
        )(scal, codes.astype(jnp.int32), preds.astype(jnp.int32),
          centers.astype(jnp.int32), sinks.astype(jnp.int32),
          seq.astype(jnp.int32))

    return jax.jit(call)
