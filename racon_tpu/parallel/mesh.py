"""Multi-chip batch sharding.

The reference scales across GPUs by instantiating batch objects per device
and pulling work from a shared index — no inter-GPU communication at all
(src/cuda/cudapolisher.cpp:165-199,228-345; SURVEY.md §2c-5). The TPU
equivalent is simpler and declarative: one `jax.sharding.Mesh` over all
chips with a single 'batch' axis, inputs placed with a batch-sharded
`NamedSharding`, and XLA partitions the jitted kernel across chips over
ICI. The workload needs no collectives (every window/overlap is
independent), so sharding the leading axis is the complete distribution
story; multi-host runs add only file-level scatter/gather (SURVEY.md §5).
"""

from __future__ import annotations

import numpy as np


class BatchRunner:
    """Runs batched kernels with the leading axis sharded over all devices.

    On a single device this degrades to plain dispatch with zero overhead;
    on N devices each chip receives B/N rows of every operand.
    """

    def __init__(self, devices=None):
        import jax

        self.devices = list(devices) if devices is not None else jax.devices()
        if len(self.devices) > 1:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            self.mesh = Mesh(np.array(self.devices), ("batch",))
            self.sharding = NamedSharding(self.mesh, PartitionSpec("batch"))
        else:
            self.mesh = None
            self.sharding = None

    @property
    def n_devices(self) -> int:
        return len(self.devices)

    def round_batch(self, batch: int) -> int:
        """Smallest multiple of n_devices >= batch (so shards are equal)."""
        n = self.n_devices
        return ((batch + n - 1) // n) * n

    def run(self, fn, *arrays):
        """Invoke jitted `fn` on operands whose leading dim is the batch.

        All operands must share the same leading dimension, divisible by
        the device count (use round_batch + padding).
        """
        import jax

        if self.sharding is None:
            return fn(*arrays)
        placed = [jax.device_put(a, self.sharding) for a in arrays]
        return fn(*placed)
