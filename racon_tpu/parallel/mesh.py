"""Multi-chip batch sharding.

The reference scales across GPUs by instantiating batch objects per device
and pulling work from a shared index — no inter-GPU communication at all
(src/cuda/cudapolisher.cpp:165-199,228-345; SURVEY.md §2c-5). The TPU
equivalent is simpler and declarative: one `jax.sharding.Mesh` over all
chips with a single 'batch' axis, inputs placed with a batch-sharded
`NamedSharding`, and XLA partitions the jitted kernel across chips over
ICI. The workload needs no collectives (every window/overlap is
independent), so sharding the leading axis is the complete distribution
story; multi-host runs add only file-level scatter/gather (SURVEY.md §5).
"""

from __future__ import annotations

import numpy as np


def mesh_info(worker_lanes: int = 1) -> dict:
    """The mesh posture a perf artifact was measured under — ONE schema
    shared by bench.py / synthbench / servebench, because
    tools/perfgate.py refuses cross-mesh comparisons key-by-key: a
    field added here reaches every artifact at once instead of drifting
    per tool. (windows/s on 1 chip vs 8 is a different machine, not a
    regression.)"""
    import os

    return {"n_devices": BatchRunner().n_devices,
            "worker_lanes": int(worker_lanes),
            "max_devices_env": os.environ.get(
                "RACON_TPU_MAX_DEVICES") or None}


def partition_devices(devices=None, k: int = 1) -> list[list]:
    """Split a device list into `k` contiguous, near-equal sub-lists —
    the serve layer's worker-lane partition (each lane becomes an
    independent sub-mesh with its own BatchRunner). `k` clamps to the
    device count (a lane with zero devices schedules nothing) and the
    first len(devices) % k lanes carry the extra device.

    `devices` may be an explicit list — in particular the GLOBAL
    device list of a `jax.distributed` run, the prep seam for the
    multi-host mesh (ROADMAP item 1): carving lanes from the global
    list instead of the process-local set is what lets one job's
    worker lanes span hosts. `devices=None` auto-discovers via
    `jax.devices()` (which IS the global list once jax.distributed is
    initialized, ordered by process index — so contiguous lanes stay
    host-contiguous), honoring the same RACON_TPU_MAX_DEVICES cap as
    `BatchRunner`."""
    if devices is None:
        import os

        import jax

        devices = jax.devices()
        cap = int(os.environ.get("RACON_TPU_MAX_DEVICES", "0") or 0)
        if cap > 0:
            devices = devices[:cap]
    devices = list(devices)
    k = max(1, min(int(k), len(devices)))
    base, extra = divmod(len(devices), k)
    out, start = [], 0
    for i in range(k):
        n = base + (1 if i < extra else 0)
        out.append(devices[start:start + n])
        start += n
    return out


class BatchRunner:
    """Runs batched kernels with the leading axis sharded over all devices.

    On a single device this degrades to plain dispatch with zero overhead;
    on N devices each chip receives B/N rows of every operand.
    """

    def __init__(self, devices=None):
        import os

        import jax

        if devices is not None:
            self.devices = list(devices)  # explicit list: caller decides
        else:
            self.devices = jax.devices()
            # RACON_TPU_MAX_DEVICES caps the auto-discovered mesh
            # (operators pinning chips; tests that don't exercise
            # sharding keep the 8-virtual-device CPU mesh from
            # multiplying their sequential work)
            cap = int(os.environ.get("RACON_TPU_MAX_DEVICES", "0") or 0)
            if cap > 0:
                self.devices = self.devices[:cap]
        if len(self.devices) > 1:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            self.mesh = Mesh(np.array(self.devices), ("batch",))
            self.sharding = NamedSharding(self.mesh, PartitionSpec("batch"))
        else:
            self.mesh = None
            self.sharding = None
        self._wrapped: dict = {}
        self._subs: dict[int, "BatchRunner"] = {}

    @property
    def n_devices(self) -> int:
        return len(self.devices)

    def round_batch(self, batch: int) -> int:
        """Smallest multiple of n_devices >= batch (so shards are equal)."""
        n = self.n_devices
        return ((batch + n - 1) // n) * n

    def for_batch(self, batch: int) -> "BatchRunner":
        """The runner a batch of `batch` rows should dispatch through:
        this runner when the batch fills the mesh, else a cached
        SUB-MESH over the first `batch` devices — so a tail batch
        smaller than the mesh ships with ZERO padding lanes instead of
        rounding up to the full device count (`round_batch` padding
        waste grows with slice size; a 3-row tail on an 8-chip slice
        would burn 5 whole padded lanes). Per-row results are
        independent of batch composition, so the output is
        byte-identical either way (dryrun-pinned)."""
        n = self.n_devices
        if batch >= n or batch < 1 or n == 1:
            return self
        sub = self._subs.get(batch)
        if sub is None:
            sub = self._subs[batch] = BatchRunner(
                devices=self.devices[:batch])
        return sub

    def run_split(self, fn, *arrays):
        """Manual per-device batch split for kernels whose grid is
        sequential per core (the Pallas resident kernels): each chip
        gets B/N rows dispatched async — the multi-GPU batch-per-device
        loop of cudapolisher.cpp:228-345, shared by BOTH kernel planes
        (DeviceGraphPOA._run_pallas, align.BatchAligner). The leading
        dim must be a multiple of n_devices (round_batch). Returns the
        kernel's output directly on one device, else the list of
        per-shard outputs in device order (caller concatenates).

        ALL shards are placed before the first kernel call: device_put
        is async, so shard k+1's host->device transfer overlaps shard
        k's compute instead of serializing transfer/dispatch per device
        (the old interleaved loop paid the full transfer latency on the
        dispatch path for every device after the first). Concatenating
        the per-shard outputs in device order is identical to the
        single-device result row-for-row (test-pinned)."""
        if len(self.devices) == 1:
            return fn(*arrays)
        import jax

        per = arrays[0].shape[0] // len(self.devices)
        placed = [[jax.device_put(a[i * per:(i + 1) * per], d)
                   for a in arrays]
                  for i, d in enumerate(self.devices)]
        return [fn(*ops) for ops in placed]

    def run(self, fn, *arrays, out_batch_axes=0, donate_argnums=()):
        """Invoke jitted `fn` on operands whose leading dim is the batch.

        All operands must share the same leading dimension, divisible by
        the device count (use round_batch + padding). `out_batch_axes`
        names the batch axis of each output: an int when every output
        carries the batch on the same axis, or a tuple with one entry per
        output of a tuple-returning kernel. `donate_argnums` is applied
        to the outer jit on accelerator backends (state-carrying kernels
        chain calls without duplicating their buffers); ignored on the
        CPU test backend, which cannot donate.

        Multi-device dispatch goes through `shard_map`, so each device
        runs an INDEPENDENT copy of the program on its batch shard — no
        cross-device communication exists in the compiled module. Plain
        sharded-jit would instead let XLA turn batch-wide reductions
        (e.g. a while-loop's `jnp.any` exit test) into all-reduces, and
        with several async batches in flight those collectives can
        interleave across programs and deadlock the per-device rendezvous
        (observed as an abort on the 8-virtual-device CPU test mesh; the
        workload needs no collectives, per SURVEY.md §2c-5, so none
        should be emitted). Per-shard loop exits are semantically
        identical: finished lanes iterate as no-ops either way.
        """
        import jax

        if self.sharding is None:
            return fn(*arrays)
        donate = (tuple(donate_argnums)
                  if jax.default_backend() != "cpu" else ())
        key = (fn, len(arrays), out_batch_axes, donate)
        shard_fn = self._wrapped.get(key)
        if shard_fn is None:
            from jax.sharding import PartitionSpec

            def axis_spec(axis: int) -> PartitionSpec:
                return PartitionSpec(*([None] * axis + ["batch"]))

            spec = PartitionSpec("batch")
            if isinstance(out_batch_axes, int):
                out_specs = axis_spec(out_batch_axes)
            else:
                out_specs = tuple(axis_spec(a) for a in out_batch_axes)
            # check_vma/check_rep off: the kernels mix literal-initialized
            # and data-derived loop carries, which the varying-axes checker
            # rejects even though every output is plainly batch-sharded
            kwargs = dict(mesh=self.mesh, in_specs=(spec,) * len(arrays),
                          out_specs=out_specs)
            # TypeError covers jax versions where jax.shard_map exists
            # but takes check_rep instead of check_vma
            try:
                smapped = jax.shard_map(fn, check_vma=False, **kwargs)
            except AttributeError:  # pragma: no cover — older jax
                from jax.experimental.shard_map import shard_map

                smapped = shard_map(fn, check_rep=False, **kwargs)
            except TypeError:  # pragma: no cover — check_rep-era jax
                smapped = jax.shard_map(fn, check_rep=False, **kwargs)
            shard_fn = jax.jit(smapped, donate_argnums=donate)
            self._wrapped[key] = shard_fn
        placed = [jax.device_put(a, self.sharding) for a in arrays]
        return shard_fn(*placed)
