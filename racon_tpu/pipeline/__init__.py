"""Double-buffered async dispatch pipeline.

The reference keeps every CUDA device saturated by running several batch
objects per GPU off a shared work index, so host-side fill/fetch of one
batch overlaps device compute of another (cudapolisher.cpp:165-199,
228-345). Both of our hot phases were strictly synchronous instead: pack a
chunk on host, block on the device call, unpack on host, repeat — with all
host-fallback work serialized after the device pass. `DispatchPipeline` is
the TPU-shaped equivalent of the reference's per-device batch threads:

  - a PACK worker thread builds chunk k+1's padded operands while
  - the caller's thread DISPATCHES chunk k to the device (JAX dispatch is
    async — the call returns as soon as the program is enqueued) while
  - an UNPACK worker thread blocks on chunk k-1's results and finishes
    them on host (CIGAR traceback for the aligner, C++ consensus for the
    fused POA engine) while
  - a small FALLBACK thread pool chews host-only work (envelope-tail
    windows, band-clipped overlaps) as soon as it is discovered instead
    of after the device pass.

`depth` bounds how many chunks sit packed-but-undispatched and
dispatched-but-unwaited (double buffering at the default depth=2);
`depth=0` is the fully synchronous single-threaded path — byte-identical
output, kept for bisection — in which `submit_fallback` also runs inline.

Stage wall-clock is accumulated into a `PipelineStats` (shareable across
phases): pack / device / unpack / fallback seconds plus chunk, launch and
error counts. "device seconds" is time spent against the compute stage:
dispatching (which for a host compute engine is the blocking native call
itself) plus the time the unpack worker spends blocked on results — with
real overlap, pack+unpack+device stage seconds exceed the phase's wall
time; in a dead (synchronous) pipeline they are additive. bench.py
publishes the counters in its JSON artifact so the overlap is measurable,
not anecdotal.

Error discipline: without `on_error`, the first stage exception aborts the
run and re-raises (the RACON_TPU_STRICT posture). With `on_error(item,
exc)`, the failed chunk is skipped and the run continues — callers route
the chunk's items to their host fallback, the per-window GPU->CPU
discipline of cudapolisher.cpp:354-383 at chunk granularity. `on_error`
itself raising aborts the run with that exception.

Resilience (racon_tpu/resilience/): the pipeline is the arming point for
the deterministic fault-injection harness (RACON_TPU_FAULT_PLAN hooks at
the pack/device/unpack stages and the fallback pool) and for the device
watchdog — with a `Watchdog` configured, dispatch runs under its deadline
with bounded retry + exponential backoff, the result wait under the
deadline only (re-waiting on a hung handle would just burn a second
deadline), and fallback jobs get the same bounded retry. Both default
from the environment and stay None when unconfigured, so the clean path
pays a single `is None` check per stage.

RACON_TPU_DEVICE_LATENCY_S / RACON_TPU_DEVICE_LATENCY_X (default
unset) sleep a simulated accelerator round-trip per chunk — a fixed
floor after the result wait, or a multiplier on the chunk's measured
dispatch time. The CPU dev posture's device stage is pure host compute;
these reproduce the device-dominated regime (off-CPU waits that
overlap across replicas) the serve fleet benches measure their scaling
against.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

from ..errors import RaconError
from ..obs import trace
from ..resilience import Watchdog, get_fault_plan

_STOP = object()


def _env_device_latency(name: str) -> float:
    """Simulated-device pacing knobs, both slept OFF-CPU per chunk (the
    CPU dev posture has no real accelerator, so its device stage is pure
    host compute; these reproduce the device-DOMINATED regime — waits a
    caller can overlap across replicas — the serve fleet benches scale
    against):

      RACON_TPU_DEVICE_LATENCY_S  fixed seconds added after each
                                  chunk's result wait (round-trip floor)
      RACON_TPU_DEVICE_LATENCY_X  multiplier on each chunk's measured
                                  dispatch time (a device whose
                                  round-trip scales with batch size)

    Unset/0 is the default and costs one comparison per run."""
    raw = os.environ.get(name, "")
    if not raw:
        return 0.0
    try:
        lat = float(raw)
    except ValueError:
        raise RaconError(
            "pipeline.DispatchPipeline",
            f"invalid {name} {raw!r} (expected a float)!") from None
    if lat < 0:
        raise RaconError(
            "pipeline.DispatchPipeline", f"{name} must be >= 0!")
    return lat

#: PipelineStats keys whose bumps are semantic events, mirrored as trace
#: instant events when the tracer is armed — the counter and the trace
#: can never disagree because both come from the same bump
_INSTANT_KEYS = frozenset(("faults", "retries", "timeouts",
                           "breaker_trips", "quarantined", "cancelled"))

#: stage-seconds keys mirrored into latency histograms when a
#: HistogramSet is attached (obs/hist.py): each bump is one chunk's
#: stage duration, so the histogram is the per-chunk distribution of
#: the same wall-clock the counters total. device_s is NOT here: it is
#: bumped twice per chunk (dispatch + wait segments), so the loops
#: observe `pipeline.device` themselves as the per-chunk SUM — one
#: sample per chunk, comparable with the other stages
_HIST_KEYS = {"pack_s": "pipeline.pack",
              "unpack_s": "pipeline.unpack",
              "fallback_s": "pipeline.fallback"}


class PipelineStats:
    """Thread-safe per-stage counters, shareable across pipeline phases.

    The first two key groups are the PR-1 overlap counters; the
    resilience group (faults injected, watchdog retries/timeouts, backoff
    seconds slept, circuit-breaker trips, quarantined windows, cancelled
    fallback futures) is the degradation report — all zero on a clean
    run, published together in bench.py's JSON artifact."""

    _FLOAT_KEYS = ("pack_s", "device_s", "unpack_s", "fallback_s",
                   "backoff_s")
    _INT_KEYS = ("launches", "chunks", "errors",
                 "faults", "retries", "timeouts", "breaker_trips",
                 "quarantined", "cancelled")
    KEYS = _FLOAT_KEYS + _INT_KEYS

    def __init__(self, hists=None):
        self._lock = threading.Lock()
        self._v = {k: 0.0 for k in self._FLOAT_KEYS}
        self._v.update({k: 0 for k in self._INT_KEYS})
        #: optional obs.hist.HistogramSet: per-chunk stage durations
        #: observed as latency distributions (None — one `is None`
        #: check per bump — when nothing is watching)
        self.hists = hists

    def bump(self, key: str, amount=1) -> None:
        with self._lock:
            self._v[key] += amount
        if self.hists is not None:
            name = _HIST_KEYS.get(key)
            if name is not None:
                self.hists.observe(name, amount)
        if key in _INSTANT_KEYS:
            tr = trace.get_tracer()
            if tr is not None:
                tr.instant(f"resilience.{key}", {"n": amount})

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._v)


class DispatchPipeline:
    """Stage driver for one device-batched loop (see module docstring).

    run(items, pack, dispatch, wait, unpack):
      pack(item) -> operands            host work, pack worker thread
      dispatch(item, operands) -> h     caller's thread (async device call)
      wait(h) -> result                 blocks on the device, unpack thread
      unpack(item, result) -> None      host work, unpack worker thread

    Items flow through the stages in order; unpack order equals dispatch
    order (FIFO), so result assembly is deterministic. All device calls
    stay on the caller's thread — the only JAX interaction off it is
    blocking on/fetching finished results in `wait`.
    """

    def __init__(self, depth: int = 2, fallback_workers: int = 2,
                 stats: PipelineStats | None = None, watchdog=None,
                 faults=None):
        self.depth = max(0, int(depth))
        self.fallback_workers = max(1, int(fallback_workers))
        self.stats = stats if stats is not None else PipelineStats()
        # resilience hooks: explicit objects win (the polisher threads its
        # CLI knobs through); otherwise the env posture applies so every
        # pipeline in the process is injectable/guarded. Both are None —
        # zero-overhead — when nothing is configured. `faults=False`
        # DISABLES injection entirely, ignoring even the env plan — the
        # audit sentinel's oracle re-execution must reproduce ground
        # truth, never re-fire the fault it is trying to detect.
        self.watchdog = watchdog if watchdog is not None \
            else Watchdog.from_env()
        self.faults = (None if faults is False
                       else faults if faults is not None
                       else get_fault_plan())
        self.device_latency_s = _env_device_latency(
            "RACON_TPU_DEVICE_LATENCY_S")
        self.device_latency_x = _env_device_latency(
            "RACON_TPU_DEVICE_LATENCY_X")
        self._fb_counter = itertools.count()
        self._executor: ThreadPoolExecutor | None = None
        self._futures: list[Future] = []

    # ------------------------------------------------------------ stages
    def run(self, items, pack, dispatch, wait, unpack, on_error=None,
            label: str | None = None, describe=None) -> None:
        """`label` names this loop in the trace (aligner / fused /
        host_poa); `describe(item) -> dict` supplies per-chunk span args
        (engine, bucket, job count). Both are ignored — zero cost — when
        tracing is off."""
        items = list(items)
        if self.device_latency_x > 0.0:
            # wrapped before instrumentation so the stall counts as
            # device time under the watchdog deadline, exactly as a
            # real accelerator round-trip would
            inner_dispatch, x = dispatch, self.device_latency_x

            def dispatch(item, ops, _d=inner_dispatch, _x=x):
                t0 = time.perf_counter()
                handle = _d(item, ops)
                time.sleep((time.perf_counter() - t0) * _x)
                return handle
        if self.device_latency_s > 0.0:
            inner_wait, lat = wait, self.device_latency_s

            def wait(handle, _wait=inner_wait, _lat=lat):
                res = _wait(handle)
                time.sleep(_lat)
                return res
        if self.faults is not None or self.watchdog is not None:
            pack, dispatch, wait, unpack = self._instrument(
                pack, dispatch, wait, unpack)
        tr = trace.get_tracer()
        args_of = None
        if tr is not None:
            def args_of(idx, item):
                a = {"chunk": idx}
                if label:
                    a["loop"] = label
                if describe is not None:
                    a.update(describe(item))
                return a
        if self.depth == 0:
            self._run_sync(items, pack, dispatch, wait, unpack, on_error,
                           tr, args_of)
            return
        self._run_async(items, pack, dispatch, wait, unpack, on_error,
                        tr, args_of)

    def _instrument(self, pack, dispatch, wait, unpack):
        """Wrap the stage callbacks with the resilience hooks: fault
        injection fires as each stage starts its Nth item (each stage is
        single-threaded, so a plain per-stage counter is the submission
        order), and the watchdog applies its policy per stage — dispatch
        under deadline + retry (faults are one-shot, so a retried
        dispatch finds its injected fault consumed: the transient-fault
        shape), the result wait under the deadline only, and the
        idempotent host stages (pack/unpack: pure functions of their
        inputs) under retry only."""
        faults, wd, stats = self.faults, self.watchdog, self.stats
        counters = {s: itertools.count() for s in ("pack", "device",
                                                   "unpack")}

        def fire(stage, idx):
            if faults is not None:
                faults.fire(stage, idx, stats=stats)

        cancel = faults.cancel_hangs if faults is not None else None

        def staged(stage, fn, retry=True, deadline=False):
            idx = next(counters[stage])

            def attempt():
                fire(stage, idx)
                return fn()

            if wd is None:
                return attempt()
            return wd.call(attempt, stats=stats, retry=retry,
                           deadline=deadline, on_timeout=cancel)

        def pack_w(item):
            return staged("pack", lambda: pack(item))

        def dispatch_w(item, ops):
            return staged("device", lambda: dispatch(item, ops),
                          deadline=True)

        def wait_w(handle):
            if wd is None:
                return wait(handle)
            return wd.call(lambda: wait(handle), stats=stats, retry=False,
                           on_timeout=cancel)

        def unpack_w(item, res):
            return staged("unpack", lambda: unpack(item, res))

        return pack_w, dispatch_w, wait_w, unpack_w

    def _run_sync(self, items, pack, dispatch, wait, unpack, on_error,
                  tr=None, args_of=None):
        # spans reuse the exact perf_counter endpoints the stats bumps
        # charge, so per-stage span-duration sums equal the stage
        # wall-clock counters by construction (tests/test_obs.py)
        stats = self.stats
        for idx, item in enumerate(items):
            try:
                t0 = time.perf_counter()
                ops = pack(item)
                t1 = time.perf_counter()
                stats.bump("pack_s", t1 - t0)
                if tr is not None:
                    tr.complete("pipeline.pack", t0, t1, args_of(idx, item))
                t0 = time.perf_counter()
                handle = dispatch(item, ops)
                t1 = time.perf_counter()
                disp_dt = t1 - t0
                stats.bump("device_s", disp_dt)
                stats.bump("chunks")
                if tr is not None:
                    tr.complete("pipeline.device", t0, t1,
                                dict(args_of(idx, item), seg="dispatch"))
                t0 = time.perf_counter()
                res = wait(handle)
                t1 = time.perf_counter()
                stats.bump("device_s", t1 - t0)
                if stats.hists is not None:
                    stats.hists.observe("pipeline.device",
                                        disp_dt + (t1 - t0))
                if tr is not None:
                    tr.complete("pipeline.device", t0, t1,
                                dict(args_of(idx, item), seg="wait"))
                t0 = time.perf_counter()
                unpack(item, res)
                t1 = time.perf_counter()
                stats.bump("unpack_s", t1 - t0)
                if tr is not None:
                    tr.complete("pipeline.unpack", t0, t1,
                                args_of(idx, item))
            except Exception as exc:
                stats.bump("errors")
                if on_error is None:
                    raise
                on_error(item, exc)

    def _run_async(self, items, pack, dispatch, wait, unpack, on_error,
                   tr=None, args_of=None):
        stats = self.stats
        fatal: list[BaseException] = []
        abort = threading.Event()

        def guard(item, exc):
            stats.bump("errors")
            if on_error is None:
                fatal.append(exc)
                abort.set()
                return
            try:
                on_error(item, exc)
            except BaseException as handler_exc:
                fatal.append(handler_exc)
                abort.set()

        packed_q: queue.Queue = queue.Queue(maxsize=self.depth)
        waiting_q: queue.Queue = queue.Queue(maxsize=self.depth)

        def packer():
            try:
                for idx, item in enumerate(items):
                    if abort.is_set():
                        break
                    try:
                        t0 = time.perf_counter()
                        ops = pack(item)
                        t1 = time.perf_counter()
                        stats.bump("pack_s", t1 - t0)
                        if tr is not None:
                            tr.complete("pipeline.pack", t0, t1,
                                        args_of(idx, item))
                    except Exception as exc:
                        guard(item, exc)
                        continue
                    packed_q.put((idx, item, ops))
            finally:
                packed_q.put(_STOP)

        def unpacker():
            while True:
                entry = waiting_q.get()
                if entry is _STOP:
                    return
                if abort.is_set():
                    continue
                idx, item, handle, disp_dt = entry
                try:
                    t0 = time.perf_counter()
                    res = wait(handle)
                    t1 = time.perf_counter()
                    stats.bump("device_s", t1 - t0)
                    if stats.hists is not None:
                        stats.hists.observe("pipeline.device",
                                            disp_dt + (t1 - t0))
                    if tr is not None:
                        tr.complete("pipeline.device", t0, t1,
                                    dict(args_of(idx, item), seg="wait"))
                    t0 = time.perf_counter()
                    unpack(item, res)
                    t1 = time.perf_counter()
                    stats.bump("unpack_s", t1 - t0)
                    if tr is not None:
                        tr.complete("pipeline.unpack", t0, t1,
                                    args_of(idx, item))
                except Exception as exc:
                    guard(item, exc)

        def drain(q):
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    return

        t_pack = threading.Thread(target=packer, name="racon-tpu-pack",
                                  daemon=True)
        t_unpack = threading.Thread(target=unpacker, name="racon-tpu-unpack",
                                    daemon=True)
        t_pack.start()
        t_unpack.start()
        try:
            # the dispatch loop always drains packed_q to its sentinel and
            # waiting_q always gets one, so neither worker can deadlock on
            # a bounded-queue put even when abort fires mid-stream
            while True:
                entry = packed_q.get()
                if entry is _STOP:
                    break
                if abort.is_set():
                    continue
                idx, item, ops = entry
                try:
                    t0 = time.perf_counter()
                    handle = dispatch(item, ops)
                    t1 = time.perf_counter()
                    stats.bump("device_s", t1 - t0)
                    stats.bump("chunks")
                    if tr is not None:
                        tr.complete("pipeline.device", t0, t1,
                                    dict(args_of(idx, item),
                                         seg="dispatch"))
                except Exception as exc:
                    guard(item, exc)
                    continue
                waiting_q.put((idx, item, handle, t1 - t0))
        except BaseException:
            # exceptional exit (KeyboardInterrupt is the real case): the
            # workers may be blocked on the bounded queues, so a plain
            # join would deadlock. Set abort, keep the queues draining
            # while the packer winds down, and never block indefinitely —
            # an unpacker stuck inside a hung device wait() is a daemon
            # thread and is abandoned rather than hanging the caller.
            abort.set()
            while t_pack.is_alive():
                drain(packed_q)
                t_pack.join(timeout=0.1)
            drain(waiting_q)
            try:
                waiting_q.put_nowait(_STOP)
            except queue.Full:
                pass
            t_unpack.join(timeout=2.0)
            raise
        waiting_q.put(_STOP)
        t_unpack.join()
        t_pack.join()
        if fatal:
            raise fatal[0]

    # ---------------------------------------------------- fallback pool
    def submit_fallback(self, fn, *args, **kwargs) -> Future:
        """Schedule host-only work concurrently with the device stages
        (inline at depth 0). Returns a Future; collect with `.result()`
        after `drain_fallback()`. Fallback jobs are an injection point
        (`fallback:chunk=<N>` counts submissions) and share the
        watchdog's bounded retry — without its deadline: host work is
        CPU-bound and finite, and abandoning it would leak the thread."""
        stats = self.stats
        faults, wd = self.faults, self.watchdog
        idx = next(self._fb_counter)

        def job():
            if faults is not None:
                faults.fire("fallback", idx, stats=stats)
            return fn(*args, **kwargs)

        def timed():
            t0 = time.perf_counter()
            try:
                if wd is None:
                    return job()
                return Watchdog(timeout=0.0, retries=wd.retries,
                                backoff=wd.backoff).call(job, stats=stats)
            finally:
                t1 = time.perf_counter()
                stats.bump("fallback_s", t1 - t0)
                tr = trace.get_tracer()
                if tr is not None:
                    tr.complete("pipeline.fallback", t0, t1, {"job": idx})

        if self.depth == 0:
            fut: Future = Future()
            try:
                fut.set_result(timed())
            except BaseException as exc:
                fut.set_exception(exc)
        else:
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=self.fallback_workers,
                    thread_name_prefix="racon-tpu-fallback")
            fut = self._executor.submit(timed)
        self._futures.append(fut)
        return fut

    def map_fallback(self, idxs, fn, chunk: int = 256) -> list:
        """Submit `fn(sub)` for successive `chunk`-sized slices of `idxs`.
        Returns [(sub, future), ...]; collect each future's result (one
        entry per index in `sub`) after drain_fallback() — the shared
        submit half of the reject-fallback protocol both hot phases use."""
        out = []
        for s in range(0, len(idxs), chunk):
            sub = list(idxs[s:s + chunk])
            out.append((sub, self.submit_fallback(fn, sub)))
        return out

    def drain_fallback(self, ignore_errors: bool = False) -> None:
        """Block until every submitted fallback job finished; re-raises
        the first failure unless `ignore_errors` (the abandon path)."""
        futures, self._futures = self._futures, []
        first: BaseException | None = None
        for fut in futures:
            try:
                fut.result()
            except BaseException as exc:
                if first is None:
                    first = exc
        if first is not None and not ignore_errors:
            raise first

    def cancel_fallback(self) -> tuple[int, int]:
        """Abandon the fallback queue: cancel every not-yet-started job
        and block until the running ones finish (their results and
        errors are discarded). Returns (cancelled, drained) counts.

        This is the device-failure reset path: before the caller
        restarts a whole phase on host, no orphaned fallback thread may
        keep working (and bumping a just-restarted progress bar) and no
        queued job may still start and burn host threads the restart
        needs."""
        futures, self._futures = self._futures, []
        cancelled = sum(1 for fut in futures if fut.cancel())
        drained = 0
        for fut in futures:
            if fut.cancelled():
                continue
            try:
                fut.result()
            except BaseException:
                pass
            drained += 1
        if cancelled:
            self.stats.bump("cancelled", cancelled)
        return cancelled, drained

    def close(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None

    def __enter__(self) -> "DispatchPipeline":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()
