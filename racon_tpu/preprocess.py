"""Paired-end FASTQ header uniquifier — the racon_preprocess role.

Illumina paired-end runs give both mates the same header up to the first
whitespace; racon needs unique names. Like the reference script
(scripts/racon_preprocess.py:11-60): the first occurrence of a name gets
'1' appended, the second '2'; output is FASTQ on stdout. Accepts one or
two input files (gzip-transparent, multi-line records supported via the
framework parser — the reference script handles wrapped FASTQ the same
way)."""

from __future__ import annotations

import argparse
import sys

from .errors import RaconError
from .io.parsers import create_sequence_parser


def process(paths: list[str], out=None) -> None:
    out = out if out is not None else sys.stdout.buffer
    seen: dict[str, int] = {}
    for path in paths:
        seqs: list = []
        create_sequence_parser(path, "preprocess").parse(seqs, -1)
        for s in seqs:
            name = s.name.split(" ")[0]
            # occurrence index: mate 1 -> "1", mate 2 -> "2" (like the
            # reference); further repeats keep counting up so names stay
            # unique even on malformed triplicated input
            count = seen.get(name, 0) + 1
            seen[name] = count
            name += str(count)
            qual = s.quality if s.quality else b"!" * len(s.data)
            out.write(b"@" + name.encode() + b"\n" + s.data + b"\n+\n"
                      + qual + b"\n")
    out.flush()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="racon_tpu_preprocess",
        description="uniquify paired-end read headers for racon_tpu")
    parser.add_argument("first")
    parser.add_argument("second", nargs="?")
    args = parser.parse_args(argv)
    paths = [args.first] + ([args.second] if args.second else [])
    try:
        process(paths)
    except RaconError as exc:
        print(str(exc), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
