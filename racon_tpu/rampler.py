"""Sequence subsampling and splitting — the rampler role.

The reference wrapper shells out to the vendored `rampler` binary for two
operations (scripts/racon_wrapper.py:62-63,87-88; SURVEY.md §2b):

  subsample <sequences> <reference_length> <coverage>
      randomly sample reads until their total length reaches
      reference_length * coverage; written once per requested coverage as
      `<base>_<coverage>x.<ext>`.
  split <sequences> <chunk_size>
      partition the sequences into consecutive chunks of at most
      `chunk_size` bytes of sequence data, written as `<base>_<i>.<ext>`.

This implementation uses the framework's own parsers (gzip-transparent)
and writes plain FASTA/FASTQ, matching rampler's output naming so the
wrapper's file discovery works identically.
"""

from __future__ import annotations

import os
import random
import sys

from .errors import RaconError
from .io.parsers import create_sequence_parser


def _load(path: str):
    seqs: list = []
    create_sequence_parser(path, "rampler").parse(seqs, -1)
    return seqs


def _base_and_ext(path: str) -> tuple[str, str]:
    base = os.path.basename(path).split(".")[0]
    is_fasta = any(path.endswith(e) for e in
                   (".fasta", ".fasta.gz", ".fa", ".fa.gz",
                    ".fna", ".fna.gz"))
    return base, (".fasta" if is_fasta else ".fastq")


def _write(path: str, seqs, ext: str) -> None:
    with open(path, "wb") as f:
        for s in seqs:
            if ext == ".fastq" and s.quality:
                f.write(b"@" + s.name.encode() + b"\n" + s.data + b"\n+\n"
                        + s.quality + b"\n")
            else:
                f.write(b">" + s.name.encode() + b"\n" + s.data + b"\n")


def _resolve_seed(seed: int | None) -> int:
    """Explicit `seed=` wins; RACON_TPU_SUBSAMPLE_SEED next; 17 (the
    historical constant) last. A typo'd env value is a hard error — a
    silently random subsample is exactly the nondeterminism the seed
    exists to prevent."""
    if seed is not None:
        return int(seed)
    raw = os.environ.get("RACON_TPU_SUBSAMPLE_SEED")
    if raw is None:
        return 17
    try:
        return int(raw)
    except ValueError:
        raise RaconError(
            "rampler.subsample",
            f"invalid RACON_TPU_SUBSAMPLE_SEED {raw!r} (want an "
            "integer)!") from None


def subsample(sequences_path: str, reference_length: int, coverage: int,
              out_directory: str = ".", seed: int | None = None) -> str:
    """Random subsample to ~reference_length * coverage total bases.
    Returns the output path `<base>_<coverage>x.<ext>`.

    Deterministic: the shuffle is seeded (explicit `seed=`, else
    RACON_TPU_SUBSAMPLE_SEED, else a fixed default), so the same inputs
    and seed always pick the same reads — subsample-on-admit
    (serve/ingest.py) and tests rely on this."""
    seed = _resolve_seed(seed)
    seqs = _load(sequences_path)
    base, ext = _base_and_ext(sequences_path)
    if ext == ".fastq" and not all(s.quality for s in seqs):
        ext = ".fasta"

    target = reference_length * coverage
    order = list(range(len(seqs)))
    random.Random(seed).shuffle(order)
    picked = []
    total = 0
    for i in order:
        if total >= target:
            break
        picked.append(i)
        total += len(seqs[i].data)
    picked.sort()  # keep input order, like a streaming sampler would

    out = os.path.join(out_directory, f"{base}_{coverage}x{ext}")
    _write(out, [seqs[i] for i in picked], ext)
    return out


def split(sequences_path: str, chunk_size: int,
          out_directory: str = ".") -> list[str]:
    """Partition into consecutive chunks of <= chunk_size sequence bytes
    (any sequence longer than chunk_size gets its own chunk). Returns the
    output paths `<base>_<i>.<ext>`."""
    if chunk_size <= 0:
        raise RaconError("rampler.split", "invalid chunk size!")
    seqs = _load(sequences_path)
    base, ext = _base_and_ext(sequences_path)

    outs: list[str] = []
    chunk: list = []
    chunk_bytes = 0
    for s in seqs:
        if chunk and chunk_bytes + len(s.data) > chunk_size:
            out = os.path.join(out_directory, f"{base}_{len(outs)}{ext}")
            _write(out, chunk, ext)
            outs.append(out)
            chunk, chunk_bytes = [], 0
        chunk.append(s)
        chunk_bytes += len(s.data)
    if chunk:
        out = os.path.join(out_directory, f"{base}_{len(outs)}{ext}")
        _write(out, chunk, ext)
        outs.append(out)
    return outs


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="racon_tpu_rampler",
        description="sequence subsampling/splitting (rampler equivalent)")
    parser.add_argument("-o", "--out-directory", default=".")
    sub = parser.add_subparsers(dest="mode", required=True)
    p_sub = sub.add_parser("subsample")
    p_sub.add_argument("sequences")
    p_sub.add_argument("reference_length", type=int)
    p_sub.add_argument("coverage", type=int)
    p_sub.add_argument("--seed", type=int, default=None,
                       help="shuffle seed (default: "
                            "RACON_TPU_SUBSAMPLE_SEED, else 17)")
    p_spl = sub.add_parser("split")
    p_spl.add_argument("sequences")
    p_spl.add_argument("chunk_size", type=int)

    args = parser.parse_args(argv)
    try:
        if args.mode == "subsample":
            subsample(args.sequences, args.reference_length, args.coverage,
                      args.out_directory, seed=args.seed)
        else:
            split(args.sequences, args.chunk_size, args.out_directory)
    except RaconError as exc:
        print(str(exc), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
