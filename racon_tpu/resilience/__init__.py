"""Resilience layer: fault injection, device watchdog/retry, quarantine.

The reference's only failure posture is a hard exit via `CU_CHECK_ERR`
(cudautils.hpp:10-18). The TPU pipeline instead degrades in bounded,
observable steps, and every failure mode is *injectable* so the whole
ladder is exercisable in CI without real hardware faults:

  1. `faults.FaultPlan` — a deterministic fault-injection harness armed
     from `RACON_TPU_FAULT_PLAN` / `--tpu-fault-plan`
     (`device:chunk=3:raise,device:chunk=7:hang=5,unpack:chunk=2:corrupt`);
     hooks sit at the dispatch pipeline's pack/device/unpack stages and
     its fallback pool (pipeline/__init__.py).
  2. `watchdog.Watchdog` — a configurable deadline on device-stage calls
     (`--tpu-device-timeout`; a timed-out call raises
     errors.DeviceTimeout instead of hanging the run) plus bounded retry
     with exponential backoff (`RACON_TPU_DEVICE_RETRIES`, default 1
     once the watchdog is on) before a chunk routes to host fallback.
  3. Per-window quarantine — a window whose consensus fails on both the
     device and the host keeps its draft backbone as consensus and is
     counted (ops/poa.py), mirroring the reference's `ratio > 0`
     unpolished handling (polisher.cpp:515) at failure time instead of
     output time.
  4. Degradation report — retries / backoff seconds / timeouts / breaker
     trips / quarantined windows / cancelled futures accumulate in the
     shared PipelineStats, surface in `polisher.stage_stats`, and ride
     bench.py's JSON artifact next to the PR-1 stage counters.

Strictness: `RACON_TPU_STRICT` / `--tpu-strict` (`strict_mode()`) turns
every degradation point back into a raise — the bench/CI discipline.
Decisions key on the error taxonomy in errors.py (DeviceError /
DeviceTimeout / ChunkCorrupt), never on exception message strings.

With no fault plan and no timeout/retry configuration, every hook in the
hot path collapses to a `None` check — the clean path stays byte- and
cost-identical to the pre-resilience code.
"""

from __future__ import annotations

import contextlib
import os
import threading

from .faults import FaultPlan, get_fault_plan, reset_fault_plan
from .watchdog import Watchdog

__all__ = ["FaultPlan", "Watchdog", "get_fault_plan", "reset_fault_plan",
           "strict_mode", "strict_scope", "degradation_summary"]

#: per-thread strictness override (serve mode: one job's strict posture
#: must not leak into concurrent jobs sharing the process, so the env
#: knob alone cannot carry it)
_strict_local = threading.local()


def strict_mode() -> bool:
    """True when device failures must re-raise instead of degrading
    (RACON_TPU_STRICT env, mirrored by the --tpu-strict CLI flag). A
    `strict_scope` override on the calling thread wins over the env —
    the serve layer's per-job posture. Every strict decision is made on
    the thread driving the failing phase (the polisher's catch sites and
    the engines' on_error selection), so a thread-local is sufficient."""
    override = getattr(_strict_local, "value", None)
    if override is not None:
        return override
    return bool(os.environ.get("RACON_TPU_STRICT"))


@contextlib.contextmanager
def strict_scope(value: bool | None):
    """Pin `strict_mode()` to `value` for the calling thread (None =
    no-op, defer to the environment). The serve worker wraps each job in
    this so a `strict: true` request degrades nothing — its failures
    surface as one typed error response — while concurrent jobs keep
    the default posture."""
    if value is None:
        yield
        return
    prev = getattr(_strict_local, "value", None)
    _strict_local.value = bool(value)
    try:
        yield
    finally:
        _strict_local.value = prev


#: stage_stats keys owned by the resilience layer (PipelineStats carries
#: them next to the PR-1 stage counters; bench.py publishes the snapshot)
REPORT_KEYS = ("faults", "retries", "timeouts", "backoff_s",
               "breaker_trips", "quarantined", "cancelled")


def degradation_summary(stats: dict) -> str | None:
    """One-line human degradation report from a PipelineStats snapshot,
    or None when the run degraded nowhere (the common case: silence)."""
    parts = []
    for key in REPORT_KEYS:
        v = stats.get(key, 0)
        if v:
            parts.append(f"{key} {v:.2f}s" if key == "backoff_s"
                         else f"{key} {v}")
    return ", ".join(parts) if parts else None
