"""Deterministic fault-injection harness.

A fault plan is a comma-separated list of armed faults:

    <stage>:chunk=<N>:<action>
    stage  ::= pack | device | unpack | fallback
    action ::= raise | corrupt | hang=<seconds>

e.g. ``device:chunk=3:raise,device:chunk=7:hang=5,unpack:chunk=2:corrupt``
arms a DeviceError on the 4th device dispatch, a 5 s stall on the 8th,
and a ChunkCorrupt on the 3rd unpack. `chunk` counts per stage per
pipeline run, in submission order; the first stage to reach the armed
index fires the fault (with the device aligner enabled the alignment
phase's pipeline runs first, otherwise the consensus phase's). Every
fault is ONE-SHOT: a retry of the same call finds it already consumed
and succeeds — exactly the transient-fault shape the watchdog/retry
policy (resilience/watchdog.py) is meant to absorb. Persistent failures
are modelled by arming the same (stage, chunk) several times.

Actions map onto the error taxonomy (errors.py): `raise` -> DeviceError,
`corrupt` -> ChunkCorrupt (the detected-corruption model: bad data raises
at the unpack boundary rather than flowing downstream), `hang=<s>` ->
the call stalls for <s> seconds — under a watchdog deadline that becomes
a DeviceTimeout; without one the run just finishes late, never deadlocks
(hangs are finite by construction). A stalled sleep is cancellable
(`cancel_hangs`) so a watchdog-abandoned thread exits promptly instead
of lingering past the run.

`sdc` is the SILENT-data-corruption model, the one failure the whole
detected-error taxonomy above cannot represent: a device that computed
WRONG BYTES without tripping any check. A `device:chunk=<N>:sdc` fault
never raises — `fire()` skips it; instead the consensus engine consumes
it at the end of its pass (`corrupt_consensus`), flipping one base of
the N-th polished window's consensus. Nothing in the resilience ladder
can catch it by design: only the identity-audit sentinel
(racon_tpu/obs/audit.py), which shadow re-executes sampled windows
through the oracle path and byte-compares, detects it — faultcheck's
audit cells gate exactly that.

The plan armed from RACON_TPU_FAULT_PLAN is process-cached per spec
string (`get_fault_plan`) so the polisher's alignment- and consensus-
phase pipelines share ONE set of one-shot faults; tests re-arm with
`reset_fault_plan()`.
"""

from __future__ import annotations

import os
import threading
import time

from ..errors import ChunkCorrupt, DeviceError, RaconError

STAGES = ("pack", "device", "unpack", "fallback")
ACTIONS = ("raise", "corrupt", "hang", "sdc")

#: the base substituted in by an `sdc` flip: deterministic (same plan,
#: same bytes) and always a REAL base, so the corruption is plausible
#: biological output — invisible to any format-level check
_SDC_FLIP = {65: 67, 67: 71, 71: 84, 84: 65}  # A->C->G->T->A

#: granularity of the cancellable hang sleep
_HANG_SLICE = 0.05


class Fault:
    """One armed fault: fires at most once, then stays consumed."""

    __slots__ = ("stage", "chunk", "action", "seconds", "fired")

    def __init__(self, stage: str, chunk: int, action: str,
                 seconds: float = 0.0):
        self.stage = stage
        self.chunk = chunk
        self.action = action
        self.seconds = seconds
        self.fired = False

    def __repr__(self):  # diagnostics only
        arg = f"={self.seconds:g}" if self.action == "hang" else ""
        return (f"{self.stage}:chunk={self.chunk}:{self.action}{arg}"
                f"{' (fired)' if self.fired else ''}")


class FaultPlan:
    """Parsed fault plan with thread-safe one-shot firing."""

    def __init__(self, faults: list[Fault], spec: str = ""):
        self.spec = spec
        self._faults = faults
        self._lock = threading.Lock()
        self._hang_abort = threading.Event()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        faults: list[Fault] = []
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":")
            if len(parts) != 3:
                raise RaconError(
                    "resilience.FaultPlan",
                    f"invalid fault entry {entry!r} (expected "
                    "<stage>:chunk=<N>:<action>)!")
            stage, chunk_s, action_s = parts
            if stage not in STAGES:
                raise RaconError(
                    "resilience.FaultPlan",
                    f"unknown fault stage {stage!r} (expected one of "
                    f"{', '.join(STAGES)})!")
            if not chunk_s.startswith("chunk="):
                raise RaconError(
                    "resilience.FaultPlan",
                    f"invalid fault target {chunk_s!r} (expected "
                    "chunk=<N>)!")
            try:
                chunk = int(chunk_s[len("chunk="):])
            except ValueError:
                raise RaconError(
                    "resilience.FaultPlan",
                    f"invalid fault chunk index {chunk_s!r}!") from None
            action, _, arg = action_s.partition("=")
            if action not in ACTIONS:
                raise RaconError(
                    "resilience.FaultPlan",
                    f"unknown fault action {action!r} (expected one of "
                    f"{', '.join(ACTIONS)})!")
            seconds = 0.0
            if action == "hang":
                try:
                    seconds = float(arg)
                except ValueError:
                    raise RaconError(
                        "resilience.FaultPlan",
                        f"invalid hang duration {arg!r} (expected "
                        "hang=<seconds>)!") from None
                if seconds <= 0:
                    raise RaconError(
                        "resilience.FaultPlan",
                        "hang duration must be positive!")
            elif arg:
                raise RaconError(
                    "resilience.FaultPlan",
                    f"action {action!r} takes no argument!")
            faults.append(Fault(stage, chunk, action, seconds))
        if not faults:
            raise RaconError("resilience.FaultPlan", "empty fault plan!")
        return cls(faults, spec)

    # ------------------------------------------------------------- firing
    def fire(self, stage: str, chunk: int, stats=None) -> None:
        """Hook called by the pipeline as `stage` starts its `chunk`-th
        item: consumes and enacts the first matching unfired fault."""
        with self._lock:
            # sdc faults are NOT stage hooks: they model corruption the
            # stages never see, consumed by corrupt_consensus() instead
            fault = next((f for f in self._faults
                          if not f.fired and f.stage == stage
                          and f.chunk == chunk
                          and f.action != "sdc"), None)
            if fault is None:
                return
            fault.fired = True
        if stats is not None:
            stats.bump("faults")
        if fault.action == "hang":
            self._hang(fault.seconds)
            return
        exc_cls = ChunkCorrupt if fault.action == "corrupt" else DeviceError
        raise exc_cls("resilience.FaultPlan",
                      f"injected {fault.action} fault at {stage} "
                      f"chunk {chunk}")

    def _hang(self, seconds: float) -> None:
        # a cancel that fired with no sleeper (a REAL slow call tripped
        # the watchdog) must not instantly void this armed stall: the
        # flag belongs to the sleep in progress, so clear it on entry
        self._hang_abort.clear()
        deadline = time.perf_counter() + seconds
        while time.perf_counter() < deadline:
            if self._hang_abort.wait(_HANG_SLICE):
                self._hang_abort.clear()
                return

    def corrupt_consensus(self, windows, stats=None) -> int:
        """Consume armed `sdc` faults against a finished consensus pass:
        for each unfired `device:chunk=N:sdc`, flip one base in the N-th
        POLISHED window's consensus (submission order) — wrong bytes,
        no exception, exactly the silent-corruption shape a bad chip
        produces. Returns the number of windows corrupted. Called by
        BatchPOA at the end of every generate_consensus; a plan with no
        sdc faults costs one lock-free scan."""
        with self._lock:
            armed = [f for f in self._faults
                     if not f.fired and f.action == "sdc"]
            if not armed:
                return 0
            polished = [w for w in windows if w.polished and w.consensus]
            hit = 0
            for fault in armed:
                if fault.chunk >= len(polished):
                    continue  # stays armed for a later, larger pass
                fault.fired = True
                w = polished[fault.chunk]
                cons = bytearray(w.consensus)
                i = len(cons) // 2
                cons[i] = _SDC_FLIP.get(cons[i], 65)
                w.consensus = bytes(cons)
                hit += 1
        if stats is not None:
            for _ in range(hit):
                stats.bump("faults")
        return hit

    def cancel_hangs(self) -> None:
        """Wake any in-progress hang sleep — the watchdog calls this on a
        deadline trip so the abandoned thread exits promptly instead of
        outliving the run."""
        self._hang_abort.set()

    @property
    def unfired(self) -> list[Fault]:
        with self._lock:
            return [f for f in self._faults if not f.fired]


# process-level plan cache: one set of one-shot faults shared by every
# pipeline the run constructs (alignment + consensus phases)
_cache: dict[str, FaultPlan] = {}


def get_fault_plan() -> FaultPlan | None:
    """The armed plan from RACON_TPU_FAULT_PLAN, or None (the common
    case — callers skip every hook)."""
    spec = os.environ.get("RACON_TPU_FAULT_PLAN")
    if not spec:
        return None
    plan = _cache.get(spec)
    if plan is None:
        plan = _cache[spec] = FaultPlan.parse(spec)
    return plan


def reset_fault_plan() -> None:
    """Drop cached plans so the next get_fault_plan() re-arms (tests and
    tools running several injected runs in one process)."""
    _cache.clear()
