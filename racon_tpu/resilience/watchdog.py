"""Device watchdog: deadline + bounded retry with exponential backoff.

The reference's failure posture on a stuck CUDA launch is to block
forever inside the driver (CU_CHECK_ERR only sees *returned* errors,
cudautils.hpp:10-18). Here every device-stage call can run under a
deadline: the call is made on a disposable worker thread and the caller
waits at most `timeout` seconds — past that a DeviceTimeout (errors.py)
is raised and the worker is abandoned (daemon; an injected hang is also
cancelled via the fault plan's `cancel_hangs` so the thread exits
promptly). The chunk then follows the normal failure route: bounded
retry here, host fallback in the caller, per-window quarantine last.

Retry policy: `retries` extra attempts with exponential backoff
(`backoff * 2^attempt` seconds). Retries and backoff seconds are counted
into the shared PipelineStats so the degradation report can show them.

Configuration (all off by default — the clean path never pays a thread
hop): `--tpu-device-timeout` / RACON_TPU_DEVICE_TIMEOUT seconds (0 =
no deadline), RACON_TPU_DEVICE_RETRIES (default 1 once a timeout is
set, else 0), RACON_TPU_RETRY_BACKOFF base seconds (default 0.25).
`from_env()` returns None when nothing is configured, and callers treat
a None watchdog as "call directly".
"""

from __future__ import annotations

import os
import threading
import time

from ..errors import DeviceTimeout, RaconError


def _env_number(var: str, default: str, conv):
    """Posture knobs fail as RaconError (clean CLI diagnostic), never a
    ValueError traceback from deep inside pipeline construction."""
    raw = os.environ.get(var, default)
    try:
        return conv(raw or default)
    except ValueError:
        raise RaconError(
            "resilience.Watchdog",
            f"invalid {var} value {raw!r} (expected a number)!") from None


class Watchdog:
    def __init__(self, timeout: float = 0.0, retries: int = 0,
                 backoff: float = 0.25):
        self.timeout = max(0.0, float(timeout))
        self.retries = max(0, int(retries))
        self.backoff = max(0.0, float(backoff))

    @classmethod
    def from_env(cls, timeout: float | None = None) -> "Watchdog | None":
        """Watchdog per the env posture knobs (explicit `timeout`, e.g.
        the CLI flag, wins over RACON_TPU_DEVICE_TIMEOUT). None when
        neither a deadline nor retries are configured."""
        if timeout is None:
            timeout = _env_number("RACON_TPU_DEVICE_TIMEOUT", "0", float)
        if os.environ.get("RACON_TPU_DEVICE_RETRIES") is not None:
            retries = _env_number("RACON_TPU_DEVICE_RETRIES", "0", int)
        else:
            retries = 1 if timeout > 0 else 0
        if timeout <= 0 and retries <= 0:
            return None
        backoff = _env_number("RACON_TPU_RETRY_BACKOFF", "0.25", float)
        return cls(timeout=timeout, retries=retries, backoff=backoff)

    # -------------------------------------------------------------- calls
    def call(self, fn, stats=None, retry: bool = True,
             deadline: bool = True, on_timeout=None):
        """Run `fn()` under the deadline, retrying failed attempts with
        exponential backoff. `retry=False` limits to one attempt (the
        result-wait stage: re-waiting on a hung handle would just burn a
        second deadline — the chunk routes to fallback instead).
        `deadline=False` keeps the retry policy but calls inline (host
        pack/unpack stages: CPU-bound and finite, and abandoning them
        would leak the thread). `on_timeout` runs when a deadline trips,
        before the retry/raise (used to cancel injected hang sleeps)."""
        attempts = 1 + (self.retries if retry else 0)
        for attempt in range(attempts):
            try:
                if deadline:
                    return self._deadline(fn, stats, on_timeout)
                return fn()
            except Exception:
                if attempt + 1 >= attempts:
                    raise
                delay = self.backoff * (2 ** attempt)
                if stats is not None:
                    stats.bump("retries")
                    stats.bump("backoff_s", delay)
                if delay:
                    t0 = time.perf_counter()
                    time.sleep(delay)
                    from ..obs import trace

                    tr = trace.get_tracer()
                    if tr is not None:
                        tr.complete("watchdog.backoff", t0,
                                    time.perf_counter(),
                                    {"attempt": attempt + 1})

    def _deadline(self, fn, stats, on_timeout):
        if self.timeout <= 0:
            return fn()
        box: dict = {}
        done = threading.Event()

        def runner():
            try:
                box["result"] = fn()
            except BaseException as exc:
                box["error"] = exc
            finally:
                done.set()

        worker = threading.Thread(target=runner, daemon=True,
                                  name="racon-tpu-watchdog")
        worker.start()
        if not done.wait(self.timeout):
            if on_timeout is not None:
                on_timeout()
            if stats is not None:
                stats.bump("timeouts")
            raise DeviceTimeout(
                "resilience.Watchdog",
                f"device stage exceeded the {self.timeout:g}s deadline")
        if "error" in box:
            raise box["error"]
        return box["result"]
