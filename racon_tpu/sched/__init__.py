"""Occupancy-aware batch scheduler shared by all three device engines.

Every device hot path pads jobs up to a shape ladder: the overlap aligner
(`ops/align.BatchAligner.BUCKETS`, 8 length edges), the session POA
engine (`ops/poa_graph.BUCKETS`, a 4-entry (nodes, len) grid) and the
fused POA engine (`ops/poa_fused.DEPTH_BUCKETS`, 4 depth buckets). The
static ladders are sized for the worst case, so easy inputs burn the
worst case's FLOPs — the occupancy problem cudapoa solves with its
add_windows-until-full batch sizing (cudabatch.cpp:77-270), transplanted
to XLA's static-shape world. `BatchScheduler` packages the three answers:

  1. ADAPTIVE LADDERS (`--tpu-adaptive-buckets` /
     RACON_TPU_ADAPTIVE_BUCKETS, default OFF — the static ladders remain
     the fallback): at run start each engine hands the scheduler its
     actual job-shape histogram and gets back a ladder of at most K
     shapes (K = the static ladder's size, so adaptive mode never
     compiles more programs than static mode) minimizing total padded
     cells — the exact DPs in `ladder.py`. Data-derived shapes recompile
     per dataset, which is why the flag composes with the persistent
     compile cache below: the second run of a dataset (or any dataset
     quantizing to the same edges) pays zero XLA.

  2. LENGTH-SORTED PACKING: with the scheduler enabled, jobs are sorted
     by shape before chunking, so each dispatched batch is
     shape-homogeneous instead of inheriting arrival order. Results are
     committed back by original index (every engine already assembles
     results positionally), so output stays byte-identical — the tests
     in tests/test_sched.py pin this on all three engines.

  3. OCCUPANCY TELEMETRY (`telemetry.OccupancyStats`, always on — the
     counters are a few adds per dispatched batch): per-bucket jobs /
     batches / lanes / useful-vs-padded cells / occupancy %% and
     per-engine compile count + seconds, flowing through
     `polisher.occupancy_stats` into bench.py's JSON artifact.

The persistent compile cache (`--tpu-compile-cache DIR` /
RACON_TPU_COMPILE_CACHE) wires jax's compilation cache
(`jax_compilation_cache_dir`) so repeated runs — including adaptive-
ladder runs with data-derived shapes — skip recompiles entirely.

The scheduler deliberately changes only WHICH static shapes exist and
HOW jobs are ordered into chunks; chunk dispatch still flows through
`pipeline.DispatchPipeline`, so the resilience layer's per-chunk fault
hooks, watchdog, and fallback/quarantine routing apply unchanged to
repacked chunks (pinned by tests).
"""

from __future__ import annotations

import os

from .ladder import ladder_1d, ladder_2d, padded_cost_1d, round_up
from .telemetry import OccupancyStats

__all__ = ["BatchScheduler", "OccupancyStats", "enable_compile_cache",
           "ladder_1d", "ladder_2d", "pack_iteration", "padded_cost_1d",
           "round_up", "shard_interleave"]


def shard_interleave(items: list, n_devices: int) -> list:
    """Strided round-robin of a shape-sorted row list across `n`
    device shards: shard s receives items s, s+n, s+2n, ... — so a
    sorted batch's large rows spread evenly across the mesh instead of
    piling the heaviest work onto the last shard (contiguous split of
    a sorted list = systematically imbalanced per-device wall time).
    Pure permutation: per-row results are position-independent, so the
    caller's output bytes cannot change."""
    n = int(n_devices)
    if n <= 1 or len(items) <= n:
        return list(items)
    out: list = []
    for s in range(n):
        out.extend(items[s::n])
    return out


def pack_iteration(items: list, cap: int, shape_key, age_key,
                   lane_multiple: int = 1):
    """Incremental packing entry point for the continuous serve feeder
    (serve/batcher.py): from a pending pool, pick ONE bounded,
    shape-homogeneous batch that still guarantees progress for the
    oldest work.

    The pool is sorted by `shape_key` (the quantities the ladders
    bucket on — depth, length), then the contiguous slab of at most
    `cap` items CONTAINING the item with the minimal `age_key` is
    taken: the batch lands in few ladder buckets (the same
    minimal-padding win as the per-run sorted packing, applied per
    iteration) while the oldest item always ships this iteration — no
    starvation however the shapes interleave.

    `lane_multiple` is the dispatching mesh's device count: when the
    pool is deep enough, the slab is rounded DOWN to a multiple of it
    so the engine's per-device shards split evenly without padding
    lanes (the trimmed items lead the very next iteration — they only
    ever wait one extra dispatch). A pool smaller than one multiple
    ships whole; the engines then dispatch it on a sub-mesh
    (`BatchRunner.for_batch`) rather than padding up to the full mesh.

    Returns `(batch, rest)`; `rest` preserves the sorted order, ready
    to re-pool."""
    if not items:
        return [], []
    ordered = sorted(items, key=shape_key)
    cap = max(1, int(cap))
    size = min(cap, len(ordered))
    m = max(1, int(lane_multiple))
    if size > m and size % m:
        size = (size // m) * m
    oldest = min(range(len(ordered)), key=lambda i: age_key(ordered[i]))
    start = min(oldest, max(0, len(ordered) - size))
    return (ordered[start:start + size],
            ordered[:start] + ordered[start + size:])


def enable_compile_cache(path: str) -> None:
    """Point jax's persistent compilation cache at `path` (created on
    first write). Idempotent; also exported via the environment so bench
    subprocesses and wrapper children inherit it. The min-compile-time
    threshold is dropped to 0 so even fast-compiling shapes (small CPU
    test kernels, warm-run probes) persist — the cache exists to make
    the SECOND run cheap, whatever the first cost."""
    path = os.path.abspath(path)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = path
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except (AttributeError, ValueError):  # older jax: knob absent
            pass
    # jax memoizes the cache object on first use: a process that already
    # compiled something (e.g. the CLI redirecting mid-init) needs the
    # memo dropped so the new directory actually takes effect
    try:
        from jax._src.compilation_cache import reset_cache

        reset_cache()
    except Exception:
        pass


class BatchScheduler:
    """Shared scheduler handle threaded from the polisher into every
    engine: the adaptive on/off posture, the occupancy counters, and the
    per-engine ladder derivations (thin wrappers over ladder.py with
    each engine's quanta and cost model).

    One instance per polisher run; engines constructed standalone (tests,
    tools) default to `BatchScheduler.from_env()`, so the env knob alone
    arms the whole stack.
    """

    def __init__(self, adaptive: bool = False,
                 stats: OccupancyStats | None = None):
        self.adaptive = bool(adaptive)
        self.stats = stats if stats is not None else OccupancyStats()

    @classmethod
    def from_env(cls, adaptive: bool | None = None,
                 compile_cache: str | None = None) -> "BatchScheduler":
        """Build from the environment posture. Explicit arguments (the
        CLI flags) win over RACON_TPU_ADAPTIVE_BUCKETS /
        RACON_TPU_COMPILE_CACHE."""
        if adaptive is None:
            adaptive = bool(os.environ.get("RACON_TPU_ADAPTIVE_BUCKETS"))
        cache = compile_cache or os.environ.get("RACON_TPU_COMPILE_CACHE")
        if cache:
            enable_compile_cache(cache)
        return cls(adaptive=adaptive)

    # ------------------------------------------------- ladder derivation
    #: compile-shape quanta: aligner edges land on multiples of 256 (the
    #: wavefront count is 2*edge+1; coarse edges make near-identical
    #: datasets share persistent-cache entries), session grids on 64s
    #: (node rows / layer columns), depth buckets on exact integers
    ALIGNER_QUANTUM = 256
    POA_QUANTUM = 64

    def aligner_ladder(self, lengths, k: int,
                       max_length: int) -> tuple[int, ...] | None:
        """Length-bucket edges for BatchAligner from a pair-length
        histogram (max(len(q), len(t)) per pair; the aligner calls this
        once per occupied static bucket with a split budget, so bands —
        which follow the static rule — stay constant per derived group).
        Cost model: within one derivation call the band is a constant
        (pinned to the static bucket's rule), so per-lane DP area is
        proportional to the wavefront count 2e+1 — exactly what the
        kernel executes at edge e."""
        if not self.adaptive:
            return None
        eligible = [v for v in lengths if 0 < v <= max_length]
        edges = ladder_1d(eligible, k, quantum=self.ALIGNER_QUANTUM,
                          cost=lambda e: 2 * e + 1)
        return tuple(edges) or None

    def poa_grid(self, shapes, k: int, max_nodes: int,
                 max_len: int) -> tuple[tuple[int, int], ...] | None:
        """(nodes, len) bucket grid for the session engine from predicted
        job shapes (poa_graph derives the prediction from the window
        set). Shapes beyond the envelope are dropped (those jobs host-
        fallback and never dispatch); the caller appends the envelope
        bucket itself, its existing safety-net discipline."""
        if not self.adaptive:
            return None
        fit = [(n, l) for n, l in shapes if n <= max_nodes and l <= max_len]
        grid = ladder_2d(fit, k, quantum_a=self.POA_QUANTUM,
                         quantum_b=self.POA_QUANTUM,
                         area=lambda ea, eb: ea * (eb + 1))
        return tuple(grid) or None

    def depth_ladder(self, depths, k: int) -> tuple[int, ...] | None:
        """Depth buckets for the fused engine from the actual chunk-max
        depths (known exactly at run start: windows are depth-sorted
        before chunking). Every chained call of depth D costs B * D
        layer steps regardless of real layer count, so the cost of an
        edge is the edge itself."""
        if not self.adaptive:
            return None
        edges = ladder_1d(depths, k, quantum=1)
        return tuple(edges) or None

    def order(self, idxs, key):
        """Length-sorted packing: a stable shape-sort of job indices
        before chunking (identity when the scheduler is off, preserving
        arrival-order packing exactly)."""
        if not self.adaptive:
            return list(idxs)
        return sorted(idxs, key=key)
