"""Persisted per-bucket kernel autotuner: profile once, dispatch forever.

tpu_smoke.py's PALLAS_PROFILE step has measured XLA-vs-Pallas per bucket
since round 4, but the numbers only ever reached stderr — every run
re-decided the kernel plane from a static env flag. This library makes
the measurement durable and load-bearing:

  - `Autotuner.profile_session_bucket` / `profile_aligner_bucket` time
    the candidate programs for one bucket on the LIVE backend (XLA scan
    vs Pallas resident kernel, int32 vs envelope-proof int16), verify
    the candidates agree bit-for-bit on synthetic jobs, and record the
    fastest (kernel, dtype) pair;
  - the winner table persists as JSON next to the XLA compile cache
    (RACON_TPU_AUTOTUNE_CACHE, else `<compile cache>/{BASENAME}`, else
    `~/.cache/racon_tpu/{BASENAME}`), keyed by (backend, engine, bucket
    shape, score params) — a table profiled on chip never leaks into a
    CPU run and vice versa;
  - under RACON_TPU_PALLAS=auto all three engine dispatchers
    (`BatchAligner`, `DeviceGraphPOA`, `FusedPOA`) consult the table
    per bucket via `winner()`: profile once (tpu_smoke, or any explicit
    profile call), then every warm serve job and CLI run dispatches the
    measured winner. A cold run without a table dispatches the XLA
    programs exactly as today.

The same table arbitrates the fused engine's CHUNK DISPATCH under
RACON_TPU_FUSED=auto (engine "fused_loop", keyed (nodes, len,
depth-bucket)): `profile_fused_bucket` times the split chained-call
path against the single-launch fused align→window-slice→POA program
(ops/poa_fused.py) under the same identity veto, and
FusedPOA._fused_plan dispatches the measured winner per bucket — a
cold table dispatches the split path exactly as before.

Profiling is explicit, never ambient: engines only READ the table, so
the steady-state hot path costs one dict lookup per bucket and a cold
process never stalls mid-run to benchmark. A bucket already in the
table is not re-profiled (`profile_* -> fresh=False`), which is what
makes the warm second profiling run free (test-pinned, like the
compile-cache warm path).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading

import numpy as np

BASENAME = "racon_tpu_autotune.json"

#: schema version: bump when entry semantics change so a stale table is
#: ignored rather than misread
VERSION = 1


def default_table_path() -> str:
    """Where the winner table lives (see module docstring)."""
    explicit = os.environ.get("RACON_TPU_AUTOTUNE_CACHE")
    if explicit:
        return explicit
    cache = (os.environ.get("JAX_COMPILATION_CACHE_DIR")
             or os.environ.get("RACON_TPU_COMPILE_CACHE"))
    if cache:
        return os.path.join(cache, BASENAME)
    return os.path.join(os.path.expanduser("~/.cache/racon_tpu"),
                        BASENAME)


def _backend() -> str:
    import jax

    return jax.default_backend()


def posture_key() -> tuple:
    """The process's kernel/dtype posture fingerprint: every mode knob
    that can change which kernel plane produces a window's consensus
    bytes, plus the backend the mesh resolves to. The serve window
    cache (serve/wincache.py) folds this into its content-addressed
    key so a posture change — a different RACON_TPU_PALLAS/DTYPES/
    FUSED/PACK_BASES arming, a different device kind — can never
    return bytes cached under the old posture."""
    from ..ops.dtypes import dtype_mode
    from ..ops.encode import pack_bases_enabled
    from ..ops.poa_fused import fused_mode
    from ..ops.poa_pallas import pallas_mode

    try:
        backend = _backend()
    except Exception:  # noqa: BLE001 — a backend-less process still
        # has a well-defined (host) posture
        backend = "none"
    return (pallas_mode(), dtype_mode(), fused_mode(),
            pack_bases_enabled(), backend)


class Autotuner:
    """One winner table: load-on-construct, explicit save, dict lookups
    in between. Entries:

        {"kernel": "pallas"|"xla", "dtype": "int16"|"int32",
         "ms": {candidate: milliseconds, ...}, "identical": bool}

    A table that fails to parse (corrupt write, schema drift) is
    treated as absent — the autotuner must never take a run down."""

    def __init__(self, path: str | None = None):
        self.path = path or default_table_path()
        self.table: dict[str, dict] = {}
        #: per-decision consult counters: (engine, kernel, dtype) ->
        #: times `winner()` handed that decision to a dispatcher
        #: (kernel "none" = a cold bucket, the XLA-default path). The
        #: serve scrape exports them as labeled counters so a fleet
        #: view can tell which buckets run which kernel plane.
        self.consults: dict[tuple[str, str, str], int] = {}
        self._consult_lock = threading.Lock()
        try:
            with open(self.path) as fh:
                doc = json.load(fh)
            if (isinstance(doc, dict)
                    and doc.get("version") == VERSION
                    and isinstance(doc.get("winners"), dict)):
                self.table = doc["winners"]
        except (OSError, ValueError):
            pass

    # ------------------------------------------------------------ keys
    @staticmethod
    def key(engine: str, bucket, params=(), backend: str | None = None
            ) -> str:
        b = backend if backend is not None else _backend()
        bs = "x".join(str(v) for v in (bucket if isinstance(
            bucket, (tuple, list)) else (bucket,)))
        ps = ",".join(str(v) for v in params)
        return f"{b}|{engine}|{bs}|{ps}"

    def winner(self, engine: str, bucket, params=()) -> dict | None:
        """The measured entry for one bucket on THIS backend, or None
        (cold — the dispatcher keeps today's XLA default). Every call
        bumps the per-decision consult counter the scrape exports."""
        ent = self.table.get(self.key(engine, bucket, params))
        decision = (engine, str((ent or {}).get("kernel") or "none"),
                    str((ent or {}).get("dtype") or ""))
        with self._consult_lock:
            self.consults[decision] = self.consults.get(decision, 0) + 1
        return ent

    def consult_counts(self) -> list[tuple[dict, int]]:
        """Labeled samples for the scrape: ({engine, decision, dtype},
        count) per distinct decision handed out so far."""
        with self._consult_lock:
            items = sorted(self.consults.items())
        return [({"engine": eng, "decision": kern, "dtype": dt}, n)
                for (eng, kern, dt), n in items]

    def record(self, engine: str, bucket, params, entry: dict) -> None:
        self.table[self.key(engine, bucket, params)] = entry

    #: per-engine oracle candidate a demoted entry falls back to (the
    #: same candidate `_pick` uses as its identity reference)
    _ORACLE_KERNEL = {"fused_loop": "split"}

    def demote(self, engine: str | None = None, bucket=None, params=None,
               backend: str | None = None) -> list[str]:
        """ONLINE identity veto: rewrite matching winner entries to the
        oracle candidate (`xla`/`split` at int32) with `identical` False
        and `demoted` True, then atomically persist the table — the
        serve-time twin of the profile-time veto in `_pick`, invoked by
        the audit sentinel (obs/audit.py) when a shadow re-execution
        catches a production mismatch. `engine`/`bucket`/`params` narrow
        the match (None = every entry of this backend / engine); entries
        already dispatching the oracle are left alone. Returns the
        demoted keys (empty = nothing matched, nothing written).

        In-process dispatchers see the demotion IMMEDIATELY (`winner()`
        reads the same dict); the atomic rewrite makes it durable, so a
        restarted replica — or a sibling process sharing the cache —
        never re-dispatches the vetoed candidate."""
        b = backend if backend is not None else _backend()
        want_key = (self.key(engine, bucket, params or (), backend=b)
                    if engine is not None and bucket is not None
                    else None)
        demoted: list[str] = []
        for key, ent in list(self.table.items()):
            if want_key is not None:
                if key != want_key:
                    continue
            else:
                parts = key.split("|", 2)
                if len(parts) < 3 or parts[0] != b:
                    continue
                if engine is not None and parts[1] != engine:
                    continue
            if not isinstance(ent, dict):
                continue
            oracle = self._ORACLE_KERNEL.get(
                key.split("|", 2)[1], "xla")
            if (ent.get("kernel") == oracle
                    and ent.get("dtype") == "int32"):
                continue  # already the oracle candidate
            self.table[key] = {"kernel": oracle, "dtype": "int32",
                               "ms": ent.get("ms", {}),
                               "identical": False, "demoted": True}
            demoted.append(key)
        if demoted:
            try:
                self.save()
            except OSError:
                # the in-process veto stands even when the table file
                # is unwritable; durability is best-effort here
                pass
        return demoted

    def save(self) -> str:
        """Atomic write (tmp + rename) so a concurrent reader never sees
        a torn table; returns the path."""
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        doc = {"version": VERSION, "winners": self.table}
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.path) or ".", suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump(doc, fh, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return self.path

    # ------------------------------------------------------- profiling
    @staticmethod
    def _time(fn, args, reps: int, materialize: bool = True):
        """-> (mean milliseconds, last output): one warm call first
        (absorbs the compile), then `reps` materialized calls.
        `materialize=False` for candidates that already fetch their
        device results internally (the fused-loop profile returns
        plain host data that numpy cannot — and need not — coerce)."""
        import time

        def run():
            out = fn(*args)
            if materialize:
                if isinstance(out, tuple):
                    for o in out:
                        np.asarray(o)
                else:
                    np.asarray(out)
            return out

        run()
        t0 = time.perf_counter()
        for _ in range(reps):
            out = run()
        return (time.perf_counter() - t0) / max(1, reps) * 1e3, out

    def profile_session_bucket(self, n_nodes: int, seq_len: int,
                               max_pred: int, match: int, mismatch: int,
                               gap: int, rows: int = 32, reps: int = 3,
                               seed: int = 7) -> tuple[dict, bool]:
        """Time the session engine's candidates for one (nodes, len)
        bucket — XLA scan (ring-carried, the shipped configuration) vs
        the Pallas window sweep, each at int32 and (when the envelope
        proof holds) int16 — on synthetic linear-graph jobs. Returns
        (entry, fresh); fresh=False means the table already had it and
        NOTHING was run (the warm path)."""
        from ..ops.dtypes import poa_int16_ok
        from ..ops.poa_graph import RING, graph_aligner
        from ..ops.poa_pallas import fits_vmem, window_sweep

        params = (match, mismatch, gap, max_pred)
        existing = self.winner("session", (n_nodes, seq_len), params)
        if existing is not None:
            return existing, False

        args = _session_jobs(n_nodes, seq_len, max_pred, rows, seed)
        nnodes = (np.asarray(args[0]) != 5).sum(axis=1).astype(np.int32)
        ring = RING if n_nodes > RING else 0
        dtypes = ["int32"]
        if poa_int16_ok(n_nodes, seq_len, match, mismatch, gap):
            dtypes.append("int16")
        interp = _backend() == "cpu"

        ms: dict[str, float] = {}
        outs: dict[str, np.ndarray] = {}
        for dt in dtypes:
            kwargs = {} if dt == "int32" else {"score_dtype": dt}
            fn = graph_aligner(n_nodes, seq_len, max_pred, match,
                               mismatch, gap, ring=ring, **kwargs)
            ms[f"xla:{dt}"], out = self._time(fn, args, reps)
            outs[f"xla:{dt}"] = np.asarray(out)
            if fits_vmem(n_nodes, seq_len, max_pred, dt):
                pfn = window_sweep(n_nodes, seq_len, max_pred, match,
                                   mismatch, gap, interpret=interp,
                                   **kwargs)
                ms[f"pallas:{dt}"], pout = self._time(
                    pfn, args + (nnodes,), reps)
                outs[f"pallas:{dt}"] = np.asarray(pout)
        entry = self._pick(ms, outs, "xla:int32")
        self.record("session", (n_nodes, seq_len), params, entry)
        return entry, True

    def profile_aligner_bucket(self, edge: int, band: int,
                               rows: int = 8, reps: int = 3,
                               seed: int = 11) -> tuple[dict, bool]:
        """Time the aligner's candidates for one (edge, band) bucket —
        the XLA wavefront scan vs the Pallas resident kernel, int32 and
        (under the envelope proof) int16 — on synthetic mutated pairs.
        Identity is compared on EVERYTHING BatchAligner consumes: the
        decoded op runs AND the touched-edge flags AND the distances —
        the latter two drive the accept/reject (host-realign) decision,
        so a candidate that gets only the path right must still be
        vetoed."""
        from ..ops import align_pallas
        from ..ops.align import (_kernel_for, _runs_of, _traceback,
                                 _unpack_bp, band_offsets)
        from ..ops.dtypes import aligner_int16_ok
        from ..ops.encode import encode_padded

        existing = self.winner("aligner", (edge, band))
        if existing is not None:
            return existing, False

        n_waves = 2 * edge + 1
        pairs = _aligner_pairs(edge, rows, seed)
        q_arr, q_lens = encode_padded([p[0] for p in pairs], edge)
        t_arr, t_lens = encode_padded([p[1] for p in pairs], edge)
        offs = np.stack([band_offsets(int(ql), int(tl), band, n_waves)
                         for ql, tl in zip(q_lens, t_lens)])
        ql32 = q_lens.astype(np.int32)
        tl32 = t_lens.astype(np.int32)
        dtypes = ["int32"]
        if aligner_int16_ok(edge):
            dtypes.append("int16")
        interp = _backend() == "cpu"

        # distances compare normalized: the sentinel magnitude differs
        # per dtype (1<<28 vs 1<<14) but both mean "never reached (M,N)"
        def _dist_norm(d):
            return ["inf" if v >= (1 << 14) else int(v)
                    for v in np.asarray(d).astype(np.int64)]

        ms: dict[str, float] = {}
        outs: dict[str, tuple] = {}
        for dt in dtypes:
            fn = _kernel_for(band, n_waves, dt, False)
            ms[f"xla:{dt}"], out = self._time(
                fn, (q_arr, t_arr, ql32, tl32, offs), reps)
            bp = _unpack_bp(np.asarray(out[0]))
            runs, touched = _traceback(bp, offs, q_lens, t_lens)
            outs[f"xla:{dt}"] = (runs, [bool(t) for t in touched],
                                 _dist_norm(out[1]))
            if align_pallas.fits_vmem(edge, band, dt):
                pfn = align_pallas.wavefront_align(edge, band, dt, False,
                                                   interpret=interp)
                qx, tx = align_pallas.build_ext(q_arr, t_arr, band)
                ms[f"pallas:{dt}"], pout = self._time(
                    pfn, (qx, tx, ql32, tl32, offs), reps)
                op_arr = np.asarray(pout[0])
                meta = np.asarray(pout[1])
                outs[f"pallas:{dt}"] = (
                    [_runs_of(op_arr[k, :meta[k, 0]][::-1])
                     for k in range(len(pairs))],
                    [bool(t) for t in meta[:, 2] > 0],
                    _dist_norm(meta[:, 1]))
        entry = self._pick(ms, outs, "xla:int32")
        self.record("aligner", (edge, band), (), entry)
        return entry, True

    def profile_fused_bucket(self, n_nodes: int, seq_len: int,
                             depth: int, max_pred: int, match: int,
                             mismatch: int, gap: int, rows: int = 4,
                             reps: int = 2,
                             seed: int = 13) -> tuple[dict, bool]:
        """Time the fused engine's chunk-dispatch candidates for one
        (nodes, len, depth-bucket) key: the SPLIT chained-call path
        (host-side window slicing, one launch per chain bucket) vs the
        FUSED single-launch program (device-side slicing, the whole
        chain in one jitted scan — ops/poa_fused `device_slice`). The
        synthetic chunk is 1.5x the bucket deep so the split path
        genuinely chains (greedy plan [depth, ...]) while the fused
        candidate runs once; the profiled key is the chunk's LEADING
        chain bucket — exactly what FusedPOA._fused_plan consults under
        RACON_TPU_FUSED=auto. The identity veto compares the finalized
        consensus (bytes + coverages + statuses) bit-for-bit; a fast
        but diverging candidate is disqualified and flagged."""
        from ..ops.poa_fused import FusedPOA

        params = (match, mismatch, gap, max_pred)
        existing = self.winner("fused_loop", (n_nodes, seq_len, depth),
                               params)
        if existing is not None:
            return existing, False

        windows = _fused_windows(n_nodes, seq_len,
                                 depth + max(1, depth // 2), rows, seed)
        eng = FusedPOA(match, mismatch, gap, max_nodes=n_nodes,
                       max_len=seq_len, max_pred=max_pred,
                       batch_rows=rows)
        chunk = list(range(len(windows)))
        plan = eng._chain_plan(max(len(w) - 1 for w in windows))
        total = sum(plan)

        def finalize(np_state):
            results: list = [None] * len(windows)
            statuses = np.ones(len(windows), np.int32)
            eng._finalize_chunk(chunk, np_state, results, statuses)
            return ([(r[0], np.asarray(r[1]).tolist())
                     if r is not None else None for r in results],
                    statuses.tolist())

        def run_split():
            state, calls = eng._pack_chunk(windows, chunk)
            for d, ops, done in calls:
                state = eng._call(d, state, *ops, done)
            return finalize(tuple(np.asarray(x) for x in state))

        def run_fused():
            state, ops = eng._pack_chunk_fused(windows, chunk, total)
            out = eng._call_fused(total, state, *ops)
            return finalize(tuple(np.asarray(x) for x in out))

        dt = eng.score_dtype
        ms: dict[str, float] = {}
        outs: dict = {}
        ms[f"split:{dt}"], outs[f"split:{dt}"] = self._time(
            run_split, (), reps, materialize=False)
        ms[f"fused:{dt}"], outs[f"fused:{dt}"] = self._time(
            run_fused, (), reps, materialize=False)
        entry = self._pick(ms, outs, f"split:{dt}")
        self.record("fused_loop", (n_nodes, seq_len, depth), params,
                    entry)
        return entry, True

    @staticmethod
    def _pick(ms: dict, outs: dict, oracle: str) -> dict:
        """Winner selection with the identity veto: any candidate that
        does not reproduce the int32 XLA oracle bit-for-bit is
        disqualified (and flagged — that's a kernel bug, not a perf
        datum)."""
        ref = outs[oracle]

        def same(o) -> bool:
            if isinstance(ref, np.ndarray):
                return bool(np.array_equal(o, ref))
            return o == ref

        ok = {k: v for k, v in ms.items() if same(outs[k])}
        identical = len(ok) == len(ms)
        best = min(ok, key=ok.get) if ok else oracle
        kernel, dtype = best.split(":")
        return {"kernel": kernel, "dtype": dtype,
                "ms": {k: round(v, 3) for k, v in ms.items()},
                "identical": identical}


def _session_jobs(n_nodes: int, seq_len: int, max_pred: int, rows: int,
                  seed: int):
    """Linear-chain POA jobs (sequence-as-graph + a deletion-bearing
    layer), densified exactly the way the C++ session does — the same
    synthetic shape tpu_smoke has always profiled with."""
    rng = np.random.default_rng(seed)
    codes = np.full((rows, n_nodes), 5, dtype=np.int8)
    preds = np.full((rows, n_nodes, max_pred), -1, dtype=np.int16)
    centers = np.zeros((rows, n_nodes), dtype=np.int16)
    sinks = np.zeros((rows, n_nodes), dtype=np.uint8)
    seqs = np.full((rows, seq_len), 5, dtype=np.int8)
    lens = np.zeros(rows, dtype=np.int32)
    band = np.zeros(rows, dtype=np.int32)
    for k in range(rows):
        t_len = int(rng.integers(n_nodes // 2, n_nodes - 1))
        t = rng.integers(0, 4, t_len).astype(np.int8)
        q = np.concatenate([t[: t_len // 2], t[t_len // 2 + 10:]])
        q = q[:seq_len]
        codes[k, :t_len] = t
        preds[k, 0, 0] = 0
        preds[k, 1:t_len, 0] = np.arange(1, t_len)
        centers[k, :t_len] = np.arange(1, t_len + 1)
        sinks[k, t_len - 1] = 1
        seqs[k, : len(q)] = q
        lens[k] = len(q)
    return codes, preds, centers, sinks, seqs, lens, band


def _fused_windows(n_nodes: int, seq_len: int, depth: int, rows: int,
                   seed: int):
    """Spanning synthetic POA windows (backbone + substitution-mutated
    layers) for the fused-loop profile. Substitutions only — aligned
    alternates cap the graph at <= 4 nodes per backbone column, so a
    backbone of n_nodes // 5 can never overflow the (n_nodes) envelope
    however deep the chunk, and no lane ever falls back mid-profile."""
    rng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", dtype=np.uint8)
    bb_len = max(16, min(seq_len - 8, n_nodes // 5))
    windows = []
    for _ in range(rows):
        bb = bases[rng.integers(0, 4, bb_len)].tobytes()
        win = [(bb, None, 0, 0)]
        for _ in range(depth):
            arr = np.frombuffer(bb, np.uint8).copy()
            sub = rng.random(bb_len) < 0.03
            arr[sub] = bases[rng.integers(0, 4, int(sub.sum()))]
            win.append((arr.tobytes(), None, 0, bb_len - 1))
        windows.append(win)
    return windows


def _aligner_pairs(edge: int, rows: int, seed: int):
    """Mutated (query, target) pairs filling ~the bucket."""
    rng = np.random.default_rng(seed)
    bases = np.frombuffer(b"ACGT", dtype=np.uint8)
    pairs = []
    for _ in range(rows):
        n = int(rng.integers(max(2, edge // 2), edge))
        t = bases[rng.integers(0, 4, n)]
        keep = rng.random(n) >= 0.05
        sub = rng.random(n) < 0.05
        q = t.copy()
        q[sub] = bases[rng.integers(0, 4, int(sub.sum()))]
        pairs.append((q[keep].tobytes()[:edge], t.tobytes()))
    return pairs


_cached: dict[str, Autotuner] = {}


def get_autotuner() -> Autotuner:
    """Process-cached table handle, keyed by the resolved path (tests
    repoint RACON_TPU_AUTOTUNE_CACHE; runs resolve it once per path)."""
    path = default_table_path()
    at = _cached.get(path)
    if at is None:
        at = _cached[path] = Autotuner(path)
    return at


def reset_autotuner_cache() -> None:
    """Drop the process cache (tests that rewrite the table on disk)."""
    _cached.clear()
