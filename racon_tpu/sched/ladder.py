"""Bucket-ladder derivation: minimal-padding shape sets under a compile
budget.

Every device hot path pads jobs up to a static shape ladder so XLA
compiles a handful of programs instead of one per job shape (the cudapoa
BatchConfig discipline, cudabatch.cpp:56-59). A static ladder tuned for
the worst case wastes FLOPs on easy inputs: a batch of 600 bp overlaps
padded to the 4096 bucket burns ~7x the useful DP area. The solvers here
derive the ladder from the run's actual job-shape histogram instead —
choose at most K edges (K = the compile-count budget, normally the static
ladder's own size so adaptive mode never compiles MORE programs than the
static one) minimizing the total padded cells:

    minimize  sum_jobs cost(edge(job))     s.t.  |edges| <= K
    where edge(job) = the smallest chosen edge >= the job's shape

Since the useful cells are fixed by the data, minimizing total dispatched
cells equals minimizing padded cells. Both solvers are exact dynamic
programs over the sorted shape histogram (segment the sorted jobs into
<= K runs; each run's edge is its own maximum, rounded up to a compile
quantum so near-identical datasets hit the same persistent-cache entry):
O(K * U^2) for U candidate edges, with U thinned to a bound so a
multi-million-overlap run spends microseconds here, not seconds.

Correctness note: bucket shapes only control PADDING — every kernel masks
computation with the per-job true lengths/node counts — so any ladder
whose largest edge covers the largest job yields byte-identical output.
The tests in tests/test_sched.py pin that property per engine.
"""

from __future__ import annotations

#: candidate-edge thinning bound: the DP is O(K * U^2), so U is capped by
#: keeping every quantized shape when few, else an even quantile sweep
#: (the maximum always kept — the top edge must cover the largest job)
MAX_CANDIDATES = 256


def round_up(v: int, quantum: int) -> int:
    """v rounded up to a positive multiple of `quantum`."""
    q = max(1, int(quantum))
    return max(q, (int(v) + q - 1) // q * q)


def _thin(sorted_vals: list, limit: int = MAX_CANDIDATES) -> list:
    """Evenly thin a sorted candidate list to <= limit entries, always
    keeping the last (the maximum: the ladder's top edge lives there)."""
    n = len(sorted_vals)
    if n <= limit:
        return list(sorted_vals)
    step = n / float(limit)
    picked = sorted({min(n - 1, int((i + 1) * step) - 1)
                     for i in range(limit)} | {n - 1})
    return [sorted_vals[i] for i in picked]


def ladder_1d(values, k: int, quantum: int = 1, cost=None) -> list[int]:
    """Choose <= k edges covering every value with minimal total cost.

    `values`: the job shapes (lengths / depths), any iterable of ints.
    `cost(edge)`: per-job cost of dispatching at `edge` (default: the
    edge itself — the right proxy when the padded area is linear in the
    bucket edge). Edges are segment maxima rounded up to `quantum`.

    Returns the ascending edge list ([] for empty input — callers keep
    their static ladder then).
    """
    vals = sorted(int(v) for v in values)
    if not vals:
        return []
    if cost is None:
        cost = lambda e: e  # noqa: E731 — default padded-area proxy
    # histogram over quantized candidate edges: jobs in (cand[i-1],
    # cand[i]] all dispatch at cand[i] or a larger chosen edge
    cands: list[int] = []
    weights: list[int] = []
    for v in vals:
        q = round_up(v, quantum)
        if cands and cands[-1] == q:
            weights[-1] += 1
        else:
            cands.append(q)
            weights.append(1)
    if len(cands) > MAX_CANDIDATES:
        kept = _thin(cands)
        wmap = dict.fromkeys(kept, 0)
        ki = 0
        for c, w in zip(cands, weights):
            while kept[ki] < c:
                ki += 1
            wmap[kept[ki]] += w
        cands = kept
        weights = [wmap[c] for c in cands]
    U = len(cands)
    k = max(1, min(int(k), U))
    W = [0] * (U + 1)  # prefix weights
    for i, w in enumerate(weights):
        W[i + 1] = W[i] + w
    ecost = [cost(c) for c in cands]
    INF = float("inf")
    # dp[j][i]: min cost covering cands[0..i] with exactly j+1 edges,
    # the last edge being cands[i]; par[j][i]: index of the previous edge
    dp = [[INF] * U for _ in range(k)]
    par = [[-1] * U for _ in range(k)]
    for i in range(U):
        dp[0][i] = W[i + 1] * ecost[i]
    for j in range(1, k):
        dpj, dpp, parj = dp[j], dp[j - 1], par[j]
        for i in range(j, U):
            for m in range(j - 1, i):
                c = dpp[m] + (W[i + 1] - W[m + 1]) * ecost[i]
                if c < dpj[i]:
                    dpj[i] = c
                    parj[i] = m
    jbest = min(range(k), key=lambda j: dp[j][U - 1])
    edges = []
    i = U - 1
    for j in range(jbest, -1, -1):
        edges.append(cands[i])
        i = par[j][i]
        if i < 0:
            break
    return sorted(edges)


def ladder_2d(shapes, k: int, quantum_a: int = 1, quantum_b: int = 1,
              area=None) -> list[tuple[int, int]]:
    """Choose <= k (a, b) bucket pairs covering every (a, b) job shape
    with minimal total dispatched area.

    Jobs are sorted by `a` and partitioned into <= k contiguous runs;
    each run's bucket is (max a, max b) over the run, rounded up to the
    quanta — so every job fits its own run's bucket by construction
    (callers still append their envelope bucket as the safety net, the
    existing engine discipline). `area(ea, eb)` is the per-job dispatch
    cost at bucket (ea, eb) (default ea * eb — the DP-matrix area).

    Returns buckets ascending in `a` (the order the engines' first-fit
    `_bucket` scan expects). The `b` edges need not be monotone; a job
    whose `b` exceeds its a-wise bucket's edge first-fits a later bucket
    or the envelope.
    """
    jobs = sorted((int(a), int(b)) for a, b in shapes)
    if not jobs:
        return []
    if area is None:
        area = lambda ea, eb: ea * eb  # noqa: E731
    # candidate segment ends: any job index (jobs are (a, b)-sorted, so
    # a segment's last job carries its max a; cuts INSIDE an equal-a run
    # are allowed — its low-b prefix may belong in a flatter bucket)
    bounds = _thin(list(range(len(jobs))))
    U = len(bounds)
    k = max(1, min(int(k), U))
    INF = float("inf")

    def seg_cost(m: int, i: int, maxb: int) -> float:
        """Jobs (bounds[m], bounds[i]] dispatched at this segment's
        bucket; m == -1 means the segment starts at job 0."""
        ea = round_up(jobs[bounds[i]][0], quantum_a)
        eb = round_up(maxb, quantum_b)
        count = bounds[i] - (bounds[m] if m >= 0 else -1)
        return count * area(ea, eb)

    # block maxima between consecutive boundaries: blk[p] = max b over
    # jobs (bounds[p-1], bounds[p]]; the m-descending sweeps below then
    # accumulate segment max-b in O(1) per step (O(k * U^2) total)
    blk = [0] * U
    prev_end = -1
    for p in range(U):
        blk[p] = max(b for _, b in jobs[prev_end + 1:bounds[p] + 1])
        prev_end = bounds[p]

    dp = [[INF] * U for _ in range(k)]
    par = [[-1] * U for _ in range(k)]
    mb = 0
    for i in range(U):
        mb = max(mb, blk[i])
        dp[0][i] = seg_cost(-1, i, mb)
    for j in range(1, k):
        dpj, dpp, parj = dp[j], dp[j - 1], par[j]
        for i in range(j, U):
            mb = blk[i]
            for m in range(i - 1, j - 2, -1):
                c = dpp[m] + seg_cost(m, i, mb)
                if c < dpj[i]:
                    dpj[i] = c
                    parj[i] = m
                mb = max(mb, blk[m])
    jbest = min(range(k), key=lambda j: dp[j][U - 1])
    ends = []
    i = U - 1
    for j in range(jbest, -1, -1):
        ends.append(bounds[i])
        i = par[j][i]
        if i < 0:
            break
    ends = sorted(ends)
    out: list[tuple[int, int]] = []
    prev = -1
    for end in ends:
        mb = max(b for _, b in jobs[prev + 1:end + 1])
        out.append((round_up(jobs[end][0], quantum_a),
                    round_up(mb, quantum_b)))
        prev = end
    return out


def padded_cost_1d(values, edges, cost=None) -> float:
    """Total dispatch cost of `values` under the edge ladder (the metric
    ladder_1d minimizes; used by tests and the occupancy report)."""
    if cost is None:
        cost = lambda e: e  # noqa: E731
    es = sorted(edges)
    total = 0.0
    for v in values:
        e = next((x for x in es if x >= v), None)
        if e is None:
            continue  # beyond the ladder: host fallback, no device cost
        total += cost(e)
    return total
