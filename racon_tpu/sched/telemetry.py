"""Occupancy telemetry: per-bucket padding-waste counters.

The padding a shape ladder imposes was invisible until now — the bench
measured windows/sec but not how much of each dispatched batch was real
work. `OccupancyStats` makes padding waste a first-class, tracked metric:
every dispatched batch records its bucket, lane count and useful-vs-total
cells (cells = DP area for the aligner and session engine, layers for the
fused engine — each engine's natural unit of padded compute), plus the
per-engine compile count and the wall seconds the first dispatch of each
new shape cost (trace + XLA compile; ~0 when the persistent compile
cache is warm).

The snapshot flows through `polisher.occupancy_stats` into bench.py's
JSON artifact next to the pipeline stage counters, so a ladder change
shows up as a measured occupancy delta, not an anecdote.

Invariant the tests pin: per bucket, useful_cells + padded_cells ==
lanes * capacity(bucket) — the counters sum to exactly the cells the
device was asked to process.
"""

from __future__ import annotations

import threading

#: program shapes already charged to compile telemetry. Process-wide by
#: design: jit caches are per-process, so a second engine instance (or a
#: second polisher) dispatching an already-built shape really does pay
#: no compile — charging it again would overreport.
_seen_shapes: set = set()


def _copy_bucket(b: dict) -> dict:
    """Deep-enough bucket copy for reads escaping the lock: the
    shard_useful LIST must be copied under the lock too, or a
    concurrent record() mutates it mid-read and exports torn per-shard
    sums."""
    return {k: (list(v) if isinstance(v, list) else v)
            for k, v in b.items()}


def accumulate_cells(acc: list, vals) -> list:
    """Element-wise accumulate `vals` into `acc`, extending past the
    end — THE shard-list accumulation, shared by record()/merge_from()/
    snapshot() and synthbench's cross-engine scale aggregation (one
    copy, so the semantics cannot drift between them)."""
    for i, v in enumerate(vals):
        if i < len(acc):
            acc[i] += int(v)
        else:
            acc.append(int(v))
    return acc


class OccupancyStats:
    """Thread-safe per-(engine, bucket) occupancy counters.

    Counter semantics per bucket:
      jobs          real (non-pad) jobs dispatched
      batches       device batches dispatched
      lanes         total batch rows incl. round-up padding lanes
      useful_cells  cells covered by real job shapes
      padded_cells  cells burned on padding (bucket edge - job shape,
                    plus whole padding lanes)
    Per engine:
      compiles      distinct program shapes built this process
      compile_s     wall seconds spent in those shapes' first dispatch
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: dict[tuple[str, str], dict] = {}
        self._compiles: dict[str, dict] = {}
        #: optional obs.hist.HistogramSet: per-engine compile wall time
        #: observed as a latency distribution (`compile.<engine>`) —
        #: the "how long does a new shape stall a round" view the serve
        #: scrape exposes; None when nothing is watching
        self.hists = None

    def record(self, engine: str, bucket, jobs: int, lanes: int,
               useful_cells: int, total_cells: int,
               kernel: str | None = None, dtype: str | None = None,
               n_devices: int | None = None,
               shard_useful=None,
               full_mesh_cells: int | None = None) -> None:
        """Account one dispatched batch. `bucket` is any hashable shape
        descriptor (stringified for the snapshot); `total_cells` is the
        batch's full dispatched capacity (>= useful_cells). `kernel`
        ('xla' | 'pallas') and `dtype` ('int32' | 'int16') record the
        bucket's dispatched program choice — the device-kernel plane's
        per-bucket decision, surfaced next to the occupancy numbers in
        the bench JSON and synthbench report (constant per bucket within
        a run; last write wins).

        The mesh view (all optional, so host-only engines stay
        unchanged): `n_devices` is the dispatching mesh width,
        `shard_useful` the per-device-shard useful-cell split of this
        batch (accumulated element-wise — the per-shard balance number
        synthbench's scale curve gates on), and `full_mesh_cells` what
        the batch WOULD have dispatched under full-mesh `round_batch`
        rounding — the baseline the sub-mesh tail dispatch is measured
        against (equal to `total_cells` when no sub-mesh was taken)."""
        key = (engine, str(bucket))
        with self._lock:
            b = self._buckets.get(key)
            if b is None:
                b = self._buckets[key] = {
                    "jobs": 0, "batches": 0, "lanes": 0,
                    "useful_cells": 0, "padded_cells": 0}
            b["jobs"] += int(jobs)
            b["batches"] += 1
            b["lanes"] += int(lanes)
            b["useful_cells"] += int(useful_cells)
            b["padded_cells"] += int(total_cells) - int(useful_cells)
            if kernel is not None:
                b["kernel"] = kernel
            if dtype is not None:
                b["dtype"] = dtype
            if n_devices is not None:
                b["n_devices"] = int(n_devices)
            if shard_useful is not None:
                accumulate_cells(b.setdefault("shard_useful", []),
                                 shard_useful)
            if full_mesh_cells is not None:
                b["full_mesh_cells"] = (b.get("full_mesh_cells", 0)
                                        + int(full_mesh_cells))

    def record_compile(self, engine: str, seconds: float,
                       count: int = 1) -> None:
        with self._lock:
            c = self._compiles.setdefault(
                engine, {"compiles": 0, "compile_s": 0.0})
            c["compiles"] += count
            c["compile_s"] += float(seconds)
        if self.hists is not None:
            self.hists.observe(f"compile.{engine}", float(seconds))

    def record_compile_once(self, engine: str, key,
                            seconds: float) -> bool:
        """Charge `seconds` as compile wall iff `key` (the FULL program
        identity, including the batch dimension — jit programs are
        shape-keyed on it, so a tail chunk with a different lane count
        is a separate compile) is new to this process. The shared
        first-dispatch idiom of all three engines: time the dispatch,
        call this, and the first occurrence of each shape is charged."""
        k = (engine, key)
        with self._lock:
            if k in _seen_shapes:
                return False
            _seen_shapes.add(k)
        self.record_compile(engine, seconds)
        # trace the compile as a span ending now (the charge is made
        # right after the first dispatch returned, so now - seconds is
        # the dispatch's start) — the Perfetto view of "where did the
        # first chunk's stall go"
        from ..obs import trace

        tr = trace.get_tracer()
        if tr is not None:
            import time

            now = time.perf_counter()
            tr.complete("xla.compile", now - float(seconds), now,
                        {"engine": engine, "shape": str(key)})
        return True

    def merge_from(self, other: "OccupancyStats") -> None:
        """Fold another instance's counters into this one. The serve
        batcher keeps ONE OccupancyStats per worker lane — so each
        lane's per-iteration compile delta is exact under lane
        concurrency (a shared instance would charge one lane's compile
        to whichever other lane's delta window it landed in) — and
        merges them through a scratch instance for the lifetime
        occupancy view."""
        with other._lock:
            buckets = {k: _copy_bucket(v)
                       for k, v in other._buckets.items()}
            compiles = {k: dict(v) for k, v in other._compiles.items()}
        with self._lock:
            for key, b in buckets.items():
                mine = self._buckets.get(key)
                if mine is None:
                    self._buckets[key] = b
                    continue
                for k, v in b.items():
                    if k == "n_devices" or isinstance(v, str):
                        mine[k] = v  # descriptors: last write wins
                    elif isinstance(v, list):
                        accumulate_cells(mine.setdefault(k, []), v)
                    else:
                        mine[k] = mine.get(k, 0) + v
            for engine, c in compiles.items():
                mine = self._compiles.setdefault(
                    engine, {"compiles": 0, "compile_s": 0.0})
                mine["compiles"] += c["compiles"]
                mine["compile_s"] += c["compile_s"]

    def snapshot(self) -> dict:
        """{engine: {"buckets": {bucket: {..., "occupancy_pct"}},
                     "occupancy_pct", "compiles", "compile_s"}} —
        JSON-ready; empty dict when nothing was dispatched."""
        with self._lock:
            buckets = {k: _copy_bucket(v)
                       for k, v in self._buckets.items()}
            compiles = {k: dict(v) for k, v in self._compiles.items()}
        out: dict = {}
        for (engine, bucket), b in sorted(buckets.items()):
            e = out.setdefault(engine, {"buckets": {}})
            total = b["useful_cells"] + b["padded_cells"]
            e["buckets"][bucket] = dict(
                b, occupancy_pct=round(100.0 * b["useful_cells"] / total, 2)
                if total else 0.0)
        for engine, e in out.items():
            useful = sum(b["useful_cells"] for b in e["buckets"].values())
            total = useful + sum(b["padded_cells"]
                                 for b in e["buckets"].values())
            e["occupancy_pct"] = (round(100.0 * useful / total, 2)
                                  if total else 0.0)
            # the mesh view, aggregated across buckets that carry it:
            # per-shard useful-cell balance (max/min over the engine's
            # element-wise shard sums) and the padded-cell fraction vs
            # what full-mesh round_batch rounding would have dispatched
            # — the numbers the scale-curve perfgate gates. RAW sums
            # (useful/total/full-mesh cells) ride along so cross-engine
            # consumers (synthbench _scale_point) can combine fractions
            # without re-walking buckets.
            shards: list[int] = []
            fm_cells = fm_useful = 0
            for b in e["buckets"].values():
                accumulate_cells(shards, b.get("shard_useful", ()))
                if "full_mesh_cells" in b:
                    fm_cells += b["full_mesh_cells"]
                    fm_useful += b["useful_cells"]
            if shards:
                e["shard_useful"] = shards
                if min(shards) > 0:
                    e["shard_balance"] = round(
                        max(shards) / min(shards), 4)
            if total:
                e["useful_cells"] = useful
                e["total_cells"] = total
                e["padded_frac"] = round((total - useful) / total, 6)
            if fm_cells:
                e["full_mesh_cells"] = fm_cells
                e["full_mesh_useful"] = fm_useful
                e["padded_frac_full_mesh"] = round(
                    (fm_cells - fm_useful) / fm_cells, 6)
        for engine, c in compiles.items():
            e = out.setdefault(engine, {"buckets": {}})
            e["compiles"] = c["compiles"]
            e["compile_s"] = round(c["compile_s"], 3)
        return out

    def summary(self) -> str | None:
        """One-line per-engine occupancy report for stderr, or None when
        nothing was dispatched (the common host-only case: silence)."""
        snap = self.snapshot()
        parts = []
        for engine, e in snap.items():
            if not e.get("buckets"):
                continue
            jobs = sum(b["jobs"] for b in e["buckets"].values())
            batches = sum(b["batches"] for b in e["buckets"].values())
            s = (f"{engine} {e['occupancy_pct']:.1f}% "
                 f"({jobs} jobs / {batches} batches"
                 f" / {len(e['buckets'])} shapes")
            if "compiles" in e:
                s += f", {e['compiles']} compiles {e['compile_s']:.1f}s"
            parts.append(s + ")")
        return "; ".join(parts) if parts else None
