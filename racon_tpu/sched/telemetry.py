"""Occupancy telemetry: per-bucket padding-waste counters.

The padding a shape ladder imposes was invisible until now — the bench
measured windows/sec but not how much of each dispatched batch was real
work. `OccupancyStats` makes padding waste a first-class, tracked metric:
every dispatched batch records its bucket, lane count and useful-vs-total
cells (cells = DP area for the aligner and session engine, layers for the
fused engine — each engine's natural unit of padded compute), plus the
per-engine compile count and the wall seconds the first dispatch of each
new shape cost (trace + XLA compile; ~0 when the persistent compile
cache is warm).

The snapshot flows through `polisher.occupancy_stats` into bench.py's
JSON artifact next to the pipeline stage counters, so a ladder change
shows up as a measured occupancy delta, not an anecdote.

Invariant the tests pin: per bucket, useful_cells + padded_cells ==
lanes * capacity(bucket) — the counters sum to exactly the cells the
device was asked to process.
"""

from __future__ import annotations

import threading

#: program shapes already charged to compile telemetry. Process-wide by
#: design: jit caches are per-process, so a second engine instance (or a
#: second polisher) dispatching an already-built shape really does pay
#: no compile — charging it again would overreport.
_seen_shapes: set = set()


class OccupancyStats:
    """Thread-safe per-(engine, bucket) occupancy counters.

    Counter semantics per bucket:
      jobs          real (non-pad) jobs dispatched
      batches       device batches dispatched
      lanes         total batch rows incl. round-up padding lanes
      useful_cells  cells covered by real job shapes
      padded_cells  cells burned on padding (bucket edge - job shape,
                    plus whole padding lanes)
    Per engine:
      compiles      distinct program shapes built this process
      compile_s     wall seconds spent in those shapes' first dispatch
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: dict[tuple[str, str], dict] = {}
        self._compiles: dict[str, dict] = {}
        #: optional obs.hist.HistogramSet: per-engine compile wall time
        #: observed as a latency distribution (`compile.<engine>`) —
        #: the "how long does a new shape stall a round" view the serve
        #: scrape exposes; None when nothing is watching
        self.hists = None

    def record(self, engine: str, bucket, jobs: int, lanes: int,
               useful_cells: int, total_cells: int,
               kernel: str | None = None, dtype: str | None = None) -> None:
        """Account one dispatched batch. `bucket` is any hashable shape
        descriptor (stringified for the snapshot); `total_cells` is the
        batch's full dispatched capacity (>= useful_cells). `kernel`
        ('xla' | 'pallas') and `dtype` ('int32' | 'int16') record the
        bucket's dispatched program choice — the device-kernel plane's
        per-bucket decision, surfaced next to the occupancy numbers in
        the bench JSON and synthbench report (constant per bucket within
        a run; last write wins)."""
        key = (engine, str(bucket))
        with self._lock:
            b = self._buckets.get(key)
            if b is None:
                b = self._buckets[key] = {
                    "jobs": 0, "batches": 0, "lanes": 0,
                    "useful_cells": 0, "padded_cells": 0}
            b["jobs"] += int(jobs)
            b["batches"] += 1
            b["lanes"] += int(lanes)
            b["useful_cells"] += int(useful_cells)
            b["padded_cells"] += int(total_cells) - int(useful_cells)
            if kernel is not None:
                b["kernel"] = kernel
            if dtype is not None:
                b["dtype"] = dtype

    def record_compile(self, engine: str, seconds: float,
                       count: int = 1) -> None:
        with self._lock:
            c = self._compiles.setdefault(
                engine, {"compiles": 0, "compile_s": 0.0})
            c["compiles"] += count
            c["compile_s"] += float(seconds)
        if self.hists is not None:
            self.hists.observe(f"compile.{engine}", float(seconds))

    def record_compile_once(self, engine: str, key,
                            seconds: float) -> bool:
        """Charge `seconds` as compile wall iff `key` (the FULL program
        identity, including the batch dimension — jit programs are
        shape-keyed on it, so a tail chunk with a different lane count
        is a separate compile) is new to this process. The shared
        first-dispatch idiom of all three engines: time the dispatch,
        call this, and the first occurrence of each shape is charged."""
        k = (engine, key)
        with self._lock:
            if k in _seen_shapes:
                return False
            _seen_shapes.add(k)
        self.record_compile(engine, seconds)
        # trace the compile as a span ending now (the charge is made
        # right after the first dispatch returned, so now - seconds is
        # the dispatch's start) — the Perfetto view of "where did the
        # first chunk's stall go"
        from ..obs import trace

        tr = trace.get_tracer()
        if tr is not None:
            import time

            now = time.perf_counter()
            tr.complete("xla.compile", now - float(seconds), now,
                        {"engine": engine, "shape": str(key)})
        return True

    def snapshot(self) -> dict:
        """{engine: {"buckets": {bucket: {..., "occupancy_pct"}},
                     "occupancy_pct", "compiles", "compile_s"}} —
        JSON-ready; empty dict when nothing was dispatched."""
        with self._lock:
            buckets = {k: dict(v) for k, v in self._buckets.items()}
            compiles = {k: dict(v) for k, v in self._compiles.items()}
        out: dict = {}
        for (engine, bucket), b in sorted(buckets.items()):
            e = out.setdefault(engine, {"buckets": {}})
            total = b["useful_cells"] + b["padded_cells"]
            e["buckets"][bucket] = dict(
                b, occupancy_pct=round(100.0 * b["useful_cells"] / total, 2)
                if total else 0.0)
        for engine, e in out.items():
            useful = sum(b["useful_cells"] for b in e["buckets"].values())
            total = useful + sum(b["padded_cells"]
                                 for b in e["buckets"].values())
            e["occupancy_pct"] = (round(100.0 * useful / total, 2)
                                  if total else 0.0)
        for engine, c in compiles.items():
            e = out.setdefault(engine, {"buckets": {}})
            e["compiles"] = c["compiles"]
            e["compile_s"] = round(c["compile_s"], 3)
        return out

    def summary(self) -> str | None:
        """One-line per-engine occupancy report for stderr, or None when
        nothing was dispatched (the common host-only case: silence)."""
        snap = self.snapshot()
        parts = []
        for engine, e in snap.items():
            if not e.get("buckets"):
                continue
            jobs = sum(b["jobs"] for b in e["buckets"].values())
            batches = sum(b["batches"] for b in e["buckets"].values())
            s = (f"{engine} {e['occupancy_pct']:.1f}% "
                 f"({jobs} jobs / {batches} batches"
                 f" / {len(e['buckets'])} shapes")
            if "compiles" in e:
                s += f", {e['compiles']} compiles {e['compile_s']:.1f}s"
            parts.append(s + ")")
        return "; ".join(parts) if parts else None
