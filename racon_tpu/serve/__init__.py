"""Warm polishing service: a persistent job server over the polisher.

The one-shot CLI pays engine construction, XLA compilation and ladder
warmup per run; this subsystem amortizes those across many requests —
the request-queue + continuous-batching shape of modern inference
servers, built from the repo's existing layers:

    protocol.py   length-prefixed JSON frames (unix socket / localhost
                  TCP), typed error responses, streamed `result_part`
                  frames
    queue.py      bounded JobQueue: admission control with retry-after,
                  per-tenant weighted fair scheduling within priority,
                  per-job deadlines
    batcher.py    CONTINUOUS cross-job window batching: a persistent
                  device feeder packs bounded shape-homogeneous
                  iterations through the sched ladders — late jobs join
                  the next dispatch, no round barrier (per-job output
                  byte-identical to a solo run)
    server.py     PolishServer: warm engine set, worker pool, per-contig
                  result streaming, graceful SIGTERM drain, per-job
                  failure isolation + obs scoping
    client.py     PolishClient / `racon_tpu submit [--stream]`
    router.py     PolishRouter / `racon_tpu router`: shard-aware
                  front-end over N warm replicas — contig-sharded
                  fan-out (byte-identical merge), journal-backed
                  requeue on replica loss, rolling restarts without
                  job loss

CLI: `python -m racon_tpu.cli serve ...` / `... submit ...`;
benchmarks: tools/servebench.py; failure matrix: tools/faultcheck.py
(serve column). See README "Serving".
"""

from .batcher import WindowBatcher
from .client import (DeadlineDoomed, JobCancelled, JobFailed,
                     PolishClient, PolishResult, QueueFull, ServeError,
                     ServerDraining, TenantQuota)
from .ingest import IngestError
from .queue import Job, JobQueue
from .router import PolishRouter, RouterConfig
from .server import (PolishServer, ServeConfig,
                     make_fragment_dataset, make_synth_dataset)

__all__ = ["WindowBatcher", "PolishClient", "PolishResult", "PolishServer",
           "PolishRouter", "RouterConfig",
           "ServeConfig", "Job", "JobQueue", "ServeError", "QueueFull",
           "ServerDraining", "TenantQuota", "JobFailed",
           "JobCancelled", "DeadlineDoomed",
           "IngestError",
           "make_fragment_dataset", "make_synth_dataset"]
