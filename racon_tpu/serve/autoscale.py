"""Elastic replica autoscaling for the serve router.

`Autoscaler` closes the loop the router left open: the fleet poll
already computes every signal an operator would scale on — per-replica
queue depth and inflight from healthz, the deadline-miss burn rate
(`obs.fleet.BurnRateTracker` fast/slow windows), the admission EMA
behind `queue.ema_service_s` — and the router already survives replicas
joining and leaving (`add_replica` / `remove_replica`, journal-backed
requeue). The autoscaler just connects signal to action:

  - **Scale-up.** When backlog pressure (queued + inflight jobs per
    routable replica) stays above ``up_pressure`` for ``up_sustain_s``
    seconds — or the deadline burn-rate alert is firing — and the fleet
    is below ``max_replicas``, spawn one warm replica subprocess
    (``racon_tpu serve --socket <dir>/autoscale_<n>.sock``), wait for
    its first clean healthz, and join it to the routing set: rejoin is
    instant because the router routes on healthz, not on config.
  - **Scale-down.** When the fleet has been fully idle (zero backlog,
    zero router in-flight jobs) for ``down_idle_s`` seconds and the
    autoscaler owns at least one replica above ``min_replicas``, drain
    the NEWEST spawned replica: SIGTERM triggers the server's graceful
    drain (stop admitting, finish in-flight), and if it dies mid-job
    anyway the router's journal-backed requeue re-dispatches the shard
    — scale-down loses zero jobs by construction, the same invariant
    the rolling-restart runbook pins.
  - Only replicas the autoscaler spawned are ever drained; the
    operator's configured replicas are the floor it never touches.
    Every action journals (``autoscale-up`` / ``autoscale-down``,
    outside LIFECYCLE_EVENTS) and counts into the router's armed-only
    ``router.autoscale.*`` metric families.
  - **Scale-up hold.** While the autoscaler is armed and below
    ``max_replicas``, a shard whose only routable replicas are already
    busy (device in use) HOLDS in the router's dispatch loop for up to
    ``hold_s`` seconds instead of committing to a busy queue — and the
    held shard itself counts into the pressure signal, so the hold is
    what summons the capacity it is waiting for. The moment any
    replica goes idle (or the spawned one joins), the hold ends and
    the shard dispatches there. Without an armed autoscaler the hold
    path is never taken and dispatch behaves exactly as before.

Env knobs (strict-parsed at construction, the --metrics-port
discipline — a typo fails the start, never silently defaults):
RACON_TPU_ROUTER_AUTOSCALE_MIN / _MAX (fleet size bounds, default
1 / 4), _INTERVAL (loop seconds, default 1), _UP_PRESSURE (backlog per
routable replica that counts as pressure, default 2), _UP_SUSTAIN_S
(how long pressure must hold, default 2), _DOWN_IDLE_S (idle before a
drain, default 10), _COOLDOWN_S (minimum gap between actions, default
3), _DIR (socket directory for spawned replicas, default a tempdir),
_HOLD_S (how long a shard may hold out for an idle/new replica before
settling for a busy one, default 5; 0 disables the hold).

CLI: ``racon_tpu router --autoscale`` (router_main wires the loop and
tears it down on drain). Tests drive `step()` directly with injected
`spawn` / `stop` callables — no subprocesses, no clocks.
"""

from __future__ import annotations

import contextlib
import os
import subprocess
import sys
import tempfile
import threading
import time

from ..errors import RaconError
from ..utils.logger import log_info
from .protocol import ProtocolError


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise RaconError(
            "autoscale",
            f"{name} must be an integer, got {raw!r}") from None


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise RaconError(
            "autoscale",
            f"{name} must be a number, got {raw!r}") from None


class AutoscaleConfig:
    """Autoscaler knobs; every constructor override has an env twin
    (module docstring) and parse failures raise NOW."""

    def __init__(self, **kw):
        mn = kw.pop("min_replicas", None)
        self.min_replicas = (
            int(mn) if mn is not None
            else _env_int("RACON_TPU_ROUTER_AUTOSCALE_MIN", 1))
        mx = kw.pop("max_replicas", None)
        self.max_replicas = (
            int(mx) if mx is not None
            else _env_int("RACON_TPU_ROUTER_AUTOSCALE_MAX", 4))
        iv = kw.pop("interval_s", None)
        self.interval_s = (
            float(iv) if iv is not None
            else _env_float("RACON_TPU_ROUTER_AUTOSCALE_INTERVAL", 1.0))
        up = kw.pop("up_pressure", None)
        self.up_pressure = (
            float(up) if up is not None
            else _env_float("RACON_TPU_ROUTER_AUTOSCALE_UP_PRESSURE",
                            2.0))
        us = kw.pop("up_sustain_s", None)
        self.up_sustain_s = (
            float(us) if us is not None
            else _env_float("RACON_TPU_ROUTER_AUTOSCALE_UP_SUSTAIN_S",
                            2.0))
        di = kw.pop("down_idle_s", None)
        self.down_idle_s = (
            float(di) if di is not None
            else _env_float("RACON_TPU_ROUTER_AUTOSCALE_DOWN_IDLE_S",
                            10.0))
        cd = kw.pop("cooldown_s", None)
        self.cooldown_s = (
            float(cd) if cd is not None
            else _env_float("RACON_TPU_ROUTER_AUTOSCALE_COOLDOWN_S",
                            3.0))
        self.socket_dir = (
            kw.pop("socket_dir", None)
            or os.environ.get("RACON_TPU_ROUTER_AUTOSCALE_DIR") or "")
        rt = kw.pop("ready_timeout_s", None)
        self.ready_timeout_s = (
            float(rt) if rt is not None
            else _env_float(
                "RACON_TPU_ROUTER_AUTOSCALE_READY_TIMEOUT", 20.0))
        hs = kw.pop("hold_s", None)
        self.hold_s = (
            float(hs) if hs is not None
            else _env_float("RACON_TPU_ROUTER_AUTOSCALE_HOLD_S", 5.0))
        if self.hold_s < 0:
            raise RaconError(
                "autoscale", f"hold_s must be >= 0, got {self.hold_s}")
        if self.min_replicas < 0 or \
                self.max_replicas < max(1, self.min_replicas):
            raise RaconError(
                "autoscale",
                f"bad fleet bounds min={self.min_replicas} "
                f"max={self.max_replicas}")
        if kw:
            raise RaconError(
                "autoscale",
                f"unknown autoscale option(s): {', '.join(sorted(kw))}")


def _default_spawn(spec: str):
    """Spawn one warm replica subprocess serving on `spec` (unix
    socket). The child inherits the environment, so the operator's
    RACON_TPU_SERVE_* posture applies to scaled-up replicas too."""
    return subprocess.Popen(
        [sys.executable, "-m", "racon_tpu", "serve", "--socket", spec],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def _default_stop(handle) -> None:
    """SIGTERM -> the server's graceful drain; SIGKILL only if it
    ignores us (the requeue path covers even that)."""
    with contextlib.suppress(Exception):
        handle.terminate()
    try:
        handle.wait(timeout=15.0)
    except Exception:  # noqa: BLE001 — escalate, requeue covers it
        with contextlib.suppress(Exception):
            handle.kill()
            handle.wait(timeout=5.0)


class Autoscaler:
    """The elastic-fleet control loop (module docstring). `spawn(spec)
    -> handle` and `stop(handle)` are injectable so tests scale
    in-process PolishServers with no subprocesses; `step(now)` is the
    whole decision function, drivable without the thread."""

    def __init__(self, router, config: AutoscaleConfig | None = None,
                 spawn=None, stop=None, **overrides):
        self.router = router
        self.config = config if config is not None \
            else AutoscaleConfig(**overrides)
        self._spawn = spawn or _default_spawn
        self._stop_replica = stop or _default_stop
        self._dir = self.config.socket_dir or tempfile.mkdtemp(
            prefix="racon_tpu_autoscale_")
        #: replicas this loop owns, oldest first:
        #: {"spec", "handle", "t"} — scale-down drains the newest
        self.spawned: list[dict] = []
        self._seq = 0
        self._lock = threading.Lock()
        self._pressure_since: float | None = None
        self._idle_since: float | None = None
        self._last_action_t = float("-inf")
        self._last_pressure = 0.0
        self.counters = {"scale_ups": 0, "scale_downs": 0,
                         "spawn_failures": 0}
        self._thread: threading.Thread | None = None
        self._halt = threading.Event()
        router.autoscaler = self

    # ------------------------------------------------------------ loop
    def start(self) -> "Autoscaler":
        t = threading.Thread(target=self._loop,
                             name="racon-tpu-router-autoscale",
                             daemon=True)
        t.start()
        self._thread = t
        log_info(f"[racon_tpu::autoscale] armed: "
                 f"{self.config.min_replicas}-"
                 f"{self.config.max_replicas} replicas, "
                 f"up at pressure {self.config.up_pressure:g} for "
                 f"{self.config.up_sustain_s:g}s, down after "
                 f"{self.config.down_idle_s:g}s idle")
        return self

    def _loop(self) -> None:
        while not self._halt.is_set():
            self._halt.wait(self.config.interval_s)
            if self._halt.is_set():
                return
            with contextlib.suppress(Exception):
                self.step()

    def close(self, stop_spawned: bool = True) -> None:
        """Stop the loop; by default also drain every replica this
        loop spawned (the router tear-down path)."""
        self._halt.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if stop_spawned:
            with self._lock:
                owned, self.spawned = self.spawned, []
            for entry in owned:
                self.router.remove_replica(entry["spec"])
                with contextlib.suppress(Exception):
                    self._stop_replica(entry["handle"])

    # -------------------------------------------------------- decision
    def _signals(self) -> tuple[float, bool, int, int]:
        """(pressure, burn_firing, backlog, router_inflight) from the
        router's LAST fleet poll — the health loop already paid for the
        probe; the autoscaler never double-polls replicas."""
        snap = self.router.fleet.last()
        backlog = 0
        if snap is not None:
            for rs in snap.replicas:
                if not rs.ok or not isinstance(rs.health, dict):
                    continue
                backlog += int(rs.health.get("queue_depth", 0) or 0)
                backlog += int(rs.health.get("inflight", 0) or 0)
        burn = getattr(snap, "burn", None) or {}
        firing = bool(burn.get("firing"))
        with self.router._state_lock:
            routable = sum(1 for r in self.router.replicas
                           if r.routable)
            inflight = self.router._inflight_jobs
            outstanding = self.router._requeued_outstanding
            waiting = getattr(self.router, "_dispatch_waiting", 0)
        # shards holding in the dispatch loop for an idle replica ARE
        # backlog — counting them is what lets the hold summon the
        # scale-up it is waiting for
        backlog += outstanding + waiting
        pressure = backlog / max(1, routable)
        return pressure, firing, backlog, inflight

    def step(self, now: float | None = None) -> str | None:
        """One control decision; returns "up" / "down" / None (what it
        did). `now` is injectable for clockless tests."""
        now = time.monotonic() if now is None else now
        cfg = self.config
        pressure, firing, backlog, inflight = self._signals()
        self._last_pressure = pressure

        if pressure >= cfg.up_pressure or firing:
            if self._pressure_since is None:
                self._pressure_since = now
        else:
            self._pressure_since = None
        if backlog == 0 and inflight == 0:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None

        if now - self._last_action_t < cfg.cooldown_s:
            return None
        total = len(self.router.replicas)
        if (self._pressure_since is not None
                and now - self._pressure_since >= cfg.up_sustain_s
                and total < cfg.max_replicas):
            if self._scale_up(reason="burn" if firing else "pressure",
                              pressure=pressure):
                self._last_action_t = now
                self._pressure_since = None
                return "up"
            return None
        if (self._idle_since is not None
                and now - self._idle_since >= cfg.down_idle_s
                and self.spawned
                and total > max(1, cfg.min_replicas)):
            self._scale_down()
            self._last_action_t = now
            self._idle_since = None
            return "down"
        return None

    # --------------------------------------------------------- actions
    def _scale_up(self, reason: str, pressure: float) -> bool:
        with self._lock:
            self._seq += 1
            spec = os.path.join(self._dir,
                                f"autoscale_{self._seq}.sock")
        try:
            handle = self._spawn(spec)
        except Exception as exc:  # noqa: BLE001 — never kill the loop
            self.counters["spawn_failures"] += 1
            log_info(f"[racon_tpu::autoscale] spawn failed: {exc}")
            return False
        if not self._wait_ready(spec):
            self.counters["spawn_failures"] += 1
            log_info(f"[racon_tpu::autoscale] replica {spec} never "
                     "answered healthz; giving up on it")
            with contextlib.suppress(Exception):
                self._stop_replica(handle)
            return False
        with self._lock:
            self.spawned.append({"spec": spec, "handle": handle,
                                 "t": time.monotonic()})
        self.router.add_replica(spec)
        self.counters["scale_ups"] += 1
        if self.router.journal is not None:
            self.router.journal.record(
                "autoscale-up", replica=spec, reason=reason,
                pressure=round(pressure, 3),
                replicas=len(self.router.replicas))
        log_info(f"[racon_tpu::autoscale] scaled up to "
                 f"{len(self.router.replicas)} replicas "
                 f"({reason}, pressure {pressure:.2f})")
        return True

    def _wait_ready(self, spec: str) -> bool:
        """Poll the new replica's healthz RPC until its first clean
        answer (ok, not draining) — routable from its first poll."""
        from .client import PolishClient, ServeError

        deadline = time.monotonic() + self.config.ready_timeout_s
        while time.monotonic() < deadline:
            if self._halt.is_set():
                return False
            try:
                doc = PolishClient(socket_path=spec,
                                   timeout=2.0).healthz()
                if doc.get("ok") and not doc.get("draining"):
                    return True
            except (ServeError, ProtocolError, OSError):
                pass
            time.sleep(0.1)
        return False

    def _scale_down(self) -> None:
        with self._lock:
            entry = self.spawned.pop()
        # unroute FIRST, then drain: nothing new lands on the replica
        # while it finishes; a mid-job death is the normal requeue path
        self.router.remove_replica(entry["spec"])
        with contextlib.suppress(Exception):
            self._stop_replica(entry["handle"])
        self.counters["scale_downs"] += 1
        if self.router.journal is not None:
            self.router.journal.record(
                "autoscale-down", replica=entry["spec"],
                replicas=len(self.router.replicas))
        log_info(f"[racon_tpu::autoscale] scaled down to "
                 f"{len(self.router.replicas)} replicas")

    # -------------------------------------------------------- exposure
    def snapshot(self) -> dict:
        return {"min": self.config.min_replicas,
                "max": self.config.max_replicas,
                "spawned": len(self.spawned),
                "pressure": round(self._last_pressure, 3),
                "scale_ups": self.counters["scale_ups"],
                "scale_downs": self.counters["scale_downs"],
                "spawn_failures": self.counters["spawn_failures"]}
