"""Continuous cross-job window batching: iteration-level dispatch.

A window's consensus depends only on the window itself (backbone +
layers) and the engine parameters — never on which other windows share
its device batch. The scheduler's sorted packing already exploits this
within one run (results restore by index, byte-identical, PR-3 pinned);
`WindowBatcher` extends the same invariant ACROSS jobs, continuously:
windows from concurrent polish requests pool per engine-parameter key
and a persistent DEVICE FEEDER drains the pool in bounded, shape-
homogeneous ITERATIONS — one engine pass each — so a job that arrives
mid-flight joins the very next dispatch instead of waiting for anyone
else's round to finish. Each job's windows come back carrying their
consensus exactly as a solo run would have produced (test-pinned in
tests/test_serve.py, including under injected faults).

This replaces the PR-5 leader/joiner round barrier (gather window +
`min_gather`, one `generate_consensus` over every gathered job, the exec
lock held for the whole round). The round design made a job's latency
the SLOWEST co-round job's latency and made a late submit wait out the
entire in-flight round; the feeder holds the exec lock only per
iteration, so:

  - late-arriving jobs' windows join the next iteration (bounded by
    `iteration_windows`, not by the largest co-tenant job);
  - a job's windows COMPLETE INCREMENTALLY — `consensus(on_windows=...)`
    delivers each iteration's finished windows as they land, which is
    what lets the polisher stitch and stream finished contigs before
    the job is done (core/polisher.py, `result_part` frames);
  - per-iteration telemetry (`serve.iteration` span/histogram, lane
    accounting via the shared sched occupancy stats) replaces the old
    round granularity.

Iteration packing: the feeder always serves the key holding the
globally oldest pending window (no starvation), sorts that key's pool
by window shape (depth, backbone length — the quantities the sched
ladders bucket on) and takes the contiguous shape-sorted slab of at
most `iteration_windows` windows that CONTAINS the oldest one: the
batch stays shape-homogeneous for the ladders while the oldest work
always makes progress. `max_wait_s` (default 0 — dispatch immediately)
optionally lets a sparse pool coalesce briefly before a short
iteration; it bounds added latency, unlike the old gather window it
never waits when a full iteration is already pending.

Isolation: a job carrying its own fault plan or a strict posture never
shares an iteration — it runs its polisher's own `_consensus_pass()`
(own pipeline, own injected faults) solo on ONE lane, so an injected
`DeviceError` storm fails exactly one job while the feeders, the warm
engines and every concurrent job continue untouched. Scope note: the
lane pin covers the CONSENSUS pass (the batcher's domain); a job that
additionally arms the device aligner runs its align phase inside
`Polisher.initialize()` on the worker thread BEFORE it reaches the
batcher, over the full mesh — serve jobs default to host alignment, so
this only matters when a request opts into `tpu_aligner_batches`. An engine-pass
failure inside a shared iteration fails every job with windows IN that
iteration (their remaining pooled windows are withdrawn); jobs in other
iterations and the feeders themselves survive.

PERSISTENT DISPATCH LOOP: each lane caches ONE (DispatchPipeline,
BatchPOA) pair per engine-parameter key, built at the first iteration
that needs it and reused for every later one — per-iteration Python
dispatch (engine construction, kernel-plan resolution, watchdog/
pipeline wiring) collapses to a dict lookup, and under the fused
engine's single-launch mode (RACON_TPU_FUSED, ops/poa_fused.py) an
iteration's device work is one launch + one fetch per chunk. The
measured remainder is accounted: `host_s` (iteration wall minus the
pipeline's device-stage seconds, exact per lane via per-lane
PipelineStats) accumulates in the counters, rides the
`serve.iteration` trace span and the `serve.iteration_host` histogram
— the dispatch-overhead number servebench and the scrape expose.

PREEMPTION (the QoS layer, serve/server.py `--preempt`): a running
job's NOT-YET-DISPATCHED pooled windows can be withdrawn between
iterations (`withdraw_job` — the entries move, tuples intact, into a
parked store keyed by serve job id; the job's consumer thread keeps
waiting on its ticket, its already-delivered windows and ContigStreamer
state untouched) and later returned (`resume_job` — the entries rejoin
their pools carrying their ORIGINAL arrival sequence, so the oldest-
window guarantee and byte-identity both survive: per-window consensus
is independent of batch composition, and a resumed job's output is
exactly what an undisturbed run would have produced). `cancel_job`
rides the same ticket-error withdrawal seam an iteration failure uses:
the ticket dies typed (`queue.JobCancelledError`), the feeder drops its
pooled windows at the next scan, and the job's own thread re-raises to
the worker. The iteration-boundary speculative deadline-abort
(`abort_margin` + the polisher's `serve_deadline`) extrapolates the
remaining windows' finish time from this job's observed per-window rate
after every delivered batch and raises `queue.DeadlineDoomed` when the
deadline is provably lost — device time stops burning within one
iteration, not at job completion.

WORKER LANES (`worker_lanes` / RACON_TPU_WORKER_LANES / `serve
--worker-lanes`, default 1 = the single-feeder behavior): the device
list partitions into K contiguous SUB-MESHES (parallel.mesh
.partition_devices), each backed by its own BatchRunner, feeder thread
and execution lock — so iterations (including ones for different
engine-parameter keys, which can never share a batch anyway) run
CONCURRENTLY across the slice instead of queueing on one full-mesh
exec lock. Per-window consensus is independent of both batch
composition and mesh width, so output stays byte-identical at any lane
count (test-pinned at --worker-lanes {1,2}). Isolation jobs pick the
least-busy lane and hold only ITS lock. `K` clamps to the device
count; per-lane iteration/busy telemetry rides `snapshot()` and the
serve `scrape` (one busy gauge per lane).
"""

from __future__ import annotations

import itertools
import threading
import time

from ..obs import trace


class _Ticket:
    """One job's consensus request in the pool. The feeder DELIVERS
    each iteration's finished windows through a small queue; the job's
    own blocked thread consumes them (and runs the incremental-stitch
    callback there) — stitching, journaling and frame encoding never
    run on the feeder thread, so one job's heavy contig cannot stall
    device dispatch for everyone else."""

    __slots__ = ("polisher", "key", "event", "error",
                 "total", "remaining", "done", "iterations",
                 "iteration_ids", "shared_iterations", "compiles",
                 "compile_s", "device_s", "device_share_s", "_delivery")

    def __init__(self, polisher, key):
        from .queue import DeliveryQueue

        self.polisher = polisher
        self.key = key
        self.error: BaseException | None = None
        self.total = len(polisher.windows)
        self.remaining = self.total
        self.done = 0
        self.iterations = 0
        self.iteration_ids: list[int] = []
        self.shared_iterations = 0
        self.compiles = 0
        self.compile_s = 0.0
        self.device_s = 0.0
        #: this job's PRORATED slice of shared iteration wall (its
        #: window count over the iteration's total) — the cost-
        #: accounting number, vs device_s which charges each rider the
        #: FULL iteration wall (the latency number)
        self.device_share_s = 0.0
        #: finished-window handoff feeder -> job thread; the queue owns
        #: the completion flag and the wakeup discipline (see
        #: queue.DeliveryQueue — a bare event.set() would leave the
        #: consumer burning out its take() timeout, a silent latency
        #: floor on every job's tail)
        self._delivery = DeliveryQueue()
        self.event = self._delivery.event

    def deliver(self, windows: list) -> None:
        """Feeder thread: hand a batch of finished windows to the
        waiting job thread (cheap — an append and a notify)."""
        self._delivery.push(windows)

    def finish(self) -> None:
        """Feeder thread: mark the ticket complete AND wake the
        consumer."""
        self._delivery.finish()

    def take(self, timeout: float | None = None) -> list | None:
        """Job thread: the oldest undelivered batch, or None."""
        return self._delivery.take(timeout)

    def batch_info(self, solo: bool = False) -> dict:
        info = {"iterations": self.iterations,
                "iteration_ids": list(self.iteration_ids),
                "shared_iterations": self.shared_iterations,
                "windows": self.total, "solo": solo,
                "compiles": self.compiles,
                "compile_s": round(self.compile_s, 3),
                "device_s": round(self.device_s, 4)}
        tenant = getattr(self.polisher, "serve_tenant", None)
        if tenant:
            # armed-only: tenanted jobs carry their prorated device
            # cost in the result frame; untenanted frames stay
            # byte-identical to the pre-accounting wire shape
            info["tenant"] = tenant
            info["device_share_s"] = round(self.device_share_s, 4)
        return info


class _IterProgress:
    """Duck-typed Logger for one iteration: the engine sees the usual
    `bar_total`/`bar` surface, but the bin-level ticks fan out to every
    participating job's live-progress hook, scaled to that job's window
    share of THIS iteration and offset by the windows it completed in
    earlier iterations — so a client's consensus bar advances smoothly
    across iterations. Monotonicity across re-armed bars (an engine's
    fallback pass calls bar_total again) is enforced downstream by
    Polisher.emit_progress's per-phase high-water mark. Silent by
    design: shared iterations never print."""

    def __init__(self, parts, iteration: int):
        #: (polisher, done_before, n_in_iteration, job_total)
        self._parts = [(t.polisher, t.done, n, t.total)
                       for t, n in parts
                       if t.polisher.progress_hook is not None]
        self._iter = iteration
        self._total = 1
        self._count = 0
        self._bins = 0
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        return bool(self._parts)

    def bar_total(self, total: int) -> None:
        with self._lock:
            self._total = max(1, int(total))
            self._count = 0
            self._bins = 0

    def bar(self, msg: str) -> None:
        with self._lock:
            self._count += 1
            bins = min(20 * self._count // self._total, 20)
            if bins == self._bins:
                return
            self._bins = bins
            frac = min(1.0, self._count / self._total)
        for polisher, before, n, total in self._parts:
            polisher.emit_progress(before + int(frac * n), total,
                                   phase="consensus",
                                   iteration=self._iter)

    # the rest of the Logger surface, defensively no-op
    def log(self, msg=None) -> None:
        pass

    def total(self, msg) -> None:
        pass


def _trace_ids(tickets) -> list[str]:
    """The client-minted trace ids riding this iteration's jobs (the
    server stamps `serve_trace_id` on each job's polisher) — tagged onto
    the iteration spans so a merged client+server trace can attribute
    shared iterations."""
    return [tid for tid in
            (getattr(t.polisher, "serve_trace_id", None) for t in tickets)
            if tid]


class _Lane:
    """One worker lane: a sub-mesh BatchRunner, its own exec lock (the
    feeder thread and any isolation job routed here serialize on it; two
    LANES never share it), its own BatchScheduler/OccupancyStats and
    PipelineStats (so per-iteration compile AND device-seconds deltas
    are exact — a shared stats object would charge one lane's
    concurrent work into another lane's delta window), its telemetry
    counters, and the PERSISTENT dispatch-loop cache: one
    (DispatchPipeline, BatchPOA) pair per engine-parameter key, built
    on first use and reused for every later iteration — per-iteration
    Python dispatch (engine construction, kernel-plan resolution,
    pipeline/watchdog wiring) collapses to a dict lookup. Counter
    fields are guarded by the batcher's `_cond`; `engines` is touched
    only under this lane's exec lock."""

    __slots__ = ("index", "runner", "scheduler", "pipeline_stats",
                 "lock", "busy", "iterations", "busy_s", "engines",
                 "health", "quarantined", "reprobes", "flush_engines")

    def __init__(self, index: int, runner, scheduler, pipeline_stats):
        self.index = index
        self.runner = runner
        self.scheduler = scheduler
        self.pipeline_stats = pipeline_stats
        self.lock = threading.Lock()
        self.busy = False
        self.iterations = 0
        self.busy_s = 0.0
        #: engine key -> (DispatchPipeline, BatchPOA), the persistent
        #: dispatch loop (see class docstring)
        self.engines: dict = {}
        #: audit-sentinel lane health (obs/audit.py): 1.0 healthy, 0.0
        #: quarantined, 0.5 degraded (failed its re-probe but is the
        #: last serving lane). The scrape's racon_tpu_lane_health gauge.
        self.health = 1.0
        self.quarantined = False
        self.reprobes = 0
        #: set on quarantine: the next re-probe rebuilds the cached
        #: engines so a just-demoted winner table takes effect
        self.flush_engines = False


def _engine_key(p) -> tuple:
    """Engine-parameter identity: jobs share an iteration only when
    every knob that can influence a window's consensus bytes matches."""
    return (p.match, p.mismatch, p.gap, p.window_length, p.trim,
            p.num_threads, p.tpu_poa_batches, p.tpu_banded_alignment,
            p.tpu_aligner_band_width, p.tpu_engine,
            p.tpu_pipeline_depth, p.tpu_device_timeout)


def _shape_key(window) -> tuple[int, int]:
    """The quantities the sched ladders bucket on: layer depth and
    backbone length. Sorting the pool by this keeps each iteration's
    batch shape-homogeneous, so the per-iteration engine pass packs
    into few ladder buckets instead of inheriting arrival order."""
    return (len(window.sequences), len(window.sequences[0]))


class WindowBatcher:
    """Continuous batching core (see module docstring).

    `iteration_windows` bounds one iteration's batch (the latency
    quantum under load); `max_wait_s` optionally lets a sparse pool
    coalesce before a short iteration (0 = dispatch immediately)."""

    def __init__(self, iteration_windows: int = 256,
                 max_wait_s: float = 0.0, scheduler=None,
                 worker_lanes: int | None = None, devices=None):
        import os

        from ..pipeline import PipelineStats
        from ..sched import BatchScheduler

        self.iteration_windows = max(1, int(iteration_windows))
        self.max_wait_s = max(0.0, float(max_wait_s))
        #: sub-mesh worker lanes (see module docstring); None defers to
        #: RACON_TPU_WORKER_LANES, default 1 — the single-feeder path
        if worker_lanes is None:
            try:
                worker_lanes = int(
                    os.environ.get("RACON_TPU_WORKER_LANES", "") or 1)
            except ValueError:
                worker_lanes = 1
        self.worker_lanes = max(1, int(worker_lanes))
        #: explicit device list (tests); None = auto-discovery with the
        #: RACON_TPU_MAX_DEVICES cap, resolved lazily at first consensus
        #: so constructing a batcher never forces the jax import
        self._devices = devices
        self._lanes: list[_Lane] | None = None
        #: one scheduler + stage-stat sink for every shared iteration:
        #: the server-lifetime occupancy/compile telemetry servebench
        #: reads
        self.scheduler = (scheduler if scheduler is not None
                          else BatchScheduler.from_env())
        self.pipeline_stats = PipelineStats()
        #: optional obs.hist.HistogramSet (the server's lifetime set):
        #: device iteration durations observed as latency distributions
        #: for the scrape view
        self.hists = None
        self._cond = threading.Condition()
        #: per-engine-key pending pool: list of
        #: [arrival_seq, arrival_t, ticket, window]
        self._pools: dict[tuple, list] = {}
        self._entry_seq = itertools.count()
        self._iter_seq = itertools.count()
        #: per-lane feeder threads, indexed by lane (None = not yet
        #: spawned; dead feeders are respawned at the next submit)
        self._feeders: list[threading.Thread | None] = []
        self._stop = False
        self._held = False
        #: QoS preemption state (all `_cond`-guarded). `_withdrawn`:
        #: serve job ids whose pooled windows are currently parked —
        #: consulted at pooling time too, so a window arriving AFTER
        #: the withdraw parks directly instead of racing the feeder.
        #: `_parked`: job id -> list of (engine_key, pool_entry), the
        #: withdrawn entries verbatim (original arrival_seq preserved:
        #: resume restores the oldest-window ordering exactly).
        #: `_job_tickets`: serve job id -> live tickets, the handle
        #: cancel_job uses to kill a running job through the ticket-
        #: error seam.
        self._withdrawn: set[str] = set()
        self._parked: dict[str, list] = {}
        self._job_tickets: dict[str, list] = {}
        #: speculative deadline-abort margin (seconds) or None = off;
        #: the server wires it from RACON_TPU_SERVE_ABORT_MARGIN /
        #: --abort-margin. Consulted on the JOB thread at iteration
        #: boundaries against the polisher's `serve_deadline`.
        self.abort_margin: float | None = None
        #: the identity-audit sentinel (obs/audit.WindowAuditor) or
        #: None; the server wires it when RACON_TPU_AUDIT_RATE > 0.
        #: Audits run on the feeder thread AFTER the lane lock is
        #: released and BEFORE windows are delivered — off the device
        #: hot path, but in time to repair a caught corruption
        self.auditor = None
        #: the content-addressed window consensus cache
        #: (serve/wincache.WindowCache) or None; the server wires it
        #: when RACON_TPU_WINCACHE / --wincache arms it. Consulted
        #: before a window enters the pooled stream (a hit skips
        #: device dispatch), populated on iteration completion AFTER
        #: the audit pass, invalidated on demotion / lane quarantine.
        #: Isolation jobs (fault plan / strict) bypass it entirely.
        self.wincache = None
        self.counters = {"iterations": 0, "solo_iterations": 0,
                         "shared_iterations": 0, "jobs": 0, "windows": 0,
                         "max_jobs_in_iteration": 0,
                         "max_windows_in_iteration": 0,
                         "max_concurrent_iterations": 0,
                         #: cumulative measured per-iteration host
                         #: overhead (iteration wall − device-stage
                         #: seconds); solo/isolation iterations run on
                         #: the job's own pipeline and are not included
                         "host_s": 0.0,
                         #: queue-side audit overhead accounting: wall
                         #: seconds feeders spent in the sentinel's
                         #: sample+shadow+compare, and lane health flow
                         "audit_s": 0.0,
                         "lane_quarantines": 0, "lane_rejoins": 0,
                         "lane_reprobes": 0}
        #: per-tenant device-seconds: each iteration's wall prorated
        #: onto the tenants whose windows rode it (window count over
        #: the iteration total — the shares of one iteration sum to
        #: its wall by construction, so the buckets sum to total lane
        #: busy seconds). The "" bucket is untenanted traffic.
        self._tenant_device: dict[str, float] = {}

    def _accrue_tenant_device(self, tenant: str, share_s: float) -> None:
        with self._cond:
            self._tenant_device[tenant] = (
                self._tenant_device.get(tenant, 0.0) + share_s)

    # ------------------------------------------------------------ entry
    def consensus(self, polisher, on_windows=None) -> None:
        """Run the consensus pass for `polisher.windows`, merged into
        the continuous iteration stream with concurrent jobs' windows
        (see module docstring). `on_windows`, when given, is invoked
        with each batch of THIS job's windows as their iteration
        completes (serialized, in completion order) — the incremental-
        stitch hook. On return every window carries consensus/polished;
        iteration telemetry is left on `polisher.serve_batch` for the
        server's response."""
        from ..resilience import strict_mode

        if polisher.faults is not None or strict_mode():
            # isolation iteration: injected faults / strict posture stay
            # on this job's own pipeline and never touch a shared batch.
            # It runs SOLO on the least-busy lane — holding only that
            # lane's lock and dispatching on its sub-mesh — so the other
            # lanes' iterations keep flowing underneath a poisoned job
            with self._cond:
                lanes = self._lanes_locked()
                # a quarantined lane takes no new work while healthy
                # siblings exist (it is busy re-probing anyway)
                healthy = [l for l in lanes if not l.quarantined]
                lane = min(healthy or lanes,
                           key=lambda l: (l.busy, l.index))
            it = next(self._iter_seq)
            polisher.device_runner = lane.runner
            with lane.lock:
                # clock starts INSIDE the lock (the shared-iteration
                # discipline): time spent queueing behind a running
                # iteration must not inflate the lane's busy seconds
                t0 = time.perf_counter()
                self._lane_busy(lane, True)
                try:
                    polisher._consensus_pass()
                finally:
                    t1 = time.perf_counter()
                    self._lane_busy(lane, False, t1 - t0)
            # the sentinel audits SOLO iterations too: a per-job fault
            # plan is exactly where injected silent corruption lives,
            # and a caught window is repaired before delivery
            self._audit([(w, polisher) for w in polisher.windows],
                        lane, it)
            # a solo iteration is still an iteration to the trace
            # plane: without this span a traced fault-plan job's
            # device seconds would be invisible to tracereport's
            # span-sums-vs-stage_stats check (host_s unmeasured on
            # the isolation path — the whole wall bills as device)
            tr = trace.get_tracer()
            if tr is not None:
                tid = getattr(polisher, "serve_trace_id", None)
                tr.complete("serve.iteration", t0, t1,
                            {"iteration": it, "lane": lane.index,
                             "jobs": 1,
                             "windows": len(polisher.windows),
                             "solo": True, "host_s": 0.0,
                             "trace_ids": [tid] if tid else []})
            if self.hists is not None:
                self.hists.observe("serve.iteration", t1 - t0)
            self._account(1, len(polisher.windows), solo=True)
            ticket = _Ticket(polisher, None)
            ticket.iterations = 1
            ticket.iteration_ids = [it]
            ticket.device_s = t1 - t0
            # a solo iteration has exactly one rider: its full wall IS
            # that tenant's prorated cost
            ticket.device_share_s = t1 - t0
            self._accrue_tenant_device(
                getattr(polisher, "serve_tenant", None) or "", t1 - t0)
            polisher.serve_batch = ticket.batch_info(solo=True)
            if on_windows is not None:
                on_windows(list(polisher.windows))
            return

        ticket = _Ticket(polisher, _engine_key(polisher))
        if ticket.total == 0:
            polisher.serve_batch = ticket.batch_info()
            return
        # content-addressed cache consult (serve/wincache.py): a hit
        # carries bytes an earlier dispatch of the SAME content under
        # the SAME engine key + posture produced — deliver it straight
        # to this job's thread and keep it out of the pooled stream.
        # Only the shared path consults: isolation jobs returned above.
        pend = polisher.windows
        cache = self.wincache
        if cache is not None:
            from ..sched.autotune import posture_key

            posture = posture_key()
            hits: list = []
            pend = []
            hit_keys: dict[int, tuple] = {}
            for w in polisher.windows:
                ck = cache.key(w, ticket.key, posture)
                ent = cache.lookup(ck)
                if ent is None:
                    pend.append(w)
                else:
                    w.consensus, w.polished = ent
                    hits.append(w)
                    hit_keys[id(w)] = ck
            polisher.serve_cache = {"hits": len(hits),
                                    "misses": len(pend)}
            if hits:
                # the sentinel samples cache-HIT windows too: a
                # poisoned entry is caught (and the ENTRY evicted +
                # quarantined) before this job stitches it, the
                # window repaired with oracle bytes — same output
                # guarantee as an iteration mismatch
                self._audit_cache_hits(polisher, hits, hit_keys)
                ticket.done += len(hits)
                ticket.remaining -= len(hits)
                ticket.deliver(hits)
                if ticket.remaining <= 0:
                    ticket.finish()
        now = time.monotonic()
        job_id = getattr(polisher, "serve_job_id", None)
        if pend:
            with self._cond:
                if self._stop:
                    from ..errors import RaconError

                    raise RaconError(
                        "WindowBatcher",
                        "batcher is closed (server draining)")
                self._ensure_feeder_locked()
                if job_id is not None:
                    self._job_tickets.setdefault(
                        job_id, []).append(ticket)
                entries = [[next(self._entry_seq), now, ticket, w]
                           for w in pend]
                if job_id is not None and job_id in self._withdrawn:
                    # the job was preempted before these windows
                    # pooled (an iterative-rounds job re-entering, or
                    # a withdraw racing the submit): park them
                    # directly — never let a preempted job's windows
                    # slip into the next extraction
                    self._parked.setdefault(job_id, []).extend(
                        (ticket.key, e) for e in entries)
                else:
                    self._pools.setdefault(ticket.key,
                                           []).extend(entries)
                self._cond.notify_all()
        # consume deliveries ON THIS THREAD: the incremental-stitch
        # callback (and whatever it does — journal writes, frame
        # encodes) bills to this job, never to the feeder; an exception
        # from it propagates and fails THIS job loudly, exactly like
        # the isolation path above — a stitch bug must not silently
        # truncate a "successful" result
        deadline = getattr(polisher, "serve_deadline", None)
        t_run0 = time.perf_counter()
        try:
            try:
                while True:
                    ws = ticket.take(timeout=0.1)
                    if ws is not None:
                        if on_windows is not None:
                            on_windows(ws)
                        self._doomed_check(ticket, deadline, t_run0)
                        continue
                    if ticket.event.is_set():
                        break
                while True:  # feeder set event after its last deliver
                    ws = ticket.take()
                    if ws is None:
                        break
                    if on_windows is not None:
                        on_windows(ws)
            except BaseException as exc:
                # mark the ticket dead so the feeder WITHDRAWS its
                # remaining pooled windows instead of burning device
                # iterations on a job whose client already got an error
                with self._cond:
                    if ticket.error is None:
                        ticket.error = exc
                raise
        finally:
            if job_id is not None:
                with self._cond:
                    ts = self._job_tickets.get(job_id)
                    if ts is not None:
                        try:
                            ts.remove(ticket)
                        except ValueError:
                            pass
                        if not ts:
                            del self._job_tickets[job_id]
                    # a ticket leaving errored while preempted strands
                    # its parked entries (nothing will resume a dead
                    # job) — drop them here; an unerrored ticket never
                    # reaches this point with entries still parked
                    parked = self._parked.get(job_id)
                    if parked:
                        parked[:] = [pe for pe in parked
                                     if pe[1][2] is not ticket]
                        if not parked:
                            del self._parked[job_id]
                            self._withdrawn.discard(job_id)
        if ticket.error is not None:
            raise ticket.error
        polisher.serve_batch = ticket.batch_info()

    def _doomed_check(self, ticket: _Ticket, deadline: float | None,
                      t0: float) -> None:
        """Iteration-boundary speculative deadline-abort (runs on the
        JOB thread after each delivered batch): extrapolate the
        remaining windows' finish from this job's observed per-window
        rate; when even that optimistic estimate (the queue ahead of
        us is ignored) overshoots the deadline by more than the
        configured margin, the job is provably doomed — fail it typed
        NOW instead of burning device iterations on a result the
        client will discard. `deadline` is the queue's absolute
        perf_counter deadline (Job.deadline, stamped on the polisher
        as `serve_deadline`)."""
        margin = self.abort_margin
        if deadline is None or margin is None:
            return
        done, remaining = ticket.done, ticket.remaining
        if done <= 0 or remaining <= 0:
            return
        now = time.perf_counter()
        predicted_s = (now - t0) / done * remaining
        remaining_s = deadline - now
        if predicted_s > remaining_s + margin:
            from .queue import DeadlineDoomed

            raise DeadlineDoomed(predicted_s, remaining_s,
                                 phase="mid-run")

    # ----------------------------------------------------------- lanes
    def _lanes_locked(self) -> list[_Lane]:
        """Build the lane partition on first use (caller holds `_cond`):
        one sub-mesh BatchRunner per lane over a contiguous slice of the
        device list, plus one scheduler/stats instance per lane (the
        single-lane case keeps the batcher's own — today's behavior
        exactly). worker_lanes=1 keeps today's single full-mesh lane;
        K clamps to the device count."""
        if self._lanes is None:
            from ..parallel.mesh import BatchRunner, partition_devices
            from ..pipeline import PipelineStats
            from ..sched import BatchScheduler, OccupancyStats

            base = BatchRunner(devices=self._devices)
            if self.worker_lanes == 1 or base.n_devices == 1:
                self._lanes = [_Lane(0, base, self.scheduler,
                                     self.pipeline_stats)]
            else:
                lanes = []
                for i, group in enumerate(partition_devices(
                        base.devices, self.worker_lanes)):
                    sched = BatchScheduler(
                        adaptive=self.scheduler.adaptive,
                        stats=OccupancyStats())
                    sched.stats.hists = self.scheduler.stats.hists
                    lanes.append(_Lane(
                        i, BatchRunner(devices=group), sched,
                        PipelineStats(hists=self.pipeline_stats.hists)))
                self._lanes = lanes
        return self._lanes

    def _lane_busy(self, lane: _Lane, busy: bool,
                   dt: float = 0.0) -> None:
        """Flip a lane's busy flag (the scrape gauge) and, on release,
        charge the iteration to its counters; tracks the high-water mark
        of concurrently-executing lanes — servebench's receipt that the
        lanes genuinely overlap."""
        with self._cond:
            lane.busy = busy
            if busy:
                n = sum(1 for l in (self._lanes or ()) if l.busy)
                self.counters["max_concurrent_iterations"] = max(
                    self.counters["max_concurrent_iterations"], n)
            else:
                lane.iterations += 1
                lane.busy_s += dt

    # ----------------------------------------------------------- feeder
    def _ensure_feeder_locked(self) -> None:
        """Start one feeder thread per lane lazily, and RESTART any lane
        whose feeder died (caller holds `_cond` and has already checked
        `_stop` — a refused submit must not spawn throwaway threads or
        clobber handles close() is joining). Per-lane granularity
        matters: a feeder killed by an unexpected pool-scan error must
        not leave its sub-mesh permanently idle while the siblings keep
        the batcher looking alive."""
        lanes = self._lanes_locked()
        if len(self._feeders) < len(lanes):
            self._feeders += [None] * (len(lanes) - len(self._feeders))
        for lane in lanes:
            t = self._feeders[lane.index]
            if t is not None and t.is_alive():
                continue
            t = threading.Thread(target=self._feeder_loop, args=(lane,),
                                 name="racon-tpu-serve-feeder-"
                                      f"{lane.index}",
                                 daemon=True)
            self._feeders[lane.index] = t
            t.start()

    def close(self, timeout: float = 5.0) -> None:
        """Stop the feeders once the pool is empty. Jobs already pooled
        finish; new consensus() calls are refused."""
        with self._cond:
            self._stop = True
            self._held = False
            self._cond.notify_all()
        for feeder in self._feeders:
            if feeder is not None and feeder.is_alive() \
                    and feeder is not threading.current_thread():
                feeder.join(timeout)
        # the persistent dispatch loops' fallback executors (one per
        # cached lane pipeline) shut down with the batcher. The lane
        # lock is taken per lane so a straggler iteration (a feeder
        # whose join timed out above) can neither mutate `engines`
        # mid-iteration nor have its live pipeline closed under it; a
        # lane that stays wedged past the timeout keeps its pipelines
        # (daemon-abandoned, like its feeder) rather than breaking the
        # iteration still using them.
        with self._cond:
            lanes = list(self._lanes or ())
        for lane in lanes:
            if not lane.lock.acquire(timeout=timeout):
                continue
            try:
                pipelines = [p for p, _ in lane.engines.values()]
            finally:
                lane.lock.release()
            for pipeline in pipelines:
                pipeline.close()

    def _feeder_loop(self, lane: _Lane) -> None:
        while True:
            with self._cond:
                quarantined = lane.quarantined
                stop = self._stop
            if quarantined:
                # suspect lane: drain (no extraction) and solo re-probe
                # with the auditor's known-good window; a failed probe
                # backs off and retries while healthy siblings serve
                if not self._reprobe_lane(lane):
                    if stop:
                        return
                    with self._cond:
                        if lane.quarantined:
                            self._cond.wait(
                                min(5.0, 0.25 * max(1, lane.reprobes)))
                    continue
            batch = None
            with self._cond:
                while True:
                    if lane.quarantined:
                        break
                    if self._held and not self._stop:
                        self._cond.wait(0.1)
                        continue
                    key = self._oldest_key_locked()
                    if key is None:
                        if self._stop:
                            return
                        self._cond.wait(0.5)
                        continue
                    pool = self._pools[key]
                    if (self.max_wait_s > 0.0 and not self._stop
                            and len(pool) < self.iteration_windows):
                        # a FULL iteration pending under any other key
                        # dispatches right away — the coalescing wait
                        # must never idle the device past ready work
                        full = next(
                            (k for k, p in self._pools.items()
                             if len(p) >= self.iteration_windows),
                            None)
                        if full is not None:
                            batch = self._extract_locked(full, lane)
                            break
                        # brief coalescing wait, bounded by the OLDEST
                        # entry's age
                        left = (min(e[1] for e in pool)
                                + self.max_wait_s - time.monotonic())
                        if left > 0:
                            self._cond.wait(min(left, 0.5))
                            continue
                    batch = self._extract_locked(key, lane)
                    break
            if not batch:
                continue
            try:
                self._run_iteration(batch, lane)
            except BaseException as exc:  # noqa: BLE001 — the feeder
                # must outlive any single iteration: fail the
                # participants, keep draining the pool
                self._fail_tickets({e[2] for e in batch}, exc)

    def _oldest_key_locked(self) -> tuple | None:
        """The engine key holding the globally oldest pending window —
        cross-key FIFO, so one parameter set cannot starve another."""
        best, best_seq = None, None
        for key, pool in list(self._pools.items()):
            pool[:] = [e for e in pool if e[2].error is None]
            if not pool:
                del self._pools[key]
                continue
            seq = min(e[0] for e in pool)
            if best_seq is None or seq < best_seq:
                best, best_seq = key, seq
        return best

    def _extract_locked(self, key: tuple, lane: _Lane) -> list:
        """Take one iteration's entries via the sched layer's
        incremental packing: a shape-homogeneous slab of at most
        `iteration_windows` windows that contains (and therefore
        ships) the oldest pending entry, rounded to the extracting
        LANE's device multiple when the pool is deep enough (zero
        round_batch padding lanes on the sub-mesh)."""
        from ..sched import pack_iteration

        batch, rest = pack_iteration(
            self._pools[key], self.iteration_windows,
            shape_key=lambda e: _shape_key(e[3]),
            age_key=lambda e: e[0],
            lane_multiple=lane.runner.n_devices)
        if rest:
            self._pools[key] = rest
        else:
            del self._pools[key]
        return batch

    # -------------------------------------------------------- execution
    def _merged_stats(self):
        """One OccupancyStats view across the batcher's own stats and
        every distinct per-lane instance (a scratch merge — cheap, the
        counters are a handful of dicts)."""
        from ..sched import OccupancyStats

        with self._cond:
            lanes = list(self._lanes or ())
        parts = [self.scheduler.stats] + [
            lane.scheduler.stats for lane in lanes
            if lane.scheduler is not self.scheduler]
        if len(parts) == 1:
            return self.scheduler.stats
        merged = OccupancyStats()
        for p in parts:
            merged.merge_from(p)
        return merged

    def _merged_pipeline(self) -> dict:
        """One PipelineStats snapshot across every distinct per-lane
        instance (the single-lane default shares the batcher's own, the
        multi-lane partition keeps one per lane so per-iteration deltas
        stay exact under concurrency)."""
        with self._cond:
            lanes = list(self._lanes or ())
        snaps = [self.pipeline_stats.snapshot()] + [
            lane.pipeline_stats.snapshot() for lane in lanes
            if lane.pipeline_stats is not self.pipeline_stats]
        out = snaps[0]
        for snap in snaps[1:]:
            for k, v in snap.items():
                out[k] = out.get(k, 0) + v
        return out

    def _compile_totals(self, stats=None) -> tuple[int, float]:
        """(compiles, compile_s) of `stats` — one lane's instance for
        per-iteration deltas (exact under lane concurrency), or the
        merged server-lifetime view when omitted."""
        snap = (stats if stats is not None
                else self._merged_stats()).snapshot()
        return (sum(e.get("compiles", 0) for e in snap.values()),
                sum(e.get("compile_s", 0.0) for e in snap.values()))

    def _lane_engine(self, lane: _Lane, key: tuple, p0):
        """The lane's PERSISTENT (pipeline, engine) pair for one engine
        key — built on the first iteration that needs it, reused for
        every later one (the persistent dispatch loop: engine
        construction, kernel-plan resolution and pipeline/watchdog
        wiring leave the per-iteration hot path; the engines' own
        device-engine caches then keep jit lookups warm too). Caller
        holds the lane's exec lock. Every knob that feeds construction
        is part of `key` (_engine_key), so two jobs sharing an
        iteration always resolve the same pair."""
        from ..ops.poa import BatchPOA
        from ..pipeline import DispatchPipeline
        from ..resilience import Watchdog

        ent = lane.engines.get(key)
        if ent is None:
            pipeline = DispatchPipeline(
                depth=p0.tpu_pipeline_depth,
                stats=lane.pipeline_stats,
                fallback_workers=max(1, min(4, p0.num_threads)),
                watchdog=Watchdog.from_env(
                    timeout=p0.tpu_device_timeout or None))
            engine = BatchPOA(p0.match, p0.mismatch, p0.gap,
                              p0.window_length,
                              num_threads=p0.num_threads,
                              device_batches=p0.tpu_poa_batches,
                              banded=p0.tpu_banded_alignment,
                              band_width=p0.tpu_aligner_band_width,
                              engine=p0.tpu_engine,
                              pipeline=pipeline,
                              scheduler=lane.scheduler,
                              runner=lane.runner)
            ent = lane.engines[key] = (pipeline, engine)
        return ent

    def _run_iteration(self, batch: list, lane: _Lane) -> None:
        windows = [e[3] for e in batch]
        per_ticket: dict = {}
        for e in batch:
            per_ticket.setdefault(e[2], []).append(e[3])
        tickets = list(per_ticket)
        p0 = tickets[0].polisher
        it = next(self._iter_seq)
        progress = _IterProgress(
            [(t, len(ws)) for t, ws in per_ticket.items()], it)
        with lane.lock:
            self._lane_busy(lane, True)
            # a winner-table demotion flags every lane's engines stale:
            # rebuild here so the vetoed kernel stops dispatching at
            # the very next iteration, quarantined or not
            self._fresh_engines_locked(lane)
            pre_c, pre_s = self._compile_totals(lane.scheduler.stats)
            pre_dev = lane.pipeline_stats.snapshot()["device_s"]
            _, engine = self._lane_engine(lane, tickets[0].key, p0)
            # only the logger varies per iteration; everything else in
            # the engine's identity is pinned by the key
            engine.logger = progress if progress.active else None
            t0 = time.perf_counter()
            try:
                engine.generate_consensus(windows, p0.trim)
            finally:
                t1 = time.perf_counter()
                self._lane_busy(lane, False, t1 - t0)
            post_c, post_s = self._compile_totals(lane.scheduler.stats)
            post_dev = lane.pipeline_stats.snapshot()["device_s"]
        # measured per-iteration host overhead: the wall the lane held
        # its lock minus the device-stage seconds the iteration's
        # pipeline charged (dispatch + result wait) — the number the
        # fused dispatch loop exists to shrink. Exact per lane: the
        # lane's own PipelineStats sees no concurrent writer.
        host_s = max(0.0, (t1 - t0) - (post_dev - pre_dev))
        tr = trace.get_tracer()
        if tr is not None:
            tr.complete("serve.iteration", t0, t1,
                        {"iteration": it, "lane": lane.index,
                         "jobs": len(tickets),
                         "windows": len(windows),
                         "host_s": round(host_s, 4),
                         "trace_ids": _trace_ids(tickets)})
        if self.hists is not None:
            self.hists.observe("serve.iteration", t1 - t0)
            self.hists.observe("serve.iteration_host", host_s)
        self._account(len(tickets), len(windows), solo=False,
                      host_s=host_s)
        # identity audit (obs/audit.py): sampled shadow re-execution off
        # the lane lock, BEFORE delivery so a caught corruption is
        # repaired before any job stitches it
        self._audit([(w, t.polisher)
                     for t, ws in per_ticket.items() for w in ws],
                    lane, it)
        # populate the content cache AFTER the audit pass: a window the
        # sentinel caught and repaired ships (and caches) the oracle
        # bytes — the cache can never be seeded by a caught corruption
        cache = self.wincache
        if cache is not None:
            from ..sched.autotune import posture_key

            posture = posture_key()
            for t, ws in per_ticket.items():
                for w in ws:
                    cache.store(cache.key(w, t.key, posture),
                                w.consensus, w.polished)
        shared = len(tickets) > 1
        for ticket, ws in per_ticket.items():
            ticket.iterations += 1
            ticket.iteration_ids.append(it)
            if shared:
                ticket.shared_iterations += 1
            ticket.compiles += post_c - pre_c
            ticket.compile_s += post_s - pre_s
            ticket.device_s += t1 - t0
            # cost proration: this job's slice of the iteration wall is
            # its window share (the slices of one iteration sum to its
            # wall, so tenant buckets sum to total lane busy seconds)
            share = (t1 - t0) * len(ws) / len(windows)
            ticket.device_share_s += share
            self._accrue_tenant_device(
                getattr(ticket.polisher, "serve_tenant", None) or "",
                share)
            ticket.done += len(ws)
            ticket.remaining -= len(ws)
            # iteration boundary: every participant's bar reaches its
            # exact completed-window count even if the engine's tick
            # quantization stopped short of the last bin
            ticket.polisher.emit_progress(ticket.done, ticket.total,
                                          phase="consensus",
                                          iteration=it)
            # hand the finished windows to the job's own thread (which
            # runs the incremental stitch there); event LAST so the
            # consumer's drain-after-event sees every delivery
            ticket.deliver(ws)
            if ticket.remaining <= 0:
                ticket.finish()

    # ------------------------------------------------------------- audit
    def _audit(self, pairs, lane: _Lane, iteration: int) -> None:
        """Run the armed identity auditor over one iteration's finished
        windows (shared or solo). Never fails production: an audit bug
        is logged, the iteration's delivery proceeds untouched. The
        wall spent here is accounted as `audit_s` — the queue-side
        overhead number servebench measures and perfgate gates."""
        auditor = self.auditor
        if auditor is None or not auditor.armed or not pairs:
            return
        t0 = time.perf_counter()
        try:
            auditor.audit_windows(pairs, lane_index=lane.index,
                                  iteration=iteration, batcher=self)
        except Exception as exc:  # noqa: BLE001 — see docstring
            from ..utils.logger import log_info

            log_info(f"[racon_tpu::audit] warning: audit pass failed "
                     f"({type(exc).__name__}: {exc})")
        with self._cond:
            self.counters["audit_s"] += time.perf_counter() - t0

    def _audit_cache_hits(self, polisher, windows: list,
                          hit_keys: dict) -> None:
        """Sentinel pass over one job's cache-HIT windows (runs on the
        JOB thread — hits never cross a feeder). Mismatch consequences
        are redirected at the CACHE: the poisoned ENTRY is evicted and
        its key quarantined (obs/audit.py cache path) instead of
        demoting an engine or quarantining a lane that never produced
        these bytes — the populating iteration already had its own
        audit. Same never-fails-production contract as `_audit`."""
        auditor = self.auditor
        if auditor is None or not auditor.armed or not windows:
            return
        t0 = time.perf_counter()
        try:
            auditor.audit_windows(
                [(w, polisher) for w in windows], lane_index=-1,
                iteration=-1, batcher=self, wincache=self.wincache,
                cache_keys=hit_keys)
        except Exception as exc:  # noqa: BLE001 — see _audit
            from ..utils.logger import log_info

            log_info(f"[racon_tpu::audit] warning: cache-hit audit "
                     f"pass failed ({type(exc).__name__}: {exc})")
        with self._cond:
            self.counters["audit_s"] += time.perf_counter() - t0

    def flush_lane_engines(self) -> None:
        """Mark EVERY lane's cached (pipeline, engine) pairs stale —
        rebuilt lazily at each lane's next iteration (or re-probe). The
        auditor calls this after an online winner-table demotion: the
        engines' per-bucket plan caches resolved the OLD winner, so
        without a flush a demoted kernel would keep dispatching on
        every lane that already built its engines."""
        with self._cond:
            for lane in (self._lanes or ()):
                lane.flush_engines = True
        # every cached entry was produced under the now-demoted winner
        # table: the content key cannot tell old-winner bytes from
        # new-winner bytes (both are supposed to be identical, but the
        # demotion exists precisely because one of them was not)
        if self.wincache is not None:
            self.wincache.invalidate_all("winner-table demotion")

    def _fresh_engines_locked(self, lane: _Lane) -> None:
        """Drop the lane's cached engines if flagged stale (caller
        holds the LANE lock; the flag is _cond-guarded)."""
        with self._cond:
            flush, lane.flush_engines = lane.flush_engines, False
        if flush:
            for pipeline, _e in lane.engines.values():
                pipeline.close()
            lane.engines.clear()

    def quarantine_lane(self, index: int) -> None:
        """Mark a lane suspect (the auditor calls this on a mismatch):
        its health gauge drops to 0, it stops extracting iterations,
        its cached engines are flushed (so a just-demoted winner table
        takes effect on rebuild), and its feeder re-probes it with the
        auditor's known-good window — rejoining on a clean probe,
        staying quarantined otherwise (unless it is the last serving
        lane, which rejoins DEGRADED at health 0.5 rather than wedging
        the service)."""
        with self._cond:
            lanes = self._lanes or []
            if index >= len(lanes):
                return
            lane = lanes[index]
            if lane.quarantined:
                return
            lane.quarantined = True
            lane.health = 0.0
            lane.flush_engines = True
            self.counters["lane_quarantines"] += 1
            self._cond.notify_all()
        # a suspect lane may have populated cache entries from its
        # UNSAMPLED windows — drop them all rather than serve a
        # corrupt byte stream from memory after the lane drains
        if self.wincache is not None:
            self.wincache.invalidate_all(f"lane {index} quarantined")
        if self.auditor is not None:
            self.auditor.lane_event(index, "quarantined")

    def _reprobe_lane(self, lane: _Lane) -> bool:
        """One solo re-probe of a quarantined lane: run the auditor's
        known-good window through THIS lane's (rebuilt) engine and
        byte-compare against the oracle-verified bytes. Returns True
        when the lane rejoined (clean probe, or degraded last-lane
        fallback), False when it stays quarantined."""
        from ..ops.oracle import rebuild_window

        auditor = self.auditor
        probe = auditor.probe() if auditor is not None else None
        ok = None
        if probe is not None:
            p0, snap, expect_cons, expect_pol = probe
            try:
                w = rebuild_window(snap)
                key = _engine_key(p0)
                with lane.lock:
                    self._fresh_engines_locked(lane)
                    _, engine = self._lane_engine(lane, key, p0)
                    engine.logger = None
                    engine.generate_consensus([w], p0.trim)
                ok = (w.consensus == expect_cons
                      and w.polished == expect_pol)
            except Exception:  # noqa: BLE001 — a raising probe is a
                # failing probe
                ok = False
        with self._cond:
            lane.reprobes += 1
            self.counters["lane_reprobes"] += 1
            reprobes = lane.reprobes
        if ok:
            with self._cond:
                lane.quarantined = False
                lane.health = 1.0
                self.counters["lane_rejoins"] += 1
                self._cond.notify_all()
            if auditor is not None:
                auditor.lane_event(lane.index, "rejoined",
                                   reprobes=reprobes)
            return True
        # failed (or no probe material): stay quarantined while any
        # healthy sibling serves; the LAST lane rejoins degraded — a
        # loudly-flagged lane beats a wedged service, and the sentinel
        # keeps repairing whatever it samples
        with self._cond:
            others = any(l is not lane and not l.quarantined
                         for l in (self._lanes or ()))
            if not others:
                lane.quarantined = False
                lane.health = 0.5
                self._cond.notify_all()
        if not others:
            if auditor is not None:
                auditor.lane_event(
                    lane.index, "degraded",
                    reason=("re-probe failed with no healthy sibling"
                            if ok is False else "no known-good probe"))
            return True
        if auditor is not None and ok is False:
            auditor.lane_event(lane.index, "reprobe-failed",
                               reprobes=reprobes)
        return False

    def _fail_tickets(self, tickets, exc: BaseException) -> None:
        """An iteration died (strict-off degradation happens INSIDE
        generate_consensus; reaching here means even the degraded path
        gave up): fail every participant the same way a solo run would
        have, withdraw their remaining pooled windows, keep feeding."""
        with self._cond:
            for t in tickets:
                t.error = exc
        for t in tickets:
            t.finish()

    def _account(self, jobs: int, windows: int, solo: bool,
                 host_s: float = 0.0) -> None:
        with self._cond:
            self.counters["iterations"] += 1
            self.counters["jobs"] += jobs
            self.counters["windows"] += windows
            self.counters["host_s"] += host_s
            if solo:
                self.counters["solo_iterations"] += 1
            if jobs > 1:
                self.counters["shared_iterations"] += 1
            self.counters["max_jobs_in_iteration"] = max(
                self.counters["max_jobs_in_iteration"], jobs)
            self.counters["max_windows_in_iteration"] = max(
                self.counters["max_windows_in_iteration"], windows)

    # ------------------------------------------------- preemption / QoS
    def withdraw_job(self, job_id: str) -> int:
        """Preempt a running job: move its not-yet-dispatched pooled
        windows into the parked store (tuples verbatim — original
        arrival sequence preserved for the resume) and mark the job
        withdrawn so windows it pools LATER (iterative rounds, a
        racing submit) park directly. Windows already inside an
        extracted iteration complete and deliver normally — preemption
        is a between-iterations operation, which is exactly what keeps
        the job's ContigStreamer state intact and its eventual output
        byte-identical. Returns the number of entries parked. Safe on
        ids the batcher has never seen (the withdrawn mark still
        guards future pooling)."""
        with self._cond:
            self._withdrawn.add(job_id)
            parked = self._parked.setdefault(job_id, [])
            n = 0
            for key, pool in list(self._pools.items()):
                keep = []
                for e in pool:
                    if getattr(e[2].polisher, "serve_job_id",
                               None) == job_id:
                        parked.append((key, e))
                        n += 1
                    else:
                        keep.append(e)
                if len(keep) != len(pool):
                    if keep:
                        self._pools[key] = keep
                    else:
                        del self._pools[key]
            if not parked:
                del self._parked[job_id]
            return n

    def resume_job(self, job_id: str) -> int:
        """Return a preempted job's parked windows to their pools and
        clear its withdrawn mark. The entries rejoin carrying their
        ORIGINAL arrival sequence, so the feeder's oldest-window
        guarantee treats them with their true age — a resumed job goes
        back to the front of the line it already earned, and the
        packing it lands in cannot change its bytes (per-window
        consensus is batch-composition-independent). Returns the
        number of entries returned."""
        with self._cond:
            self._withdrawn.discard(job_id)
            parked = self._parked.pop(job_id, [])
            for key, e in parked:
                self._pools.setdefault(key, []).append(e)
            if parked:
                self._cond.notify_all()
            return len(parked)

    def cancel_job(self, job_id: str) -> bool:
        """Cancel a RUNNING job through the ticket-error withdrawal
        seam (the same path a failed shared iteration uses): its live
        tickets die with a typed `queue.JobCancelledError`, the feeder
        drops their still-pooled windows at its next scan, parked
        entries are purged, and the job's own consumer thread re-raises
        to the worker — which answers the client with the typed
        `cancelled` terminal. Returns False when the job has no live
        ticket here (isolation/solo jobs never pool; the server falls
        back to its round-boundary cancel flag)."""
        from .queue import JobCancelledError

        with self._cond:
            tickets = list(self._job_tickets.get(job_id) or ())
            if not tickets:
                return False
            exc = JobCancelledError("running")
            for t in tickets:
                if t.error is None:
                    t.error = exc
            self._parked.pop(job_id, None)
            self._withdrawn.discard(job_id)
            self._cond.notify_all()
        for t in tickets:
            t.finish()
        return True

    # ------------------------------------------------------- test hooks
    def hold(self) -> None:
        """Pause the feeder BEFORE it extracts its next iteration
        (tests: make multi-job iterations deterministic by pooling
        several jobs before releasing)."""
        with self._cond:
            self._held = True

    def release(self) -> None:
        with self._cond:
            self._held = False
            self._cond.notify_all()

    def snapshot(self) -> dict:
        with self._cond:
            out = dict(self.counters)
            out["host_s"] = round(out["host_s"], 4)
            out["audit_s"] = round(out["audit_s"], 4)
            out["worker_lanes"] = (len(self._lanes)
                                   if self._lanes is not None
                                   else self.worker_lanes)
            out["lanes"] = [
                {"lane": l.index, "n_devices": l.runner.n_devices,
                 "iterations": l.iterations,
                 "busy": l.busy, "busy_s": round(l.busy_s, 4),
                 "health": round(l.health, 3),
                 "quarantined": l.quarantined,
                 "reprobes": l.reprobes}
                for l in (self._lanes or ())]
            # armed-only (byte-identity when QoS is unconfigured):
            # surfaced only while a preemption is actually in flight
            if self._withdrawn or self._parked:
                out["withdrawn_jobs"] = len(self._withdrawn)
                out["parked_windows"] = sum(
                    len(v) for v in self._parked.values())
            # armed-only: appears once any NAMED tenant has accrued
            # device time (the "" bucket alone is untenanted traffic
            # and stays invisible, keeping flagless snapshots and
            # scrapes byte-identical)
            if any(t for t in self._tenant_device):
                out["tenant_device_s"] = {
                    t: round(v, 4)
                    for t, v in sorted(self._tenant_device.items())}
        stats = self._merged_stats()
        compiles, compile_s = self._compile_totals(stats)
        out["compiles"] = compiles
        out["compile_s"] = round(compile_s, 3)
        out["occupancy"] = stats.snapshot()
        out["pipeline"] = self._merged_pipeline()
        if self.wincache is not None:
            out["wincache"] = self.wincache.snapshot()
        return out
