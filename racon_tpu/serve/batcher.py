"""Cross-job window batching: one warm engine pass over many jobs.

A window's consensus depends only on the window itself (backbone +
layers) and the engine parameters — never on which other windows share
its device batch. The scheduler's sorted packing already exploits this
within one run (results restore by index, byte-identical, PR-3 pinned);
`WindowBatcher` extends the same invariant ACROSS jobs: windows from
concurrent polish requests are concatenated into one engine pass, so one
job's stragglers fill the padding lanes another job's batch would have
burned, and each job's windows come back carrying their consensus exactly
as a solo run would have produced (test-pinned in tests/test_serve.py).

Mechanics — the leader/joiner gather pattern:

  - a job thread calling `consensus(polisher)` files a ticket under the
    job's engine-parameter key (jobs with different scores / window
    length / engine must not share a pass);
  - the first ticket for a key becomes the LEADER: it waits up to
    `gather_window_s` (or until `min_gather` tickets joined), takes the
    whole group, and runs ONE `BatchPOA.generate_consensus` over the
    concatenated windows;
  - joiners block on their ticket; results demultiplex for free because
    every window object belongs to exactly one job's polisher.

Engine passes are serialized on one executor lock — the device is a
single shared resource, and serialization makes the per-round compile
telemetry (the "warm submit = 0 compiles" acceptance signal) exact.

Isolation: a job carrying its own fault plan or a strict posture never
shares a batch — it runs its polisher's own `_consensus_pass()` (own
pipeline, own injected faults), so an injected `DeviceError` storm fails
exactly one job while the batcher, the warm engines and every concurrent
job continue untouched.
"""

from __future__ import annotations

import itertools
import threading
import time

from ..obs import trace


class _Ticket:
    __slots__ = ("polisher", "event", "error", "round_info")

    def __init__(self, polisher):
        self.polisher = polisher
        self.event = threading.Event()
        self.error: BaseException | None = None
        self.round_info: dict | None = None


class _RoundProgress:
    """Duck-typed Logger for shared rounds: the engine sees the usual
    `bar_total`/`bar` surface, but instead of stderr the bin-level ticks
    fan out to every participating job's live-progress hook, scaled to
    that job's own window count (a tick in a shared round advances every
    participant's bar by its share — windows are not attributable to
    jobs mid-engine, fractions of the round are). Monotonicity across
    re-armed bars (an engine's fallback pass calls bar_total again) is
    enforced downstream by Polisher.emit_progress' per-phase
    high-water mark. Silent by design: shared rounds never print."""

    def __init__(self, tickets, round_no: int):
        self._jobs = [(t.polisher, len(t.polisher.windows))
                      for t in tickets
                      if t.polisher.progress_hook is not None]
        self._round = round_no
        self._total = 1
        self._count = 0
        self._bins = 0
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        return bool(self._jobs)

    def bar_total(self, total: int) -> None:
        with self._lock:
            self._total = max(1, int(total))
            self._count = 0
            self._bins = 0

    def bar(self, msg: str) -> None:
        with self._lock:
            self._count += 1
            bins = min(20 * self._count // self._total, 20)
            if bins == self._bins:
                return
            self._bins = bins
            frac = min(1.0, self._count / self._total)
        for polisher, n in self._jobs:
            polisher.emit_progress(int(frac * n), n, phase="consensus",
                                   round=self._round)

    # the rest of the Logger surface, defensively no-op
    def log(self, msg=None) -> None:
        pass

    def total(self, msg) -> None:
        pass


def _trace_ids(tickets) -> list[str]:
    """The client-minted trace ids riding this round's jobs (the server
    stamps `serve_trace_id` on each job's polisher) — tagged onto the
    gather/round spans so a merged client+server trace can attribute
    shared rounds."""
    return [tid for tid in
            (getattr(t.polisher, "serve_trace_id", None) for t in tickets)
            if tid]


def _engine_key(p) -> tuple:
    """Engine-parameter identity: jobs share a pass only when every
    knob that can influence a window's consensus bytes matches."""
    return (p.match, p.mismatch, p.gap, p.window_length, p.trim,
            p.num_threads, p.tpu_poa_batches, p.tpu_banded_alignment,
            p.tpu_aligner_band_width, p.tpu_engine,
            p.tpu_pipeline_depth, p.tpu_device_timeout)


class WindowBatcher:
    def __init__(self, gather_window_s: float = 0.05, min_gather: int = 2,
                 scheduler=None):
        from ..pipeline import PipelineStats
        from ..sched import BatchScheduler

        self.gather_window_s = max(0.0, float(gather_window_s))
        self.min_gather = max(1, int(min_gather))
        #: one scheduler + stage-stat sink for every shared round: the
        #: server-lifetime occupancy/compile telemetry servebench reads
        self.scheduler = (scheduler if scheduler is not None
                          else BatchScheduler.from_env())
        self.pipeline_stats = PipelineStats()
        self._cond = threading.Condition()
        self._pending: dict[tuple, list[_Ticket]] = {}
        self._leading: set[tuple] = set()
        #: optional callable -> number of jobs currently executing
        #: (the server wires its in-flight count): a leader whose ticket
        #: group already holds every executing job skips the gather wait
        #: — a lone job must not idle out the window for company that
        #: cannot arrive
        self.active_hint = None
        #: optional obs.hist.HistogramSet (the server's lifetime set):
        #: leader gather waits and device round durations observed as
        #: latency distributions for the scrape view
        self.hists = None
        self._exec_lock = threading.Lock()
        self._round_seq = itertools.count()
        self.counters = {"rounds": 0, "solo_rounds": 0,
                         "multi_job_rounds": 0, "jobs": 0, "windows": 0,
                         "max_jobs_in_round": 0}

    # ------------------------------------------------------------ entry
    def consensus(self, polisher) -> None:
        """Run the consensus pass for `polisher.windows`, possibly merged
        with concurrent jobs' windows (see module docstring). On return
        every window carries consensus/polished; round telemetry is left
        on `polisher.serve_round` for the server's response."""
        from ..resilience import strict_mode

        if polisher.faults is not None or strict_mode():
            # isolation round: injected faults / strict posture stay on
            # this job's own pipeline and never touch a shared batch
            rnd = next(self._round_seq)
            t0 = time.perf_counter()
            with self._exec_lock:
                polisher._consensus_pass()
            if self.hists is not None:
                self.hists.observe("serve.round",
                                   time.perf_counter() - t0)
            self._account(1, len(polisher.windows), solo=True)
            polisher.serve_round = {"round": rnd, "jobs": 1,
                                    "windows": len(polisher.windows),
                                    "solo": True}
            return

        key = _engine_key(polisher)
        ticket = _Ticket(polisher)
        with self._cond:
            self._pending.setdefault(key, []).append(ticket)
            leader = key not in self._leading
            if leader:
                self._leading.add(key)
            self._cond.notify_all()
        if not leader:
            ticket.event.wait()
        else:
            t_gather = time.monotonic()
            t_gather_pc = time.perf_counter()
            deadline = t_gather + self.gather_window_s
            hint = self.active_hint
            with self._cond:
                while len(self._pending[key]) < self.min_gather:
                    if (hint is not None
                            and hint() <= len(self._pending[key])):
                        break
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(left)
                batch = self._pending.pop(key)
                if self.hists is not None:
                    self.hists.observe("serve.gather_wait",
                                       time.monotonic() - t_gather)
                # release the key BEFORE executing: tickets arriving
                # mid-round start gathering the next round immediately
                self._leading.discard(key)
            tr = trace.get_tracer()
            if tr is not None:
                tr.complete("serve.gather_wait", t_gather_pc,
                            time.perf_counter(),
                            {"jobs": len(batch),
                             "trace_ids": _trace_ids(batch)})
            self._execute(batch)
        if ticket.error is not None:
            raise ticket.error
        polisher.serve_round = ticket.round_info

    # -------------------------------------------------------- execution
    def _compile_totals(self) -> tuple[int, float]:
        snap = self.scheduler.stats.snapshot()
        return (sum(e.get("compiles", 0) for e in snap.values()),
                sum(e.get("compile_s", 0.0) for e in snap.values()))

    def _execute(self, tickets: list[_Ticket]) -> None:
        from ..ops.poa import BatchPOA
        from ..pipeline import DispatchPipeline
        from ..resilience import Watchdog

        p0 = tickets[0].polisher
        windows = []
        for t in tickets:
            windows.extend(t.polisher.windows)
        rnd = next(self._round_seq)
        progress = _RoundProgress(tickets, rnd)
        try:
            with self._exec_lock:
                pre_c, pre_s = self._compile_totals()
                pipeline = DispatchPipeline(
                    depth=p0.tpu_pipeline_depth,
                    stats=self.pipeline_stats,
                    fallback_workers=max(1, min(4, p0.num_threads)),
                    watchdog=Watchdog.from_env(
                        timeout=p0.tpu_device_timeout or None))
                engine = BatchPOA(p0.match, p0.mismatch, p0.gap,
                                  p0.window_length,
                                  num_threads=p0.num_threads,
                                  device_batches=p0.tpu_poa_batches,
                                  banded=p0.tpu_banded_alignment,
                                  band_width=p0.tpu_aligner_band_width,
                                  logger=(progress if progress.active
                                          else None),
                                  engine=p0.tpu_engine,
                                  pipeline=pipeline,
                                  scheduler=self.scheduler)
                t0 = time.perf_counter()
                with pipeline:
                    engine.generate_consensus(windows, p0.trim)
                t1 = time.perf_counter()
                post_c, post_s = self._compile_totals()
            tr = trace.get_tracer()
            if tr is not None:
                tr.complete("serve.batch_round", t0, t1,
                            {"round": rnd, "jobs": len(tickets),
                             "windows": len(windows),
                             "trace_ids": _trace_ids(tickets)})
            if self.hists is not None:
                self.hists.observe("serve.round", t1 - t0)
        except BaseException as exc:
            # a shared-round failure fails every participant the same
            # way a solo run would have (strict-off degradation happens
            # INSIDE generate_consensus; reaching here means even the
            # degraded path gave up) — the batcher itself stays alive
            for t in tickets:
                t.error = exc
                t.event.set()
            return
        info = {"round": rnd, "jobs": len(tickets),
                "windows": len(windows), "solo": False,
                "compiles": post_c - pre_c,
                "compile_s": round(post_s - pre_s, 3),
                "round_s": round(t1 - t0, 4)}
        self._account(len(tickets), len(windows), solo=False)
        for polisher, n in progress._jobs:
            # the round is done: every participant's consensus bar
            # completes even if the engine's tick quantization stopped
            # short of the last bin
            polisher.emit_progress(n, n, phase="consensus", round=rnd)
        for t in tickets:
            t.round_info = dict(info, job_windows=len(t.polisher.windows))
            t.event.set()

    def _account(self, jobs: int, windows: int, solo: bool) -> None:
        with self._cond:
            self.counters["rounds"] += 1
            self.counters["jobs"] += jobs
            self.counters["windows"] += windows
            if solo:
                self.counters["solo_rounds"] += 1
            if jobs > 1:
                self.counters["multi_job_rounds"] += 1
            self.counters["max_jobs_in_round"] = max(
                self.counters["max_jobs_in_round"], jobs)

    def snapshot(self) -> dict:
        with self._cond:
            out = dict(self.counters)
        compiles, compile_s = self._compile_totals()
        out["compiles"] = compiles
        out["compile_s"] = round(compile_s, 3)
        out["occupancy"] = self.scheduler.stats.snapshot()
        out["pipeline"] = self.pipeline_stats.snapshot()
        return out
