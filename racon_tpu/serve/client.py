"""PolishClient: Python + CLI client for the warm polishing service.

One request = one connection (the server multiplexes concurrency across
connections, so a client that wants N jobs in flight opens N sockets —
exactly what `tools/servebench.py` does from a thread pool). Errors come
back as the protocol's typed error responses and are re-raised as the
exception taxonomy below, so callers branch on types, not message
strings:

    QueueFull       admission control rejected; `retry_after` seconds
    ServerDraining  server is shutting down, resubmit elsewhere
    JobFailed       the job ran and failed; `error_type` names the
                    errors.py class (DeviceError, DeviceTimeout, ...)
    ServeError      anything else typed (bad-request, bad-frame, ...)

`racon_tpu submit ...` (cli.py) is the CLI face: same three positional
inputs as the one-shot CLI, polished FASTA on stdout — byte-identical
to the one-shot run, just served warm.
"""

from __future__ import annotations

import os
import socket
import sys
import time

from .protocol import WIRE_LIMIT, recv_frame, send_frame
from .server import DEFAULT_SOCKET


class ServeError(Exception):
    """Typed error response from the server."""

    def __init__(self, code: str, message: str, response: dict):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.response = response


class QueueFull(ServeError):
    def __init__(self, code, message, response):
        super().__init__(code, message, response)
        self.retry_after = float(response.get("retry_after", 1.0))


class ServerDraining(ServeError):
    pass


class JobFailed(ServeError):
    def __init__(self, code, message, response):
        super().__init__(code, message, response)
        self.error_type = response.get("error_type", "RaconError")


_ERROR_TYPES = {"queue-full": QueueFull, "draining": ServerDraining,
                "job-failed": JobFailed}


class PolishResult:
    __slots__ = ("job_id", "fasta", "metrics", "serve", "trace")

    def __init__(self, resp: dict):
        self.job_id = resp.get("job_id")
        self.fasta = resp.get("fasta", "").encode("latin-1")
        self.metrics = resp.get("metrics") or {}
        self.serve = resp.get("serve") or {}
        self.trace = resp.get("trace")


class PolishClient:
    def __init__(self, socket_path: str | None = None,
                 port: int | None = None, timeout: float | None = None):
        self.socket_path = (socket_path
                            or os.environ.get("RACON_TPU_SERVE_SOCKET")
                            or DEFAULT_SOCKET)
        self.port = port
        self.timeout = timeout

    def _connect(self) -> socket.socket:
        if self.port:
            sock = socket.create_connection(("127.0.0.1", self.port),
                                            timeout=self.timeout)
        else:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self.timeout)
            sock.connect(self.socket_path)
        return sock

    def request(self, obj: dict) -> dict:
        """One round trip; raises the ServeError taxonomy on a typed
        error response."""
        sock = self._connect()
        try:
            send_frame(sock, obj)
            # results come from a trusted server: accept up to the wire
            # limit, not the server's anti-abuse request ceiling — a
            # multi-hundred-MiB polished assembly must come back whole
            resp = recv_frame(sock, max_frame=WIRE_LIMIT)
        finally:
            sock.close()
        if resp is None:
            raise ServeError("closed", "server closed the connection",
                             {})
        if resp.get("type") == "error":
            code = resp.get("code", "error")
            raise _ERROR_TYPES.get(code, ServeError)(
                code, resp.get("message", ""), resp)
        return resp

    # ------------------------------------------------------------ calls
    def submit(self, sequences: str, overlaps: str, target: str, *,
               options: dict | None = None, priority: int = 0,
               deadline_s: float | None = None,
               fault_plan: str | None = None, strict: bool | None = None,
               trace: bool = False, retries: int = 0) -> PolishResult:
        """Polish one input triple on the server. Paths are resolved to
        absolute before they cross the wire (the server's cwd is not the
        client's). `retries` re-submits after `retry_after` on full-queue
        rejects — simple client-side backoff."""
        req = {"type": "submit",
               "sequences": os.path.abspath(sequences),
               "overlaps": os.path.abspath(overlaps),
               "target": os.path.abspath(target)}
        if options:
            req["options"] = options
        if priority:
            req["priority"] = int(priority)
        if deadline_s is not None:
            req["deadline_s"] = float(deadline_s)
        if fault_plan:
            req["fault_plan"] = fault_plan
        if strict is not None:
            req["strict"] = bool(strict)
        if trace:
            req["trace"] = True
        attempt = 0
        while True:
            try:
                return PolishResult(self.request(req))
            except QueueFull as exc:
                if attempt >= retries:
                    raise
                attempt += 1
                time.sleep(exc.retry_after)

    def ping(self) -> dict:
        return self.request({"type": "ping"})

    def stats(self) -> dict:
        return self.request({"type": "stats"})

    def scrape(self) -> str:
        """Live Prometheus text exposition (the same body the optional
        `--metrics-port` HTTP endpoint serves) — counters, gauges and
        latency histograms, refreshed at call time."""
        return self.request({"type": "scrape"})["text"]

    def debug(self, max_events: int = 5000) -> dict:
        """The flight recorder's recent events plus the automatic dump
        artifacts written so far — the live post-mortem view."""
        return self.request({"type": "debug", "max_events": max_events})

    def shutdown(self) -> dict:
        return self.request({"type": "shutdown"})


# ------------------------------------------------------------------ CLI
def submit_main(argv: list[str]) -> int:
    """`racon_tpu submit` entry point: send one job to a running server,
    polished FASTA on stdout (byte-identical to the one-shot CLI)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="racon_tpu submit",
        description="submit a polishing job to a running "
                    "`racon_tpu serve` instance")
    ap.add_argument("sequences")
    ap.add_argument("overlaps")
    ap.add_argument("target")
    ap.add_argument("--socket", default=None)
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--timeout", type=float, default=None,
                    help="socket timeout in seconds (default: none)")
    ap.add_argument("--priority", type=int, default=0)
    ap.add_argument("--deadline", type=float, default=None,
                    help="job deadline in seconds: a job not STARTED in "
                         "time is cancelled in queue (deadline-expired "
                         "error); one that runs but FINISHES late still "
                         "returns its result, counted as an SLO "
                         "deadline miss (server stats `slo` view + "
                         "flight-recorder dump)")
    ap.add_argument("--retries", type=int, default=0,
                    help="re-submit after retry_after on queue-full")
    ap.add_argument("-u", "--include-unpolished", action="store_true")
    ap.add_argument("-f", "--fragment-correction", action="store_true")
    ap.add_argument("-w", "--window-length", type=int, default=None)
    ap.add_argument("-q", "--quality-threshold", type=float, default=None)
    ap.add_argument("-e", "--error-threshold", type=float, default=None)
    ap.add_argument("--no-trimming", action="store_true")
    ap.add_argument("-m", "--match", type=int, default=None)
    ap.add_argument("-x", "--mismatch", type=int, default=None)
    ap.add_argument("-g", "--gap", type=int, default=None)
    ap.add_argument("-c", "--tpupoa-batches", type=int, default=None)
    ap.add_argument("--tpualigner-batches", type=int, default=None)
    ap.add_argument("--tpu-engine", choices=("session", "fused"),
                    default=None)
    args = ap.parse_args(argv)

    options: dict = {}
    for key, val in (("include_unpolished", args.include_unpolished
                      or None),
                     ("fragment_correction", args.fragment_correction
                      or None),
                     ("window_length", args.window_length),
                     ("quality_threshold", args.quality_threshold),
                     ("error_threshold", args.error_threshold),
                     ("trim", False if args.no_trimming else None),
                     ("match", args.match),
                     ("mismatch", args.mismatch),
                     ("gap", args.gap),
                     ("tpu_poa_batches", args.tpupoa_batches),
                     ("tpu_aligner_batches", args.tpualigner_batches),
                     ("tpu_engine", args.tpu_engine)):
        if val is not None:
            options[key] = val

    client = PolishClient(socket_path=args.socket, port=args.port,
                          timeout=args.timeout)
    try:
        result = client.submit(args.sequences, args.overlaps, args.target,
                               options=options, priority=args.priority,
                               deadline_s=args.deadline,
                               retries=args.retries)
    except (ServeError, OSError) as exc:
        print(f"[racon_tpu::serve] error: {exc}", file=sys.stderr)
        return 1
    sys.stdout.buffer.write(result.fasta)
    sys.stdout.buffer.flush()
    serve = result.serve
    if serve:
        print(f"[racon_tpu::serve] job {result.job_id}: queue wait "
              f"{serve.get('queue_wait_s', 0):.3f}s, exec "
              f"{serve.get('exec_s', 0):.3f}s", file=sys.stderr)
    return 0
