"""PolishClient: Python + CLI client for the warm polishing service.

One request = one connection (the server multiplexes concurrency across
connections, so a client that wants N jobs in flight opens N sockets —
exactly what `tools/servebench.py` does from a thread pool). Errors come
back as the protocol's typed error responses and are re-raised as the
exception taxonomy below, so callers branch on types, not message
strings:

    QueueFull       admission control rejected; `retry_after` seconds
    TenantQuota     this tenant's queued-job quota is full (QueueFull
                    subclass, same `retry_after` backoff contract)
    ServerDraining  server is shutting down, resubmit elsewhere
    JobFailed       the job ran and failed; `error_type` names the
                    errors.py class (DeviceError, DeviceTimeout, ...)
    JobCancelled    the job was cancelled (cancel RPC / cancel-on-
                    timeout) before finishing
    DeadlineDoomed  the server speculatively aborted: predicted finish
                    past the deadline by more than its abort margin
                    (carries `predicted_s` / `remaining_s`)
    ServeError      anything else typed (bad-request, bad-frame, ...)

`racon_tpu submit ...` (cli.py) is the CLI face: same three positional
inputs as the one-shot CLI, polished FASTA on stdout — byte-identical
to the one-shot run, just served warm. Two observability extras ride
the same submit (README "End-to-end tracing & progress"):

  - `--progress` / `submit(..., on_progress=cb)`: the server interleaves
    `progress` frames (queue position while pending, then phase /
    windows-done / total) before the final result frame — live
    visibility into a job that used to be a black box until its bytes
    arrived.
  - `--trace-out t.json` / `submit_traced(...)`: the client mints a
    `trace_id`, estimates the server's perf_counter offset from an
    RTT-bracketed ping handshake, records its OWN spans (connect /
    submit / wait / receive, progress instants), asks the server for
    the job's server-side trace, and merges both into one Chrome-trace
    JSON — two Perfetto process tracks on a single timeline.
  - `--stream` / `submit(..., on_part=cb)`: the server streams each
    polished contig as a `result_part` frame the moment its windows
    complete (continuous batching stitches per contig); the final
    result frame carries the stats and the concatenation of the parts
    is byte-identical to the buffered FASTA. Time-to-first-byte becomes
    the FIRST contig's finish time, not the job's.
"""

from __future__ import annotations

import json
import os
import random
import socket
import sys
import time
import uuid

from .protocol import WIRE_LIMIT, recv_frame, send_frame
from .server import DEFAULT_SOCKET

#: ceiling on any single retry sleep — a server advertising a huge
#: retry_after must not park a client for minutes
RETRY_DELAY_CAP_S = 30.0


def _retry_delay(retry_after: float, cap: float = RETRY_DELAY_CAP_S,
                 rng: random.Random | None = None) -> float:
    """Jittered backoff for full-queue retries: the server's
    `retry_after` hint spread by ±25% and capped. Every client waiting
    out the same hint sleeping EXACTLY retry_after would re-submit in
    one synchronized thundering herd the instant a restarted replica
    comes back — the jitter de-correlates the storm. Bounds are pinned
    by test: 0 <= delay <= cap, and within [0.75, 1.25] * hint when the
    hint is under the cap."""
    base = min(max(float(retry_after), 0.0), cap)
    r = (rng or random).random()
    return min(base * (0.75 + 0.5 * r), cap)


class ServeError(Exception):
    """Typed error response from the server."""

    def __init__(self, code: str, message: str, response: dict):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.response = response


class QueueFull(ServeError):
    def __init__(self, code, message, response):
        super().__init__(code, message, response)
        self.retry_after = float(response.get("retry_after", 1.0))


class ServerDraining(ServeError):
    pass


class TenantQuota(QueueFull):
    """Per-tenant admission quota hit; carries `retry_after` like a
    full-queue reject (and subclasses QueueFull, so `retries=` backoff
    in submit() covers it too)."""

    def __init__(self, code, message, response):
        super().__init__(code, message, response)
        self.tenant = response.get("tenant", "")


class JobFailed(ServeError):
    def __init__(self, code, message, response):
        super().__init__(code, message, response)
        self.error_type = response.get("error_type", "RaconError")


class JobCancelled(ServeError):
    """The job was cancelled before it finished — by an explicit
    `cancel` RPC or by this client's own `cancel_on_timeout`."""


class DeadlineDoomed(ServeError):
    """The server aborted speculatively: the predicted finish exceeds
    the job's deadline by more than the server's abort margin (at
    admission or mid-run)."""

    def __init__(self, code, message, response):
        super().__init__(code, message, response)
        self.predicted_s = float(response.get("predicted_s", 0.0))
        self.remaining_s = float(response.get("remaining_s", 0.0))


_ERROR_TYPES = {"queue-full": QueueFull, "draining": ServerDraining,
                "tenant-quota": TenantQuota, "job-failed": JobFailed,
                "cancelled": JobCancelled,
                "deadline-doomed": DeadlineDoomed}


class PolishResult:
    __slots__ = ("job_id", "fasta", "metrics", "serve", "trace",
                 "trace_base_mono", "trace_replicas", "streamed",
                 "parts", "router", "rounds")

    def __init__(self, resp: dict):
        self.job_id = resp.get("job_id")
        #: whether the FASTA arrived as streamed result_part frames
        #: (then the final frame carries stats only and `fasta` below
        #: is the parts' concatenation — byte-identical to the
        #: non-streamed body, test-pinned)
        self.streamed = bool(resp.get("streamed"))
        self.parts = resp.get("parts", 0)
        if self.streamed:
            self.fasta = b"".join(
                p.get("fasta", "").encode("latin-1")
                for p in resp.get("_parts") or [])
        else:
            self.fasta = resp.get("fasta", "").encode("latin-1")
        self.metrics = resp.get("metrics") or {}
        self.serve = resp.get("serve") or {}
        #: fan-out accounting when the job went through a shard-aware
        #: router (shards / requeues / parts / wall_s); {} for a direct
        #: replica submit
        self.router = resp.get("router") or {}
        #: per-round accounting when the submit asked for rounds=N
        #: (requested / completed / per_round walls + cache hit
        #: totals); {} on a plain single-pass job
        self.rounds = resp.get("rounds") or {}
        self.trace = resp.get("trace")
        #: the server-side recorder's time zero in SERVER perf_counter
        #: terms — merge_trace() needs it to rebase server spans
        self.trace_base_mono = resp.get("trace_base_mono")
        #: routed trace collection (router._attach_trace): one entry
        #: per participating replica — {replica, events, base_mono,
        #: offset_s (replica clock relative to the ROUTER), rtt_s};
        #: None for direct submits and untraced routed jobs
        self.trace_replicas = resp.get("trace_replicas")


class PolishClient:
    def __init__(self, socket_path: str | None = None,
                 port: int | None = None, timeout: float | None = None):
        self.socket_path = (socket_path
                            or os.environ.get("RACON_TPU_SERVE_SOCKET")
                            or DEFAULT_SOCKET)
        self.port = port
        self.timeout = timeout

    def _connect(self) -> socket.socket:
        if self.port:
            sock = socket.create_connection(("127.0.0.1", self.port),
                                            timeout=self.timeout)
        else:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self.timeout)
            sock.connect(self.socket_path)
        return sock

    def request(self, obj: dict, on_progress=None, on_part=None,
                recorder=None) -> dict:
        """One round trip; raises the ServeError taxonomy on a typed
        error response. Interleaved `progress` frames (a `submit` with
        "progress": true) are handed to `on_progress` as they arrive,
        and streamed `result_part` frames (a `submit` with "stream":
        true) to `on_part`; the method returns on the first frame that
        is neither, with the collected parts attached as `_parts` so
        PolishResult can assemble the full FASTA. `recorder` (an
        obs.trace.TraceRecorder) captures client-side spans — connect /
        submit / wait / receive plus `client.progress` /
        `client.result_part` instants per interleaved frame — passed
        PER CALL so one client may serve concurrent threads without a
        traced request absorbing an unrelated request's spans."""
        rec = recorder
        t0 = time.perf_counter()
        sock = self._connect()
        if rec is not None:
            rec.complete("client.connect", t0, time.perf_counter())
        frames = 0
        parts: list[dict] = []
        try:
            t_send = time.perf_counter()
            send_frame(sock, obj)
            t_wait = time.perf_counter()
            if rec is not None:
                rec.complete("client.submit", t_send, t_wait,
                             {"type": obj.get("type")})
            while True:
                # results come from a trusted server: accept up to the
                # wire limit, not the server's anti-abuse request
                # ceiling — a multi-hundred-MiB polished assembly must
                # come back whole
                resp = recv_frame(sock, max_frame=WIRE_LIMIT)
                # stamped AFTER the recv: the blocking time (server
                # compute + transfer) belongs to client.wait — stamping
                # before would charge a whole no-progress polish to
                # client.receive and ~0 to wait
                t_frame = time.perf_counter()
                rtype = resp.get("type") if resp is not None else None
                if rtype == "result_part":
                    parts.append(resp)
                    if rec is not None:
                        rec.instant("client.result_part",
                                    {k: resp[k] for k in
                                     ("part", "name", "job_id")
                                     if k in resp})
                    if on_part is not None:
                        on_part(resp)
                    continue
                if rtype != "progress":
                    break
                frames += 1
                if rec is not None:
                    rec.instant("client.progress",
                                {k: resp[k] for k in
                                 ("phase", "done", "total", "position",
                                  "job_id") if k in resp})
                if on_progress is not None:
                    on_progress(resp)
            if rec is not None:
                now = time.perf_counter()
                rec.complete("client.wait", t_wait, t_frame,
                             {"progress_frames": frames,
                              "result_parts": len(parts)})
                rec.complete("client.receive", t_frame, now)
        finally:
            sock.close()
        if resp is None:
            raise ServeError("closed", "server closed the connection",
                             {})
        if resp.get("type") == "error":
            code = resp.get("code", "error")
            raise _ERROR_TYPES.get(code, ServeError)(
                code, resp.get("message", ""), resp)
        if parts:
            resp["_parts"] = parts
        return resp

    def clock_sync(self, samples: int = 3) -> dict:
        """Estimate the server's perf_counter offset from RTT-bracketed
        pings: for each sample, offset = server_mono - client RTT
        midpoint; the minimum-RTT sample wins (least queueing noise).
        Returns {"offset_s", "rtt_s"} — merge_trace() uses the offset
        to put server spans on the client timeline, good to ~rtt/2."""
        best = None
        for _ in range(max(1, samples)):
            t0 = time.perf_counter()
            pong = self.request({"type": "ping"})
            t1 = time.perf_counter()
            mono = pong.get("mono_s")
            if mono is None:
                raise ServeError(
                    "bad-response",
                    "server ping carries no mono_s clock sample "
                    "(pre-tracing server?)", pong)
            cand = {"offset_s": float(mono) - (t0 + t1) / 2.0,
                    "rtt_s": t1 - t0}
            if best is None or cand["rtt_s"] < best["rtt_s"]:
                best = cand
        return best

    # ------------------------------------------------------------ calls
    def submit(self, sequences: str, overlaps: str, target: str, *,
               options: dict | None = None, priority: int = 0,
               deadline_s: float | None = None,
               fault_plan: str | None = None, strict: bool | None = None,
               trace: bool = False, trace_id: str | None = None,
               tenant: str | None = None, rounds: int | None = None,
               fragment: bool = False,
               frag_lo: int | None = None, frag_hi: int | None = None,
               ingest: bool = False, subsample: dict | None = None,
               normalize: bool = False,
               on_progress=None, on_part=None, stream: bool = False,
               recorder=None, retries: int = 0,
               cancel_on_timeout: bool = False) -> PolishResult:
        """Polish one input triple on the server. Paths are resolved to
        absolute before they cross the wire (the server's cwd is not the
        client's). `retries` re-submits after `retry_after` on full-queue
        rejects — simple client-side backoff. `on_progress` (callable
        taking each progress frame dict) turns on the server's live
        progress stream; `on_part` (callable taking each `result_part`
        frame dict) or `stream=True` turns on per-contig streamed
        results — finished contigs arrive BEFORE the final frame, and
        `PolishResult.fasta` is their byte-identical concatenation.
        `tenant` names the fair-scheduling bucket this job bills to
        (queue.py weighted DRR); `trace_id` stamps the job's
        server-side spans, journal lines and interleaved frames with a
        client-chosen correlation id. `rounds=N` runs N serve-native
        polishing rounds — the server feeds round k's stitched contigs
        back as round k+1's draft without leaving the warm process —
        and `PolishResult.rounds` carries the per-round accounting.
        `cancel_on_timeout=True` (needs a client `timeout`) frees the
        server side when this client gives up: a socket timeout while
        the job is queued or running sends a `cancel` for the job's
        trace id on a FRESH connection — without it the abandoned job
        keeps its queue and quota slots until the worker pops it —
        then raises `JobCancelled`; the full-queue retry loop likewise
        stops retrying once the elapsed wall time would exceed the
        timeout budget."""
        if cancel_on_timeout and not trace_id:
            # the cancel RPC needs a handle the client knows BEFORE
            # the result frame arrives: mint the correlation id
            trace_id = uuid.uuid4().hex[:16]
        req = {"type": "submit",
               "sequences": os.path.abspath(sequences),
               "overlaps": os.path.abspath(overlaps),
               "target": os.path.abspath(target)}
        if options:
            req["options"] = options
        if priority:
            req["priority"] = int(priority)
        if deadline_s is not None:
            req["deadline_s"] = float(deadline_s)
        if fault_plan:
            req["fault_plan"] = fault_plan
        if strict is not None:
            req["strict"] = bool(strict)
        if trace:
            req["trace"] = True
        if trace_id:
            req["trace_id"] = str(trace_id)
        if tenant:
            req["tenant"] = str(tenant)
        if rounds is not None:
            req["rounds"] = int(rounds)
        if fragment:
            # fragment traffic class (`mode: "fragment"`): corrected
            # reads instead of polished contigs — PolisherType.kF with
            # bounded-group result_part streaming (protocol.py
            # "Fragment jobs")
            req["mode"] = "fragment"
        if frag_lo is not None:
            req["frag_lo"] = int(frag_lo)
        if frag_hi is not None:
            req["frag_hi"] = int(frag_hi)
        # admit-time ingest plane (serve/ingest.py): validate-only,
        # subsample-on-admit, paired-end normalization
        if ingest:
            req["ingest"] = True
        if subsample is not None:
            req["subsample"] = dict(subsample)
        if normalize:
            req["normalize"] = True
        if on_progress is not None:
            req["progress"] = True
        if stream or on_part is not None:
            req["stream"] = True
        attempt = 0
        t_first = time.perf_counter()
        while True:
            try:
                return PolishResult(
                    self.request(req, on_progress=on_progress,
                                 on_part=on_part, recorder=recorder))
            except QueueFull as exc:
                if attempt >= retries:
                    raise
                delay = _retry_delay(exc.retry_after)
                if self.timeout is not None and \
                        (time.perf_counter() - t_first + delay
                         > self.timeout):
                    # the client-side budget is spent: stop the backoff
                    # loop instead of overshooting it (the reject means
                    # the server holds NO state for this job — there is
                    # nothing to cancel)
                    raise
                attempt += 1
                time.sleep(delay)
            except TimeoutError:
                # the socket timed out with the job possibly queued or
                # running server-side: without a cancel it keeps its
                # queue and quota slots until the worker pops it
                if not cancel_on_timeout:
                    raise
                try:
                    self.cancel(trace_id=trace_id)
                except (ServeError, OSError):
                    pass  # best-effort: the job may have just finished
                raise JobCancelled(
                    "cancelled",
                    f"client timeout after {self.timeout}s: sent "
                    f"cancel for trace {trace_id}",
                    {"trace_id": trace_id}) from None

    def submit_traced(self, sequences: str, overlaps: str, target: str,
                      *, trace_out: str | None = None, on_progress=None,
                      **kw) -> tuple[PolishResult, dict]:
        """One end-to-end traced submit: mints a trace_id (unless `kw`
        carries one), handshakes the server clock offset, records
        client-side spans, requests the server-side per-job trace, and
        merges both into a single Chrome-trace JSON (written to
        `trace_out` when given). Returns (result, merged_doc)."""
        from ..obs.trace import TraceRecorder

        kw.pop("trace", None)
        trace_id = kw.pop("trace_id", None) or uuid.uuid4().hex[:16]
        clock = self.clock_sync()
        rec = TraceRecorder(None)
        result = self.submit(sequences, overlaps, target,
                             trace=True, trace_id=trace_id,
                             on_progress=on_progress, recorder=rec,
                             **kw)
        doc = merge_trace(result, rec, clock, trace_id=trace_id)
        if trace_out:
            with open(trace_out, "w") as fh:
                json.dump(doc, fh)
        return result, doc

    def cancel(self, job_id: str | None = None,
               trace_id: str | None = None) -> dict:
        """Cancel a queued or running job by id and/or trace id, on a
        FRESH connection (so it works while the submitting connection
        is blocked waiting for the result). Queued jobs are dequeued —
        their waiting submitter receives a typed `cancelled` error;
        running jobs are withdrawn at the next iteration/round
        boundary. Returns the server's ok body ({"cancelled":
        "queued"|"running", "job_id"}); raises ServeError code
        `unknown-job` when nothing matches (e.g. the job already
        finished)."""
        req: dict = {"type": "cancel"}
        if job_id:
            req["job_id"] = job_id
        if trace_id:
            req["trace_id"] = trace_id
        return self.request(req)

    def ping(self) -> dict:
        return self.request({"type": "ping"})

    def stats(self) -> dict:
        return self.request({"type": "stats"})

    def healthz(self) -> dict:
        """The replica health body ({ok, draining, queue_depth, ...})
        — `ok` false once the server started draining, mirroring the
        HTTP endpoint's 503."""
        return self.request({"type": "healthz"})

    def scrape(self) -> str:
        """Live Prometheus text exposition (the same body the optional
        `--metrics-port` HTTP endpoint serves) — counters, gauges and
        latency histograms, refreshed at call time."""
        return self.request({"type": "scrape"})["text"]

    def debug(self, max_events: int = 5000) -> dict:
        """The flight recorder's recent events plus the automatic dump
        artifacts written so far — the live post-mortem view. On a
        server with the identity-audit sentinel armed, the response
        additionally carries the `audit` counters snapshot."""
        return self.request({"type": "debug", "max_events": max_events})

    def audit_ack(self) -> dict:
        """Operator acknowledgement of the identity-audit alert: clears
        the racon_tpu_audit_alert gauge (and journals the typed clear)
        until the NEXT mismatch. Returns the server's post-ack audit
        snapshot."""
        return self.request({"type": "debug", "audit_ack": True,
                             "max_events": 0})

    def shutdown(self) -> dict:
        return self.request({"type": "shutdown"})


def merge_trace(result: PolishResult, client_rec, clock: dict,
                trace_id: str | None = None) -> dict:
    """Merge the server's per-job trace (`result.trace`, timestamps in
    the SERVER recorder's timeline) with the client recorder's events
    into one Chrome-trace document on the client clock: client spans on
    pid 1, server spans on pid 2, both labeled via process_name
    metadata. A server event at ts (µs past `result.trace_base_mono`)
    lands at server_mono - offset on the client's perf_counter, then
    rebases onto the client recorder's zero. Accuracy is the handshake's
    ±rtt/2 — microseconds on localhost, which is what the transports
    here are.

    Routed jobs extend the same construction fleet-wide: pid 2 is the
    ROUTER (its plan/dispatch/stream/merge spans), and every entry the
    router pulled into `result.trace_replicas` becomes its own process
    track on pid 3+. A replica event's clock chains TWO handshakes —
    replica→router (`offset_s`, measured by the router) and
    router→client (`clock`) — so all tracks land on the client
    timeline and the per-hop rtt brackets simply add. `trace_context`
    carries the per-replica clock metadata plus a `stats` snapshot
    (serve / router / rounds blocks), which is what
    tools/tracereport.py checks span sums against."""
    from ..obs.trace import rebase_events

    events = rebase_events(client_rec.events(), pid=1,
                           name="racon_tpu client")
    routed = bool(result.router)
    if result.trace and result.trace_base_mono is not None:
        shift_us = ((result.trace_base_mono - clock["offset_s"])
                    - client_rec._base) * 1e6
        events += rebase_events(
            result.trace, pid=2, shift_us=shift_us,
            name="racon_tpu router" if routed else "racon_tpu server")
    ctx_replicas = []
    for i, rep in enumerate(result.trace_replicas or []):
        base = rep.get("base_mono")
        if base is None:
            continue
        off = float(rep.get("offset_s") or 0.0)
        # replica mono -> router mono (-off) -> client mono (-clock
        # offset), then onto the client recorder's zero
        shift_us = ((base - off - clock["offset_s"])
                    - client_rec._base) * 1e6
        events += rebase_events(
            rep.get("events") or [], pid=3 + i, shift_us=shift_us,
            name=f"racon_tpu replica {rep.get('replica')}")
        ctx_replicas.append({"replica": rep.get("replica"),
                             "offset_s": rep.get("offset_s"),
                             "rtt_s": rep.get("rtt_s")})
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    ctx = {"trace_id": trace_id,
           "job_id": result.job_id,
           "clock_offset_s": round(clock["offset_s"], 6),
           "clock_rtt_s": round(clock["rtt_s"], 6)}
    if ctx_replicas:
        ctx["replicas"] = ctx_replicas
    stats: dict = {}
    if result.serve:
        stats["serve"] = result.serve
    if result.router:
        stats["router"] = result.router
    if result.rounds:
        stats["rounds"] = result.rounds
    if stats:
        ctx["stats"] = stats
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "trace_context": ctx}


class _ProgressPrinter:
    """stderr renderer for `submit --progress`: a \\r-redrawn status
    line on a tty, one line per phase transition when stderr is a pipe
    (so logs stay readable, mirroring the Logger bar discipline)."""

    def __init__(self):
        self._last_phase = None
        self._tty = sys.stderr.isatty()

    def __call__(self, ev: dict) -> None:
        phase = ev.get("phase", "?")
        if phase == "queued":
            text = (f"queued at position {ev.get('position', '?')} "
                    f"(depth {ev.get('depth', '?')})")
        elif ev.get("total"):
            unit = (" windows" if phase in ("consensus", "stitch")
                    else "")  # align counts overlap pairs
            text = f"{phase} {ev.get('done', 0)}/{ev['total']}{unit}"
        else:
            text = phase
        if self._tty:
            sys.stderr.write(f"\r[racon_tpu::submit] {text:<56}")
            sys.stderr.flush()
        elif phase != self._last_phase:
            print(f"[racon_tpu::submit] {text}", file=sys.stderr)
        self._last_phase = phase

    def close(self) -> None:
        if self._tty and self._last_phase is not None:
            sys.stderr.write("\n")
            sys.stderr.flush()


# ------------------------------------------------------------------ CLI
def submit_main(argv: list[str]) -> int:
    """`racon_tpu submit` entry point: send one job to a running server,
    polished FASTA on stdout (byte-identical to the one-shot CLI)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="racon_tpu submit",
        description="submit a polishing job to a running "
                    "`racon_tpu serve` instance")
    ap.add_argument("sequences")
    ap.add_argument("overlaps")
    ap.add_argument("target")
    ap.add_argument("--socket", default=None)
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--timeout", type=float, default=None,
                    help="socket timeout in seconds (default: none)")
    ap.add_argument("--priority", type=int, default=0)
    ap.add_argument("--deadline", type=float, default=None,
                    help="job deadline in seconds: a job not STARTED in "
                         "time is cancelled in queue (deadline-expired "
                         "error); one that runs but FINISHES late still "
                         "returns its result, counted as an SLO "
                         "deadline miss (server stats `slo` view + "
                         "flight-recorder dump)")
    ap.add_argument("--retries", type=int, default=0,
                    help="re-submit after retry_after on queue-full")
    ap.add_argument("--cancel-on-timeout", action="store_true",
                    help="with --timeout: when the client socket times "
                         "out, send a cancel for this job on a fresh "
                         "connection so it frees its queue/quota slot "
                         "(and its device time if running) instead of "
                         "lingering server-side until popped")
    ap.add_argument("--progress", action="store_true",
                    help="stream live progress to stderr while the job "
                         "runs: queue position while pending, then "
                         "phase / windows-done / total as the server "
                         "interleaves progress frames before the "
                         "result")
    ap.add_argument("--stream", action="store_true",
                    help="stream polished contigs to stdout AS THEY "
                         "FINISH (`result_part` frames): each contig's "
                         "FASTA is written the moment its windows "
                         "complete on the server, the final frame "
                         "carries only the stats — the concatenated "
                         "stream is byte-identical to the buffered "
                         "output. CAVEAT: a job that fails mid-stream "
                         "leaves the already-streamed contigs on "
                         "stdout (well-formed but partial); consumers "
                         "MUST check the exit status, which is "
                         "nonzero on any failure")
    ap.add_argument("--rounds", type=int, default=None,
                    help="serve-native polishing rounds: the server "
                         "feeds round k's stitched contigs back as "
                         "round k+1's draft without leaving the warm "
                         "process (in-process re-overlap, no external "
                         "mapper); the result carries per-round wall "
                         "clocks and window-cache hit counts")
    ap.add_argument("--tenant", default=None,
                    help="fair-scheduling tenant id this job bills to "
                         "(1-64 chars of [A-Za-z0-9._-]; server "
                         "weights via RACON_TPU_SERVE_TENANT_WEIGHTS)")
    ap.add_argument("--trace-id", default=None,
                    help="name this job with a caller-chosen trace id "
                         "so another terminal can `racon_tpu cancel "
                         "--trace-id ID` it while this submit blocks "
                         "(also the correlation key in the journal "
                         "and flight-recorder artifacts)")
    ap.add_argument("--trace-out", metavar="PATH", default=None,
                    help="end-to-end trace: record client-side spans, "
                         "fetch the job's server-side spans, and write "
                         "ONE merged Chrome-trace JSON (open in "
                         "Perfetto) with both sides on a handshake-"
                         "aligned timeline")
    ap.add_argument("-u", "--include-unpolished", action="store_true")
    ap.add_argument("-f", "--fragment-correction", action="store_true",
                    help="fragment (read) error correction instead of "
                         "contig polishing: submits the job with "
                         "mode \"fragment\" — corrected reads stream "
                         "in bounded groups, byte-identical to the "
                         "one-shot CLI's -f output")
    ap.add_argument("--ingest", action="store_true",
                    help="admit-time validation: the server streaming-"
                         "parses all three inputs before queueing, so "
                         "a malformed file fails typed at the door")
    ap.add_argument("--subsample", nargs=2, type=int, default=None,
                    metavar=("REF_LEN", "COV"),
                    help="subsample-on-admit: the server subsamples "
                         "the reads to ~REF_LEN*COV bases (seeded "
                         "rampler.subsample) before polishing")
    ap.add_argument("--subsample-seed", type=int, default=None,
                    help="explicit subsample shuffle seed (default: "
                         "the server's RACON_TPU_SUBSAMPLE_SEED, else "
                         "the fixed default)")
    ap.add_argument("--normalize", action="store_true",
                    help="paired-end header normalization on admit "
                         "(racon_tpu preprocess equivalent)")
    ap.add_argument("-w", "--window-length", type=int, default=None)
    ap.add_argument("-q", "--quality-threshold", type=float, default=None)
    ap.add_argument("-e", "--error-threshold", type=float, default=None)
    ap.add_argument("--no-trimming", action="store_true")
    ap.add_argument("-m", "--match", type=int, default=None)
    ap.add_argument("-x", "--mismatch", type=int, default=None)
    ap.add_argument("-g", "--gap", type=int, default=None)
    ap.add_argument("-c", "--tpupoa-batches", type=int, default=None)
    ap.add_argument("--tpualigner-batches", type=int, default=None)
    ap.add_argument("--tpu-engine", choices=("session", "fused"),
                    default=None)
    args = ap.parse_args(argv)

    options: dict = {}
    for key, val in (("include_unpolished", args.include_unpolished
                      or None),
                     ("window_length", args.window_length),
                     ("quality_threshold", args.quality_threshold),
                     ("error_threshold", args.error_threshold),
                     ("trim", False if args.no_trimming else None),
                     ("match", args.match),
                     ("mismatch", args.mismatch),
                     ("gap", args.gap),
                     ("tpu_poa_batches", args.tpupoa_batches),
                     ("tpu_aligner_batches", args.tpualigner_batches),
                     ("tpu_engine", args.tpu_engine)):
        if val is not None:
            options[key] = val

    client = PolishClient(socket_path=args.socket, port=args.port,
                          timeout=args.timeout)
    on_progress = _ProgressPrinter() if args.progress else None
    on_part = None
    if args.stream:
        # parts hit stdout the moment they arrive — time-to-first-byte
        # is the first finished contig, not the whole job
        def on_part(frame):
            sys.stdout.buffer.write(
                frame.get("fasta", "").encode("latin-1"))
            sys.stdout.buffer.flush()
    subsample = None
    if args.subsample is not None:
        subsample = {"reference_length": args.subsample[0],
                     "coverage": args.subsample[1]}
        if args.subsample_seed is not None:
            subsample["seed"] = args.subsample_seed
    common = dict(options=options, priority=args.priority,
                  deadline_s=args.deadline, retries=args.retries,
                  tenant=args.tenant, rounds=args.rounds,
                  trace_id=args.trace_id,
                  fragment=args.fragment_correction,
                  ingest=args.ingest, subsample=subsample,
                  normalize=args.normalize,
                  on_progress=on_progress, on_part=on_part,
                  cancel_on_timeout=args.cancel_on_timeout)
    trace_doc = None
    try:
        if args.trace_out:
            # trace_out deliberately NOT passed through: the artifact
            # is written below, AFTER the polished bytes reach stdout —
            # an unwritable trace path must not discard a completed
            # polish (same posture as the metrics/trace flush in
            # emit_observability)
            result, trace_doc = client.submit_traced(
                args.sequences, args.overlaps, args.target, **common)
        else:
            result = client.submit(args.sequences, args.overlaps,
                                   args.target, **common)
    except (ServeError, OSError) as exc:
        if on_progress is not None:
            on_progress.close()
        print(f"[racon_tpu::serve] error: {exc}", file=sys.stderr)
        return 1
    if on_progress is not None:
        on_progress.close()
    if not result.streamed:
        # the body was NOT streamed (or the server ignored the stream
        # request): write it now — `--stream` against a non-streaming
        # server must still produce the FASTA, never empty stdout
        sys.stdout.buffer.write(result.fasta)
        sys.stdout.buffer.flush()
    serve = result.serve
    if serve:
        print(f"[racon_tpu::serve] job {result.job_id}: queue wait "
              f"{serve.get('queue_wait_s', 0):.3f}s, exec "
              f"{serve.get('exec_s', 0):.3f}s", file=sys.stderr)
    if result.rounds:
        walls = ", ".join(f"r{r['round']}={r['wall_s']:.3f}s"
                          for r in result.rounds.get("per_round", []))
        cache = result.rounds.get("cache")
        tail = (f", cache hits {cache['hits']}/{cache['hits'] + cache['misses']}"
                if cache else "")
        print(f"[racon_tpu::serve] rounds "
              f"{result.rounds.get('completed')}/"
              f"{result.rounds.get('requested')}: {walls}{tail}",
              file=sys.stderr)
    if trace_doc is not None:
        try:
            with open(args.trace_out, "w") as fh:
                json.dump(trace_doc, fh)
            print(f"[racon_tpu::serve] merged client+server trace "
                  f"written to {args.trace_out} (open in "
                  "https://ui.perfetto.dev)", file=sys.stderr)
        except OSError as exc:
            print(f"[racon_tpu::serve] warning: could not write trace "
                  f"to {args.trace_out} ({exc}); polished FASTA is "
                  "unaffected", file=sys.stderr)
    return 0


def cancel_main(argv: list[str]) -> int:
    """`racon_tpu cancel` entry point: cancel a queued or running job
    on a live server (or through the router, which fans the cancel out
    to the job's shards) by job id or trace id."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="racon_tpu cancel",
        description="cancel a queued or running job on a running "
                    "`racon_tpu serve` instance (or through the "
                    "router) by --job-id or --trace-id")
    ap.add_argument("--socket", default=None)
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--timeout", type=float, default=None,
                    help="socket timeout in seconds (default: none)")
    ap.add_argument("--job-id", default=None)
    ap.add_argument("--trace-id", default=None,
                    help="the id passed to `submit --trace-id` (or "
                         "minted by --cancel-on-timeout)")
    args = ap.parse_args(argv)
    if not args.job_id and not args.trace_id:
        print("[racon_tpu::serve] error: cancel needs --job-id or "
              "--trace-id", file=sys.stderr)
        return 1
    client = PolishClient(socket_path=args.socket, port=args.port,
                          timeout=args.timeout)
    try:
        body = client.cancel(job_id=args.job_id,
                             trace_id=args.trace_id)
    except (ServeError, OSError) as exc:
        print(f"[racon_tpu::serve] error: {exc}", file=sys.stderr)
        return 1
    extra = (f", {body['shards_cancelled']} shard(s) cancelled"
             if "shards_cancelled" in body else "")
    print(f"[racon_tpu::serve] cancelled {body.get('cancelled')} job "
          f"{body.get('job_id', args.trace_id)}{extra}",
          file=sys.stderr)
    return 0
