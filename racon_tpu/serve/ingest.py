"""Admit-time streaming ingest plane.

The reference's L7 wrapper owns ingest: `racon_wrapper` subsamples reads
with rampler and `racon_preprocess` uniquifies paired-end headers BEFORE
racon ever sees a file. This module promotes that role into the server,
so clients ship raw (optionally gzipped) FASTA/FASTQ/SAM files and opt
in per job on the submit frame:

    ingest: true                  validate-only — streaming-parse all
                                  three inputs on admit so a malformed
                                  file fails the job typed at the door
                                  instead of mid-polish
    subsample: {reference_length: int, coverage: int[, seed: int]}
                                  subsample-on-admit via the seeded
                                  `rampler.subsample` (deterministic:
                                  explicit seed, else
                                  RACON_TPU_SUBSAMPLE_SEED, else the
                                  fixed default)
    normalize: true               paired-end header uniquification via
                                  `preprocess.process` (mate 1 -> "1",
                                  mate 2 -> "2" suffixes)

Any opt-in implies validation. All parsing is STREAMING — bounded
chunks through the framework parsers (gzip sniffed from magic bytes),
never a whole-file slurp — so a multi-GiB read set costs O(chunk)
admit-time memory. Failures raise `IngestError` (typed with the failing
stage); the server maps that to a `bad-request` response plus a
`rejected-ingest` journal terminal. Jobs that opt in get `ingested` /
`normalized` / `subsampled` journal annotations; jobs that don't never
touch this module, keeping the flagless serve surface byte-identical.

Rewritten inputs (subsample output, normalized reads) land in the
server-lifetime ingest workdir (PolishServer._ingest_workdir), named by
job id so concurrent admits never collide.
"""

from __future__ import annotations

import os

from ..errors import RaconError
from ..io.parsers import create_overlap_parser, create_sequence_parser

#: per-parse byte budget: the admit-time memory bound. Matches the
#: polisher's own streaming chunk scale — large enough to amortize the
#: generator overhead, small enough that admission never balloons.
CHUNK_BYTES = 4 << 20


class IngestError(RaconError):
    """A typed admit-time ingest failure. `stage` names the phase that
    failed — "spec" (malformed opt-in keys), "validate" (parse error in
    an input file), "normalize", or "subsample" — and rides the
    `rejected-ingest` journal line as `error`."""

    def __init__(self, stage: str, message: str):
        self.stage = stage
        super().__init__(f"serve.ingest.{stage}", message)


class IngestSpec:
    """Validated ingest opt-in parsed from a submit frame."""

    __slots__ = ("subsample", "normalize")

    def __init__(self, subsample: dict | None = None,
                 normalize: bool = False):
        self.subsample = subsample
        self.normalize = normalize

    @classmethod
    def from_request(cls, req: dict) -> "IngestSpec":
        """Parse and validate the `ingest` / `subsample` / `normalize`
        submit-frame keys. Raises IngestError("spec") on any malformed
        shape — the server maps that to `bad-request` BEFORE a job id
        is minted."""
        ing = req.get("ingest")
        if ing is not None and not isinstance(ing, bool):
            raise IngestError("spec", "ingest must be a boolean")
        norm = req.get("normalize")
        if norm is not None and not isinstance(norm, bool):
            raise IngestError("spec", "normalize must be a boolean")
        sub = req.get("subsample")
        if sub is not None:
            if not isinstance(sub, dict):
                raise IngestError(
                    "spec",
                    "subsample must be an object like "
                    "{reference_length, coverage}")
            unknown = set(sub) - {"reference_length", "coverage", "seed"}
            if unknown:
                raise IngestError(
                    "spec",
                    "unknown subsample key(s): "
                    f"{', '.join(sorted(unknown))}")
            for key in ("reference_length", "coverage"):
                val = sub.get(key)
                if isinstance(val, bool) or not isinstance(val, int) \
                        or val <= 0:
                    raise IngestError(
                        "spec",
                        f"subsample.{key} must be a positive integer")
            seed = sub.get("seed")
            if seed is not None and (isinstance(seed, bool)
                                     or not isinstance(seed, int)):
                raise IngestError(
                    "spec", "subsample.seed must be an integer")
        return cls(subsample=dict(sub) if sub else None,
                   normalize=bool(norm))


def _count_sequences(path: str) -> tuple[int, int]:
    """Streaming-validate one sequence file; returns (records, bytes).
    Bounded memory: each CHUNK_BYTES batch of records is discarded
    before the next is parsed."""
    try:
        parser = create_sequence_parser(path, "serve.ingest")
        records = 0
        nbytes = 0
        more = True
        while more:
            chunk: list = []
            more = parser.parse(chunk, CHUNK_BYTES)
            records += len(chunk)
            nbytes += sum(len(s.data) for s in chunk)
    except RaconError as exc:
        raise IngestError("validate", str(exc)) from None
    if records == 0:
        raise IngestError("validate", f"empty sequence file {path}!")
    return records, nbytes


def _count_overlaps(path: str) -> int:
    """Streaming-validate one overlap file; returns the record count."""
    try:
        parser = create_overlap_parser(path, "serve.ingest")
        records = 0
        more = True
        while more:
            chunk: list = []
            more = parser.parse(chunk, CHUNK_BYTES)
            records += len(chunk)
    except RaconError as exc:
        raise IngestError("validate", str(exc)) from None
    if records == 0:
        raise IngestError("validate", f"empty overlap file {path}!")
    return records


def prepare(sequences: str, overlaps: str, target: str,
            spec: IngestSpec, workdir: str, job_id: str,
            trace_id: str | None = None,
            journal=None) -> tuple[str, str, str]:
    """Run the admit-time ingest pipeline for one job: validate all
    three inputs (always), then optionally pair-normalize and/or
    subsample the reads. Returns the (sequences, overlaps, target)
    paths the job should actually polish — rewritten files live in
    `workdir`, untouched stages pass the original paths through."""
    n_reads, read_bytes = _count_sequences(sequences)
    n_targets, _ = _count_sequences(target)
    n_overlaps = _count_overlaps(overlaps)
    if journal is not None:
        journal.record("ingested", job=job_id, trace=trace_id,
                       reads=n_reads, read_bytes=read_bytes,
                       targets=n_targets, overlaps=n_overlaps)

    if spec.normalize:
        # paired-end header uniquification (preprocess.process): output
        # is FASTQ by construction (dummy qualities for FASTA input)
        from .. import preprocess

        norm_path = os.path.join(workdir, f"{job_id}_norm.fastq")
        try:
            with open(norm_path, "wb") as fh:
                preprocess.process([sequences], out=fh)
        except RaconError as exc:
            raise IngestError("normalize", str(exc)) from None
        sequences = norm_path
        if journal is not None:
            journal.record("normalized", job=job_id, trace=trace_id,
                           reads=n_reads)

    if spec.subsample is not None:
        from .. import rampler

        sub = spec.subsample
        subdir = os.path.join(workdir, job_id)
        os.makedirs(subdir, exist_ok=True)
        try:
            sub_path = rampler.subsample(
                sequences, sub["reference_length"], sub["coverage"],
                out_directory=subdir, seed=sub.get("seed"))
        except RaconError as exc:
            raise IngestError("subsample", str(exc)) from None
        reads_out, _ = _count_sequences(sub_path)
        if journal is not None:
            journal.record("subsampled", job=job_id, trace=trace_id,
                           reads_in=n_reads, reads_out=reads_out,
                           reference_length=sub["reference_length"],
                           coverage=sub["coverage"],
                           seed=sub.get("seed"))
        sequences = sub_path

    return sequences, overlaps, target
