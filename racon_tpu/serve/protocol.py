"""Length-prefixed JSON frame protocol for the warm polishing service.

One frame = an 8-byte header (4-byte magic ``RTPU`` + 4-byte big-endian
payload length) followed by a UTF-8 JSON object payload. JSON keeps the
wire format debuggable (``socat`` + a hexdump is a full protocol
analyzer) and dependency-free; the length prefix makes framing O(1) and
lets the server bound memory BEFORE reading a payload. Polished FASTA
rides inside the JSON as a latin-1 string — lossless for arbitrary
bytes, so byte-identity survives the wire.

Malformed-input discipline (the server must outlive every bad client):

  - payload longer than ``max_frame``   -> the declared bytes are read
    and DISCARDED in bounded chunks (the stream stays in sync), then
    `FrameTooLarge`; the server answers with a typed error response and
    the connection remains usable.
  - payload that is not valid JSON (or not a JSON object) ->
    `FrameGarbage`; stream is still framed, connection remains usable.
  - bad magic -> `FrameGarbage` with ``resync=False``: the stream can
    no longer be trusted byte-for-byte, so the server answers the typed
    error and then closes THAT connection (the server itself is
    untouched).
  - EOF mid-frame -> `FrameTruncated`; the peer is gone, nothing can be
    answered — the handler cleans up the connection quietly.

Request types: ``submit`` / ``ping`` / ``stats`` / ``healthz`` /
``scrape`` / ``debug`` / ``trace_pull`` / ``cancel`` / ``shutdown``.
Response types: ``result`` /
``pong`` / ``stats`` / ``healthz`` (``ok`` false while draining — the
RPC twin of the HTTP endpoint's 503) / ``metrics`` (Prometheus text in
``text``) / ``debug``
(flight-recorder events + dump paths) / ``trace`` (flight-ring spans
windowed to one trace id) / ``ok`` / ``error`` (with a
machine-readable ``code``; ``queue-full`` errors carry ``retry_after``
seconds, ``job-failed`` errors carry ``error_type`` from the errors.py
taxonomy).

Cancellation & QoS (README "QoS & preemption"): a ``cancel`` request
carries ``job_id`` and/or ``trace_id`` and answers ``{"type": "ok",
"cancelled": "queued"|"running", "job_id"}`` — a queued job is
dequeued (its waiting submitter receives a typed ``cancelled`` error
response through its own connection), a running job is withdrawn at
the next iteration/round boundary and fails typed ``cancelled``; an
unmatched id answers ``error`` code ``unknown-job``. A submit whose
deadline is provably unmeetable (server started with an abort margin)
is refused typed ``deadline-doomed`` with ``predicted_s`` /
``remaining_s``; the same code can arrive mid-run when the
iteration-boundary estimate says the deadline is lost.

Trace context, live progress and streamed results (all opt-in per
submit, README "Serving"): a ``submit`` may carry a client-minted
``trace_id`` (1-64 chars of ``[A-Za-z0-9._-]``) that the server stamps
onto its spans, journal lines and interleaved frames; a ``tenant`` id
(same charset) naming the fair-scheduling bucket the job bills to;
``"progress": true``, which makes the server INTERLEAVE ``progress``
frames on the submitting connection before the final
``result``/``error`` frame — ``{"type": "progress", "job_id", "seq",
"phase", ...}`` with monotonically increasing ``seq``, queue
``position``/``depth`` while pending, then ``done``/``total`` window
counts per phase; and ``"stream": true``, which makes the server send
each polished contig as a ``{"type": "result_part", "job_id", "part",
"name", "fasta"}`` frame the moment its windows complete — the final
``result`` frame then carries ``streamed: true`` + ``parts`` and the
stats WITHOUT the fasta body (the parts' concatenation IS the body,
byte-identical to the buffered path). ``pong`` responses carry
``mono_s`` (the server's ``time.perf_counter``), the clock-handshake
sample clients RTT-bracket to merge client- and server-side spans onto
one timeline.

Iterative rounds (opt-in per submit, README "Iterative rounds & window
cache"): a ``submit`` may carry ``"rounds": N`` (1..64) asking the
server to run N serve-native polishing rounds — round k's stitched
contigs are fed back as round k+1's draft without leaving the warm
process (in-process re-overlap, ``core/remap.py``). The FASTA returned
(or streamed: only the FINAL round streams ``result_part`` frames) is
round N's output, byte-identical to N chained solo runs through
``Polisher.redraft``. The final ``result`` then adds a ``rounds`` block:
``{"requested", "completed", "per_round": [{"round", "wall_s",
"windows", "iterations", "sequences", "cache"?}], "cache": {"hits",
"misses"}?}`` (the ``cache`` entries appear only on servers with the
content-addressed window cache armed). Omitting ``rounds`` keeps the
classic single-pass contract untouched.

Child-job fields (router fan-out, serve/router.py): when a shard-aware
router splits one client submit across replicas, each child ``submit``
carries ``parent`` (the router-side parent job id), ``shard`` /
``shards`` (this child's slot in the contig fan-out), the parent's
``rounds`` field when set (each shard runs its own rounds over its
contig subset) and a derived ``trace_id`` of ``<parent trace>.s<k>`` — the "." is in the trace-id
charset precisely so child ids stay valid. The parent's QoS fields
ride every child frame too: ``priority`` and ``tenant`` verbatim, and
``deadline_s`` as the REMAINING parent budget recomputed at each
dispatch attempt (a requeued shard inherits what is left of the
parent's deadline, never a reset one); a parent-level cancel or
deadline-abort fans ``cancel`` frames out to all sibling shards by
child trace id. Replicas journal the three
fields on the child's ``received`` line for cross-correlation with the
router's ledger and otherwise ignore them, which also means a child
submit sent to a pre-router replica is handled as a plain job (unknown
top-level submit keys are ignored by contract). A router's
``result_part`` frames add a ``shard`` field and renumber ``part``
globally in contig order; its final ``result`` adds a ``router`` block
(``shards`` / ``requeues`` / ``parts`` / ``wall_s``).

Distributed tracing (README "Distributed tracing & cost accounting"):
a ``trace_pull`` request carries ``trace_id`` (trace-id charset; a
parent id matches its dotted ``<trace>.s<k>`` children too) and an
optional ``max_events`` cap (RACON_TPU_TRACE_PULL_EVENTS, default
2048); the ``trace`` response carries ``events`` (the replica's
always-on flight-ring spans windowed to that trace), ``base_mono``
(the ring recorder's time zero in that process's ``perf_counter``
terms, ``null`` when no ring is installed) and a fresh ``mono_s``
sample. Child submits deliberately do NOT carry ``trace: true`` —
replica spans come from the always-on ring via ``trace_pull``, never
from a per-job scoped recorder (which would serialize same-replica
shards). A ROUTED submit with ``trace: true`` answers with ``trace`` /
``trace_base_mono`` holding the ROUTER's own spans (plan / dispatch
with held-for-idle time / stream / merge / requeue / cancel fan-out),
``trace_replicas`` — one ``{replica, events, base_mono, offset_s,
rtt_s}`` entry per participating replica, clock-synced against the
router via the ping ``mono_s`` min-RTT bracket — and a per-shard
``shards_detail`` list inside the ``router`` block (queue_wait_s /
exec_s / batch per shard, the stage-stats side of tracereport's
span-sums consistency check). All three keys appear ONLY on traced
submits; untraced routed frames are byte-identical to the pre-tracing
wire shape.

Window-range child jobs (sub-contig sharding): when routable replicas
outnumber contigs, the router also splits single contigs by target
coordinate at window-grid boundaries. Such a child ``submit`` adds
``range_lo`` / ``range_hi`` (integers, ``0 <= lo < hi``): the replica
polishes only windows whose grid start ``j`` (multiples of
``window_length``) satisfies ``lo <= j < hi``, and streams the contig
*segment*. Range-child ``result_part`` frames differ from whole-contig
parts: ``fasta`` is the raw polished segment (latin-1 bytes, **no**
``>name`` header, no trailing newline — the concatenation-is-the-body
rule does not apply) plus a ``seg`` stats dict ``{"polished",
"windows", "total_windows", "coverage", "lo", "hi"}`` from which the
router reassembles the full contig in coordinate order and re-derives
the solo-identical header tags (LN/RC/XC). ``range_lo``/``range_hi``
cannot be combined with ``rounds`` (typed ``bad-request``). Because a
pre-range replica would silently ignore the keys and return the FULL
contig, the router treats a range part arriving without ``seg`` as a
typed ``replica-incompatible`` failure rather than merging garbage.

Fragment jobs (read error correction, README "Fragment correction &
ingest"): a ``submit`` may carry ``mode: "contig"`` (the default, a
no-op) or ``mode: "fragment"``, which routes the job into the
reference's second workload — ``PolisherType.kF`` read correction
(one-shot CLI ``-f``) — through the same warm-reuse / continuous-
batcher / QoS / audit / journal path contig jobs use. Because targets
are many small reads, a streaming fragment job ships its corrected
reads in BOUNDED GROUPS, never one frame per read: each
``result_part`` frame carries ``{"part", "reads", "frag": [lo, hi),
"fasta"}`` — ``fasta`` is the classic concatenation-is-the-body FASTA
of up to ``frag_group`` (RACON_TPU_FRAG_GROUP, default 64) consecutive
corrected reads, ``reads`` how many survived dropping, and ``frag``
the half-open GLOBAL target-index interval the group accounts for
(dropped reads still advance it, so consecutive frames' intervals
tile). Invalid combinations are typed ``bad-request``: an unknown
``mode`` value, ``mode: "fragment"`` with ``range_lo``/``range_hi``
(fragment jobs shard the read INDEX axis, not a coordinate axis), and
``mode: "fragment"`` with ``rounds > 1`` (corrected reads are not a
draft to re-map onto; ``rounds: 1`` is accepted). A submit WITHOUT a
``mode`` field is byte-identical to the pre-fragment wire contract —
including legacy ``options.fragment_correction`` jobs, which keep
their per-contig streaming shape.

Fragment child jobs (read-range sharding, serve/router.py): the
router's third planner shards a ``mode: "fragment"`` submit across
replicas by TARGET-INDEX slices at read boundaries — every child
shares the parent's original target path (no per-shard file rewrite)
and adds ``frag_lo`` / ``frag_hi`` (integers, ``0 <= lo < hi``,
require ``mode: "fragment"``, reject ``rounds``): the replica corrects
only the reads whose target-file index falls in ``[lo, hi)`` and
rebases its group frames' ``frag`` receipts to the GLOBAL read axis.
Slices are contiguous and ascending, so the router's shard-order merge
IS global read order, and the requeue/dedupe ledger (kill -9 failover,
preemption, tracing all unchanged) operates at read-group granularity
— the ``frag`` receipts across shards tile ``[0, n_reads)``. The
routed ``result`` adds ``fragment: true`` / ``frag_shards`` /
``reads`` to its ``router`` block.

Admit-time ingest (serve/ingest.py, README "Fragment correction &
ingest"): a ``submit`` may opt in with ``ingest: true`` (streaming-
validate all three inputs on admit — gzipped FASTA/FASTQ/SAM parsed in
bounded chunks; a malformed file fails typed ``bad-request`` with a
``rejected-ingest`` journal terminal, never mid-polish and never the
server), ``subsample: {"reference_length": int, "coverage": int,
"seed"?: int}`` (subsample-on-admit through the seeded
``rampler.subsample`` — deterministic, so resubmits and router
children agree byte-for-byte) and/or ``normalize: true`` (paired-end
header uniquification, the ``racon_tpu preprocess`` role). Jobs
without these keys never touch the ingest plane.
"""

from __future__ import annotations

import json
import os
import socket
import struct

MAGIC = b"RTPU"
_HEADER = struct.Struct(">4sI")

#: discard granularity while draining an oversized payload
_DRAIN_CHUNK = 1 << 16


def max_frame_bytes() -> int:
    """The SERVER's receive ceiling (RACON_TPU_SERVE_MAX_FRAME, default
    256 MiB) — it bounds what an untrusted client can make the server
    buffer. Clients reading RESULTS from a trusted server use the wire
    limit instead (PolishClient passes `WIRE_LIMIT`), so a polished
    assembly bigger than the server's request ceiling still comes back."""
    try:
        return int(os.environ.get("RACON_TPU_SERVE_MAX_FRAME", 0)) or \
            (256 << 20)
    except ValueError:
        return 256 << 20


class ProtocolError(Exception):
    """Base for frame-level failures; `code` is the wire error code."""

    code = "bad-frame"
    #: whether the stream is still framed after this error (the server
    #: may answer and keep the connection)
    resync = True

    def __init__(self, message: str, resync: bool | None = None):
        super().__init__(message)
        if resync is not None:
            self.resync = resync


class FrameTooLarge(ProtocolError):
    code = "frame-too-large"


class FrameGarbage(ProtocolError):
    code = "bad-frame"


class FrameTruncated(ProtocolError):
    code = "bad-frame"
    resync = False


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly `n` bytes; b"" on clean EOF at offset 0,
    FrameTruncated on EOF mid-read."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, _DRAIN_CHUNK))
        if not chunk:
            if got == 0:
                return b""
            raise FrameTruncated(
                f"connection closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


#: hard wire limit: the length prefix is a u32
WIRE_LIMIT = 0xFFFFFFFF


def send_frame(sock: socket.socket, obj: dict) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    if len(payload) > WIRE_LIMIT:
        # the u32 length prefix cannot carry it; raise typed (the
        # server handler answers with an error frame) instead of
        # letting struct.error escape mid-send
        raise FrameTooLarge(
            f"frame of {len(payload)} bytes exceeds the 4 GiB wire "
            "limit")
    sock.sendall(_HEADER.pack(MAGIC, len(payload)) + payload)


def recv_frame(sock: socket.socket,
               max_frame: int | None = None) -> dict | None:
    """Read one frame; None on clean EOF (peer closed between frames).
    Raises the ProtocolError taxonomy above on malformed input."""
    limit = max_frame if max_frame is not None else max_frame_bytes()
    header = _recv_exact(sock, _HEADER.size)
    if not header:
        return None
    magic, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameGarbage(
            f"bad frame magic {magic!r} (stream desynced)", resync=False)
    if length > limit:
        # the client DID send these bytes: drain them so the stream
        # stays framed, then report — the connection survives
        left = length
        while left > 0:
            chunk = sock.recv(min(left, _DRAIN_CHUNK))
            if not chunk:
                raise FrameTruncated(
                    "connection closed draining oversized frame")
            left -= len(chunk)
        raise FrameTooLarge(
            f"frame of {length} bytes exceeds limit {limit}")
    payload = _recv_exact(sock, length)
    if length and not payload:
        raise FrameTruncated("connection closed before frame payload")
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise FrameGarbage(f"frame payload is not JSON ({exc})") from None
    if not isinstance(obj, dict):
        raise FrameGarbage(
            f"frame payload is {type(obj).__name__}, expected object")
    return obj


def error_response(code: str, message: str, **extra) -> dict:
    out = {"type": "error", "code": code, "message": message}
    out.update(extra)
    return out
