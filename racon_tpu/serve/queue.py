"""Bounded job queue with admission control and per-tenant fairness.

The admission surface is where a warm server defends itself: a queue
that grows without bound converts overload into unbounded latency for
EVERYONE (and eventually an OOM), so `JobQueue` is bounded and a submit
against a full queue is REJECTED immediately with a `retry_after` hint —
the client backs off instead of camping on a socket. The hint is derived
from observed service time (EMA) times the work ahead of the would-be
job, so it tracks the actual drain rate rather than a constant.

Ordering is WEIGHTED FAIR within priority: higher `priority` classes
pop first; within a class, jobs are grouped by the submit frame's
`tenant` id and served by weighted deficit round-robin — each active
tenant accrues `weight` credits per scheduler rotation and spends one
per popped job, so a tenant with weight 4 gets ~4x the pop rate of a
weight-1 tenant UNDER CONTENTION while an uncontended queue stays pure
FIFO (a single tenant's jobs pop in submission order, and an absent
tenant accrues nothing — credit never banks across idle periods). This
is what keeps one heavy client from monopolizing the continuous
batcher's feeder: the light tenant's next job is at most ~weight pops
away regardless of how deep the heavy tenant's backlog is. Weights come
from the server config (`RACON_TPU_SERVE_TENANT_WEIGHTS`, e.g.
"gold=4,free=1,default=1"); unknown tenants get the `default` weight
(1.0). Jobs without a tenant id share the "" tenant. TRUST BOUNDARY:
tenant ids are client-asserted and unauthenticated — fairness is
meaningful among COOPERATING clients (the localhost/unix-socket
deployment shape this server targets); an adversarial client minting a
fresh tenant per job gets one DRR slot per job, so binding tenant
identity to an authenticated transport is a deployment concern, not
this queue's.

Per-job deadlines are enforced at POP time: a job whose deadline passed
while queued is never handed to a worker — it is marked expired, its
waiter is woken with a typed error, and the `expired` counter bumps.
(Jobs already executing are not preempted; one process, shared device.)

Draining (`drain()`) flips admission off atomically: every later submit
raises `Draining`, while already-admitted jobs keep flowing to workers —
the SIGTERM half of graceful shutdown.

SLO accounting rides the same completion path: `task_done` records each
job's service seconds into BOTH the admission EMA and a rolling window
(last `ROLLING_JOBS` jobs), and classifies deadline-carrying jobs as
`deadline_hit` / `deadline_miss` (finished after the deadline it was
admitted under — distinct from `expired`, which never ran). The
retry-after hint and the stats/scrape SLO view therefore come from the
same numbers, by construction. With a `hists` HistogramSet attached the
queue also observes every popped job's queue wait (`job.queue_wait`).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque


def nearest_rank(sorted_vals, q: float):
    """Nearest-rank percentile: value at rank ceil(q*n) (1-based) of an
    ascending list — `int(n*q)` overshoots by one whole rank whenever
    n*q is integral, reporting the max as p99 for n=100."""
    n = len(sorted_vals)
    return sorted_vals[max(0, min(n - 1, math.ceil(q * n) - 1))]


class AdmissionError(Exception):
    """Base: the queue refused the job at the door."""


class QueueFull(AdmissionError):
    def __init__(self, retry_after: float):
        super().__init__(
            f"job queue full; retry in {retry_after:.2f}s")
        self.retry_after = retry_after


class TenantQuotaExceeded(AdmissionError):
    """Hard per-tenant admission quota (RACON_TPU_SERVE_TENANT_QUOTA):
    the tenant already has `quota` jobs QUEUED. Weights alone only shape
    service ORDER — without this cap one tenant can still fill the whole
    queue depth and every other tenant eats full-queue rejects."""

    def __init__(self, tenant: str, quota: int, retry_after: float):
        super().__init__(
            f"tenant {tenant or '<anonymous>'!r} has {quota} job(s) "
            f"queued (per-tenant quota {quota}); retry in "
            f"{retry_after:.2f}s")
        self.tenant = tenant
        self.quota = quota
        self.retry_after = retry_after


class Draining(AdmissionError):
    def __init__(self):
        super().__init__("server is draining; not admitting jobs")


class DeadlineDoomed(AdmissionError):
    """Speculative deadline-abort (RACON_TPU_SERVE_ABORT_MARGIN): the
    service-time EMA predicts this job cannot finish inside its own
    deadline (plus the configured margin), so it is failed FAST at the
    door — a typed `deadline-doomed` error instead of queue time plus
    device time that the deadline would throw away anyway. Raised again
    mid-run (by the batcher's iteration-boundary estimate) when the
    remaining-work projection says an admitted job's deadline is lost."""

    def __init__(self, predicted_s: float, remaining_s: float,
                 phase: str = "admission"):
        super().__init__(
            f"deadline doomed at {phase}: predicted finish in "
            f"{predicted_s:.2f}s exceeds the {remaining_s:.2f}s left "
            "before the deadline")
        self.predicted_s = predicted_s
        self.remaining_s = remaining_s
        self.phase = phase


class JobCancelledError(Exception):
    """A client (or the router, on behalf of a doomed parent) cancelled
    this job via the `cancel` RPC. For a QUEUED job the queue consumes
    it directly; for a RUNNING job the batcher's withdrawal seam raises
    this through the job's consensus loop within one iteration."""

    def __init__(self, state: str = "running"):
        super().__init__(f"job cancelled while {state}")
        self.state = state


class DeadlineExpired(Exception):
    def __init__(self, waited: float):
        super().__init__(
            f"job deadline expired after {waited:.2f}s in queue")
        self.waited = waited


class DeliveryQueue:
    """Single-consumer handoff queue with a completion flag — the one
    shape both the job outbox (progress/result_part frames -> handler
    thread) and the batcher's window delivery (finished windows -> job
    thread) need. The wakeup discipline lives HERE, once:

      - `push` notifies under the cv;
      - `finish` sets `event` and notifies under the cv — a bare
        event.set() would strand a consumer mid-timed-wait;
      - `take` never starts a timed wait once `event` is set (the
        set happens-before the check, so a consumer that was busy
        when `finish`'s notify fired — the dropped-notify case —
        still returns immediately instead of burning its timeout:
        a silent per-job latency floor otherwise)."""

    __slots__ = ("_items", "_cv", "event")

    def __init__(self):
        self._items: deque = deque()
        self._cv = threading.Condition()
        self.event = threading.Event()

    def push(self, item) -> None:
        with self._cv:
            self._items.append(item)
            self._cv.notify()

    def finish(self) -> None:
        self.event.set()
        with self._cv:
            self._cv.notify()

    def take(self, timeout: float | None = None):
        """The oldest pending item, or None (immediately when complete
        or `timeout` is falsy, else after waiting up to `timeout`)."""
        with self._cv:
            if not self._items and timeout and not self.event.is_set():
                self._cv.wait(timeout)
            return self._items.popleft() if self._items else None


class Job:
    """One polish request in flight. The handler thread that admitted it
    blocks on `event`; the worker that executes it fills `response` (a
    protocol response dict) before setting the event. Jobs that asked
    for live progress and/or streamed results relay frames through the
    `_outbox` DeliveryQueue, drained by the handler thread while it
    waits."""

    __slots__ = ("id", "sequences", "overlaps", "target", "options",
                 "priority", "deadline", "fault_plan", "strict",
                 "want_trace", "enqueued_t", "started_t", "response",
                 "event", "stats_ref", "trace_id", "want_progress",
                 "want_stream", "tenant", "rounds", "cancelled",
                 "range_lo", "range_hi", "fragment", "frag_lo",
                 "frag_hi", "_outbox")

    def __init__(self, id_: str, sequences: str, overlaps: str,
                 target: str, options: dict, priority: int = 0,
                 deadline_s: float | None = None,
                 fault_plan: str | None = None,
                 strict: bool | None = None, want_trace: bool = False,
                 trace_id: str | None = None,
                 want_progress: bool = False,
                 want_stream: bool = False, tenant: str = "",
                 rounds: int | None = None,
                 range_lo: int | None = None,
                 range_hi: int | None = None,
                 fragment: bool = False,
                 frag_lo: int | None = None,
                 frag_hi: int | None = None):
        self.id = id_
        self.sequences = sequences
        self.overlaps = overlaps
        self.target = target
        self.options = options
        self.priority = int(priority)
        self.enqueued_t = time.perf_counter()
        self.deadline = (self.enqueued_t + float(deadline_s)
                         if deadline_s else None)
        self.fault_plan = fault_plan
        self.strict = strict
        self.want_trace = bool(want_trace)
        #: client-minted trace-context id: rides every progress frame,
        #: journal line and serve-side span for this job, so a client
        #: artifact and the server's telemetry correlate by construction
        self.trace_id = trace_id
        self.want_progress = bool(want_progress)
        #: stream per-contig `result_part` frames before the result
        self.want_stream = bool(want_stream)
        #: fair-scheduling identity ("" = the anonymous shared tenant)
        self.tenant = tenant or ""
        #: serve-native polishing rounds (None = unspecified = 1): the
        #: worker loops round k's stitched contigs back in as round
        #: k+1's draft without leaving the warm process (server.py
        #: `_run_job`, core/polisher.redraft). The response carries a
        #: `rounds` accounting block only when the request asked.
        self.rounds = rounds if rounds is None else max(1, int(rounds))
        #: sub-contig window-range shard slice (router fan-out,
        #: serve/protocol.py "Child-job fields"): the worker polishes
        #: only the target windows whose grid start falls in
        #: [range_lo, range_hi) and streams bare-named SEGMENTS; None =
        #: classic whole-target job. Mutually exclusive with `rounds`
        #: (enforced at submit validation).
        self.range_lo = range_lo
        self.range_hi = range_hi
        #: fragment traffic class (`mode: "fragment"` on the submit
        #: frame, protocol.py "Fragment jobs"): the worker runs
        #: PolisherType.kF and streams corrected reads in bounded
        #: GROUPS through the read-order FragmentStreamer instead of
        #: one part per target. Mutually exclusive with range_lo/hi
        #: and with rounds > 1 (enforced at submit validation).
        self.fragment = bool(fragment)
        #: fragment read-range shard slice (router fan-out, protocol.py
        #: "Fragment child jobs"): the worker corrects only the reads
        #: whose TARGET-FILE index falls in [frag_lo, frag_hi); None =
        #: the whole read set. Requires `fragment`.
        self.frag_lo = frag_lo
        self.frag_hi = frag_hi
        #: cancel-RPC flag for RUNNING jobs the batcher cannot reach
        #: (isolation/solo paths never pool): the worker checks it at
        #: round boundaries and fails the job typed `cancelled`
        self.cancelled = False
        self._outbox = DeliveryQueue()
        self.started_t: float | None = None
        self.response: dict | None = None
        #: completion flag; set it via finish() — a bare set() would
        #: leave a handler blocked in next_frame's timed wait
        self.event = self._outbox.event
        #: live PipelineStats of the polisher executing this job (set by
        #: the worker) — the flight-recorder dump snapshots it so a
        #: failed job's artifact carries the stage stats its spans pin to
        self.stats_ref = None

    @property
    def queue_wait_s(self) -> float:
        return (self.started_t or time.perf_counter()) - self.enqueued_t

    @property
    def relaying(self) -> bool:
        """Whether the handler thread must pump the outbox while
        waiting (progress frames, streamed parts, or both)."""
        return self.want_progress or self.want_stream

    # -------------------------------------------------- frame relay
    def notify_progress(self, ev: dict) -> None:
        """Queue one progress event for the handler thread streaming
        this job's connection (server.py). Worker/pipeline/feeder
        threads call it (via the polisher's progress hook); a no-op
        unless the client asked for progress, so the clean path stays
        free."""
        if self.want_progress:
            self._outbox.push(ev)

    def notify_part(self, frame: dict) -> None:
        """Queue one ready-to-send `result_part` frame; a no-op unless
        the client asked for streamed results."""
        if self.want_stream:
            self._outbox.push(frame)

    def next_frame(self, timeout: float | None = None) -> dict | None:
        """Pop the oldest pending outbox entry, waiting up to `timeout`
        for one; None when nothing arrived."""
        return self._outbox.take(timeout)

    def finish(self) -> None:
        """Mark the job complete and wake the handler immediately
        (see DeliveryQueue: event.set() alone leaves the handler
        burning out a timed wait before it sends the result frame)."""
        self._outbox.finish()


class _PriorityClass:
    """One priority level's per-tenant queues + DRR rotation state."""

    __slots__ = ("tenants", "rr", "deficit", "count")

    def __init__(self):
        self.tenants: dict[str, deque] = {}
        self.rr: deque = deque()
        self.deficit: dict[str, float] = {}
        self.count = 0


class JobQueue:
    """Thread-safe bounded weighted-fair queue (see module docstring)."""

    #: retry_after clamp (seconds)
    RETRY_MIN, RETRY_MAX = 0.05, 60.0
    #: rolling service-time window size (jobs) behind the SLO view
    ROLLING_JOBS = 64
    #: floor for configured weights (0/negative would stall the DRR)
    MIN_WEIGHT = 0.01
    #: distinct tenants tracked in the lifetime counters (tenant ids
    #: are client-controlled: without a cap, a client minting a fresh
    #: id per job would grow server memory and scrape cardinality
    #: forever); overflow folds into the "~other" bucket. Scheduling
    #: itself is unaffected — only the per-tenant accounting caps.
    MAX_TRACKED_TENANTS = 64

    def __init__(self, maxsize: int, workers: int = 1, hists=None,
                 tenant_weights: dict | None = None,
                 tenant_quota: int = 0, tenant_burst: int = 0,
                 abort_margin: float | None = None):
        self.maxsize = max(1, int(maxsize))
        self.workers = max(1, int(workers))
        self.tenant_weights = dict(tenant_weights or {})
        #: hard cap on QUEUED jobs per tenant (0 = off): admission-time
        #: protection weights cannot give — see TenantQuotaExceeded
        self.tenant_quota = max(0, int(tenant_quota))
        #: burst-token bucket capacity per tenant (0 = off): lets a
        #: tenant briefly exceed `tenant_quota` by spending banked
        #: tokens, refilled at its DRR weight in tokens/second — so a
        #: gold tenant re-earns burst headroom faster than a free one
        self.tenant_burst = max(0, int(tenant_burst))
        #: tenant -> [tokens, last_refill_monotonic]
        self._burst: dict[str, list] = {}
        self.burst_admits = 0
        #: speculative deadline-abort margin in seconds (None = off):
        #: a deadline-carrying submit whose EMA-predicted finish
        #: overshoots its deadline by more than this is rejected typed
        #: (`deadline-doomed`) instead of admitted to die later
        self.abort_margin = (None if abort_margin is None
                             else max(0.0, float(abort_margin)))
        #: live queued count per tenant (quota enforcement; jobs leave
        #: the count at pop time, expired included)
        self._queued_by_tenant: dict[str, int] = {}
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        #: priority -> _PriorityClass; scheduling pops the highest
        #: priority first, weighted-DRR across tenants within it
        self._classes: dict[int, _PriorityClass] = {}
        self._count = 0
        #: bumped on every push/pop: progress streamers poll queue
        #: position while their job is pending, and the version lets
        #: them skip the O(depth) position() simulation (and its lock
        #: acquisition) when nothing moved
        self._version = 0
        self._draining = False
        #: EMA of job service seconds, seeded pessimistically so the
        #: first rejections before any completion still back off
        self._ema_service_s = 1.0
        #: the same service seconds the EMA eats, kept verbatim for the
        #: rolling SLO percentiles — one stream, two views
        self._recent: deque = deque(maxlen=self.ROLLING_JOBS)
        #: optional obs.hist.HistogramSet (the server's lifetime set)
        self.hists = hists
        #: optional callable(event: str, job: Job, **fields) fired on
        #: queue-side lifecycle transitions (`admitted`, `started`,
        #: `expired`) — the server wires its event journal
        #: (obs/journal.py) and the progress relay here. `admitted` and
        #: `expired` fire UNDER the queue lock (admitted must
        #: happen-before the popping worker's started): the callback
        #: must not call back into the queue; `started` fires on the
        #: worker thread after pop releases the lock, keeping the
        #: per-job disk write off the hot lock. Exceptions are
        #: swallowed — accounting must never strand a job.
        self.on_event = None
        #: optional callable(job, hit_total, miss_total) fired OUTSIDE
        #: the lock after each deadline-carrying job is accounted — the
        #: server's SLO burn-rate tracker samples the cumulative
        #: counters here (obs/fleet.py). Exceptions are swallowed:
        #: alerting must never strand a job.
        self.on_slo = None
        self.counters = {"submitted": 0, "admitted": 0, "rejected_full": 0,
                         "rejected_draining": 0, "rejected_quota": 0,
                         "expired": 0, "completed": 0, "failed": 0,
                         "deadline_hit": 0, "deadline_miss": 0}
        #: per-tenant lifetime counters (admitted/completed/failed) —
        #: the fairness story's receipt in stats/scrape
        self.tenant_counters: dict[str, dict] = {}

    def weight(self, tenant: str) -> float:
        w = self.tenant_weights.get(
            tenant, self.tenant_weights.get("default", 1.0))
        try:
            return max(float(w), self.MIN_WEIGHT)
        except (TypeError, ValueError):
            return 1.0

    # -------------------------------------------------------- admission
    def _retry_after_locked(self) -> float:
        """Backoff for a rejected submit (caller holds the lock):
        estimated time until a slot frees = work ahead / drain rate,
        from the service-time EMA."""
        est = (self._ema_service_s * max(1, self._count)
               / self.workers)
        return min(max(est, self.RETRY_MIN), self.RETRY_MAX)

    def _tenant_counter_locked(self, tenant: str) -> dict:
        if (tenant not in self.tenant_counters
                and len(self.tenant_counters)
                >= self.MAX_TRACKED_TENANTS):
            tenant = "~other"
        return self.tenant_counters.setdefault(
            tenant, {"admitted": 0, "completed": 0, "failed": 0,
                     "expired": 0})

    def _burst_take_locked(self, tenant: str) -> bool:
        """Spend one burst token for `tenant` if its bucket (capacity
        `tenant_burst`, refilled at the tenant's DRR weight per second,
        starting full) holds one; caller holds the lock."""
        now = time.monotonic()
        bucket = self._burst.get(tenant)
        if bucket is None:
            bucket = self._burst[tenant] = [float(self.tenant_burst),
                                            now]
        tokens = min(float(self.tenant_burst),
                     bucket[0] + (now - bucket[1]) * self.weight(tenant))
        bucket[1] = now
        if tokens >= 1.0:
            bucket[0] = tokens - 1.0
            self.burst_admits += 1
            return True
        bucket[0] = tokens
        return False

    def _doomed_check_locked(self, job: Job) -> None:
        """Speculative deadline-abort at admission: with `abort_margin`
        armed, reject a deadline-carrying job whose EMA-predicted
        finish (work at-or-above its priority class ahead of it, plus
        itself, over the worker drain rate) overshoots the deadline by
        more than the margin. Priority-aware on purpose: a gold job is
        never doomed by a lower-class flood it would pop past."""
        if self.abort_margin is None or job.deadline is None:
            return
        ahead = sum(c.count for p, c in self._classes.items()
                    if p >= job.priority)
        predicted_s = (self._ema_service_s * (ahead + 1) / self.workers)
        remaining_s = job.deadline - time.perf_counter()
        if predicted_s > remaining_s + self.abort_margin:
            raise DeadlineDoomed(predicted_s, remaining_s)

    def submit(self, job: Job) -> None:
        with self._lock:
            self.counters["submitted"] += 1
            if self._draining:
                self.counters["rejected_draining"] += 1
                raise Draining()
            if self._count >= self.maxsize:
                self.counters["rejected_full"] += 1
                raise QueueFull(self._retry_after_locked())
            queued = self._queued_by_tenant.get(job.tenant, 0)
            if (self.tenant_quota and queued >= self.tenant_quota
                    and not (self.tenant_burst
                             and self._burst_take_locked(job.tenant))):
                self.counters["rejected_quota"] += 1
                # backoff until one of THIS tenant's queued jobs drains,
                # from the same service-time EMA the full-queue hint uses
                est = (self._ema_service_s * max(1, queued)
                       / self.workers)
                raise TenantQuotaExceeded(
                    job.tenant, self.tenant_quota,
                    min(max(est, self.RETRY_MIN), self.RETRY_MAX))
            self._doomed_check_locked(job)
            self._queued_by_tenant[job.tenant] = queued + 1
            self.counters["admitted"] += 1
            self._tenant_counter_locked(job.tenant)["admitted"] += 1
            cls = self._classes.setdefault(job.priority,
                                           _PriorityClass())
            q = cls.tenants.get(job.tenant)
            if q is None:
                # a (re)joining tenant starts with zero credit: absence
                # banks nothing
                q = cls.tenants[job.tenant] = deque()
                cls.rr.append(job.tenant)
                cls.deficit[job.tenant] = 0.0
            q.append(job)
            cls.count += 1
            self._count += 1
            self._version += 1
            # fired UNDER the lock deliberately: a worker can pop this
            # job the instant the lock releases, and the journal's
            # `admitted` line must happen-before its `started` line.
            # The on_event contract keeps under-lock callbacks disk-
            # free (the server STAGES this event; see its sink)
            self._notify("admitted", job, depth=self._count)
            self._not_empty.notify()

    # ------------------------------------------------------------- pop
    @staticmethod
    def _retire_tenant(tenants: dict, rr: deque, deficit: dict,
                       tenant: str) -> None:
        try:
            rr.remove(tenant)
        except ValueError:
            pass
        tenants.pop(tenant, None)
        deficit.pop(tenant, None)

    def _drr_select(self, tenants: dict, rr: deque,
                    deficit: dict) -> str:
        """ONE weighted-DRR decision over a (tenants, rr, deficit)
        state triple: retire drained tenants, rotate accruing credit,
        return the tenant to serve (its deficit already debited). The
        SINGLE copy of the scheduling algorithm — the live pop path
        passes the class's state, position()'s simulation passes a
        copy, so the two can never diverge. Precondition: at least one
        tenant has a job. Terminates: every full rotation adds at
        least MIN_WEIGHT to some non-empty tenant's deficit."""
        while True:
            tenant = rr[0]
            q = tenants.get(tenant)
            if not q:
                self._retire_tenant(tenants, rr, deficit, tenant)
                continue
            if deficit.get(tenant, 0.0) >= 1.0:
                deficit[tenant] -= 1.0
                return tenant
            deficit[tenant] = (deficit.get(tenant, 0.0)
                               + self.weight(tenant))
            rr.rotate(-1)

    def _pop_next_locked(self) -> Job | None:
        """One scheduling decision (caller holds the lock); None when
        empty: highest non-empty priority class, weighted DRR across
        its tenants."""
        if self._count == 0:
            return None
        prio = max(p for p, c in self._classes.items() if c.count > 0)
        cls = self._classes[prio]
        tenant = self._drr_select(cls.tenants, cls.rr, cls.deficit)
        q = cls.tenants[tenant]
        job = q.popleft()
        cls.count -= 1
        self._count -= 1
        # quota ledger: expired jobs pop through here too, so a tenant
        # whose jobs all expired regains its quota slots
        left = self._queued_by_tenant.get(job.tenant, 0) - 1
        if left > 0:
            self._queued_by_tenant[job.tenant] = left
        else:
            self._queued_by_tenant.pop(job.tenant, None)
        if not q:
            self._retire_tenant(cls.tenants, cls.rr, cls.deficit,
                                tenant)
        if cls.count == 0:
            del self._classes[prio]
        return job

    def pop(self, timeout: float | None = None) -> Job | None:
        """Next runnable job, or None on timeout. Deadline-expired jobs
        are consumed here: their waiters get a typed error and workers
        never see them."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        popped: Job | None = None
        with self._not_empty:
            while popped is None:
                while self._count:
                    job = self._pop_next_locked()
                    if job is None:
                        break
                    self._version += 1
                    now = time.perf_counter()
                    if job.deadline is not None and now > job.deadline:
                        self.counters["expired"] += 1
                        # the tenant's ledger must balance: admitted ==
                        # completed + failed + expired + queued
                        self._tenant_counter_locked(job.tenant)[
                            "expired"] += 1
                        exc = DeadlineExpired(now - job.enqueued_t)
                        job.response = {
                            "type": "error", "code": "deadline-expired",
                            "message": str(exc), "job_id": job.id}
                        self._notify("expired", job,
                                     waited_s=round(exc.waited, 4))
                        job.finish()
                        continue
                    job.started_t = now
                    if self.hists is not None:
                        self.hists.observe("job.queue_wait",
                                           now - job.enqueued_t)
                    popped = job
                    break
                if popped is not None:
                    break
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._not_empty.wait(left):
                        if not self._count:
                            return None
                else:
                    self._not_empty.wait()
        # fired OUTSIDE the lock: `started` triggers a journal write
        # (disk) on the per-job hot path, and the admitted->started
        # ordering is already guaranteed by `admitted` firing under the
        # submit lock that this pop had to wait out
        self._notify("started", popped,
                     queue_wait_s=round(
                         popped.started_t - popped.enqueued_t, 4))
        return popped

    def task_done(self, job: Job, ok: bool, service_s: float,
                  exemplar: dict | None = None) -> bool:
        """Account a finished job. Returns True when the job carried a
        deadline and finished PAST it (the SLO miss the server's flight
        recorder dumps on) — expired-in-queue jobs never reach here.
        `exemplar` (trace id / flight-dump path, built by the serve
        worker) rides the job-latency observation so the scrape's
        latency buckets name a representative job."""
        missed = (job.deadline is not None
                  and time.perf_counter() > job.deadline)
        with self._lock:
            self.counters["completed" if ok else "failed"] += 1
            self._tenant_counter_locked(job.tenant)[
                "completed" if ok else "failed"] += 1
            if job.deadline is not None:
                self.counters["deadline_miss" if missed
                              else "deadline_hit"] += 1
                hit = self.counters["deadline_hit"]
                miss = self.counters["deadline_miss"]
            else:
                hit = None
            # EMA over the last ~8 jobs: adapts to workload shifts
            # without a rejection spike swinging the hint wildly
            self._ema_service_s += (service_s - self._ema_service_s) / 8.0
            self._recent.append(service_s)
        if self.hists is not None:
            self.hists.observe("job.service", service_s)
            self.hists.observe("job.latency",
                               time.perf_counter() - job.enqueued_t,
                               exemplar=exemplar)
        if hit is not None and self.on_slo is not None:
            try:
                self.on_slo(job, hit, miss)
            except Exception:  # noqa: BLE001 — see on_slo contract
                pass
        return missed

    def _notify(self, event: str, job: Job, **fields) -> None:
        cb = self.on_event
        if cb is None:
            return
        try:
            cb(event, job, **fields)
        except Exception:  # noqa: BLE001 — see on_event contract
            pass

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def _simulated_order_locked(self) -> list[Job]:
        """Predicted pop order of every queued job — the SAME
        `_drr_select` the live pop path runs, over copied state (caller
        holds the lock; O(depth) with the queue's bounded depth)."""
        order: list[Job] = []
        sim = {}
        for prio, cls in self._classes.items():
            if cls.count:
                sim[prio] = (dict((t, deque(q))
                                  for t, q in cls.tenants.items() if q),
                             deque(cls.rr), dict(cls.deficit))
        while sim:
            prio = max(sim)
            tenants, rr, deficit = sim[prio]
            if not tenants:
                del sim[prio]
                continue
            tenant = self._drr_select(tenants, rr, deficit)
            q = tenants[tenant]
            order.append(q.popleft())
            if not q:
                self._retire_tenant(tenants, rr, deficit, tenant)
        return order

    def position(self, job: Job) -> int | None:
        """0-based count of queued jobs that would pop before `job`, or
        None once the job is no longer queued (started / expired) — the
        live queue-position number the progress stream reports while a
        job is pending."""
        with self._lock:
            for i, j in enumerate(self._simulated_order_locked()):
                if j is job:
                    return i
        return None

    # ---------------------------------------------------------- cancel
    def cancel(self, job_id: str | None = None,
               trace_id: str | None = None) -> Job | None:
        """Remove a QUEUED job by id (or client-minted trace id — the
        handle a router holds for its child shards), wake its waiter
        with a typed `cancelled` error, and free its queue + quota
        slots immediately. Returns the job, or None when nothing queued
        matches (already running, finished, or unknown — the caller
        distinguishes). Accounted like an expiry: the job left the
        queue without running, so the tenant ledger stays balanced."""
        with self._lock:
            job: Job | None = None
            for j in self._iter_queued_locked():
                if ((job_id is not None and j.id == job_id)
                        or (trace_id is not None
                            and j.trace_id == trace_id)):
                    job = j
                    break
            if job is None:
                return None
            cls = self._classes[job.priority]
            q = cls.tenants[job.tenant]
            q.remove(job)
            cls.count -= 1
            self._count -= 1
            self._version += 1
            left = self._queued_by_tenant.get(job.tenant, 0) - 1
            if left > 0:
                self._queued_by_tenant[job.tenant] = left
            else:
                self._queued_by_tenant.pop(job.tenant, None)
            if not q:
                self._retire_tenant(cls.tenants, cls.rr, cls.deficit,
                                    job.tenant)
            if cls.count == 0:
                del self._classes[job.priority]
            self.counters["expired"] += 1
            self._tenant_counter_locked(job.tenant)["expired"] += 1
            exc = JobCancelledError("queued")
            job.response = {"type": "error", "code": "cancelled",
                            "message": str(exc), "job_id": job.id}
            self._notify("cancelled", job, state="queued",
                         waited_s=round(
                             time.perf_counter() - job.enqueued_t, 4))
            job.finish()
            return job

    def highest_queued_priority(self) -> int | None:
        """Highest priority class with queued work, or None when empty
        — the resume gate for preempted jobs (server.py): a parked job
        resumes only when nothing strictly above it is still waiting."""
        with self._lock:
            prios = [p for p, c in self._classes.items() if c.count > 0]
            return max(prios) if prios else None

    # ----------------------------------------------------------- drain
    def drain(self) -> None:
        """Stop admitting; queued jobs keep flowing to workers."""
        with self._lock:
            self._draining = True
            self._not_empty.notify_all()

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def __len__(self) -> int:
        with self._lock:
            return self._count

    def _iter_queued_locked(self):
        for cls in self._classes.values():
            for q in cls.tenants.values():
                yield from q

    def snapshot(self) -> dict:
        with self._lock:
            recent = sorted(self._recent)
            queued = list(self._iter_queued_locked())
            oldest = min((j.enqueued_t for j in queued), default=None)
            tenants: dict[str, dict] = {}
            for t, c in self.tenant_counters.items():
                tenants[t] = dict(c, weight=self.weight(t), queued=0)
            for j in queued:
                tenants.setdefault(
                    j.tenant, {"admitted": 0, "completed": 0,
                               "failed": 0, "expired": 0,
                               "weight": self.weight(j.tenant),
                               "queued": 0})
                tenants[j.tenant]["queued"] += 1
            # live DRR credit (accrued deficit across priority classes)
            # — the fairness dial servetop renders per tenant
            credit: dict[str, float] = {}
            for cls in self._classes.values():
                for t, d in cls.deficit.items():
                    credit[t] = credit.get(t, 0.0) + d
            for t, tc in tenants.items():
                tc["credit"] = round(credit.get(t, 0.0), 3)
            out = dict(self.counters, depth=self._count,
                       maxsize=self.maxsize,
                       draining=self._draining,
                       oldest_wait_s=(
                           round(time.perf_counter() - oldest, 4)
                           if oldest is not None else 0.0),
                       ema_service_s=round(self._ema_service_s, 4),
                       tenants=tenants)
            # armed-only keys: an unconfigured server's stats payload
            # stays byte-identical to the pre-QoS shape
            if self.tenant_burst:
                out["tenant_burst"] = self.tenant_burst
                out["burst_admits"] = self.burst_admits
            if self.abort_margin is not None:
                out["abort_margin_s"] = self.abort_margin
        if recent:
            n = len(recent)
            out["recent"] = {
                "jobs": n,
                "p50_s": round(nearest_rank(recent, 0.50), 4),
                "p95_s": round(nearest_rank(recent, 0.95), 4),
                "p99_s": round(nearest_rank(recent, 0.99), 4),
                "mean_s": round(sum(recent) / n, 4)}
        return out
