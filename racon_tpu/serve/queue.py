"""Bounded job queue with admission control for the polishing service.

The admission surface is where a warm server defends itself: a queue
that grows without bound converts overload into unbounded latency for
EVERYONE (and eventually an OOM), so `JobQueue` is bounded and a submit
against a full queue is REJECTED immediately with a `retry_after` hint —
the client backs off instead of camping on a socket. The hint is derived
from observed service time (EMA) times the work ahead of the would-be
job, so it tracks the actual drain rate rather than a constant.

Ordering is FIFO within priority: higher `priority` pops first, equal
priorities pop in submission order (a monotonic sequence number breaks
heap ties, so starvation within a priority class is impossible).

Per-job deadlines are enforced at POP time: a job whose deadline passed
while queued is never handed to a worker — it is marked expired, its
waiter is woken with a typed error, and the `expired` counter bumps.
(Jobs already executing are not preempted; one process, shared device.)

Draining (`drain()`) flips admission off atomically: every later submit
raises `Draining`, while already-admitted jobs keep flowing to workers —
the SIGTERM half of graceful shutdown.

SLO accounting rides the same completion path: `task_done` records each
job's service seconds into BOTH the admission EMA and a rolling window
(last `ROLLING_JOBS` jobs), and classifies deadline-carrying jobs as
`deadline_hit` / `deadline_miss` (finished after the deadline it was
admitted under — distinct from `expired`, which never ran). The
retry-after hint and the stats/scrape SLO view therefore come from the
same numbers, by construction. With a `hists` HistogramSet attached the
queue also observes every popped job's queue wait (`job.queue_wait`).
"""

from __future__ import annotations

import heapq
import itertools
import math
import threading
import time
from collections import deque


def nearest_rank(sorted_vals, q: float):
    """Nearest-rank percentile: value at rank ceil(q*n) (1-based) of an
    ascending list — `int(n*q)` overshoots by one whole rank whenever
    n*q is integral, reporting the max as p99 for n=100."""
    n = len(sorted_vals)
    return sorted_vals[max(0, min(n - 1, math.ceil(q * n) - 1))]


class AdmissionError(Exception):
    """Base: the queue refused the job at the door."""


class QueueFull(AdmissionError):
    def __init__(self, retry_after: float):
        super().__init__(
            f"job queue full; retry in {retry_after:.2f}s")
        self.retry_after = retry_after


class Draining(AdmissionError):
    def __init__(self):
        super().__init__("server is draining; not admitting jobs")


class DeadlineExpired(Exception):
    def __init__(self, waited: float):
        super().__init__(
            f"job deadline expired after {waited:.2f}s in queue")
        self.waited = waited


class Job:
    """One polish request in flight. The handler thread that admitted it
    blocks on `event`; the worker that executes it fills `response` (a
    protocol response dict) before setting the event."""

    __slots__ = ("id", "sequences", "overlaps", "target", "options",
                 "priority", "deadline", "fault_plan", "strict",
                 "want_trace", "enqueued_t", "started_t", "response",
                 "event", "stats_ref", "trace_id", "want_progress",
                 "_progress", "_progress_cv")

    def __init__(self, id_: str, sequences: str, overlaps: str,
                 target: str, options: dict, priority: int = 0,
                 deadline_s: float | None = None,
                 fault_plan: str | None = None,
                 strict: bool | None = None, want_trace: bool = False,
                 trace_id: str | None = None,
                 want_progress: bool = False):
        self.id = id_
        self.sequences = sequences
        self.overlaps = overlaps
        self.target = target
        self.options = options
        self.priority = int(priority)
        self.enqueued_t = time.perf_counter()
        self.deadline = (self.enqueued_t + float(deadline_s)
                         if deadline_s else None)
        self.fault_plan = fault_plan
        self.strict = strict
        self.want_trace = bool(want_trace)
        #: client-minted trace-context id: rides every progress frame,
        #: journal line and serve-side span for this job, so a client
        #: artifact and the server's telemetry correlate by construction
        self.trace_id = trace_id
        self.want_progress = bool(want_progress)
        self._progress: deque = deque()
        self._progress_cv = threading.Condition()
        self.started_t: float | None = None
        self.response: dict | None = None
        self.event = threading.Event()
        #: live PipelineStats of the polisher executing this job (set by
        #: the worker) — the flight-recorder dump snapshots it so a
        #: failed job's artifact carries the stage stats its spans pin to
        self.stats_ref = None

    @property
    def queue_wait_s(self) -> float:
        return (self.started_t or time.perf_counter()) - self.enqueued_t

    # -------------------------------------------------- progress relay
    def notify_progress(self, ev: dict) -> None:
        """Queue one progress event for the handler thread streaming
        this job's connection (server.py). Worker/pipeline threads call
        it (via the polisher's progress hook); a no-op unless the
        client asked for progress, so the clean path stays free."""
        if not self.want_progress:
            return
        with self._progress_cv:
            self._progress.append(ev)
            self._progress_cv.notify()

    def next_progress(self, timeout: float | None = None) -> dict | None:
        """Pop the oldest pending progress event, waiting up to
        `timeout` for one; None when nothing arrived."""
        with self._progress_cv:
            if not self._progress and timeout:
                self._progress_cv.wait(timeout)
            return self._progress.popleft() if self._progress else None


class JobQueue:
    """Thread-safe bounded priority queue (see module docstring)."""

    #: retry_after clamp (seconds)
    RETRY_MIN, RETRY_MAX = 0.05, 60.0
    #: rolling service-time window size (jobs) behind the SLO view
    ROLLING_JOBS = 64

    def __init__(self, maxsize: int, workers: int = 1, hists=None):
        self.maxsize = max(1, int(maxsize))
        self.workers = max(1, int(workers))
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._heap: list = []
        self._seq = itertools.count()
        #: bumped on every push/pop: progress streamers poll queue
        #: position while their job is pending, and the version lets
        #: them skip the O(n log n) position() recompute (and its lock
        #: acquisition) when nothing moved
        self._version = 0
        self._draining = False
        #: EMA of job service seconds, seeded pessimistically so the
        #: first rejections before any completion still back off
        self._ema_service_s = 1.0
        #: the same service seconds the EMA eats, kept verbatim for the
        #: rolling SLO percentiles — one stream, two views
        self._recent: deque = deque(maxlen=self.ROLLING_JOBS)
        #: optional obs.hist.HistogramSet (the server's lifetime set)
        self.hists = hists
        #: optional callable(event: str, job: Job, **fields) fired on
        #: queue-side lifecycle transitions (`admitted`, `started`,
        #: `expired`) — the server wires its event journal
        #: (obs/journal.py) and the progress relay here. `admitted` and
        #: `expired` fire UNDER the queue lock (admitted must
        #: happen-before the popping worker's started): the callback
        #: must not call back into the queue; `started` fires on the
        #: worker thread after pop releases the lock, keeping the
        #: per-job disk write off the hot lock. Exceptions are
        #: swallowed — accounting must never strand a job.
        self.on_event = None
        self.counters = {"submitted": 0, "admitted": 0, "rejected_full": 0,
                         "rejected_draining": 0, "expired": 0,
                         "completed": 0, "failed": 0,
                         "deadline_hit": 0, "deadline_miss": 0}

    # -------------------------------------------------------- admission
    def _retry_after_locked(self) -> float:
        """Backoff for a rejected submit (caller holds the lock):
        estimated time until a slot frees = work ahead / drain rate,
        from the service-time EMA."""
        est = (self._ema_service_s * max(1, len(self._heap))
               / self.workers)
        return min(max(est, self.RETRY_MIN), self.RETRY_MAX)

    def submit(self, job: Job) -> None:
        with self._lock:
            self.counters["submitted"] += 1
            if self._draining:
                self.counters["rejected_draining"] += 1
                raise Draining()
            if len(self._heap) >= self.maxsize:
                self.counters["rejected_full"] += 1
                raise QueueFull(self._retry_after_locked())
            self.counters["admitted"] += 1
            heapq.heappush(self._heap,
                           (-job.priority, next(self._seq), job))
            self._version += 1
            # fired UNDER the lock deliberately: a worker can pop this
            # job the instant the lock releases, and the journal's
            # `admitted` line must happen-before its `started` line.
            # The on_event contract keeps under-lock callbacks disk-
            # free (the server STAGES this event; see its sink)
            self._notify("admitted", job, depth=len(self._heap))
            self._not_empty.notify()

    # ------------------------------------------------------------- pop
    def pop(self, timeout: float | None = None) -> Job | None:
        """Next runnable job, or None on timeout. Deadline-expired jobs
        are consumed here: their waiters get a typed error and workers
        never see them."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        popped: Job | None = None
        with self._not_empty:
            while popped is None:
                while self._heap:
                    _, _, job = heapq.heappop(self._heap)
                    self._version += 1
                    now = time.perf_counter()
                    if job.deadline is not None and now > job.deadline:
                        self.counters["expired"] += 1
                        exc = DeadlineExpired(now - job.enqueued_t)
                        job.response = {
                            "type": "error", "code": "deadline-expired",
                            "message": str(exc), "job_id": job.id}
                        self._notify("expired", job,
                                     waited_s=round(exc.waited, 4))
                        job.event.set()
                        continue
                    job.started_t = now
                    if self.hists is not None:
                        self.hists.observe("job.queue_wait",
                                           now - job.enqueued_t)
                    popped = job
                    break
                if popped is not None:
                    break
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._not_empty.wait(left):
                        if not self._heap:
                            return None
                else:
                    self._not_empty.wait()
        # fired OUTSIDE the lock: `started` triggers a journal write
        # (disk) on the per-job hot path, and the admitted->started
        # ordering is already guaranteed by `admitted` firing under the
        # submit lock that this pop had to wait out
        self._notify("started", popped,
                     queue_wait_s=round(
                         popped.started_t - popped.enqueued_t, 4))
        return popped

    def task_done(self, job: Job, ok: bool, service_s: float) -> bool:
        """Account a finished job. Returns True when the job carried a
        deadline and finished PAST it (the SLO miss the server's flight
        recorder dumps on) — expired-in-queue jobs never reach here."""
        missed = (job.deadline is not None
                  and time.perf_counter() > job.deadline)
        with self._lock:
            self.counters["completed" if ok else "failed"] += 1
            if job.deadline is not None:
                self.counters["deadline_miss" if missed
                              else "deadline_hit"] += 1
            # EMA over the last ~8 jobs: adapts to workload shifts
            # without a rejection spike swinging the hint wildly
            self._ema_service_s += (service_s - self._ema_service_s) / 8.0
            self._recent.append(service_s)
        if self.hists is not None:
            self.hists.observe("job.service", service_s)
            self.hists.observe("job.latency",
                               time.perf_counter() - job.enqueued_t)
        return missed

    def _notify(self, event: str, job: Job, **fields) -> None:
        cb = self.on_event
        if cb is None:
            return
        try:
            cb(event, job, **fields)
        except Exception:  # noqa: BLE001 — see on_event contract
            pass

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def position(self, job: Job) -> int | None:
        """0-based count of queued jobs that would pop before `job`, or
        None once the job is no longer queued (started / expired) — the
        live queue-position number the progress stream reports while a
        job is pending."""
        with self._lock:
            # heap entries sort exactly in pop order: (-priority, seq)
            # is unique, so the job object itself is never compared
            for i, (_, _, j) in enumerate(sorted(self._heap)):
                if j is job:
                    return i
        return None

    # ----------------------------------------------------------- drain
    def drain(self) -> None:
        """Stop admitting; queued jobs keep flowing to workers."""
        with self._lock:
            self._draining = True
            self._not_empty.notify_all()

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)

    def snapshot(self) -> dict:
        with self._lock:
            recent = sorted(self._recent)
            oldest = min((j.enqueued_t for _, _, j in self._heap),
                         default=None)
            out = dict(self.counters, depth=len(self._heap),
                       maxsize=self.maxsize,
                       draining=self._draining,
                       oldest_wait_s=(
                           round(time.perf_counter() - oldest, 4)
                           if oldest is not None else 0.0),
                       ema_service_s=round(self._ema_service_s, 4))
        if recent:
            n = len(recent)
            out["recent"] = {
                "jobs": n,
                "p50_s": round(nearest_rank(recent, 0.50), 4),
                "p95_s": round(nearest_rank(recent, 0.95), 4),
                "p99_s": round(nearest_rank(recent, 0.99), 4),
                "mean_s": round(sum(recent) / n, 4)}
        return out
